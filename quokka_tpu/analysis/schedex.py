"""Deterministic-schedule explorer for the recovery protocol (schedex).

    python -m quokka_tpu.analysis.schedex                  # explore + report
    python -m quokka_tpu.analysis.schedex --seed 7 --rule covering
    python -m quokka_tpu.analysis.schedex --minimize

TestKill9Recovery wedged about once in ten runs: after a SIGKILL took a
worker that owned both a producer and its consumer, the consumer's exec
task spun on ``plan_get=None`` forever while the stall report blamed the
dead worker's stale heartbeat.  The root cause was an *interleaving* —
checkpoint placement vs kill timing — which wall-clock soak runs reproduce
only probabilistically.  This module replays the protocol under a seeded
virtual clock instead: every interleaving is a pure function of its seed,
so a failing schedule is a permalink, and delta-debugging can shrink it to
the minimal action sequence that still wedges.

The model is the recovery protocol stripped to its load-bearing state
(runtime/engine.py): per-channel out_seq / input frontiers / lineage tape /
checkpoint history (LCT + ("ckpts", ...) + IRT), worker-owned seq caches
that die with their worker, and a coordinator whose ``recover`` step runs
the rewind planner.  Two planner rules are implemented:

- ``covering`` — the OLD rule: co-dead producers are rewound only far
  enough to cover seqs recorded on consumers' tape slices.  A co-dead
  consumer whose LIVE phase (after replaying its tape) needs a seq its
  tape never recorded leaves the producer at a checkpoint PAST that seq:
  the seq exists nowhere (producer-side spill and consumer-side cache both
  died), and the consumer blocks forever — the wedge.
- ``frontier`` — the SHIPPED rule (engine.plan_rewinds): each dead
  channel's post-tape input frontier (IRT at the chosen state advanced
  through the tape slice) is computed, and co-dead producers must also
  cover THAT.  Exploration across every seed finds no wedge under it.

Wedge detection is exact, not timeout-based: the world is quiescent when
no action can make progress; quiescent with an unmet need is a wedge.
"""

from __future__ import annotations

import argparse
import random
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# topology of the repro: source -> producer -> consumer, with the producer
# and consumer co-located on one worker (the SIGKILL takes both — the
# TestKill9Recovery shape: worker 1 owned (2,1) and (3,1))
SOURCE, PROD, CONS = "S", "P", "X"
WORKER_OF = {SOURCE: 0, PROD: 1, CONS: 1}
UPSTREAM = {PROD: SOURCE, CONS: PROD}
MAX_SEQS = 4  # source run length: enough for every checkpoint/kill phasing


@dataclass
class Chan:
    """One channel's control-plane state (LCT/IRT/tape/ckpts essentials)."""
    name: str
    out_seq: int = 0
    frontier: int = 0            # next upstream seq this channel consumes
    tape: List[int] = field(default_factory=list)   # recorded input seqs
    # checkpoint history: (state_seq, out_seq, tape_pos, frontier=IRT)
    ckpts: List[Tuple[int, int, int, int]] = field(
        default_factory=lambda: [(0, 0, 0, 0)])
    alive: bool = True


@dataclass
class World:
    chans: Dict[str, Chan]
    # (producer, seq) -> owning worker while the copy is alive
    cache: Dict[Tuple[str, int], int] = field(default_factory=dict)
    killed: bool = False
    recovered: bool = False

    @classmethod
    def fresh(cls) -> "World":
        return cls({n: Chan(n) for n in (SOURCE, PROD, CONS)})


Action = Tuple[str, str]  # (verb, channel-or-'') — one schedule step


def enabled(w: World) -> List[Action]:
    out: List[Action] = []
    for name, c in w.chans.items():
        if not c.alive:
            continue
        if name == SOURCE:
            if c.out_seq < MAX_SEQS:
                out.append(("produce", name))
        else:
            # the needed upstream seq must still be cached somewhere
            # (produced seqs enter the cache at produce time)
            if (UPSTREAM[name], c.frontier) in w.cache:
                out.append(("produce", name))
            if (c.ckpts[-1][2] < len(c.tape)
                    or c.out_seq > c.ckpts[-1][1]):
                out.append(("checkpoint", name))
    if not w.killed:
        out.append(("kill", ""))
    if w.killed and not w.recovered:
        out.append(("recover", ""))
    return out


def _produce(w: World, name: str) -> None:
    c = w.chans[name]
    if name != SOURCE:
        # consume the input seq at the frontier, record it on the tape
        del_key = (UPSTREAM[name], c.frontier)
        # the copy stays cached for other (hypothetical) consumers; the
        # engine's seq-keyed cache keeps it until GC — keep it here too
        assert del_key in w.cache
        c.tape.append(c.frontier)
        c.frontier += 1
    w.cache[(name, c.out_seq)] = WORKER_OF[name]
    c.out_seq += 1


def _checkpoint(w: World, name: str) -> None:
    c = w.chans[name]
    state = c.ckpts[-1][0] + 1
    c.ckpts.append((state, c.out_seq, len(c.tape), c.frontier))


def _kill(w: World) -> None:
    """SIGKILL worker 1: its channels die, every cached copy it owned dies
    with it (consumer-side cache and producer-side async spill both lived
    in the killed process)."""
    w.killed = True
    for name, owner in WORKER_OF.items():
        if owner == 1:
            w.chans[name].alive = False
    w.cache = {k: v for k, v in w.cache.items() if v != 1}


def plan_rewinds_model(w: World, rule: str) -> Dict[str, int]:
    """The rewind planner over the dead set: returns channel -> chosen
    checkpoint index.  ``covering`` reproduces the old engine rule (tape-
    recorded needs only); ``frontier`` adds the live-phase frontier pass
    that engine.plan_rewinds ships."""
    dead = [n for n, c in w.chans.items() if not c.alive]
    choice = {n: len(w.chans[n].ckpts) - 1 for n in dead}  # latest first

    def rewind_to_cover(name: str, seq: int) -> bool:
        c = w.chans[name]
        if c.ckpts[choice[name]][1] <= seq:
            return False
        best = max((i for i, h in enumerate(c.ckpts) if h[1] <= seq),
                   default=0)
        if best == choice[name]:
            return False
        choice[name] = best
        return True

    changed = True
    while changed:
        changed = False
        for name in dead:
            c = w.chans[name]
            if name == SOURCE:
                continue
            _st, _out, tape_pos, frontier = c.ckpts[choice[name]]
            # walk the tape slice: recorded needs (the old rule's whole
            # coverage set), advancing the frontier as replay would
            for seq in c.tape[tape_pos:]:
                if UPSTREAM[name] in dead:
                    if rewind_to_cover(UPSTREAM[name], seq):
                        changed = True
                frontier = max(frontier, seq + 1)
            if rule == "frontier" and UPSTREAM[name] in dead:
                # the shipped fix: the LIVE phase after replay needs the
                # post-tape frontier seq too
                if rewind_to_cover(UPSTREAM[name], frontier):
                    changed = True
    return choice


def _recover(w: World, rule: str) -> None:
    choice = plan_rewinds_model(w, rule)
    for name, idx in choice.items():
        c = w.chans[name]
        state, out, tape_pos, frontier = c.ckpts[idx]
        c.out_seq = out
        c.frontier = frontier
        c.tape = c.tape[:tape_pos]
        c.ckpts = c.ckpts[:idx + 1]
        c.alive = True  # tape truncated to the checkpoint: no replay gap
    w.recovered = True


def apply(w: World, action: Action, rule: str) -> None:
    verb, name = action
    if verb == "produce":
        _produce(w, name)
    elif verb == "checkpoint":
        _checkpoint(w, name)
    elif verb == "kill":
        _kill(w)
    elif verb == "recover":
        _recover(w, rule)


@dataclass
class Result:
    wedged: bool
    trace: List[Action]
    detail: str


def _wedge_report(w: World) -> Optional[str]:
    """Quiescent-state analysis: an alive consumer whose needed seq exists
    nowhere and will never be produced again is the wedge."""
    for name, c in w.chans.items():
        if name == SOURCE or not c.alive:
            continue
        up_name = UPSTREAM[name]
        up = w.chans[up_name]
        need = c.frontier
        if c.out_seq >= MAX_SEQS and name == CONS:
            continue  # drained
        if (up_name, need) in w.cache:
            continue
        if up.alive and up.out_seq <= need:
            continue  # upstream will regenerate it
        if up_name == SOURCE and up.out_seq >= MAX_SEQS and \
                c.frontier >= MAX_SEQS:
            continue  # stream finished
        return (f"channel {name} blocked on seq {need} from {up_name}: "
                f"no cached copy survives and {up_name} restarts at "
                f"out_seq {up.out_seq} > {need} — the seq exists nowhere "
                "(the 'object nobody regenerates' wedge)")
    return None


def run_schedule(seed: Optional[int], rule: str,
                 trace: Optional[Sequence[Action]] = None,
                 max_steps: int = 200) -> Result:
    """Run one deterministic schedule: either RNG-driven by ``seed`` or
    replayed from an explicit ``trace`` (disabled actions are skipped, so
    ddmin subsets stay executable)."""
    w = World.fresh()
    rng = random.Random(seed)
    taken: List[Action] = []
    if trace is not None:
        for a in trace:
            if a in enabled(w):
                apply(w, a, rule)
                taken.append(a)
    else:
        for _ in range(max_steps):
            acts = enabled(w)
            if not acts:
                break
            a = acts[rng.randrange(len(acts))]
            apply(w, a, rule)
            taken.append(a)
            if w.recovered and _drained(w):
                break
    # drain deterministically so "kill early, recover, finish" completes:
    # after the scheduled prefix, give every channel a fair chance
    for _ in range(max_steps):
        if not w.killed or not w.recovered:
            break
        acts = [a for a in enabled(w) if a[0] == "produce"]
        if not acts or _drained(w):
            break
        apply(w, acts[0], rule)
    report = _wedge_report(w) if (w.killed and w.recovered) else None
    return Result(report is not None, taken, report or "completed")


def _drained(w: World) -> bool:
    return all(c.out_seq >= MAX_SEQS for c in w.chans.values())


def explore(rule: str, seeds: int = 300) -> List[Tuple[int, Result]]:
    """Every seed is an interleaving; return the wedged ones."""
    wedges = []
    for seed in range(seeds):
        r = run_schedule(seed, rule)
        if r.wedged:
            wedges.append((seed, r))
    return wedges


def minimize(trace: Sequence[Action], rule: str) -> List[Action]:
    """ddmin to a 1-minimal wedging schedule: removing any single action
    no longer wedges.  The loop itself is the shared analysis/shrink.py
    minimizer (the plan fuzzer uses the same one); replay tolerates
    arbitrary subsequences because run_schedule skips disabled actions."""
    from quokka_tpu.analysis.shrink import ddmin

    return ddmin(list(trace),
                 lambda cand: run_schedule(None, rule, trace=cand).wedged)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m quokka_tpu.analysis.schedex", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--rule", choices=("covering", "frontier"),
                   default=None,
                   help="planner rule (default: compare both)")
    p.add_argument("--seed", type=int, default=None,
                   help="replay one seed and print its trace")
    p.add_argument("--seeds", type=int, default=300,
                   help="seeds to explore (default 300)")
    p.add_argument("--minimize", action="store_true",
                   help="ddmin the first wedging schedule to 1-minimal")
    args = p.parse_args(argv)

    if args.seed is not None:
        rule = args.rule or "covering"
        r = run_schedule(args.seed, rule)
        print(f"seed {args.seed} rule={rule}: "
              f"{'WEDGED' if r.wedged else 'ok'}")
        for a in r.trace:
            print(f"  {a[0]} {a[1]}".rstrip())
        print(r.detail)
        return 1 if r.wedged else 0

    rules = [args.rule] if args.rule else ["covering", "frontier"]
    status = 0
    for rule in rules:
        wedges = explore(rule, args.seeds)
        print(f"rule={rule}: {len(wedges)}/{args.seeds} seeds wedge")
        if wedges and rule == "frontier":
            status = 1  # the shipped rule must never wedge
        if wedges and args.minimize:
            seed, r = wedges[0]
            mini = minimize(r.trace, rule)
            print(f"  minimal repro (from seed {seed}, "
                  f"{len(r.trace)} -> {len(mini)} actions):")
            for a in mini:
                print(f"    {a[0]} {a[1]}".rstrip())
            print(f"  {run_schedule(None, rule, trace=mini).detail}")
    return status


if __name__ == "__main__":
    sys.exit(main())
