"""Lint driver + baseline workflow.

    python -m quokka_tpu.analysis.lint quokka_tpu/          # gate (exit 1 on
                                                            # new findings)
    python -m quokka_tpu.analysis.lint path.py --no-baseline
    python -m quokka_tpu.analysis.lint quokka_tpu/ --write-baseline

Baseline discipline: ``baseline.json`` (next to this module) holds the
accepted findings of the shipped tree, each with a rationale.  The gate
fails on any finding NOT in the baseline — the baseline may only shrink.
Entries whose code was fixed show up as "stale"; ``--write-baseline``
rewrites the file from the current tree (preserving rationales of surviving
entries), which is also how you shrink it.  Growing it requires editing the
JSON by hand, with a rationale, in a reviewed diff — that is the point.

Keys are line-number-free (see ``rules.Finding.key``), so unrelated edits
do not churn the baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Sequence

from quokka_tpu.analysis.rules import Finding, run_rules

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

# generated/vendored trees never linted
_SKIP_DIRS = {"__pycache__", ".git", "build", "dist", "retired"}


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _relpath(path: str) -> str:
    """Stable baseline path: relative to the repo/package root when the file
    lives under a 'quokka_tpu' tree, else the basename-anchored path given."""
    norm = os.path.abspath(path).replace("\\", "/")
    marker = "/quokka_tpu/"
    i = norm.rfind(marker)
    if i >= 0:
        return "quokka_tpu/" + norm[i + len(marker):]
    return os.path.relpath(path).replace("\\", "/")


def run_lint(paths: Sequence[str]) -> List[Finding]:
    """Parse every file once, build the interprocedural flow context over
    the whole set (call graph, static-arg summaries, execution-surface
    reachability), then run the rules per file against it."""
    import ast

    from quokka_tpu.analysis.flow import FlowContext

    findings: List[Finding] = []
    parsed: List[tuple] = []
    ctx = FlowContext()
    for path in iter_py_files(paths):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        rel = _relpath(path)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            # a file the engine cannot even parse is its own finding
            findings.append(Finding(
                "QK000", "syntax-error", path, rel,
                e.lineno or 0, "<module>", f"syntax error: {e.msg}", ""))
            continue
        parsed.append((source, path, rel))
        ctx.add_module(rel, tree)
    ctx.finalize()
    for source, path, rel in parsed:
        findings.extend(run_rules(source, path, rel, ctx=ctx))
    return findings


def load_baseline(path: str) -> Dict[str, str]:
    """key -> rationale.  Missing file == empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("findings", {})
    if isinstance(entries, list):  # tolerate the bare-list form
        return {k: "" for k in entries}
    return dict(entries)


def write_baseline(path: str, findings: Sequence[Finding],
                   old: Dict[str, str],
                   reason: Optional[str] = None) -> int:
    """Rewrite the baseline; surviving entries keep their rationale, NEW
    entries take ``reason``.  Returns the number of new entries written —
    the caller refuses to grow the baseline without a real reason (the
    old auto-filled "TODO: rationale" placeholder let growth ship
    unreviewed; the tier-1 gate rejects TODO rationales)."""
    entries = {}
    grew = 0
    for f in sorted(findings, key=lambda f: (f.rel, f.line, f.rule)):
        rationale = old.get(f.key())
        if rationale is None:
            grew += 1
            rationale = reason or ""
        entries[f.key()] = rationale
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({
            "comment": (
                "Accepted lint findings of the shipped tree; the gate "
                "(tests/test_lint_clean.py) fails on findings NOT listed "
                "here.  This file may only shrink: fix the code and run "
                "`python -m quokka_tpu.analysis.lint quokka_tpu/ "
                "--write-baseline`.  Every entry carries a rationale."
            ),
            "findings": entries,
        }, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return grew


def main(argv: Sequence[str] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m quokka_tpu.analysis.lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="+", help="files or directories to lint")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON (default: the checked-in one)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding (fixture/dev mode)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from the current tree "
                        "(preserves rationales of surviving entries; "
                        "GROWING it requires --reason)")
    p.add_argument("--reason", default=None,
                   help="rationale recorded on every NEW baseline entry "
                        "(required when --write-baseline would grow the "
                        "baseline; >= 10 chars, the gate rejects TODOs)")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    findings = run_lint(args.paths)
    baseline = {} if args.no_baseline else load_baseline(args.baseline)

    if args.write_baseline:
        old = load_baseline(args.baseline)
        new_keys = [f for f in findings if f.key() not in old]
        if new_keys:
            reason = (args.reason or "").strip()
            if len(reason) < 10 or "TODO" in reason:
                plural = "y" if len(new_keys) == 1 else "ies"
                print(f"--write-baseline would ADD {len(new_keys)} "
                      f"entr{plural} — pass --reason \"<why this finding "
                      "is accepted>\" (>= 10 chars, no TODO placeholders)",
                      file=sys.stderr)
                for f in new_keys:
                    print(f"  would add: {f.key()}", file=sys.stderr)
                return 2
        grew = write_baseline(args.baseline, findings, old,
                              reason=args.reason)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}"
              + (f" ({grew} new, rationale: {args.reason!r})" if grew
                 else ""))
        return 0

    new = [f for f in findings if f.key() not in baseline]
    current_keys = {f.key() for f in findings}
    stale = sorted(k for k in baseline if k not in current_keys)

    if not args.quiet:
        for f in new:
            print(f.render())
        if stale:
            print(f"{len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed code — shrink "
                  "the baseline with --write-baseline):", file=sys.stderr)
            for k in stale:
                print(f"  {k}", file=sys.stderr)
    if new:
        print(f"{len(new)} new finding(s) "
              f"({len(findings) - len(new)} baselined)", file=sys.stderr)
        return 1
    if stale:
        # the gate fails on stale entries too (baseline may only shrink, and
        # it shrinks in the same PR that fixes the finding) — keeps this CLI
        # and tests/test_lint_clean.py answering identically
        print(f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}; run --write-baseline",
              file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"clean: 0 new findings ({len(findings)} baselined)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
