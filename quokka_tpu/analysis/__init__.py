"""Engine-invariant tooling: static analysis (lint) + runtime sanitizer.

Quokka-tpu's correctness and liveness story rests on invariants that were
previously argued by hand (SURVEY.md, the reference's proof.md) and that the
round-5 multi-process hang showed are violated silently when they slip:

- no module-level ``jax.jit``/``pjit``/``shard_map`` objects (a pjit hit from
  two dispatch contexts raced on the 1-core CPU backend),
- no import-time side effects beyond the deliberate ones in ``config.py``,
- no private JAX API (``jax._src``, ``jax.core.*``) use outside the
  version-guarded shim (``quokka_tpu.analysis.compat``),
- no host round-trips inside code reachable from jitted entry points
  ("Query Processing on Tensor Computation Runtimes": tensor-runtime engines
  live or die by keeping traced code free of host syncs and recompiles),
- shared runtime tables only mutated under their owning lock,
- no silently swallowed exceptions in runtime loops.

Enforcement layers:

- ``python -m quokka_tpu.analysis.lint quokka_tpu/`` — AST rules QK001-QK013
  and QK018-QK020 (``rules.py``) with a checked-in baseline
  (``baseline.json``) that may only shrink; the tier-1 gate is
  ``tests/test_lint_clean.py``.
- ``python -m quokka_tpu.analysis.protocol quokka_tpu/`` — interprocedural
  control-store protocol verifier (QK014-QK017, ``protocol.py``), no baseline.
- ``python -m quokka_tpu.analysis.planck`` — typed plan-invariant verifier
  (QK021-QK024, ``planck.py``): schema propagation, exchange-key coverage,
  fusion legality (incl. the fuse/unfuse involution, proven by digest) and
  streaming legality, checked per optimizer pass under ``QK_PLAN_VERIFY=1``.
- ``python -m quokka_tpu.analysis.planfuzz`` — seeded differential optimizer
  fuzzer (``planfuzz.py``): random logical plans executed bit-exact across
  pass prefixes, failures ddmin-shrunk (``shrink.py``) to 1-minimal repros.
- ``python -m quokka_tpu.analysis.schedex`` — deterministic-schedule race
  explorer (``schedex.py``) over the recovery protocol, seeded + shrinking.
- ``QK_SANITIZE=1`` — runtime sanitizer (``sanitize.py``): a deadlock
  watchdog that dumps every thread's stack and fails fast when a worker stops
  making progress, a lock-order recorder on the runtime's shared locks, and a
  recompile sentinel that fails a benchmarked run on post-warmup compiles.
"""

from quokka_tpu.analysis import compat, sanitize  # noqa: F401
