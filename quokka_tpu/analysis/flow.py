"""qkflow: interprocedural dataflow engine for the lint rules.

The name-heuristic rules (QK004/QK008/QK011) matched *names*: any function
whose bare name appeared in a call was "reachable", every parameter was a
potential tracer, every config mutation was a finding.  This module gives
them actual program structure to stand on:

- **module-resolved symbol tables**: per-module import aliases
  (``import quokka_tpu.config as qconfig``), from-imports
  (``from .engine import push``), classes/methods, and *scoped* function
  qualnames (``Engine.push``, ``_partition_fn.<locals>.part``) — nested
  defs no longer collide on bare names;
- **a call graph** over the analyzed file set: plain-name calls resolve
  through the local scope chain, then module functions, then from-imports;
  ``self.m()`` resolves to the enclosing class's method; ``alias.f()``
  resolves through the import table; class-name calls resolve to
  ``__init__``; unresolvable attribute calls fall back to a *same-module*
  name over-approximation (never wider than the old heuristic);
- **reachability summaries** from configurable entry sets (jit entries,
  the push path, the ``handle_*`` task-dispatch surface);
- **all-call-sites static-argument propagation**: a parameter is *static*
  when every call site in the file set passes a literal, trace-time
  metadata (``x.dtype``/``.shape``/``.ndim``/``.size``), or a value that
  is itself static — branching on it is trace-time control flow, not a
  tracer sync (fixpoint over (function, param));
- **an async-copy def-use helper**: ``np.asarray(x)`` preceded by
  ``x.copy_to_host_async()`` on the same local is an overlap pattern, not
  a blocking readback.

The context is built once per lint invocation over the whole file set;
single-file invocations (fixtures) get a one-module context, so rules
behave identically in both settings — just with less cross-module
knowledge.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["FlowContext", "FuncInfo", "module_name_of", "build_context"]

# attribute tails that read trace-time metadata, not tracer values
STATIC_METADATA_ATTRS = ("dtype", "shape", "ndim", "size")

# functions whose result is a trace-time constant when every argument is
# static (so `jnp.issubdtype(dtype, ...)` stays static when `dtype` is)
_STATIC_PRESERVING_CALLS = {
    "issubdtype", "isinstance", "len", "result_type", "canonicalize_dtype",
}


def module_name_of(rel: str) -> str:
    """Dotted module name for a lint-relative path: files under the
    ``quokka_tpu`` tree get their real package path (so cross-module
    imports resolve); loose files (fixtures) get their stem."""
    r = rel.replace("\\", "/")
    if r.endswith(".py"):
        r = r[:-3]
    if r.endswith("/__init__"):
        r = r[: -len("/__init__")]
    if r.startswith("quokka_tpu/") or r == "quokka_tpu":
        return r.replace("/", ".")
    return r.rsplit("/", 1)[-1]


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FuncInfo:
    """One function/method in the analyzed set."""

    __slots__ = ("fid", "module", "qualname", "name", "node", "cls",
                 "parent")

    def __init__(self, fid: str, module: str, qualname: str,
                 node: ast.AST, cls: Optional[str],
                 parent: Optional[str]):
        self.fid = fid              # "module:Qual.name" — globally unique
        self.module = module
        self.qualname = qualname    # "Engine.push", "f.<locals>.g"
        self.name = node.name       # bare name
        self.node = node
        self.cls = cls              # enclosing class qualname, if a method
        self.parent = parent        # fid of the enclosing function, if nested

    def params(self) -> Set[str]:
        a = self.node.args
        return {p.arg for p in a.args + a.kwonlyargs + a.posonlyargs
                if p.arg not in ("self", "cls")}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FuncInfo({self.fid})"


class _ModuleTable:
    __slots__ = ("name", "rel", "tree", "import_alias", "from_imports",
                 "functions", "by_name", "classes", "class_methods")

    def __init__(self, name: str, rel: str, tree: ast.Module):
        self.name = name
        self.rel = rel
        self.tree = tree
        # "qconfig" -> "quokka_tpu.config"
        self.import_alias: Dict[str, str] = {}
        # local name -> (source module, original name)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.functions: Dict[str, FuncInfo] = {}     # qualname -> info
        self.by_name: Dict[str, List[FuncInfo]] = {}  # bare name index
        self.classes: Dict[str, ast.ClassDef] = {}
        # class qualname -> {method bare name -> FuncInfo}
        self.class_methods: Dict[str, Dict[str, FuncInfo]] = {}


class FlowContext:
    """Symbol tables + call graph + reachability/static-arg summaries over
    one analyzed file set."""

    def __init__(self) -> None:
        self.modules: Dict[str, _ModuleTable] = {}
        self._rel_to_module: Dict[str, str] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self._by_node: Dict[int, FuncInfo] = {}
        self.calls: Dict[str, Set[str]] = {}
        # callee fid -> [(caller fid | None for module scope, Call node)]
        self.callsites: Dict[str, List[Tuple[Optional[str], ast.Call]]] = {}
        self._static_params: Optional[Dict[str, Set[str]]] = None

    # -- construction -------------------------------------------------------

    def add_module(self, rel: str, tree: ast.Module) -> None:
        name = module_name_of(rel)
        if name in self.modules:
            # two loose files with the same stem in one run (fixture dirs):
            # keep both, first owns the importable name
            name = f"{name}#{len(self.modules)}"
        mt = _ModuleTable(name, rel, tree)
        self.modules[name] = mt
        self._rel_to_module[rel] = name
        self._index_functions(mt)

    def finalize(self) -> None:
        """Resolve imports and the call graph after every module is added
        (`from pkg import submodule` vs `from pkg import name` is decided by
        whether the target module exists in the set, and cross-module call
        edges need the full symbol table)."""
        for mt in self.modules.values():
            self._index_imports(mt)
        for mt in self.modules.values():
            for fi in mt.functions.values():
                self.calls[fi.fid] = self._resolve_calls(mt, fi)
            self._resolve_module_scope_calls(mt)

    def _index_imports(self, mt: _ModuleTable) -> None:
        is_pkg = mt.rel.replace("\\", "/").endswith("__init__.py")
        parts = mt.name.split(".")
        for node in ast.walk(mt.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = (alias.name if alias.asname
                              else alias.name.split(".")[0])
                    mt.import_alias[local] = target
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # level 1 in a module = its package; in a package
                    # __init__ = the package itself; each extra level strips
                    # one more component
                    drop = node.level - (1 if is_pkg else 0)
                    pkg = ".".join(parts[: len(parts) - drop]) \
                        if drop < len(parts) else ""
                    src = f"{pkg}.{node.module}" if node.module and pkg \
                        else (node.module or pkg)
                else:
                    src = node.module or ""
                if not src:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    if f"{src}.{alias.name}" in self.modules or (
                            node.module is None):
                        # `from pkg import submodule` binds a MODULE name
                        mt.import_alias[local] = f"{src}.{alias.name}"
                    else:
                        mt.from_imports[local] = (src, alias.name)

    def _index_functions(self, mt: _ModuleTable) -> None:
        def visit(node: ast.AST, prefix: str, cls: Optional[str],
                  parent: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = prefix + child.name
                    fid = f"{mt.name}:{qual}"
                    fi = FuncInfo(fid, mt.name, qual, child, cls, parent)
                    mt.functions[qual] = fi
                    mt.by_name.setdefault(child.name, []).append(fi)
                    if cls is not None:
                        mt.class_methods.setdefault(cls, {})[child.name] = fi
                    self.funcs[fid] = fi
                    self._by_node[id(child)] = fi
                    visit(child, qual + ".<locals>.", None, fid)
                elif isinstance(child, ast.ClassDef):
                    cq = prefix + child.name
                    mt.classes[cq] = child
                    # nested classes keep the full qualname; methods of a
                    # class nested in a function belong to that function
                    visit(child, cq + ".", cq, parent)
                elif not isinstance(child, ast.Lambda):
                    visit(child, prefix, cls, parent)

        visit(mt.tree, "", None, None)

    # -- call resolution ----------------------------------------------------

    def _lookup_plain(self, mt: _ModuleTable, fi: Optional[FuncInfo],
                      name: str) -> List[FuncInfo]:
        """Scope-chain resolution of a bare name: enclosing functions'
        nested defs, then module functions, then from-imports, then
        classes (-> __init__)."""
        # nested defs visible on the lexical chain
        cur = fi
        while cur is not None:
            nested = mt.functions.get(cur.qualname + ".<locals>." + name)
            if nested is not None:
                return [nested]
            cur = self.funcs.get(cur.parent) if cur.parent else None
        top = mt.functions.get(name)
        if top is not None:
            return [top]
        if name in mt.from_imports:
            src_mod, orig = mt.from_imports[name]
            smt = self.modules.get(src_mod)
            if smt is not None:
                hit = smt.functions.get(orig)
                if hit is not None:
                    return [hit]
                init = smt.class_methods.get(orig, {}).get("__init__")
                if init is not None:
                    return [init]
            return []
        init = mt.class_methods.get(name, {}).get("__init__")
        if init is not None:
            return [init]
        return []

    def _lookup_dotted(self, mt: _ModuleTable, fi: Optional[FuncInfo],
                       d: str) -> List[FuncInfo]:
        base, _, tail = d.rpartition(".")
        if base in ("self", "cls") and fi is not None and fi.cls is not None:
            hit = mt.class_methods.get(fi.cls, {}).get(tail)
            if hit is not None:
                return [hit]
            # method not defined on this class in this file set (inherited):
            # over-approximate by same-module name match below
        if base in mt.import_alias:
            smt = self.modules.get(mt.import_alias[base])
            if smt is not None:
                hit = smt.functions.get(tail)
                if hit is not None:
                    return [hit]
                init = smt.class_methods.get(tail, {}).get("__init__")
                if init is not None:
                    return [init]
            return []  # call into a module we can't see: no edge
        if base in mt.from_imports:
            # Class imported by name: Class.method / instance conventions
            src_mod, orig = mt.from_imports[base]
            smt = self.modules.get(src_mod)
            if smt is not None:
                hit = smt.class_methods.get(orig, {}).get(tail)
                if hit is not None:
                    return [hit]
        if "." in base:
            # alias chain like pkg.mod.f with `import pkg.mod`
            root = base.split(".", 1)[0]
            if root in mt.import_alias:
                cand = mt.import_alias[root]
                full = base if base.startswith(cand) else base.replace(
                    root, cand, 1)
                smt = self.modules.get(full)
                if smt is not None:
                    hit = smt.functions.get(tail)
                    if hit is not None:
                        return [hit]
                return []
        # unknown receiver: SAME-MODULE name over-approximation (matches the
        # old heuristic's scope, so precision only ever removes edges)
        return list(mt.by_name.get(tail, []))

    def _call_targets(self, mt: _ModuleTable, fi: Optional[FuncInfo],
                      call: ast.Call) -> List[FuncInfo]:
        d = _dotted(call.func)
        if d is None:
            return []
        if "." not in d:
            return self._lookup_plain(mt, fi, d)
        return self._lookup_dotted(mt, fi, d)

    def _resolve_calls(self, mt: _ModuleTable, fi: FuncInfo) -> Set[str]:
        out: Set[str] = set()
        referenced: Set[str] = set()
        for node in self._own_nodes(fi.node):
            if isinstance(node, ast.Call):
                for tgt in self._call_targets(mt, fi, node):
                    out.add(tgt.fid)
                    self.callsites.setdefault(tgt.fid, []).append(
                        (fi.fid, node))
                # function references passed as arguments run as callbacks
                for a in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(a, ast.Name):
                        referenced.add(a.id)
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                referenced.add(node.id)
        # a nested def whose name is referenced (returned, stored, passed)
        # escapes into the caller's dynamic extent — count the edge
        for name in referenced:
            for tgt in self._lookup_plain(mt, fi, name):
                out.add(tgt.fid)
        return out

    def _resolve_module_scope_calls(self, mt: _ModuleTable) -> None:
        """Call sites at module/class scope still count for static-argument
        propagation (a module-level `f(CONST)` is a static call site)."""
        for node in self._own_nodes(mt.tree):
            if isinstance(node, ast.Call):
                for tgt in self._call_targets(mt, None, node):
                    self.callsites.setdefault(tgt.fid, []).append(
                        (None, node))

    @staticmethod
    def _own_nodes(root: ast.AST) -> Iterable[ast.AST]:
        """Walk root WITHOUT descending into nested function bodies (their
        calls belong to the nested function's own summary)."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    # -- queries ------------------------------------------------------------

    def function_of_node(self, node: ast.AST) -> Optional[FuncInfo]:
        return self._by_node.get(id(node))

    def module_table(self, rel: str) -> Optional[_ModuleTable]:
        name = self._rel_to_module.get(rel, module_name_of(rel))
        return self.modules.get(name)

    def reachable(self, seeds: Iterable[str]) -> Set[str]:
        """Transitive closure over the call graph from seed fids."""
        seen: Set[str] = set()
        frontier = [s for s in seeds if s in self.funcs]
        while frontier:
            fid = frontier.pop()
            if fid in seen:
                continue
            seen.add(fid)
            frontier.extend(self.calls.get(fid, ()) - seen)
        return seen

    def funcs_named(self, pred) -> List[FuncInfo]:
        """All functions whose BARE name satisfies pred (callable or a
        collection of names)."""
        if not callable(pred):
            names = set(pred)
            pred = names.__contains__
        return [fi for fi in self.funcs.values() if pred(fi.name)]

    # -- static-argument propagation ----------------------------------------

    def static_params(self, fid: str) -> Set[str]:
        """Parameters of `fid` that are static at EVERY call site in the
        analyzed set (constants, trace-time metadata, or values that are
        themselves static parameters of the caller).  A function with no
        visible call sites has NO static params (conservative: it may be
        an entry point taking tracers)."""
        if self._static_params is None:
            self._static_params = self._compute_static_params()
        return self._static_params.get(fid, set())

    def _compute_static_params(self) -> Dict[str, Set[str]]:
        # optimistically assume every called-with-args param static, then
        # strike params until fixpoint (a param fed by a non-static arg, or
        # by a static-param-dependent arg whose source gets struck, falls)
        state: Dict[str, Set[str]] = {}
        sigs: Dict[str, Tuple[List[str], Dict[str, int]]] = {}
        for fid, fi in self.funcs.items():
            a = fi.node.args
            pos = [p.arg for p in a.posonlyargs + a.args]
            if pos and pos[0] in ("self", "cls"):
                pos = pos[1:]
            sigs[fid] = (pos, {p: i for i, p in enumerate(pos)})
            sites = self.callsites.get(fid, [])
            state[fid] = set(fi.params()) if sites else set()

        def arg_static(expr: ast.AST, caller: Optional[str]) -> bool:
            if isinstance(expr, ast.Constant):
                return True
            if isinstance(expr, ast.UnaryOp):
                return arg_static(expr.operand, caller)
            if (isinstance(expr, ast.Attribute)
                    and expr.attr in STATIC_METADATA_ATTRS):
                return True
            if isinstance(expr, ast.Name):
                if caller is not None and expr.id in state.get(caller, ()):
                    return True
                return False
            if isinstance(expr, ast.Call):
                d = _dotted(expr.func)
                tail = d.rsplit(".", 1)[-1] if d else ""
                return (tail in _STATIC_PRESERVING_CALLS
                        and all(arg_static(a, caller) for a in expr.args))
            return False

        changed = True
        while changed:
            changed = False
            for fid, fi in self.funcs.items():
                cur = state[fid]
                if not cur:
                    continue
                pos, idx = sigs[fid]
                keep = set(cur)
                for caller, call in self.callsites.get(fid, []):
                    if any(isinstance(a, ast.Starred) for a in call.args) \
                            or any(k.arg is None for k in call.keywords):
                        keep.clear()  # *args/**kwargs: every param tainted
                        break
                    bound_pos = min(len(call.args), len(pos))
                    for i in range(bound_pos):
                        p = pos[i]
                        if p in keep and not arg_static(call.args[i], caller):
                            keep.discard(p)
                    for kw in call.keywords:
                        if kw.arg in keep and not arg_static(kw.value, caller):
                            keep.discard(kw.arg)
                if keep != cur:
                    state[fid] = keep
                    changed = True
        return state

    # -- def-use helpers -----------------------------------------------------

    @staticmethod
    def async_copy_started(fn_node: ast.AST, name: str, line: int) -> bool:
        """True when `name.copy_to_host_async()` is called in `fn_node`
        strictly before `line` — the d2h transfer of `name` was already
        dispatched, so a later host materialization overlaps device work
        instead of draining the pipeline."""
        for node in ast.walk(fn_node):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "copy_to_host_async"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == name
                    and getattr(node, "lineno", line) < line):
                return True
        return False


def build_context(files: Sequence[Tuple[str, ast.Module]]) -> FlowContext:
    """files: (lint-relative path, parsed tree) pairs."""
    ctx = FlowContext()
    for rel, tree in files:
        ctx.add_module(rel, tree)
    ctx.finalize()
    return ctx
