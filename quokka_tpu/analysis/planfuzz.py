"""Differential optimizer fuzzer (planfuzz): seeded random logical plans,
planned under the full pass pipeline vs every cumulative pass prefix vs
``QK_STAGE_FUSE=0``, with each variant both statically verified (planck
QK021-QK024) and executed on tiny in-memory data by a reference
interpreter — results must match the unoptimized plan bit-exactly (all
fuzz data is int64, so sums/mins/maxes are order-independent and avg is
an exact ratio of exact ints).

Any failing seed is shrunk with ddmin (analysis/shrink.py) to a
1-minimal op list: removing ANY single op from the repro makes the
failure disappear.  The generator builds plans by folding an op list
over a DataStream, *skipping inapplicable ops* (a join whose key was
projected away, an agg with no value column), so every ddmin
subsequence still builds — the property ddmin's chunk removal needs.

Known-bug injection (``BREAKERS``) wires a deliberately wrong rewrite
into the pipeline so tests can prove the harness actually catches
optimizer bugs end-to-end, differentially and statically:

- ``drop-filter``     splices a FilterNode out of the plan (statically
                      clean — only the differential run catches it)
- ``phantom-column``  appends a column the node never computes (QK021)
- ``claim-order``     marks a filter sorted over an unordered input (QK024)

CLI::

    python -m quokka_tpu.analysis.planfuzz --seeds 200
    python -m quokka_tpu.analysis.planfuzz --seed 7 --breaker drop-filter
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import random
import time
from typing import Callable, List, Optional, Sequence, Tuple

from quokka_tpu import logical, optimizer
from quokka_tpu.analysis import planck
from quokka_tpu.analysis.shrink import ddmin
from quokka_tpu.expression import (
    Alias,
    BinOp,
    ColRef,
    Expr,
    Func,
    Literal,
    UnaryOp,
    col,
)

# ---------------------------------------------------------------------------
# deterministic tiny tables (int64 only: exact, order-independent arithmetic)
# ---------------------------------------------------------------------------

_TABLES = None


def _tables():
    global _TABLES
    if _TABLES is None:
        import numpy as np
        import pyarrow as pa

        r = np.random.default_rng(0)
        n = 40
        fact = pa.table({
            "r": np.arange(n, dtype=np.int64),  # unique: deterministic top-k
            "k": r.integers(0, 6, n).astype(np.int64),
            "j": r.integers(0, 4, n).astype(np.int64),
            "x": r.integers(0, 100, n).astype(np.int64),
            "v": r.integers(0, 1000, n).astype(np.int64),
        })
        dim = pa.table({  # k=5 missing: inner joins genuinely drop rows
            "k": np.arange(5, dtype=np.int64),
            "w": r.integers(0, 10, 5).astype(np.int64),
        })
        dim2 = pa.table({
            "j": np.arange(4, dtype=np.int64),
            "z": r.integers(0, 10, 4).astype(np.int64),
        })
        _TABLES = (fact, dim, dim2)
    return _TABLES


# ---------------------------------------------------------------------------
# op-list grammar
# ---------------------------------------------------------------------------

_OP_KINDS = ("filter", "project", "with_columns", "join_k", "join_j",
             "agg", "distinct", "sort", "topk")


def gen_ops(seed: int) -> List[Tuple[str, int, int]]:
    """Deterministic op list for a seed: (kind, a, b) triples whose params
    are resolved against whatever columns exist when the op applies."""
    rng = random.Random(seed)
    n = rng.randint(3, 8)
    return [(rng.choice(_OP_KINDS), rng.randrange(1 << 16), rng.randrange(1 << 16))
            for _ in range(n)]


def build(qc, ops: Sequence[Tuple[str, int, int]]):
    """Fold the op list over a DataStream, skipping inapplicable ops so any
    subsequence (ddmin!) still builds.  Returns the final DataStream."""
    fact, dim, dim2 = _tables()
    ds = qc.from_arrow(fact)
    joined = set()
    uniq = 0  # with_columns name counter: unique within one build
    for kind, a, b in ops:
        cols = list(ds.schema)
        if kind == "filter":
            c = cols[a % len(cols)]
            ds = ds.filter(col(c) > (b % 50))
        elif kind == "project":
            keep = [c for i, c in enumerate(cols) if (a >> (i % 16)) & 1]
            if not keep:
                keep = [cols[a % len(cols)]]
            ds = ds.select(keep)
        elif kind == "with_columns":
            c1 = cols[a % len(cols)]
            c2 = cols[b % len(cols)]
            ds = ds.with_columns({f"e{uniq}": col(c1) * 2 + col(c2)})
            uniq += 1
        elif kind == "join_k":
            if "k" in cols and "join_k" not in joined:
                ds = ds.join(qc.from_arrow(dim), on="k")
                joined.add("join_k")
        elif kind == "join_j":
            if "j" in cols and "join_j" not in joined:
                ds = ds.join(qc.from_arrow(dim2), on="j")
                joined.add("join_j")
        elif kind == "agg":
            keys = [c for c in ("k", "j", "w", "z") if c in cols]
            if not keys:
                continue
            key = keys[a % len(keys)]
            vals = [c for c in cols if c != key]
            if not vals:
                continue
            val = vals[b % len(vals)]
            fn = ("sum", "min", "max", "avg", "count")[(a + b) % 5]
            ds = ds.groupby(key).agg_sql(
                f"{fn}({val}) as a{uniq}, count(*) as n{uniq}")
            uniq += 1
        elif kind == "distinct":
            ds = ds.distinct([cols[a % len(cols)]])
        elif kind == "sort":
            ds = ds.sort(cols[a % len(cols)])
        elif kind == "topk":
            if "r" in cols:  # unique column: tie-free, deterministic
                ds = ds.top_k("r", 5, descending=[bool(a % 2)])
    return ds


# ---------------------------------------------------------------------------
# reference interpreter: pandas semantics of the LOGICAL plan
# ---------------------------------------------------------------------------


def _eval(e: Expr, df):
    import numpy as np

    if isinstance(e, Alias):
        return _eval(e.expr, df)
    if isinstance(e, ColRef):
        return df[e.name]
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, BinOp):
        l, r = _eval(e.left, df), _eval(e.right, df)
        if e.op == "+":
            return l + r
        if e.op == "-":
            return l - r
        if e.op == "*":
            return l * r
        if e.op == "/":
            return l / r
        if e.op == "//":
            return l // r
        if e.op == "%":
            return l % r
        if e.op == "=":
            return l == r
        if e.op == "!=":
            return l != r
        if e.op == "<":
            return l < r
        if e.op == "<=":
            return l <= r
        if e.op == ">":
            return l > r
        if e.op == ">=":
            return l >= r
        if e.op == "and":
            return l & r
        if e.op == "or":
            return l | r
        raise NotImplementedError(f"planfuzz interp: binop {e.op}")
    if isinstance(e, UnaryOp):
        v = _eval(e.operand, df)
        if e.op == "not":
            return ~v
        if e.op == "-":
            return -v
        raise NotImplementedError(f"planfuzz interp: unaryop {e.op}")
    if isinstance(e, Func):
        if e.name in ("__nn0", "__nnhigh", "__nnlow"):
            return _eval(e.args[0], df)  # null-identity wrappers: int data
        if e.name == "__nncount":
            a = _eval(e.args[0], df)
            return a.notna().astype("int64")
        if e.name == "sqrt":
            return np.sqrt(_eval(e.args[0], df))
        raise NotImplementedError(f"planfuzz interp: func {e.name}")
    raise NotImplementedError(f"planfuzz interp: {type(e).__name__}")


def _interp_node(node: logical.Node, inputs):
    import pandas as pd

    if isinstance(node, logical.SourceNode):
        df = node.reader.table.to_pandas()
        if node.predicate is not None:
            df = df[_eval(node.predicate, df).astype(bool)]
        if node.projection is not None:
            df = df[list(node.projection)]
        return df[list(node.schema)]
    if isinstance(node, logical.FusedStageNode):
        builds = iter(inputs[1:])
        cur = inputs[0]
        for m in node.members:
            if isinstance(m, logical.JoinNode):
                cur = _interp_node(m, [cur, next(builds)])
            else:
                cur = _interp_node(m, [cur])
        return cur[list(node.schema)]
    if isinstance(node, logical.FilterNode):
        df = inputs[0]
        return df[_eval(node.predicate, df).astype(bool)][list(node.schema)]
    if isinstance(node, logical.ProjectionNode):
        return inputs[0][list(node.schema)]
    if isinstance(node, logical.MapNode):
        df = inputs[0].copy()
        if node.exprs is not None:
            for k, e in node.exprs.items():
                df[k] = _eval(e, df)
            return df[list(node.schema)]
        if node.rename is not None:
            return df.rename(columns=node.rename)[list(node.schema)]
        raise NotImplementedError("planfuzz interp: opaque MapNode")
    if isinstance(node, logical.JoinNode):
        if node.how != "inner":
            raise NotImplementedError(f"planfuzz interp: {node.how} join")
        left, right = inputs
        rename = node.rename or {}
        payload = [c for c in right.columns if c not in set(node.right_on)]
        r2 = right[list(node.right_on) + payload].copy()
        r2.columns = list(node.left_on) + [rename.get(c, c) for c in payload]
        out = left.merge(r2, on=list(node.left_on), how="inner")
        return out[list(node.schema)]
    if isinstance(node, logical.AggNode):
        if node.having is not None or node.order_by or node.limit is not None:
            raise NotImplementedError("planfuzz interp: having/order/limit agg")
        df = inputs[0].copy()
        plan = node.plan
        for tmp, e in plan.pre:
            df[tmp] = _eval(e, df)
        keys = list(node.keys)
        parts = {}
        if keys:
            g = df.groupby(keys, sort=True)
            for pname, op, tmp in plan.partials:
                if op == "count":
                    parts[pname] = g.size()
                elif op == "sum":
                    parts[pname] = g[tmp].sum()
                elif op == "min":
                    parts[pname] = g[tmp].min()
                elif op == "max":
                    parts[pname] = g[tmp].max()
                else:
                    raise NotImplementedError(f"planfuzz interp: partial {op}")
            pdf = pd.DataFrame(parts).reset_index()
        else:
            for pname, op, tmp in plan.partials:
                if op == "count":
                    parts[pname] = len(df)
                elif op == "sum":
                    parts[pname] = df[tmp].sum()
                elif op == "min":
                    parts[pname] = df[tmp].min()
                elif op == "max":
                    parts[pname] = df[tmp].max()
                else:
                    raise NotImplementedError(f"planfuzz interp: partial {op}")
            pdf = pd.DataFrame({k: [v] for k, v in parts.items()})
        for out_name, e in plan.finals:
            pdf[out_name] = _eval(e, pdf)
        return pdf[list(node.schema)]
    if isinstance(node, logical.DistinctNode):
        return inputs[0][list(node.keys)].drop_duplicates()[list(node.schema)]
    if isinstance(node, logical.SortNode):
        asc = [not d for d in (node.descending or [False] * len(node.by))]
        return inputs[0].sort_values(list(node.by), ascending=asc)[
            list(node.schema)]
    if isinstance(node, logical.TopKNode):
        asc = [not d for d in (node.descending or [False] * len(node.by))]
        return inputs[0].sort_values(list(node.by), ascending=asc).head(
            node.k)[list(node.schema)]
    if isinstance(node, logical.SinkNode):
        return inputs[0][list(node.schema)]
    raise NotImplementedError(f"planfuzz interp: {type(node).__name__}")


def interpret(sub, sink_id):
    """Execute the logical plan bottom-up on pandas frames."""
    done = {}
    for nid in optimizer._reachable(sub, sink_id):
        node = sub[nid]
        done[nid] = _interp_node(node, [done[p] for p in node.parents])
    return done[sink_id]


def canon(df):
    """Order-free, dtype-normalized form for bit-exact comparison."""
    import pandas as pd

    df = df.copy()
    cols = sorted(df.columns)
    df = df[cols]
    for c in cols:
        if pd.api.types.is_integer_dtype(df[c]):
            df[c] = df[c].astype("int64")
        elif pd.api.types.is_float_dtype(df[c]):
            df[c] = df[c].astype("float64")
    return df.sort_values(cols, kind="mergesort").reset_index(drop=True)


# ---------------------------------------------------------------------------
# known-bug injection
# ---------------------------------------------------------------------------


def _break_drop_filter(sub, sink_id):
    """Splice the first FilterNode out of the plan — schemas stay valid
    (statically clean); only differential execution notices missing rows."""
    for nid in optimizer._reachable(sub, sink_id):
        node = sub[nid]
        if isinstance(node, logical.FilterNode):
            pid = node.parents[0]
            for other in sub.values():
                other.parents = [pid if p == nid else p for p in other.parents]
            del sub[nid]
            return


def _break_phantom_column(sub, sink_id):
    """Append a column a node never computes (QK021 schema propagation)."""
    for nid in optimizer._reachable(sub, sink_id):
        node = sub[nid]
        if isinstance(node, (logical.FilterNode, logical.JoinNode)):
            node.schema = list(node.schema) + ["__phantom"]
            return


def _break_claim_order(sub, sink_id):
    """Mark a filter as sorted over an unordered input (QK024)."""
    for nid in optimizer._reachable(sub, sink_id):
        node = sub[nid]
        if isinstance(node, logical.FilterNode) and \
                sub[node.parents[0]].sorted_by is None:
            node.sorted_by = [node.schema[0]]
            return


# breaker name -> (inject after this pass, rewrite)
BREAKERS = {
    "drop-filter": ("push_filters", _break_drop_filter),
    "phantom-column": ("early_projection", _break_phantom_column),
    "claim-order": ("push_filters", _break_claim_order),
}


# ---------------------------------------------------------------------------
# variant runner
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FuzzResult:
    seed: int
    ok: bool
    kind: Optional[str] = None      # "static" | "diff" | "error"
    variant: Optional[str] = None
    detail: str = ""
    ops: Optional[List[tuple]] = None
    shrunk: Optional[List[tuple]] = None

    def summary(self) -> str:
        if self.ok:
            return f"seed {self.seed}: ok"
        s = (f"seed {self.seed}: {self.kind} failure in variant "
             f"{self.variant}: {self.detail}")
        if self.shrunk is not None:
            s += f"\n  1-minimal repro ({len(self.shrunk)} ops): {self.shrunk}"
        return s


def _plan(ops, breaker=None, upto: Optional[int] = None):
    """Build ops into a plan and run the first `upto` optimizer passes
    (None = all), injecting `breaker` after its target pass."""
    from quokka_tpu.context import QuokkaContext

    qc = QuokkaContext(optimize=False)
    ds = build(qc, ops)
    sub, sink_id = qc._prepare_plan(ds.node_id)
    pipeline = optimizer.pass_pipeline(exec_channels=qc.exec_channels)
    for name, fn in pipeline[:len(pipeline) if upto is None else upto]:
        fn(sub, sink_id)
        if breaker is not None and breaker[0] == name:
            breaker[1](sub, sink_id)
    return sub, sink_id


def check_ops(ops, breaker=None, static_only=False) -> Optional[Tuple[str, str, str]]:
    """Run every variant of the op list; return (kind, variant, detail) for
    the first failure, None when all variants agree and verify clean."""
    names = [n for n, _ in optimizer.pass_pipeline()]
    variants = [("v0", 0, None)]
    variants += [(f"prefix:{names[i - 1]}", i, None)
                 for i in range(1, len(names) + 1)]
    variants += [("nofuse", len(names), "0")]

    reference = None
    for vname, upto, fuse_env in variants:
        old_fuse = os.environ.get("QK_STAGE_FUSE")
        if fuse_env is not None:
            os.environ["QK_STAGE_FUSE"] = fuse_env
        try:
            sub, sink_id = _plan(ops, breaker=breaker, upto=upto)
        finally:
            if fuse_env is not None:
                if old_fuse is None:
                    os.environ.pop("QK_STAGE_FUSE", None)
                else:
                    os.environ["QK_STAGE_FUSE"] = old_fuse
        try:
            planck.verify_plan(sub, sink_id, where=f"fuzz:{vname}")
        except planck.PlanInvariantError as e:
            return ("static", vname, str(e))
        if static_only:
            continue
        try:
            got = canon(interpret(sub, sink_id))
        except Exception as e:  # interp gap or genuinely broken plan
            return ("error", vname, f"{type(e).__name__}: {e}")
        if reference is None:
            reference = got
        elif not reference.equals(got):
            return ("diff", vname,
                    f"result mismatch vs v0 "
                    f"({len(got)} rows vs {len(reference)} rows, "
                    f"cols {list(got.columns)})")
    return None


def run_seed(seed: int, breaker=None, static_only=False,
             shrink: bool = True) -> FuzzResult:
    if isinstance(breaker, str):
        breaker = BREAKERS[breaker]
    ops = gen_ops(seed)
    failure = check_ops(ops, breaker=breaker, static_only=static_only)
    if failure is None:
        return FuzzResult(seed=seed, ok=True, ops=ops)
    kind, variant, detail = failure
    shrunk = None
    if shrink:
        shrunk = ddmin(ops, lambda cand: check_ops(
            list(cand), breaker=breaker, static_only=static_only) is not None)
    return FuzzResult(seed=seed, ok=False, kind=kind, variant=variant,
                      detail=detail, ops=ops, shrunk=shrunk)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m quokka_tpu.analysis.planfuzz",
        description="differential optimizer fuzzer: random plans, full "
                    "pipeline vs pass prefixes vs QK_STAGE_FUSE=0, verified "
                    "statically (planck) and executed on tiny data")
    p.add_argument("--seeds", type=int, default=200,
                   help="number of seeds to run (0..N-1)")
    p.add_argument("--seed", type=int, default=None,
                   help="run exactly one seed")
    p.add_argument("--breaker", choices=sorted(BREAKERS), default=None,
                   help="inject a known optimizer bug (harness self-test)")
    p.add_argument("--static-only", action="store_true")
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    seeds = [args.seed] if args.seed is not None else list(range(args.seeds))
    failures = 0
    for seed in seeds:
        r = run_seed(seed, breaker=args.breaker,
                     static_only=args.static_only)
        if not r.ok:
            failures += 1
            print(r.summary())
    dt = time.perf_counter() - t0
    print(f"planfuzz: {len(seeds) - failures}/{len(seeds)} seeds clean "
          f"in {dt:.1f}s"
          + (f" (breaker={args.breaker})" if args.breaker else ""))
    if args.breaker and failures == 0:
        print("planfuzz: breaker injected but NO seed caught it — harness gap")
        return 1
    return 1 if (failures and not args.breaker) else 0


if __name__ == "__main__":  # pragma: no cover
    from quokka_tpu.analysis import planfuzz as _canonical

    raise SystemExit(_canonical.main())
