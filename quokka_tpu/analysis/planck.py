"""Plan-invariant verifier (planck): typed invariants over the logical DAG.

Every optimizer pass is a hand-written in-place rewrite of the plan's node
dict, and until now nothing checked that a pass preserved anything: stale
interior schemas were silently tolerated by defensive executors,
``unfuse_stages`` was *trusted* to invert ``fuse_stages``, and exchange
edges were trusted to partition on columns the producer actually emits.
The next roadmap items (fusion through the exchange, adaptive re-planning
mid-query) rewrite plans far more aggressively — this module is the
correctness net they run inside, the same way the protocol verifier
(QK014-QK017) was built before streaming GC leaned on it.

Zero-baseline rules (no suppression file — a violation fails tier-1):

- **QK021 schema propagation** — every node's output schema must be EXACTLY
  derivable from its parents' schemas plus its own metadata
  (``Node.derive_schema``), including through every ``FusedStageNode``
  member; derived schemas must be non-empty and duplicate-free, and a
  source's pushed predicate may reference only columns the source reads.
- **QK022 exchange-key coverage** — every exchange edge's partition
  function references only columns its producer emits: hash-join key lists
  align positionally and exist on both inputs, stateful-operator
  partitioners name live columns of the right parent, a range-partitioned
  sort's boundaries match its channel fan-out.
- **QK023 fusion legality** — fused chains contain only fusible,
  placement-free, unordered members; interior joins are broadcast; an agg
  terminates the chain; absorbed member ids are gone from the plan and
  referenced by nobody else; and ``unfuse_stages(fuse_stages(p))`` is
  structurally identical to ``p`` — VERIFIED against a pre-pass digest
  (or by re-fusing the unfused plan when no 'before' exists), not trusted.
- **QK024 streaming legality** — order metadata stays monotone-safe: a
  node's ``sorted_by`` columns exist in its schema, order-inheriting verbs
  (filter/projection/map) only claim order their input has, time-series
  operators (asof join, window agg, shift) sit on inputs ordered by their
  time key, an UNBOUNDED source keeps the single-channel streaming
  discipline, and no checkpoint-barrier member hides inside a fused stage
  (a fused stage checkpoints as ONE unit).
- **QK025 resume-fingerprint restart-stability** — the structural
  fingerprint ``runtime/resume.py`` verifies at batch resume must be
  IDENTICAL when the same prepared plan is pickled (the manifest's plan
  payload) and re-lowered into a fresh context and control store — the
  exact round trip ``QueryService.recover_orphans`` performs after a crash
  — and its preimage must be free of object addresses and size-dependent
  buckets (a source file may grow between restarts).  Checked over live
  lowerings in the CLI corpus run, not statically.
- **QK026 adaptive-exchange legality** — ``adapt_salt`` (the mark that lets
  the runtime re-partition a skewed build exchange mid-query,
  planner/decide.py) sits only where the salt+replicate rewrite provably
  keeps every inner match exactly-once: INNER hash joins, non-broadcast,
  no claimed output order; and the reserved runtime salt column never
  appears in any node's schema.

Pass-level instrumentation lives in ``optimizer.optimize``: under
``QK_PLAN_VERIFY=1`` (default-on in tests and bench.py) every pass's
(before, after) plan pair is verified and a violation raises
``PlanInvariantError`` naming the pass and the offending node.  All checks
run at PLAN time — never on the push path.

CLI::

    python -m quokka_tpu.analysis.planck            # corpus of query shapes
    python -m quokka_tpu.analysis.planck --seeds 50 # + fuzzer-generated plans
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from quokka_tpu import logical
from quokka_tpu.optimizer import _reachable, fuse_stages, unfuse_stages
from quokka_tpu.target_info import (
    HashPartitioner,
    RangePartitioner,
)

RULES = {
    "QK021": "schema propagation: declared output schema == derived schema",
    "QK022": "exchange-key coverage: partition keys exist on the producer",
    "QK023": "fusion legality: fusible members + exact unfuse round-trip",
    "QK024": "streaming legality: monotone order metadata, 1-channel "
             "unbounded sources, no checkpoint barrier inside a stage",
    "QK025": "resume-fingerprint restart-stability: a durable batch "
             "plan's structural fingerprint survives pickle + fresh-"
             "process re-lowering, address- and size-hint-free",
    "QK026": "adaptive-exchange legality: adapt_salt only on inner "
             "non-broadcast unordered joins; salt column reserved",
}

# plan-time verification cost, surfaced per-query in bench.py detail
# (acceptance: <= 5 ms per query at plan time)
VERIFY_STATS = {"plans": 0, "checks": 0, "ms_total": 0.0, "ms_last_plan": 0.0}
_CUR_MS = [0.0]


def enabled() -> bool:
    """QK_PLAN_VERIFY gate, read dynamically (config.py env-knob idiom)."""
    return os.environ.get("QK_PLAN_VERIFY", "0") not in ("0", "false", "no", "")


@dataclasses.dataclass
class PlanViolation:
    rule: str
    node_id: int
    node: str          # node.describe() of the offender
    message: str

    def render(self) -> str:
        return f"{self.rule} node {self.node_id} [{self.node}]: {self.message}"


class PlanInvariantError(AssertionError):
    """An optimizer pass (or a hand-built plan) broke a plan invariant."""

    def __init__(self, where: str, violations: Sequence[PlanViolation]):
        self.where = where
        self.violations = list(violations)
        lines = "\n  ".join(v.render() for v in self.violations)
        super().__init__(f"plan invariants violated after {where}:\n  {lines}")


# ---------------------------------------------------------------------------
# structural digest
# ---------------------------------------------------------------------------


def _node_sig(node: logical.Node) -> tuple:
    sig = (
        type(node).__name__,
        tuple(node.parents),
        tuple(node.schema),
        node.describe(),
        node.channels,
        tuple(node.sorted_by or ()),
        tuple(getattr(node, "boundaries", None) or ()),
        tuple(sorted((getattr(node, "rename", None) or {}).items())),
        bool(getattr(node, "folded", False)),
        bool(getattr(node, "adapt_salt", False)),
    )
    if isinstance(node, logical.FusedStageNode):
        sig += (tuple(_node_sig(m) for m in node.members),)
    return sig


def digest(sub: Dict[int, logical.Node], sink_id: int) -> tuple:
    """Structural identity of the reachable plan: node ids, types, links,
    schemas, and per-type metadata.  Two plans with equal digests lower to
    identical actor graphs; the QK023 round-trip check compares these."""
    t0 = time.perf_counter()
    out = tuple(
        (nid, _node_sig(sub[nid])) for nid in sorted(_reachable(sub, sink_id))
    )
    _account(time.perf_counter() - t0)
    return out


def _account(seconds: float) -> None:
    ms = seconds * 1e3
    VERIFY_STATS["ms_total"] += ms
    VERIFY_STATS["checks"] += 1
    _CUR_MS[0] += ms


def finish_plan() -> None:
    """Roll per-pass accounting into per-plan stats (called by optimize)."""
    VERIFY_STATS["plans"] += 1
    VERIFY_STATS["ms_last_plan"] = _CUR_MS[0]
    _CUR_MS[0] = 0.0


# ---------------------------------------------------------------------------
# rule checks
# ---------------------------------------------------------------------------


def collect(sub: Dict[int, logical.Node], sink_id: int) -> List[PlanViolation]:
    """Run QK021-QK024 + QK026 over the reachable plan; return all
    violations."""
    out: List[PlanViolation] = []
    order = _reachable(sub, sink_id)
    consumers: Dict[int, List[int]] = {nid: [] for nid in order}
    for nid in order:
        for p in sub[nid].parents:
            consumers.setdefault(p, []).append(nid)
    for nid in order:
        node = sub[nid]
        parents = [list(sub[p].schema) for p in node.parents]
        out += _qk021_schema(nid, node, parents)
        out += _qk022_exchange(nid, node, parents)
        if isinstance(node, logical.FusedStageNode):
            out += _qk023_fusion(sub, nid, node, consumers)
        out += _qk024_streaming(sub, nid, node)
        out += _qk026_adaptive(nid, node)
    return out


def _qk021_schema(nid, node, parents) -> List[PlanViolation]:
    out = []

    def bad(msg):
        out.append(PlanViolation("QK021", nid, node.describe(), msg))

    schema = list(node.schema)
    if not schema:
        bad("empty output schema")
    if len(set(schema)) != len(schema):
        dupes = sorted({c for c in schema if schema.count(c) > 1})
        bad(f"duplicate output columns {dupes}")
    if not all(isinstance(c, str) for c in schema):
        bad(f"non-string column names in {schema}")
    try:
        derived = node.derive_schema(parents)
    except ValueError as e:
        bad(str(e))
        return out
    if derived is not None and list(derived) != schema:
        bad(f"declared schema {schema} != derived {list(derived)}")
    if isinstance(node, logical.SourceNode):
        if node.predicate is not None:
            missing = sorted(node.predicate.required_columns() - set(schema))
            if missing:
                bad(f"pushed predicate references pruned columns {missing}")
        if node.projection is not None and list(node.projection) != schema:
            bad(f"projection {node.projection} != schema {schema}")
    return out


def _qk022_exchange(nid, node, parents) -> List[PlanViolation]:
    out = []

    def bad(msg):
        out.append(PlanViolation("QK022", nid, node.describe(), msg))

    if isinstance(node, logical.JoinNode):
        if not node.left_on or len(node.left_on) != len(node.right_on):
            bad(f"join key arity mismatch {node.left_on} vs {node.right_on}")
        # key presence on both inputs is QK021's derive_schema _require;
        # re-check here so a QK022 report stands alone for exchange edges
        for keys, side in ((node.left_on, 0), (node.right_on, 1)):
            missing = [k for k in keys if k not in set(parents[side])]
            if missing:
                bad(f"exchange keys {missing} not produced by input {side} "
                    f"{parents[side]}")
    if isinstance(node, logical.StatefulNode):
        for i, part in (node.partitioners or {}).items():
            if i >= len(parents):
                bad(f"partitioner on missing input {i}")
                continue
            if isinstance(part, HashPartitioner):
                missing = [k for k in part.keys if k not in set(parents[i])]
                if missing:
                    bad(f"hash partition keys {missing} not produced by "
                        f"input {i} {parents[i]}")
            if isinstance(part, RangePartitioner) and part.key not in set(parents[i]):
                bad(f"range partition key {part.key!r} not produced by "
                    f"input {i} {parents[i]}")
    if isinstance(node, logical.AggNode) and node.keys:
        # the partial->final exchange hashes on the group keys; the partial
        # half always emits them, so only key sanity is checkable here
        if len(set(node.keys)) != len(node.keys):
            bad(f"duplicate group keys {node.keys}")
    if isinstance(node, logical.SortNode) and node.boundaries is not None:
        n = node.channels or 0
        if n < 2:
            bad(f"range-partitioned sort with {n} channel(s)")
        elif len(node.boundaries) != n - 1:
            bad(f"{len(node.boundaries)} boundaries for {n} channels "
                "(need channels-1)")
        if len(node.by) != 1:
            bad(f"range partition on multi-column sort {node.by}")
    return out


_FUSIBLE = (logical.FilterNode, logical.ProjectionNode, logical.MapNode,
            logical.JoinNode, logical.AggNode)


def _qk023_fusion(sub, nid, node: logical.FusedStageNode, consumers) -> List[PlanViolation]:
    out = []

    def bad(msg):
        out.append(PlanViolation("QK023", nid, "FusedStage", msg))

    members = node.members
    if len(members) < 2:
        bad(f"{len(members)}-member stage (fusion must be a real chain)")
    joins = 0
    for i, m in enumerate(members):
        if not isinstance(m, _FUSIBLE):
            bad(f"member {i} ({type(m).__name__}) is not a fusible operator")
        if m.placement is not None:
            bad(f"member {i} ({m.describe()}) carries a placement strategy")
        if m.sorted_by is not None:
            bad(f"member {i} ({m.describe()}) is order-carrying")
        if isinstance(m, logical.JoinNode):
            joins += 1
            if i > 0 and not m.broadcast:
                bad(f"interior member {i} is a non-broadcast hash join")
        if isinstance(m, logical.AggNode) and i != len(members) - 1:
            bad(f"agg member {i} does not terminate the chain")
        if m.channels is not None and node.channels is not None \
                and m.channels != node.channels:
            bad(f"member {i} pinned to {m.channels} channels, stage has "
                f"{node.channels}")
    if joins != len(node.parents) - 1:
        bad(f"{joins} join member(s) but {len(node.parents) - 1} build input(s)")
    # absorbed interior ids must be gone and unreferenced (single-consumer)
    interior = [m.parents[0] for m in members[1:]]
    for mid in interior:
        if mid in sub:
            bad(f"absorbed member id {mid} still present in the plan")
        for other, cons in consumers.items():
            if other == mid and cons:
                bad(f"absorbed member id {mid} still consumed by {cons}")
    refs = [
        (onid, mid)
        for onid, other in sub.items()
        for mid in interior
        if onid != nid and mid in other.parents
    ]
    for onid, mid in refs:
        bad(f"absorbed member id {mid} referenced by node {onid}")
    return out


def _qk024_streaming(sub, nid, node) -> List[PlanViolation]:
    out = []

    def bad(msg):
        out.append(PlanViolation("QK024", nid, node.describe(), msg))

    if node.sorted_by is not None:
        missing = [c for c in node.sorted_by if c not in set(node.schema)]
        if missing:
            bad(f"sorted_by columns {missing} not in output schema "
                f"{list(node.schema)}")
        # order-inheriting verbs can't invent order their input lacks
        if isinstance(node, (logical.FilterNode, logical.ProjectionNode,
                             logical.MapNode)):
            parent = sub[node.parents[0]]
            if parent.sorted_by is None:
                bad(f"claims order {node.sorted_by} over an unordered input "
                    f"({parent.describe()})")
        # hash-exchange operators have no order contract at all: their
        # key-partitioned shuffle interleaves channels arbitrarily
        if isinstance(node, (logical.JoinNode, logical.AggNode,
                             logical.DistinctNode)):
            bad(f"hash-exchange operator claims order {node.sorted_by}")
    if isinstance(node, logical.AsofJoinNode):
        for side, key in ((0, node.left_on), (1, node.right_on)):
            psort = sub[node.parents[side]].sorted_by or []
            if not psort or psort[0] != key:
                bad(f"asof input {side} ordered by {psort or None}, join "
                    f"needs {key!r} first")
    elif isinstance(node, (logical.WindowAggNode, logical.ShiftNode)):
        psort = sub[node.parents[0]].sorted_by or []
        if not psort or psort[0] != node.time_col:
            bad(f"time-series input ordered by {psort or None}, operator "
                f"needs {node.time_col!r} first")
    if isinstance(node, logical.SourceNode) and \
            getattr(node.reader, "UNBOUNDED", False):
        if node.channels != 1:
            bad(f"unbounded source with channels={node.channels} "
                "(streaming v1 discipline is exactly 1)")
    if isinstance(node, logical.FusedStageNode):
        for i, m in enumerate(node.members):
            if getattr(m, "checkpoint_barrier", False) or \
                    isinstance(m, logical.StatefulNode):
                bad(f"checkpoint barrier (member {i}, {m.describe()}) inside "
                    "a fused stage — the stage checkpoints as one unit")
    return out


def _qk026_adaptive(nid, node) -> List[PlanViolation]:
    out = []

    def bad(msg):
        out.append(PlanViolation("QK026", nid, node.describe(), msg))

    # the runtime salting rewrite owns this name on the wire; a plan that
    # emits it would collide with adapted exchanges (decide.SALT_COLUMN)
    from quokka_tpu.planner.decide import SALT_COLUMN

    if SALT_COLUMN in set(node.schema):
        bad(f"reserved salt column {SALT_COLUMN!r} in output schema")
    marked = [node]
    if isinstance(node, logical.FusedStageNode):
        marked += list(node.members)
    for m in marked:
        if not getattr(m, "adapt_salt", False):
            continue
        if not isinstance(m, logical.JoinNode):
            bad(f"adapt_salt on non-join {type(m).__name__}")
            continue
        if m.how != "inner":
            bad(f"adapt_salt on {m.how!r} join — only inner joins keep "
                "exactly-once matching under salt+replicate")
        if m.broadcast:
            bad("adapt_salt on a broadcast join (no build exchange to salt)")
        if m.sorted_by:
            bad(f"adapt_salt on an order-carrying join (sorted_by="
                f"{list(m.sorted_by)}) — replicated probe slices interleave")
    return out


# ---------------------------------------------------------------------------
# entry points used by optimizer.optimize
# ---------------------------------------------------------------------------


def verify_plan(sub, sink_id: int, where: str = "plan") -> None:
    """Check all invariants; additionally prove the fuse/unfuse involution
    for already-fused plans (no 'before' digest exists here, so the check
    is unfuse -> re-fuse -> identical digest)."""
    t0 = time.perf_counter()
    violations = collect(sub, sink_id)
    if any(isinstance(n, logical.FusedStageNode) for n in sub.values()) \
            and not violations:
        unfused = unfuse_stages(sub)
        refused = dict(unfused)
        fuse_stages(refused, sink_id)
        if _raw_digest(refused, sink_id) != _raw_digest(sub, sink_id):
            violations.append(PlanViolation(
                "QK023", sink_id, "plan",
                "fuse_stages(unfuse_stages(p)) != p (round-trip drift)"))
    _account(time.perf_counter() - t0)
    if violations:
        raise PlanInvariantError(where, violations)


def verify_pass(sub, sink_id: int, pass_name: str, before: Optional[tuple]) -> None:
    """Post-pass check: all invariants, plus — for the fusion pass — the
    exact round-trip ``unfuse_stages(after) == before`` (QK023)."""
    t0 = time.perf_counter()
    violations = collect(sub, sink_id)
    if pass_name == "fuse_stages" and before is not None and not violations:
        unfused = unfuse_stages(sub)
        if _raw_digest(unfused, sink_id) != before:
            violations.append(PlanViolation(
                "QK023", sink_id, "plan",
                "unfuse_stages(fuse_stages(p)) is not structurally "
                "identical to p"))
    _account(time.perf_counter() - t0)
    if violations:
        raise PlanInvariantError(f"pass {pass_name}", violations)


def _raw_digest(sub, sink_id) -> tuple:
    return tuple(
        (nid, _node_sig(sub[nid])) for nid in sorted(_reachable(sub, sink_id))
    )


# ---------------------------------------------------------------------------
# CLI corpus: every plannable query shape the tests/bench exercise
# ---------------------------------------------------------------------------


def _tables():
    import numpy as np
    import pyarrow as pa

    r = np.random.default_rng(7)
    n = 64
    fact = pa.table({
        "k": r.integers(0, 6, n).astype(np.int64),
        "j": r.integers(0, 4, n).astype(np.int64),
        "x": r.integers(0, 100, n).astype(np.int64),
        "v": r.normal(size=n),
    })
    dim = pa.table({
        "k": np.arange(6, dtype=np.int64),
        "name": np.array([f"k{i}" for i in range(6)]),
        "w": r.integers(0, 10, 6).astype(np.int64),
    })
    dim2 = pa.table({
        "j": np.arange(4, dtype=np.int64),
        "x": r.integers(0, 10, 4).astype(np.int64),  # clashes with fact.x
    })
    t = np.sort(r.integers(0, 10_000, n)).astype(np.int64)
    ticks = pa.table({
        "time": t,
        "symbol": r.integers(0, 3, n).astype(np.int64),
        "size": r.integers(1, 9, n).astype(np.int64),
    })
    return fact, dim, dim2, ticks


def corpus() -> List[Tuple[str, "callable"]]:
    """(name, build(qc) -> DataStream) for every plannable query shape in
    the tier-1 tests and bench.py — the CLI plans each one with the full
    pass pipeline and verifies every intermediate plan."""
    from quokka_tpu.expression import col
    from quokka_tpu.windows import TumblingWindow

    fact, dim, dim2, ticks = _tables()

    def filter_agg(qc):
        return (qc.from_arrow(fact).filter(col("x") > 10)
                .groupby("k").agg_sql("sum(x) as sx, avg(v) as av"))

    def q3_shape(qc):
        f = qc.from_arrow(fact).filter(col("x") > 5)
        d = qc.from_arrow(dim)
        return (f.join(d, on="k").groupby("name")
                .agg_sql("sum(x) as revenue").top_k("revenue", 3,
                                                    descending=[True]))

    def join_chain(qc):
        f = qc.from_arrow(fact)
        return (f.join(qc.from_arrow(dim), on="k")
                .join(qc.from_arrow(dim2), on="j", suffix="_d2")
                .select(["k", "name", "x_d2"]))

    def broadcast_dim(qc):
        return (qc.from_arrow(fact)
                .broadcast_join(qc.from_arrow(dim), on="k")
                .select(["k", "w"]).sum("w"))

    def semi_anti(qc):
        f = qc.from_arrow(fact)
        d = qc.from_arrow(dim).filter(col("w") > 3)
        return f.join(d, on="k", how="semi").union(
            f.join(d, on="k", how="anti")).select(["k", "x"])

    def suffix_clash(qc):
        return (qc.from_arrow(fact)
                .join(qc.from_arrow(dim2), on="j")
                .select(["k", "x_2"]))

    def union_prune(qc):
        # regression shape: each union side prunes differently (left keeps
        # a pushed predicate's column), the union schema must re-derive
        a = qc.from_arrow(fact).filter(col("x") > 50)
        b = qc.from_arrow(fact)
        return a.union(b).select(["k"]).distinct()

    def map_chain(qc):
        return (qc.from_arrow(fact)
                .with_columns({"x2": col("x") * 2})
                .rename({"v": "value"})
                .transform(lambda df: df.head(5), ["k", "j", "x", "value", "x2"])
                .select(["k", "x2"]))

    def order_verbs(qc):
        s = qc.from_arrow(fact).sort("x").filter(col("k") > 1)
        return s.head(10)

    def count_distinct(qc):
        return qc.from_arrow(fact).groupby("k").agg_sql(
            "count(distinct j) as dj")

    def asof(qc):
        t = qc.from_arrow_sorted(ticks, sorted_by="time")
        q = qc.from_arrow_sorted(ticks, sorted_by="time")
        return t.join_asof(q, on="time", by="symbol")

    def window(qc):
        t = qc.from_arrow_sorted(ticks, sorted_by="time")
        return t.window_agg(TumblingWindow(1000), "sum(size) as vol",
                            by="symbol")

    def shift(qc):
        t = qc.from_arrow_sorted(ticks, sorted_by="time")
        return t.shift("size", n=1, by="symbol")

    def quantile(qc):
        return qc.from_arrow(fact).approximate_quantile("x", [0.5, 0.9])

    return [
        ("filter_agg", filter_agg),
        ("q3_shape", q3_shape),
        ("join_chain", join_chain),
        ("broadcast_dim", broadcast_dim),
        ("semi_anti", semi_anti),
        ("suffix_clash", suffix_clash),
        ("union_prune", union_prune),
        ("map_chain", map_chain),
        ("order_verbs", order_verbs),
        ("count_distinct", count_distinct),
        ("asof", asof),
        ("window", window),
        ("shift", shift),
        ("quantile", quantile),
    ]


def check_corpus(progress=None) -> List[Tuple[str, PlanInvariantError]]:
    """Plan every corpus query with the full (instrumented) pipeline and a
    final whole-plan verify; returns (name, error) for failures.  ``progress``
    is an optional ``callable(line: str)`` invoked once per corpus query
    (the CLI passes ``print``)."""
    from quokka_tpu.context import QuokkaContext

    old = os.environ.get("QK_PLAN_VERIFY")
    os.environ["QK_PLAN_VERIFY"] = "1"
    failures: List[Tuple[str, PlanInvariantError]] = []
    try:
        for name, build in corpus():
            qc = QuokkaContext()
            try:
                ds = build(qc)
                sub, sink_id = qc._prepare_plan(ds.node_id)
                verify_plan(sub, sink_id, where=f"corpus:{name}")
            except PlanInvariantError as e:
                failures.append((name, e))
            if progress is not None:
                status = "FAIL" if failures and failures[-1][0] == name else "ok"
                progress(f"  {name:<16} {status}")
    finally:
        if old is None:
            os.environ.pop("QK_PLAN_VERIFY", None)
        else:
            os.environ["QK_PLAN_VERIFY"] = old
    return failures


def check_resume_fingerprints(progress=None) -> List[Tuple[str, str]]:
    """QK025, run over live lowerings: for each shape, prepare the plan,
    pickle it exactly like ``QueryService.submit(durable=True)`` does,
    then unpickle + lower TWICE into fresh contexts/stores (two simulated
    process restarts).  Both fingerprints must equal each other AND the
    original submit-side lowering's, and every preimage part must be free
    of memory addresses.  Returns (name, problem) failures."""
    import pickle

    import numpy as np
    import pyarrow as pa

    from quokka_tpu.context import QuokkaContext
    from quokka_tpu.runtime import resume as bresume
    from quokka_tpu.runtime.engine import TaskGraph
    from quokka_tpu.runtime.tables import ControlStore

    r = np.random.default_rng(7)
    n = 256
    fact = pa.table({
        "k": r.integers(0, 6, n).astype(np.int64),
        "v": r.integers(0, 100, n).astype(np.float64),
    })
    dim = pa.table({
        "k": np.arange(6, dtype=np.int64),
        "w": r.integers(0, 10, 6).astype(np.int64),
    })
    shapes = [
        ("agg", lambda qc: qc.from_arrow(fact)
            .groupby("k").agg_sql("sum(v) as s, count(*) as n")),
        ("join_agg", lambda qc: qc.from_arrow(fact)
            .join(qc.from_arrow(dim), on="k")
            .groupby("w").agg_sql("sum(v) as s")),
        ("filter_proj", lambda qc: qc.from_arrow(fact)
            .filter_sql("v > 10").select(["k"])),
    ]
    failures: List[Tuple[str, str]] = []
    for name, build in shapes:
        qc = QuokkaContext()
        ds = build(qc)
        sub, sink_id = qc._prepare_plan(ds.node_id)
        blob = pickle.dumps({"sub": sub, "sink_id": sink_id,
                             "exec_channels": qc.exec_channels})
        g0 = TaskGraph(qc.exec_config, store=ControlStore())
        qc._lower_plan(sub, sink_id, g0)
        fps, parts = [], []
        for _restart in range(2):
            payload = pickle.loads(blob)
            ctx = QuokkaContext()
            ctx.exec_channels = payload.get("exec_channels",
                                            ctx.exec_channels)
            g = TaskGraph(ctx.exec_config, store=ControlStore())
            ctx._lower_plan(payload["sub"], payload["sink_id"], g)
            fps.append(bresume.structural_fingerprint(g))
            parts.append(bresume.structural_parts(g))
        if len({bresume.structural_fingerprint(g0), *fps}) != 1:
            failures.append((name, f"fingerprint drifted across simulated "
                                   f"restarts: submit="
                                   f"{bresume.structural_fingerprint(g0)} "
                                   f"relowered={fps}"))
        addressed = [p for p in parts[0] if "0x" in p]
        if addressed:
            failures.append((name, "fingerprint preimage contains object "
                                   f"addresses: {addressed}"))
        if progress is not None:
            status = ("FAIL" if failures and failures[-1][0] == name
                      else "ok")
            progress(f"  resume-fp {name:<12} {status}")
    return failures


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m quokka_tpu.analysis.planck",
        description="verify plan invariants QK021-QK024 over the corpus of "
                    "plannable query shapes (plus fuzzer-generated plans)")
    p.add_argument("--seeds", type=int, default=0,
                   help="additionally verify N fuzzer-generated plans "
                        "(static checks only; see planfuzz for differential)")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    failures = check_corpus(progress=print if args.verbose else None)
    n_corpus = len(corpus())
    print(f"planck: corpus {n_corpus - len(failures)}/{n_corpus} plans clean "
          f"({VERIFY_STATS['checks']} checks, "
          f"{VERIFY_STATS['ms_total']:.1f} ms total, "
          f"last plan {VERIFY_STATS['ms_last_plan']:.2f} ms)")
    for name, e in failures:
        print(f"FAIL {name}:\n{e}")

    fp_failures = check_resume_fingerprints(
        progress=print if args.verbose else None)
    print(f"planck: resume fingerprints (QK025) "
          f"{3 - len({n for n, _ in fp_failures})}/3 shapes restart-stable")
    for name, problem in fp_failures:
        print(f"FAIL resume-fp {name}: {problem}")
    failures = failures + fp_failures

    if args.seeds:
        from quokka_tpu.analysis import planfuzz

        fuzz_failures = 0
        for seed in range(args.seeds):
            r = planfuzz.run_seed(seed, static_only=True)
            if not r.ok:
                fuzz_failures += 1
                print(f"FAIL fuzz seed {seed}: {r.summary()}")
        print(f"planck: fuzz {args.seeds - fuzz_failures}/{args.seeds} "
              "seeded plans clean")
        if fuzz_failures:
            return 1
    print(f"planck: done in {time.perf_counter() - t0:.1f}s")
    return 1 if failures else 0


if __name__ == "__main__":  # pragma: no cover
    # dispatch through the canonical module so VERIFY_STATS is shared with
    # the optimizer's instrumentation (python -m runs this file as __main__)
    from quokka_tpu.analysis import planck as _canonical

    raise SystemExit(_canonical.main())
