"""AST lint rules for the engine's hand-argued invariants.

Each rule is a function ``(module: ast.Module, path: str, rel: str) ->
List[Finding]`` registered in ``RULES``.  Rules are deliberately
heuristic-but-deterministic: they over-approximate (a flagged line that is
actually fine goes into ``baseline.json`` with a rationale) and never
under-approximate on the concrete failure modes that motivated them
(round-5 verdict: module-level pjit dispatch race, import-time listener
registration, private-API probe silently defaulting into the racy path).

Rule ids:
  QK001 module-level-jit        jit/pjit/shard_map objects built at import
  QK002 import-time-side-effect registrations/device queries/thread starts/
                                filesystem mutation at module scope
  QK003 private-api             jax._src / jax.core.* outside analysis/compat
  QK004 host-sync-in-jit        host round-trips + python control flow on
                                parameters inside functions reachable from
                                jitted entry points
  QK005 unlocked-shared-state   lock-owning classes/modules mutating their
                                shared containers without holding the lock
  QK006 swallowed-exception     except handlers whose body is only ``pass``
  QK007 bare-print              print(...) in library code outside CLI entry
                                points (route through quokka_tpu.obs.diag)
  QK008 global-config-mutation  mutation of process-global configuration
                                (jax.config.update, os.environ, config.py
                                module globals) — with the query service
                                many queries share one process, so a query
                                mutating globals corrupts its neighbors
  QK009 unbounded-io-timeout    network/socket/fsspec calls without an
                                explicit timeout — a wedged socket or
                                object-store request hangs a worker to the
                                stall timeout instead of failing fast into
                                the retry/recovery path
  QK010 adhoc-counter-dict      counter-shaped increments on plain dicts in
                                runtime code (``stats["hits"] += 1``) —
                                counters must go through the typed
                                obs.REGISTRY so the Prometheus exporter,
                                bench snapshots and /status see them
  QK011 push-path-host-sync     blocking host readbacks (np.asarray /
                                .item() / device_get / block_until_ready /
                                .tolist()) reachable from the shuffle push
                                path (Engine.push, the lowered partition
                                fns, split_by_partition) — the exchange
                                critical path must never drain the device
                                pipeline; deliberate readbacks carry
                                baseline rationales
  QK012 raw-len-cache-key       jit-program cache keys built from raw
                                (un-bucketed) batch lengths (.padded_len /
                                .shape[0]) outside ops/sigkey.py — every
                                raw length in a key multiplies the compile
                                space per 2x rung; keys must derive through
                                sigkey (bucket_rows/batch_sig/aval_sig/
                                make_key) so warmup compiles stay counted
                                and canonical
  QK013 platform-gate           jax.default_backend()/config._platform()
                                probes and platform-string comparisons
                                outside ops/strategy.py + config.py — a
                                scattered platform gate is a kernel choice
                                the strategy matrix cannot see, calibrate,
                                or record, which is exactly how the bench
                                came to measure a path the target backend
                                never runs (VERDICT r5 #2)
  QK018 unledgered-device-alloc eager device allocations (jax.device_put,
                                jnp.* array constructors on non-traced
                                paths) in runtime/executors/streaming/
                                service code — residency created outside
                                the ledgered choke points (bridge + caches
                                + HBQ) is invisible to the memory ledger
                                (obs/memplane.py), so per-query footprints
                                and OOM forensics under-report exactly the
                                allocation that mattered
  QK019 adhoc-operator-tally    per-operator row/byte tallies grown by hand
                                in runtime/executors/streaming/service code
                                (``self.rows_in += ...``,
                                ``tally["bytes_out"] += ...``) — operator
                                cardinality accounting must go through the
                                opstats ledger (obs/opstats.py: OPSTATS
                                record paths or opstats.note()) so EXPLAIN
                                ANALYZE, skew detection and the persisted
                                cardinality profile see the same numbers;
                                operational state (bare ``rows``,
                                ``pending_rows``, build buffers) is not a
                                stat and is not flagged
  QK020 multi-program-chain     executor bodies dispatching a CHAIN of
                                single-expression jit programs per batch —
                                ``evaluate_predicate``/``evaluate_to_column``
                                inside a per-expression loop, or more than
                                two straight-line calls in one function.
                                Each call launches its own program over the
                                whole batch; a linear chain of them is
                                exactly what whole-stage fusion collapses
                                into ONE program (ops/stagefuse.py
                                FusedElementwise, ops/fuse.py builders).
                                Deliberate fallback/finalize paths baseline
                                with a rationale
  QK025 obs-lock-blocking-io    blocking I/O (``open``/``time.sleep``/
                                socket/``urlopen``) executed — directly or
                                through a reachable helper — while holding
                                an obs-plane ``*_lock``.  The registry lock
                                serializes every hot-path counter increment
                                and histogram observe; a file write or
                                sleep under it stalls every engine thread
                                at once.  Snapshot under the lock, do the
                                I/O outside (obs/progress.py
                                ``_profile_for`` is the pattern)
  QK027 adhoc-wall-timing       bare ``time.time()``/``time.perf_counter()``
                                deltas used for timing outside ``obs/`` and
                                bench.py — a hand-rolled timer is invisible
                                to the span aggregator (obs/spans.py), the
                                flight recorder and the bench breakdown;
                                durations route through obs.span()/
                                spans.add(), deliberate low-level sites
                                baseline with a rationale

Finding keys (``Finding.key``) are line-number-free — ``rule::relpath::
scope::snippet[::n]`` — so a baseline survives unrelated edits above the
flagged line and goes stale (reported, prunable) when the flagged code
itself changes.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from quokka_tpu.analysis.flow import FlowContext

_JIT_MAKERS = ("jit", "pjit", "shard_map")

_REGISTRATION_CALLS = (
    "register_event_listener",
    "register_event_duration_secs_listener",
    "ensure_registered",
)
_DEVICE_QUERY_CALLS = (
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
    "jax.local_device_count",
    "jax.default_backend",
)
_FS_MUTATION_CALLS = ("os.makedirs", "os.mkdir", "os.mkdirs")

_HOST_SYNC_CALLS = (
    "asarray",          # np.asarray(tracer) -> blocking d2h
    "block_until_ready",
    "device_get",
    "item",
    "tolist",
)
_HOST_SYNC_BASES = ("np", "numpy", "onp", "jax")
_SCALAR_CONVERSIONS = ("float", "int", "bool")


@dataclass
class Finding:
    rule: str
    name: str
    path: str       # absolute or as-given path (for printing)
    rel: str        # stable relative path (for baseline keys)
    line: int
    scope: str      # qualified enclosing scope, '<module>' at top level
    message: str
    snippet: str    # stripped source of the flagged line
    occurrence: int = 0  # disambiguates identical snippets in one scope

    def key(self) -> str:
        base = f"{self.rule}::{self.rel}::{self.scope}::{self.snippet}"
        return base if self.occurrence == 0 else f"{base}::{self.occurrence}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.name}] "
                f"{self.message}  ({self.scope})")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _snippet(src_lines: Sequence[str], line: int) -> str:
    if 1 <= line <= len(src_lines):
        return src_lines[line - 1].strip()[:120]
    return ""


def _mk(rule: str, name: str, path: str, rel: str, node: ast.AST, scope: str,
        message: str, src_lines: Sequence[str]) -> Finding:
    line = getattr(node, "lineno", 0)
    return Finding(rule, name, path, rel, line, scope, message,
                   _snippet(src_lines, line))


def _is_jit_maker(d: Optional[str]) -> bool:
    return d is not None and (d in _JIT_MAKERS
                              or d.rsplit(".", 1)[-1] in _JIT_MAKERS)


def _own_exprs(st: ast.stmt) -> List[ast.expr]:
    """Expressions evaluated BY this statement itself — excluding child
    statements (compound bodies are yielded separately by
    ``_module_scope_statements``, so walking them here would double-count)."""
    out: List[ast.expr] = []
    for field in ("value", "test", "iter", "exc", "msg", "cause"):
        v = getattr(st, field, None)
        if isinstance(v, ast.expr):
            out.append(v)
    for t in getattr(st, "targets", []) or []:
        out.append(t)
    tgt = getattr(st, "target", None)
    if isinstance(tgt, ast.expr):
        out.append(tgt)
    for item in getattr(st, "items", []) or []:  # with-statement items
        out.append(item.context_expr)
    return out


def _module_scope_statements(tree: ast.Module) -> Iterable[ast.stmt]:
    """Statements executed at import time: module body, descending into
    module-level if/try/with/for blocks (still import time) but NOT into
    function bodies.  Class bodies also run at import and are included."""
    stack: List[ast.stmt] = list(tree.body)
    while stack:
        st = stack.pop(0)
        yield st
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(st, ast.ClassDef):
            # class body executes at import; method bodies do not
            stack = [s for s in st.body
                     if not isinstance(s, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))] + stack
            continue
        extra: List[ast.stmt] = []
        for field in ("body", "orelse", "finalbody"):
            extra.extend(getattr(st, field, []) or [])
        for h in getattr(st, "handlers", []) or []:
            extra.extend(h.body)
        stack = extra + stack


# ---------------------------------------------------------------------------
# QK001 — module-level jit objects
# ---------------------------------------------------------------------------


def check_module_level_jit(tree: ast.Module, path: str, rel: str,
                           src_lines: Sequence[str]) -> List[Finding]:
    out: List[Finding] = []
    for st in _module_scope_statements(tree):
        if isinstance(st, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a def's body runs later; but its DECORATORS run at import —
            # @jax.jit at module scope builds a module-level pjit object
            for dec in st.decorator_list:
                for sub in ast.walk(dec):
                    d = _dotted(sub)
                    if _is_jit_maker(d):
                        out.append(_mk(
                            "QK001", "module-level-jit", path, rel, dec,
                            "<module>",
                            f"decorator builds a module-level "
                            f"{d.rsplit('.', 1)[-1]} object for "
                            f"'{st.name}' at import time (jit-dispatch "
                            "race across engine threads; build lazily or "
                            "route via a traced/untraced dispatcher)",
                            src_lines))
            continue
        for expr in _own_exprs(st):
            for node in ast.walk(expr):
                if isinstance(node, ast.Lambda):
                    continue
                d = _dotted(node) if isinstance(node, (ast.Name,
                                                       ast.Attribute)) \
                    else None
                if _is_jit_maker(d):
                    out.append(_mk(
                        "QK001", "module-level-jit", path, rel, node,
                        "<module>",
                        f"'{d}' referenced at module scope: jit/pjit/"
                        "shard_map objects built at import time are shared "
                        "across engine threads and raced jit dispatch on "
                        "the 1-core CPU backend (build inside a function, "
                        "or dispatch via _in_trace-style routing)",
                        src_lines))
    return out


# ---------------------------------------------------------------------------
# QK002 — import-time side effects
# ---------------------------------------------------------------------------


def check_import_time_side_effects(tree: ast.Module, path: str, rel: str,
                                   src_lines: Sequence[str]) -> List[Finding]:
    out: List[Finding] = []
    for st in _module_scope_statements(tree):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Import, ast.ImportFrom)):
            continue
        for node in [n for expr in _own_exprs(st) for n in ast.walk(expr)]:
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            tail = d.rsplit(".", 1)[-1]
            reason = None
            if tail in _REGISTRATION_CALLS or d == "atexit.register":
                reason = "listener/handler registration"
            elif d in _DEVICE_QUERY_CALLS:
                reason = "device/backend query (initializes the backend)"
            elif d in _FS_MUTATION_CALLS:
                reason = "filesystem mutation"
            elif tail == "Thread" or d.endswith("start_new_thread"):
                reason = "thread construction"
            elif tail == "start" and isinstance(node.func, ast.Attribute):
                reason = "thread/service start"
            if reason is not None:
                out.append(_mk(
                    "QK002", "import-time-side-effect", path, rel, node,
                    "<module>",
                    f"'{d}(...)' runs at import time ({reason}); import of "
                    "this module from a worker/trace context inherits the "
                    "side effect — make it lazy or baseline it with a "
                    "rationale",
                    src_lines))
    return out


# ---------------------------------------------------------------------------
# QK003 — private JAX API use
# ---------------------------------------------------------------------------

# the one module allowed to touch private surfaces (version-guarded shims)
PRIVATE_API_EXEMPT_SUFFIXES = ("analysis/compat.py",)


def check_private_api(tree: ast.Module, path: str, rel: str,
                      src_lines: Sequence[str]) -> List[Finding]:
    if rel.replace("\\", "/").endswith(PRIVATE_API_EXEMPT_SUFFIXES):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        d = None
        if isinstance(node, ast.Attribute):
            full = _dotted(node)
            if full and (full.startswith("jax._src")
                         or full.startswith("jax.core.")):
                d = full
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module.startswith(("jax._src", "jax.core")):
                d = node.module
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith(("jax._src", "jax.core")):
                    d = alias.name
        if d is not None:
            out.append(_mk(
                "QK003", "private-api", path, rel, node, _scope_of(tree, node),
                f"private JAX API '{d}' used directly; route through "
                "quokka_tpu.analysis.compat (fails loudly at import when a "
                "jax upgrade moves the symbol, instead of a defensive except "
                "silently changing behavior)",
                src_lines))
    return out


# ---------------------------------------------------------------------------
# QK004 — host syncs / python control flow in jit-reachable code
# ---------------------------------------------------------------------------


def _scope_of(tree: ast.Module, target: ast.AST) -> str:
    """Qualified name of the innermost function/class containing target."""
    best = "<module>"

    def walk(node: ast.AST, prefix: str):
        nonlocal best
        for child in ast.iter_child_nodes(node):
            name = None
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = (prefix + "." if prefix else "") + child.name
            if child is target or _contains(child, target):
                if name is not None:
                    best = name
                    walk(child, name)
                else:
                    walk(child, prefix)
                return

    walk(tree, "")
    return best


def _contains(node: ast.AST, target: ast.AST) -> bool:
    for sub in ast.walk(node):
        if sub is target:
            return True
    return False


def _collect_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """name -> def node, innermost-last (nested defs keyed by bare name too:
    call-graph edges here are resolved by simple name)."""
    fns: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(node.name, node)
    return fns


def _static_argnames(call: Optional[ast.Call]) -> Set[str]:
    """Literal static_argnames of a jit(...) / partial(jax.jit, ...) call."""
    out: Set[str] = set()
    if call is None:
        return out
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
    return out


def _jit_entry_names(tree: ast.Module) -> Dict[str, Set[str]]:
    """Module functions handed to jit/pjit/shard_map anywhere in the file,
    mapped to their literal static_argnames (params excluded from the
    control-flow-on-tracers check)."""
    entries: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                for sub in ast.walk(dec):
                    if _is_jit_maker(_dotted(sub)):
                        statics = _static_argnames(
                            dec if isinstance(dec, ast.Call) else None)
                        entries.setdefault(node.name, set()).update(statics)
                        break
        if not isinstance(node, ast.Call):
            continue
        maker = _is_jit_maker(_dotted(node.func))
        statics: Set[str] = set()
        if maker and isinstance(node.func, ast.Attribute):
            statics = _static_argnames(node)
        if not maker and isinstance(node.func, ast.Call):
            # functools.partial(jax.jit, ...)(fn)
            inner = node.func
            if _dotted(inner.func) in ("functools.partial", "partial"):
                maker = any(_is_jit_maker(_dotted(a)) for a in inner.args)
                statics = _static_argnames(inner)
        if maker:
            statics |= _static_argnames(node if isinstance(node, ast.Call)
                                        else None)
            for a in node.args:
                if isinstance(a, ast.Name):
                    entries.setdefault(a.id, set()).update(statics)
    return entries


def _callees(fn: ast.FunctionDef, known: Dict[str, ast.FunctionDef]
             ) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is None:
                continue
            tail = d.rsplit(".", 1)[-1]
            if tail in known:
                out.add(tail)
            # closures handed to lax control flow count as calls
            for a in node.args:
                if isinstance(a, ast.Name) and a.id in known:
                    out.add(a.id)
    return out


def _module_reachable(ctx: FlowContext, mt, seeds: Iterable[str]) -> Set[str]:
    """Call-graph closure restricted to `mt`'s own functions (a helper in
    another module cannot re-enter the old same-file scope, so dataflow
    precision only ever REMOVES findings relative to the name heuristic)."""
    seen: Set[str] = set()
    frontier = list(seeds)
    while frontier:
        fid = frontier.pop()
        if fid in seen:
            continue
        seen.add(fid)
        frontier.extend(
            c for c in ctx.calls.get(fid, ())
            if c not in seen and ctx.funcs[c].module == mt.name
        )
    return seen


def check_host_sync_in_jit(tree: ast.Module, path: str, rel: str,
                           src_lines: Sequence[str],
                           ctx: FlowContext) -> List[Finding]:
    mt = ctx.module_table(rel)
    if mt is None:
        return []
    entry_statics = _jit_entry_names(tree)
    seeds = [fi.fid for fi in mt.functions.values()
             if fi.name in entry_statics]

    out: List[Finding] = []
    for fid in sorted(_module_reachable(ctx, mt, seeds)):
        fi = ctx.funcs[fid]
        name = fi.name
        params = fi.params()
        params -= entry_statics.get(name, set())
        # interprocedurally static parameters (literal/metadata at EVERY
        # call site in the analyzed set) are trace-time config, not tracers
        params -= ctx.static_params(fid)
        for node in FlowContext._own_nodes(fi.node):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None:
                    base, _, tail = d.rpartition(".")
                    if (tail in _HOST_SYNC_CALLS
                            and (base == "" or base in _HOST_SYNC_BASES
                                 or tail in ("block_until_ready", "item",
                                             "tolist"))):
                        out.append(_mk(
                            "QK004", "host-sync-in-jit", path, rel, node,
                            name,
                            f"'{d}(...)' inside '{name}' (reachable from a "
                            "jitted entry point) forces a host round-trip "
                            "or fails on tracers; hoist it out of the "
                            "traced region",
                            src_lines))
                    elif (d in _SCALAR_CONVERSIONS and len(node.args) == 1
                          and not isinstance(node.args[0], ast.Constant)):
                        out.append(_mk(
                            "QK004", "host-sync-in-jit", path, rel, node,
                            name,
                            f"'{d}(...)' scalar conversion inside '{name}' "
                            "(reachable from a jitted entry point) blocks "
                            "on device values and raises on tracers",
                            src_lines))
            elif isinstance(node, (ast.If, ast.While)):
                # names used only as the base of static-metadata attribute
                # access (arr.dtype / arr.shape / arr.ndim) branch on trace-
                # time constants, not on tracer VALUES — not flagged
                static_bases = {
                    n.value.id for n in ast.walk(node.test)
                    if isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.attr in ("dtype", "shape", "ndim", "size")}
                names_in_test = {n.id for n in ast.walk(node.test)
                                 if isinstance(n, ast.Name)}
                hit = (names_in_test - static_bases) & params
                if hit:
                    out.append(_mk(
                        "QK004", "host-sync-in-jit", path, rel, node, name,
                        f"python {'if' if isinstance(node, ast.If) else 'while'}"
                        f" on parameter(s) {sorted(hit)} of jit-reachable "
                        f"'{name}': control flow on tracers raises "
                        "ConcretizationTypeError (use lax.cond/where, or "
                        "mark the argument static)",
                        src_lines))
    return out


check_host_sync_in_jit._needs_flow = True


# ---------------------------------------------------------------------------
# QK005 — shared state mutated without the owning lock
# ---------------------------------------------------------------------------

_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore")
_MUTATORS = ("append", "add", "pop", "popitem", "clear", "update", "extend",
             "remove", "appendleft", "discard", "setdefault", "insert")


def _is_lock_value(value: ast.AST) -> bool:
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and d.rsplit(".", 1)[-1] in _LOCK_FACTORIES:
                return True
    return False


def _is_container_value(value: ast.AST) -> bool:
    if isinstance(value, (ast.Dict, ast.Set, ast.List, ast.DictComp,
                          ast.SetComp, ast.ListComp)):
        return True
    if isinstance(value, ast.Call):
        d = _dotted(value.func)
        if d and d.rsplit(".", 1)[-1] in ("dict", "set", "list", "deque",
                                          "defaultdict", "OrderedDict",
                                          "Counter"):
            return True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _with_holds_lock(with_stack: List[ast.With], lock_names: Set[str],
                     owner: str) -> bool:
    for w in with_stack:
        for item in w.items:
            d = _dotted(item.context_expr)
            if d is None and isinstance(item.context_expr, ast.Call):
                d = _dotted(item.context_expr.func)
            if d is None:
                continue
            parts = d.split(".")
            if owner in parts[:1] and any(p in lock_names for p in parts):
                return True
            # e.g. with self._lock / with self._lock.acquire_timeout(...)
            if parts[0] == owner and len(parts) > 1 and parts[1] in lock_names:
                return True
    return False


def _check_scope_mutations(body: Iterable[ast.stmt], owner: str,
                           lock_names: Set[str], containers: Set[str],
                           scope: str, path: str, rel: str,
                           src_lines: Sequence[str]) -> List[Finding]:
    """Walk one function body tracking the with-statement stack; flag
    mutations of `owner.<container>` outside `with owner.<lock>`.  `owner`
    is 'self' for classes or the module-global sentinel '' for modules."""
    out: List[Finding] = []

    def attr_of(node: ast.AST) -> Optional[str]:
        if owner == "self":
            return _self_attr(node)
        if isinstance(node, ast.Name):
            return node.id
        return None

    def flag(node: ast.AST, target: str, verb: str):
        prefix = "self." if owner == "self" else ""
        out.append(_mk(
            "QK005", "unlocked-shared-state", path, rel, node, scope,
            f"{verb} on shared '{prefix}{target}' in '{scope}' without "
            f"holding the owning lock "
            f"({prefix}{'/'.join(sorted(lock_names))}) — racy against the "
            "exec/IO loops",
            src_lines))

    def scan_stmt(st: ast.stmt, held: bool):
        """Mutations performed by this statement itself (not children)."""
        if isinstance(st, (ast.Assign, ast.AugAssign)):
            tgts = st.targets if isinstance(st, ast.Assign) else [st.target]
            for t in tgts:
                if isinstance(t, ast.Subscript):
                    a = attr_of(t.value)
                    if a in containers and not held:
                        flag(st, a, "subscript assignment")
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Subscript):
                    a = attr_of(t.value)
                    if a in containers and not held:
                        flag(st, a, "del")
        for expr in _own_exprs(st):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                        a = attr_of(f.value)
                        if a in containers and not held:
                            flag(node, a, f"...{f.attr}()")

    def walk(stmts: Iterable[ast.stmt], withs: List[ast.With]):
        held = _with_holds_lock(withs, lock_names, owner) if owner == "self" \
            else _module_with_holds(withs, lock_names)
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs get their own pass if ever needed
            scan_stmt(st, held)
            if isinstance(st, (ast.With, ast.AsyncWith)):
                walk(st.body, withs + [st])
            else:
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(st, field, None)
                    if sub:
                        walk(sub, withs)
                for h in getattr(st, "handlers", []) or []:
                    walk(h.body, withs)

    walk(list(body), [])
    return out


def _module_with_holds(with_stack: List[ast.With],
                       lock_names: Set[str]) -> bool:
    for w in with_stack:
        for item in w.items:
            d = _dotted(item.context_expr)
            if d and d.split(".")[0] in lock_names:
                return True
    return False


def check_unlocked_shared_state(tree: ast.Module, path: str, rel: str,
                                src_lines: Sequence[str]) -> List[Finding]:
    out: List[Finding] = []
    # -- class-level: classes whose __init__ assigns self.<lock> ------------
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        init = next((m for m in cls.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            continue
        locks: Set[str] = set()
        containers: Set[str] = set()
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    a = _self_attr(t)
                    if a is None:
                        continue
                    if _is_lock_value(node.value):
                        locks.add(a)
                    elif _is_container_value(node.value):
                        containers.add(a)
        if not locks or not containers:
            continue
        for m in cls.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if m.name == "__init__":
                continue
            out.extend(_check_scope_mutations(
                m.body, "self", locks, containers,
                f"{cls.name}.{m.name}", path, rel, src_lines))
    # -- module-level: a module-global lock guarding module-global dicts ----
    mod_locks: Set[str] = set()
    mod_containers: Set[str] = set()
    for st in tree.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            nm = st.targets[0].id
            if _is_lock_value(st.value):
                mod_locks.add(nm)
            elif _is_container_value(st.value):
                mod_containers.add(nm)
    if mod_locks and mod_containers:
        for fn in tree.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_check_scope_mutations(
                    fn.body, "", mod_locks, mod_containers, fn.name,
                    path, rel, src_lines))
    return out


# ---------------------------------------------------------------------------
# QK006 — swallowed exceptions
# ---------------------------------------------------------------------------


def check_swallowed_exceptions(tree: ast.Module, path: str, rel: str,
                               src_lines: Sequence[str]) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if all(isinstance(s, ast.Pass) for s in node.body):
            if node.type is None:
                typ = "<bare>"
            elif isinstance(node.type, ast.Tuple):
                typ = "(" + ", ".join(
                    _dotted(e) or "?" for e in node.type.elts) + ")"
            else:
                typ = _dotted(node.type) or "?"
            out.append(_mk(
                "QK006", "swallowed-exception", path, rel, node,
                _scope_of(tree, node),
                f"'except {typ}: pass' swallows failures silently — log, "
                "narrow the type, re-raise, or baseline with a rationale "
                "(runtime loops that swallow errors wedge instead of "
                "failing)",
                src_lines))
    return out


# ---------------------------------------------------------------------------
# QK007 — bare print in library code
# ---------------------------------------------------------------------------

# CLI drivers whose job IS printing (argparse entry points)
BARE_PRINT_EXEMPT_SUFFIXES = ("analysis/lint.py",)
# functions that are process entry points: `main`-style CLI drivers
_BARE_PRINT_EXEMPT_FUNCS = ("main", "_main")


def check_bare_print(tree: ast.Module, path: str, rel: str,
                     src_lines: Sequence[str]) -> List[Finding]:
    """Library code must not print: stdout lines from a worker process are
    invisible (spawned children), interleave across processes, and carry no
    timestamp/ordering.  Diagnostics route through quokka_tpu.obs.diag()
    (stderr + a flight-recorder event) so they land in merged timelines.
    Exempt: CLI entry points (``main``/``_main`` functions and the lint
    driver itself)."""
    if rel.replace("\\", "/").endswith(BARE_PRINT_EXEMPT_SUFFIXES):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            continue
        scope = _scope_of(tree, node)
        if scope.rsplit(".", 1)[-1] in _BARE_PRINT_EXEMPT_FUNCS:
            continue
        out.append(_mk(
            "QK007", "bare-print", path, rel, node, scope,
            "bare 'print(...)' in library code — route diagnostics through "
            "quokka_tpu.obs.diag() (stderr + flight-recorder event, visible "
            "in merged timelines) or baseline with a rationale",
            src_lines))
    return out


# ---------------------------------------------------------------------------
# QK008 — process-global config mutation
# ---------------------------------------------------------------------------

_ENV_MUTATOR_TAILS = ("pop", "update", "setdefault", "clear")
# module aliases under which quokka_tpu.config is imported in this codebase
_CONFIG_MODULE_NAMES = ("config", "qconfig")


def _is_environ(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in ("os.environ", "environ")


def _exec_surface(ctx: FlowContext) -> Set[str]:
    """Functions reachable from the query-execution surface: the task
    dispatch handlers (``handle_*``), the shuffle push path, and every
    jitted entry.  Code OUTSIDE this closure runs pre-query (import-time
    setup, process bootstrap, CLI/soak drivers) where a process-global
    mutation has no concurrently-running neighbor to corrupt."""
    cached = getattr(ctx, "_qk_exec_surface", None)
    if cached is not None:
        return cached
    seeds: Set[str] = set()
    for mt in ctx.modules.values():
        jit_entries = _jit_entry_names(mt.tree)
        for fi in mt.functions.values():
            if (fi.name.startswith("handle_")
                    or fi.name in _PUSH_PATH_ENTRY_FUNCS
                    or fi.name in jit_entries):
                seeds.add(fi.fid)
    surface = ctx.reachable(seeds)
    ctx._qk_exec_surface = surface
    return surface


def check_global_config_mutation(tree: ast.Module, path: str, rel: str,
                                 src_lines: Sequence[str],
                                 ctx: FlowContext) -> List[Finding]:
    """With the query service, many queries share one process: jax.config,
    quokka_tpu.config module globals and os.environ are PROCESS-global, so
    code reachable inside query execution mutating them corrupts every
    concurrently-running neighbor (dtype regime flips mid-pipeline, kernel
    strategy changes between a build and its probe, ...).  Only mutations
    inside functions reachable from the execution surface (task handlers,
    push path, jit entries — see ``_exec_surface``) are flagged: import-time
    setup, spawned-worker bootstrap and soak drivers are pre-query by
    construction, which the old name-heuristic could not see and baselined
    one rationale at a time."""
    mt = ctx.module_table(rel)
    if mt is None:
        return []
    surface = _exec_surface(ctx)
    owner: Dict[int, object] = {}
    for fi in mt.functions.values():
        for n in FlowContext._own_nodes(fi.node):
            owner[id(n)] = fi

    def gated(node: ast.AST) -> bool:
        fi = owner.get(id(node))
        return fi is not None and fi.fid in surface

    out: List[Finding] = []

    def flag(node: ast.AST, what: str):
        if not gated(node):
            return
        out.append(_mk(
            "QK008", "global-config-mutation", path, rel, node,
            _scope_of(tree, node),
            f"{what} mutates process-global configuration; with the query "
            "service a query doing this mid-flight corrupts its "
            "concurrently-running neighbors — move it to process startup "
            "(pre-service), thread it per-query, or baseline with a "
            "rationale",
            src_lines))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is None:
                continue
            parts = d.split(".")
            if parts[-2:] == ["config", "update"] and parts[0] != "self":
                flag(node, f"'{d}(...)' (jax.config.update)")
            elif d in ("os.putenv", "os.unsetenv"):
                flag(node, f"'{d}(...)'")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _ENV_MUTATOR_TAILS
                  and _is_environ(node.func.value)):
                flag(node, f"'{d}(...)' (os.environ mutation)")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = (node.targets if isinstance(node, ast.Assign)
                    else [node.target])
            for t in tgts:
                if isinstance(t, ast.Subscript) and _is_environ(t.value):
                    flag(node, "subscript assignment to os.environ")
                elif (isinstance(t, ast.Attribute)
                      and isinstance(t.value, ast.Name)
                      and t.value.id in _CONFIG_MODULE_NAMES):
                    flag(node,
                         f"assignment to '{t.value.id}.{t.attr}' "
                         "(config-module global)")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and _is_environ(t.value):
                    flag(node, "del on os.environ")
    return out


check_global_config_mutation._needs_flow = True


# ---------------------------------------------------------------------------
# QK009 — network/socket/fsspec IO without an explicit timeout
# ---------------------------------------------------------------------------

# dotted-call tails that open a network connection and accept a timeout
_NET_CALLS_NEED_TIMEOUT = ("create_connection",)
# fsspec AbstractFileSystem methods that perform remote IO; flagged when
# called on an fs-named receiver (`fs`, `self._fs`, ...), since the bound-
# filesystem idiom `fs = fsspec...; fs.open(...)` never spells "fsspec."
_FS_METHODS = ("open", "cat_file", "pipe_file", "mv", "copy", "rm", "glob",
               "exists", "makedirs", "info", "ls", "get", "put")


def check_unbounded_io(tree: ast.Module, path: str, rel: str,
                       src_lines: Sequence[str]) -> List[Finding]:
    """Runtime code must never block unboundedly on network/remote IO: a
    wedged socket or object-store request otherwise hangs a worker until
    the coordinator's stall timeout instead of failing fast into the
    retry/backoff/recovery path the chaos plane exercises.  Flags:

    - ``socket.create_connection(...)`` with neither a ``timeout=`` kwarg
      nor a positional timeout;
    - explicit ``.settimeout(None)`` (unbounded by declaration);
    - any ``fsspec.*`` call, and any ``_FS_METHODS`` call on an fs-named
      receiver (``fs.open``, ``self._fs.mv``, ...), without a ``timeout=``
      kwarg — fsspec has no portable timeout parameter, so every site is
      flagged and the deliberate ones carry baseline rationales (bounded
      by caller-side deadlines/retries/watchdogs instead).
    """
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        tail = d.rsplit(".", 1)[-1]
        # timeout=None is the unbounded pattern itself, not a bound
        has_timeout_kw = any(
            kw.arg == "timeout"
            and not (isinstance(kw.value, ast.Constant)
                     and kw.value.value is None)
            for kw in node.keywords)
        if tail in _NET_CALLS_NEED_TIMEOUT:
            if not has_timeout_kw and len(node.args) < 2:
                out.append(_mk(
                    "QK009", "unbounded-io-timeout", path, rel, node,
                    _scope_of(tree, node),
                    f"'{d}(...)' without an explicit timeout blocks forever "
                    "on a wedged peer — pass timeout= so the call fails "
                    "fast into the retry/recovery path",
                    src_lines))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "settimeout"
              and len(node.args) == 1
              and isinstance(node.args[0], ast.Constant)
              and node.args[0].value is None):
            out.append(_mk(
                "QK009", "unbounded-io-timeout", path, rel, node,
                _scope_of(tree, node),
                "'settimeout(None)' makes the socket block unboundedly — "
                "use a finite timeout, or baseline with the rationale for "
                "why this wait is legitimately unbounded",
                src_lines))
        elif d.startswith("fsspec.") and not has_timeout_kw:
            out.append(_mk(
                "QK009", "unbounded-io-timeout", path, rel, node,
                _scope_of(tree, node),
                f"'{d}(...)' (remote filesystem IO) has no timeout — bound "
                "it with a caller-side deadline/retry and baseline with "
                "that rationale",
                src_lines))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _FS_METHODS
              and not has_timeout_kw):
            recv = _dotted(node.func.value)
            base = recv.rsplit(".", 1)[-1] if recv else ""
            if base == "fs" or base.endswith("_fs"):
                out.append(_mk(
                    "QK009", "unbounded-io-timeout", path, rel, node,
                    _scope_of(tree, node),
                    f"'{d}(...)' (bound-filesystem remote IO) has no "
                    "timeout — bound it with a caller-side deadline/retry "
                    "and baseline with that rationale",
                    src_lines))
    return out


# ---------------------------------------------------------------------------
# QK010 — ad-hoc counter dicts in runtime code
# ---------------------------------------------------------------------------

# the typed Registry itself (and its exporter) legitimately manipulate raw
# count stores; everything else routes through it
ADHOC_COUNTER_EXEMPT_PREFIXES = ("quokka_tpu/obs/",)
# receiver names that mark a dict as a metrics store
_COUNTERISH_TOKENS = ("counter", "metric", "stat", "count", "hit", "miss")


def _counterish(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return any(tok in low for tok in _COUNTERISH_TOKENS)


def _sub_base_name(node: ast.AST) -> Optional[str]:
    """The base identifier of a subscript target: ``stats`` for
    ``stats[k]``, ``_hits`` for ``self._hits[k]``, dotted tail otherwise."""
    if not isinstance(node, ast.Subscript):
        return None
    base = node.value
    d = _dotted(base)
    if d is not None:
        return d.rsplit(".", 1)[-1]
    return None


def check_adhoc_counter_dict(tree: ast.Module, path: str, rel: str,
                             src_lines: Sequence[str]) -> List[Finding]:
    """Runtime code must not grow hand-rolled counter dicts: they are
    invisible to the Prometheus exporter (obs/export.py), to bench's
    counter snapshot and to /status, they race without the Registry lock,
    and every one eventually grows its own flush/reset idiom.  Flags the
    two counter-increment shapes on counter-named subscript bases:

    - ``stats["hits"] += n`` (AugAssign-Add on a subscript);
    - ``stats[k] = stats.get(k, 0) + n`` (read-modify-write via .get).

    The typed Registry (quokka_tpu/obs/metrics.py) is exempt — it is what
    the rule points at.  Pre-existing stores carry baseline rationales.
    """
    if rel.replace("\\", "/").startswith(ADHOC_COUNTER_EXEMPT_PREFIXES):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        hit = None
        if (isinstance(node, ast.AugAssign)
                and isinstance(node.op, (ast.Add, ast.Sub))
                and isinstance(node.target, ast.Subscript)):
            base = _sub_base_name(node.target)
            if _counterish(base):
                op = "+=" if isinstance(node.op, ast.Add) else "-="
                hit = (node, base, f"'{base}[...] {op} ...'")
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)):
            base = _sub_base_name(node.targets[0])
            if _counterish(base):
                for sub in ast.walk(node.value):
                    d = (_dotted(sub.func.value)
                         if isinstance(sub, ast.Call)
                         and isinstance(sub.func, ast.Attribute) else None)
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "get"
                            and d is not None
                            and d.rsplit(".", 1)[-1] == base
                            and isinstance(node.value, ast.BinOp)
                            and isinstance(node.value.op, ast.Add)):
                        hit = (node, base,
                               f"'{base}[k] = {base}.get(k, ...) + ...'")
                        break
        if hit is not None:
            n, base, shape = hit
            out.append(_mk(
                "QK010", "adhoc-counter-dict", path, rel, n,
                _scope_of(tree, n),
                f"{shape} grows an ad-hoc counter dict — route it through "
                "the typed registry (quokka_tpu.obs.REGISTRY: "
                "Counter.inc() for monotone counts, Gauge.set() for "
                "up-and-down quantities) so the /metrics exporter, bench "
                "snapshots and stall reports see it, or baseline with a "
                "rationale",
                src_lines))
    return out


# ---------------------------------------------------------------------------
# QK011 — blocking host readbacks on the shuffle push path
# ---------------------------------------------------------------------------

# Function names that ARE the shuffle push path: Engine.push, the partition-
# fn lowering (and the closures it builds), the range splitter and the
# multi-partition kernels.  The rule walks same-module reachability from
# these (simple-name call edges + nested defs), like QK004 does from jit
# entry points.  _spill_one is deliberately NOT an entry: it is the
# background spill worker, whose whole job is an off-critical-path d2h.
_PUSH_PATH_ENTRY_FUNCS = (
    "push", "_partition_fn", "_range_split",
    "split_by_partition", "partition_ids",
)
# the readback shapes banned on the push path (host round trips / pipeline
# drains); scalar int()/float() conversions are NOT flagged here — the push
# path legitimately converts host-side plan metadata (e.g. range boundaries)
_PUSH_SYNC_TAILS = ("asarray", "item", "tolist", "device_get",
                    "block_until_ready")


def check_push_path_host_sync(tree: ast.Module, path: str, rel: str,
                              src_lines: Sequence[str],
                              ctx: FlowContext) -> List[Finding]:
    """The shuffle push path (Engine.push -> partition fn -> split kernels)
    is the producer's hot loop: a blocking host readback there drains the
    whole queued device pipeline once per batch per edge — exactly the
    stall the device-resident data plane removed.  Flags np.asarray/.item()/
    .tolist()/device_get/block_until_ready in functions reachable from the
    push-path entry set.  Reachability comes from the flow call graph:
    nested closures count only when they actually ESCAPE into the caller
    (called, returned, stored or passed — the old rule pulled in every
    nested def of an entry unconditionally), and an ``np.asarray(x)`` whose
    ``x.copy_to_host_async()`` was dispatched earlier in the same function
    is an overlapped transfer, not a pipeline drain."""
    mt = ctx.module_table(rel)
    if mt is None:
        return []
    entries = [fi.fid for fi in mt.functions.values()
               if fi.name in _PUSH_PATH_ENTRY_FUNCS]
    if not entries:
        return []

    out: List[Finding] = []
    for fid in sorted(_module_reachable(ctx, mt, entries)):
        fi = ctx.funcs[fid]
        for node in FlowContext._own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                # chained-call receivers (x.sum().item()) defeat _dotted;
                # the attribute tail alone decides for the no-base shapes
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _PUSH_SYNC_TAILS
                        and node.func.attr != "asarray"):
                    d = f"...{node.func.attr}"
                    tail = node.func.attr
                else:
                    continue
            else:
                base, _, tail = d.rpartition(".")
                if tail not in _PUSH_SYNC_TAILS:
                    continue
                # jnp.asarray is an h2d upload, not a readback; np/numpy/
                # bare asarray (and any-receiver .item()/.tolist()/
                # device_get/block_until_ready) are the blocking shapes
                if tail == "asarray" and base not in ("np", "numpy", "onp",
                                                      ""):
                    continue
                # def-use: the d2h copy of this local was already dispatched
                # asynchronously earlier in the function — materializing it
                # here overlaps the device pipeline instead of draining it
                if (tail == "asarray" and len(node.args) == 1
                        and isinstance(node.args[0], ast.Name)
                        and FlowContext.async_copy_started(
                            fi.node, node.args[0].id, node.lineno)):
                    continue
            scope = _scope_of(tree, node)
            out.append(_mk(
                "QK011", "push-path-host-sync", path, rel, node, scope,
                f"'{d}(...)' inside '{scope}' (reachable from the shuffle "
                "push path) blocks on a device->host readback, draining "
                "the queued pipeline once per batch per edge — keep the "
                "push path sync-free (async counts / masked views / "
                "background spill), or baseline with a rationale",
                src_lines))
    return out


check_push_path_host_sync._needs_flow = True


# ---------------------------------------------------------------------------
# QK012 — jit cache keys built from raw (un-bucketed) batch lengths
# ---------------------------------------------------------------------------

# the one module allowed to turn raw lengths into key material
_SIGKEY_EXEMPT_SUFFIX = "ops/sigkey.py"
# receivers that are program/kernel caches: .get()/subscript on these with
# a raw length inside the key is the flagged shape
_PROGRAM_CACHE_NAMES = ("PROGRAMS", "CACHE", "CACHES")


def _raw_len_in(node: ast.AST) -> Optional[str]:
    """'.padded_len' / '.shape[0]' when the expression embeds a raw batch
    length, else None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "padded_len":
            return ".padded_len"
        if (isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "shape"):
            return ".shape[...]"
    return None


def _cacheish(name: Optional[str]) -> bool:
    return name is not None and any(
        name.upper().endswith(s) for s in _PROGRAM_CACHE_NAMES)


def check_raw_len_cache_key(tree: ast.Module, path: str, rel: str,
                            src_lines: Sequence[str]) -> List[Finding]:
    """The compile plane's whole premise is ONE canonical key space: a jit
    cache key built from a raw batch length fragments per 2x rung and per
    call site, exactly the 11-15-compiles-per-query warmup BENCH_r05
    measured.  Flags, outside ops/sigkey.py: (a) sig/key-named tuples
    embedding .padded_len or .shape[...], (b) .get()/subscript access on
    *_PROGRAMS/*_CACHE receivers whose key embeds one.  Canonical lengths
    come from sigkey.bucket_rows/batch_sig/aval_sig/make_key."""
    if rel.replace("\\", "/").endswith(_SIGKEY_EXEMPT_SUFFIX):
        return []
    out: List[Finding] = []

    def _flag(node: ast.AST, what: str, shape: str) -> None:
        out.append(_mk(
            "QK012", "raw-len-cache-key", path, rel, node,
            _scope_of(tree, node),
            f"{shape} builds a jit cache key from a raw (un-bucketed) "
            f"batch length ({what}) — every raw length fragments the "
            "compile space per 2x rung; derive key dimensions through "
            "quokka_tpu.ops.sigkey (bucket_rows / batch_sig / aval_sig / "
            "make_key), or baseline with a rationale",
            src_lines))

    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            tname = node.targets[0].id.lower()
            if (("sig" in tname or tname.endswith("key"))
                    and isinstance(node.value, ast.Tuple)):
                what = _raw_len_in(node.value)
                if what is not None:
                    _flag(node, what, f"'{node.targets[0].id} = (...)'")
            # subscript-store into a program cache: _CACHE[(... len ...)] = fn
            continue
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "get":
                recv = _dotted(node.func.value)
                if _cacheish(recv) and node.args:
                    what = _raw_len_in(node.args[0])
                    if what is not None:
                        _flag(node, what, f"'{recv}.get(...)'")
            continue
        if isinstance(node, ast.Subscript):
            recv = _dotted(node.value)
            if _cacheish(recv):
                what = _raw_len_in(node.slice)
                if what is not None:
                    _flag(node, what, f"'{recv}[...]'")
    return out


# ---------------------------------------------------------------------------
# QK013 — platform probes / platform-string gates outside the strategy matrix
# ---------------------------------------------------------------------------

# the two modules allowed to ask "what backend am I on": the strategy matrix
# (which turns the answer into a calibrated, recorded kernel choice) and
# config.py (its delegates + dtype policy)
_PLATFORM_EXEMPT_SUFFIXES = ("ops/strategy.py", "/config.py")
_PLATFORM_LITERALS = {"cpu", "gpu", "tpu", "cuda", "rocm"}
_PLATFORM_PROBE_CALLS = ("default_backend", "_platform")


def check_platform_gate(tree: ast.Module, path: str, rel: str,
                        src_lines: Sequence[str]) -> List[Finding]:
    """Flags, outside ops/strategy.py + config.py: (a) direct backend
    probes (``jax.default_backend()``, ``config._platform()``), (b)
    comparisons of a platform/backend-named expression against a platform
    string literal.  Kernel choices keyed on the platform must route
    through the strategy matrix; non-strategy uses (cache namespacing)
    carry baseline rationales."""
    r = rel.replace("\\", "/")
    if r.endswith(_PLATFORM_EXEMPT_SUFFIXES) or r == "config.py":
        return []
    out: List[Finding] = []
    flagged: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name is None:
            continue
        last = name.rsplit(".", 1)[-1]
        if last in _PLATFORM_PROBE_CALLS:
            flagged.add(id(node))
            out.append(_mk(
                "QK013", "platform-gate", path, rel, node,
                _scope_of(tree, node),
                f"backend probe '{name}(...)' outside the strategy matrix "
                "— per-backend kernel decisions belong in "
                "quokka_tpu.ops.strategy (choice()/calibrate(), recorded "
                "via note_used) so the bench can verify what actually ran; "
                "non-strategy uses baseline with a rationale",
                src_lines))
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Compare) and len(node.comparators) == 1):
            continue
        sides = (node.left, node.comparators[0])
        lit = next(
            (s for s in sides
             if isinstance(s, ast.Constant) and isinstance(s.value, str)
             and s.value.lower() in _PLATFORM_LITERALS), None)
        if lit is None:
            continue
        other = sides[0] if lit is sides[1] else sides[1]
        if any(id(x) in flagged for x in ast.walk(other)):
            continue  # the probe call inside is already its own finding
        mention = _dotted(other)
        if mention is None and isinstance(other, ast.Call):
            mention = _dotted(other.func)
        txt = (mention or "").lower()
        if "platform" in txt or "backend" in txt:
            out.append(_mk(
                "QK013", "platform-gate", path, rel, node,
                _scope_of(tree, node),
                f"platform-string gate ('{mention}' vs "
                f"{lit.value!r}) outside the strategy matrix — route the "
                "decision through quokka_tpu.ops.strategy.choice() or "
                "baseline with a rationale",
                src_lines))
    return out


# ---------------------------------------------------------------------------
# QK018 — eager device allocations outside the ledgered choke points
# ---------------------------------------------------------------------------

# where the rule applies: the code that creates device/host residency the
# memory ledger must see (obs/memplane.py).  ops/ is exempt — the bridge
# and kernels are themselves the ledgered helpers — as are tests.
_QK018_SCOPED_DIRS = ("quokka_tpu/runtime/", "quokka_tpu/executors/",
                      "quokka_tpu/streaming/", "quokka_tpu/service/")
_QK018_CONSTRUCTORS = {
    "array", "asarray", "zeros", "ones", "full", "arange", "empty",
    "linspace", "zeros_like", "ones_like", "full_like", "empty_like",
}
_QK018_JNP_BASES = ("jnp", "jax.numpy")


def _qk018_traced_functions(tree: ast.Module) -> List[ast.AST]:
    """Function nodes whose bodies trace under jit — decorated with a jit
    maker (directly or via functools.partial), or wrapped by a ``jit(fn)``
    call anywhere in the module.  ``jnp`` constructors there are lazy
    tracer ops the compiler fuses, not eager device allocations."""
    jit_wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_maker(_dotted(node.func)):
            for a in node.args[:1]:
                if isinstance(a, ast.Name):
                    jit_wrapped.add(a.id)
    out: List[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in jit_wrapped:
            out.append(node)
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = _dotted(target) or ""
            if _is_jit_maker(d):
                out.append(node)
                break
            if (d.rsplit(".", 1)[-1] == "partial"
                    and isinstance(dec, ast.Call) and dec.args
                    and _is_jit_maker(_dotted(dec.args[0]))):
                out.append(node)
                break
    return out


def check_unledgered_device_alloc(tree: ast.Module, path: str, rel: str,
                                  src_lines: Sequence[str]) -> List[Finding]:
    """Flags eager device allocations — ``jax.device_put`` and ``jnp.*``
    array constructors on non-traced paths — in runtime/executors/
    streaming/service code.  Device residency must be created through the
    ledgered choke points (ops/bridge, BatchCache, ScanCache, HBQ) so the
    memory ledger (obs/memplane.py) accounts for it; a raw allocation here
    is bytes the per-query footprints, the OOM forensics bundle and
    measured admission never see.  Deliberate small allocations baseline
    with a rationale (shrink-only contract)."""
    r = rel.replace("\\", "/")
    base = r.rsplit("/", 1)[-1]
    if not (any(d in r for d in _QK018_SCOPED_DIRS)
            or base.startswith("qk018")):
        return []
    exempt: Set[int] = set()
    for fn in _qk018_traced_functions(tree):
        for sub in ast.walk(fn):
            exempt.add(id(sub))
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) in exempt:
            continue
        name = _dotted(node.func)
        if name is None:
            continue
        head, _, attr = name.rpartition(".")
        hit = None
        if attr == "device_put" and head in ("jax", ""):
            hit = f"'{name}(...)'"
        elif attr in _QK018_CONSTRUCTORS and head in _QK018_JNP_BASES:
            hit = f"array constructor '{name}(...)'"
        if hit is None:
            continue
        out.append(_mk(
            "QK018", "unledgered-device-alloc", path, rel, node,
            _scope_of(tree, node),
            f"eager device allocation {hit} outside the ledgered choke "
            "points — this residency is invisible to the memory ledger "
            "(obs/memplane.py): route it through the bridge/cache/HBQ "
            "helpers that LEDGER.track() it, or baseline with a rationale",
            src_lines))
    return out


# ---------------------------------------------------------------------------
# QK019 — ad-hoc per-operator row/byte tallies outside the opstats ledger
# ---------------------------------------------------------------------------

# where the rule applies: the code that moves operator rows/bytes the
# EXPLAIN ANALYZE ledger (obs/opstats.py) must see.  obs/ is exempt — the
# ledger and its exporter are what the rule points at.
_QK019_SCOPED_DIRS = ("quokka_tpu/runtime/", "quokka_tpu/executors/",
                      "quokka_tpu/streaming/", "quokka_tpu/service/")
_QK019_EXEMPT_PREFIXES = ("quokka_tpu/obs/",)
# the ledger's field vocabulary, matched EXACTLY (modulo leading
# underscores): bare ``rows``, ``_build_rows``, ``pending_rows`` and
# friends are operational state — buffers a channel drains — not
# statistics, and substring matching would drown the rule in them.
_QK019_STAT_NAMES = {
    "rows_in", "rows_out", "bytes_in", "bytes_out", "batches_in",
    "batches_out", "rows_seen", "bytes_seen", "rows_emitted",
    "rows_delivered", "total_rows", "total_bytes_in", "total_bytes_out",
    "dispatches", "padded_in", "rows_unknown",
}


def _qk019_stat_name(node: ast.AST) -> Optional[str]:
    """The stats-shaped identifier behind a tally target: an attribute
    name, a bare name, or a string-literal subscript key."""
    if isinstance(node, ast.Attribute):
        n = node.attr
    elif isinstance(node, ast.Name):
        n = node.id
    elif isinstance(node, ast.Subscript) \
            and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, str):
        n = node.slice.value
    else:
        return None
    return n if n.lstrip("_") in _QK019_STAT_NAMES else None


def check_adhoc_operator_tally(tree: ast.Module, path: str, rel: str,
                               src_lines: Sequence[str]) -> List[Finding]:
    """Flags hand-grown per-operator row/byte statistics — increments of
    stat-vocabulary names (``rows_in``, ``bytes_out``, ...) as attributes,
    locals, or string-keyed dict slots — in runtime/executors/streaming/
    service code.  Operator cardinality accounting must flow through the
    opstats ledger (obs/opstats.py) so EXPLAIN ANALYZE, the skew report,
    /status and the persisted cardinality profile all read ONE set of
    numbers; a private tally is a second bookkeeping that drifts from the
    one admission and calibration trust.  Deliberate exceptions baseline
    with a rationale (shrink-only contract)."""
    r = rel.replace("\\", "/")
    base = r.rsplit("/", 1)[-1]
    if r.startswith(_QK019_EXEMPT_PREFIXES):
        return []
    if not (any(d in r for d in _QK019_SCOPED_DIRS)
            or base.startswith("qk019")):
        return []
    out: List[Finding] = []
    for node in ast.walk(tree):
        hit = None
        if (isinstance(node, ast.AugAssign)
                and isinstance(node.op, (ast.Add, ast.Sub))):
            name = _qk019_stat_name(node.target)
            if name is not None:
                op = "+=" if isinstance(node.op, ast.Add) else "-="
                hit = (node, f"'... {name} {op} ...'")
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)):
            # t["rows_in"] = t.get("rows_in", 0) + n — the RMW spelling
            name = _qk019_stat_name(node.targets[0])
            if (name is not None and isinstance(node.value, ast.BinOp)
                    and isinstance(node.value.op, ast.Add)
                    and any(isinstance(s, ast.Call)
                            and isinstance(s.func, ast.Attribute)
                            and s.func.attr == "get"
                            for s in ast.walk(node.value))):
                hit = (node, f"'[{name!r}] = .get({name!r}, ...) + ...'")
        if hit is not None:
            n, shape = hit
            out.append(_mk(
                "QK019", "adhoc-operator-tally", path, rel, n,
                _scope_of(tree, n),
                f"{shape} grows an ad-hoc per-operator row/byte tally — "
                "route operator statistics through the opstats ledger "
                "(quokka_tpu.obs.opstats: the engine's scan/exec_in/"
                "exec_out record paths, or opstats.note() from inside an "
                "executor) so EXPLAIN ANALYZE, skew detection and the "
                "cardinality profile see it, or baseline with a rationale",
                src_lines))
    return out


# ---------------------------------------------------------------------------
# QK020 — per-batch chains of single-expression program dispatches
# ---------------------------------------------------------------------------

# where the rule applies: executor bodies — the code the optimizer's
# whole-stage fusion rewrites past.  ops/ is exempt: the fused builders
# themselves own the deliberate expression-at-a-time fallback paths.
_QK020_SCOPED_DIRS = ("quokka_tpu/executors/",)
# each of these launches ONE jit program over the whole batch
# (expr_compile compiles per expression); a chain of them per batch is
# exactly what ops/stagefuse.FusedElementwise / the ops/fuse.py builders
# collapse into a single program dispatch.
_QK020_DISPATCH_CALLS = ("evaluate_predicate", "evaluate_to_column")
# straight-line dispatches tolerated per function body before the chain
# counts as fusible (two ~= one predicate + one projection; a third says
# "pipeline of expression programs" rather than "a kernel and its guard")
_QK020_MAX_STRAIGHT = 2


def _qk020_dispatch_name(node: ast.Call) -> Optional[str]:
    """'evaluate_predicate' / 'evaluate_to_column' behind a call, matched
    bare or attribute-qualified (``expr_compile.evaluate_to_column``)."""
    d = _dotted(node.func)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1]
    return last if last in _QK020_DISPATCH_CALLS else None


def check_multi_program_chain(tree: ast.Module, path: str, rel: str,
                              src_lines: Sequence[str]) -> List[Finding]:
    """Flags executor bodies that dispatch a CHAIN of single-expression jit
    programs per batch: ``evaluate_predicate``/``evaluate_to_column`` calls
    inside a per-expression ``for``/``while`` loop (one program launch per
    expression per batch), or more than ``_QK020_MAX_STRAIGHT`` straight-line
    calls in one function.  Each call compiles and launches its own program
    over the whole padded batch; a linear chain of them re-reads every
    column from HBM per step — the exact dispatch shape whole-stage fusion
    (ops/stagefuse.py, ops/fuse.py) collapses into one program.  Deliberate
    CompileError fallbacks and once-per-query finalize paths baseline with
    a rationale (shrink-only contract)."""
    r = rel.replace("\\", "/")
    base = r.rsplit("/", 1)[-1]
    if not (any(d in r for d in _QK020_SCOPED_DIRS)
            or base.startswith("qk020")):
        return []
    # (owner function, call node, callee, inside-loop?) with the OWNER being
    # the innermost enclosing def — a whole-tree walk per function would
    # double-count calls under nested defs
    hits: List[Tuple[ast.AST, ast.Call, str, bool]] = []

    def visit(node: ast.AST, fn: Optional[ast.AST], loop_depth: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn, loop_depth = node, 0
        elif isinstance(node, (ast.For, ast.While)):
            loop_depth += 1
        elif isinstance(node, ast.Call) and fn is not None:
            nm = _qk020_dispatch_name(node)
            if nm is not None:
                hits.append((fn, node, nm, loop_depth > 0))
        for child in ast.iter_child_nodes(node):
            visit(child, fn, loop_depth)

    visit(tree, None, 0)
    out: List[Finding] = []
    straight_seen: Dict[int, int] = {}
    for fn, call, nm, looped in hits:
        if looped:
            out.append(_mk(
                "QK020", "multi-program-chain", path, rel, call,
                _scope_of(tree, call),
                f"'{nm}(...)' inside a loop dispatches one jit program per "
                "expression per batch — lower the chain through a fused "
                "single-program builder (ops/fuse.py Prepass idiom) or let "
                "stage fusion collapse it (ops/stagefuse.FusedElementwise), "
                "or baseline with a rationale",
                src_lines))
            continue
        n = straight_seen.get(id(fn), 0) + 1
        straight_seen[id(fn)] = n
        if n > _QK020_MAX_STRAIGHT:
            out.append(_mk(
                "QK020", "multi-program-chain", path, rel, call,
                _scope_of(tree, call),
                f"'{nm}(...)' is straight-line program dispatch #{n} in "
                "this body (> " f"{_QK020_MAX_STRAIGHT} per batch) — a "
                "fusible elementwise chain; fold it into one program "
                "(ops/stagefuse.FusedElementwise / ops/fuse.py builders) "
                "or baseline with a rationale",
                src_lines))
    return out


# ---------------------------------------------------------------------------
# QK025 — blocking I/O while holding an obs-plane lock
# ---------------------------------------------------------------------------

# where the rule applies: the observability plane.  Its locks (the metrics
# Registry's, the opstats ledger's, the history ring's, the alert engine's,
# the progress tracker's) sit on every hot-path counter increment; blocking
# under any of them stalls all engine threads at once.
_QK025_SCOPED_DIRS = ("quokka_tpu/obs/",)


def _qk025_blocking_name(node: ast.Call) -> Optional[str]:
    """The dotted name when `node` is a blocking I/O call: file opens,
    sleeps, socket construction/connection, urllib fetches.  Condition/
    event ``wait`` is deliberately NOT here — waiting on a condition under
    its own lock is the correct pattern, not a defect."""
    d = _dotted(node.func)
    if d is None:
        return None
    base, _, tail = d.rpartition(".")
    if tail == "open" and base in ("", "io", "os", "gzip"):
        return d
    if tail == "sleep" and base in ("", "time"):
        return d
    if tail == "urlopen":
        return d
    if base == "socket" or base.endswith(".socket") \
            or tail == "create_connection":
        return d
    return None


def _qk025_lock_name(item: ast.withitem) -> Optional[str]:
    """The dotted lock name when a with-item acquires an obs-style lock
    (last path segment ends in ``_lock``: ``self._lock``,
    ``_sampler_lock``, ``REGISTRY._lock``)."""
    d = _dotted(item.context_expr)
    if d is not None and d.rsplit(".", 1)[-1].endswith("_lock"):
        return d
    return None


def _qk025_body_calls(stmts: Sequence[ast.stmt]) -> Iterable[ast.Call]:
    """Every call executed WITHIN the with-body's dynamic extent: nested
    defs/lambdas are skipped — their bodies run later, after release."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _qk025_reached_blocking(ctx: FlowContext, tgt) -> Optional[Tuple[str,
                                                                     str]]:
    """(blocking dotted name, owning qualname) for the first blocking call
    in `tgt`'s same-module call-graph closure, else None."""
    tmt = ctx.modules.get(tgt.module)
    if tmt is None:
        return None
    for fid in sorted(_module_reachable(ctx, tmt, [tgt.fid])):
        fi = ctx.funcs[fid]
        for node in FlowContext._own_nodes(fi.node):
            if isinstance(node, ast.Call):
                b = _qk025_blocking_name(node)
                if b is not None:
                    return b, fi.qualname
    return None


def check_obs_lock_blocking_io(tree: ast.Module, path: str, rel: str,
                               src_lines: Sequence[str],
                               ctx: FlowContext) -> List[Finding]:
    """Flags blocking I/O reachable while an obs-plane ``*_lock`` is held:
    ``open``/``time.sleep``/socket/``urlopen`` either directly inside a
    ``with <lock>:`` body, or inside a helper the body calls (same-module
    call-graph closure via the flow engine).  The registry lock is on the
    increment path of every operator in every engine thread — one /status
    scrape doing file I/O under it would stall the whole data plane.  The
    correct shape copies the figures under the lock and performs the I/O
    outside (``HistoryRing.record``, ``ProgressTracker._profile_for``).
    Nested defs under the lock are exempt: their bodies run after release."""
    r = rel.replace("\\", "/")
    base = r.rsplit("/", 1)[-1]
    if not (any(d in r for d in _QK025_SCOPED_DIRS)
            or base.startswith("qk025")):
        return []
    mt = ctx.module_table(rel)
    if mt is None:
        return []
    out: List[Finding] = []
    for fi in mt.functions.values():
        for node in FlowContext._own_nodes(fi.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = [nm for nm in map(_qk025_lock_name, node.items)
                     if nm is not None]
            if not locks:
                continue
            for call in _qk025_body_calls(node.body):
                d = _qk025_blocking_name(call)
                if d is not None:
                    out.append(_mk(
                        "QK025", "obs-lock-blocking-io", path, rel, call,
                        _scope_of(tree, call),
                        f"'{d}(...)' runs while holding '{locks[0]}' — "
                        "blocking I/O under an obs lock stalls every "
                        "thread incrementing through it; copy the figures "
                        "under the lock and do the I/O outside, or "
                        "baseline with a rationale",
                        src_lines))
                    continue
                for tgt in ctx._call_targets(mt, fi, call):
                    hit = _qk025_reached_blocking(ctx, tgt)
                    if hit is not None:
                        blk, owner = hit
                        cd = _dotted(call.func) or call.func.__class__.__name__
                        out.append(_mk(
                            "QK025", "obs-lock-blocking-io", path, rel,
                            call, _scope_of(tree, call),
                            f"'{cd}(...)' called while holding "
                            f"'{locks[0]}' reaches blocking '{blk}(...)' "
                            f"(in '{owner}') — hoist the helper call out "
                            "of the critical section, or baseline with a "
                            "rationale",
                            src_lines))
                        break
    return out


check_obs_lock_blocking_io._needs_flow = True


# ---------------------------------------------------------------------------
# QK027 — ad-hoc wall timing outside the obs plane
# ---------------------------------------------------------------------------

# the clock calls whose subtraction means "someone hand-rolled a timer"
_QK027_TIMER_CALLS = ("time.time", "time.perf_counter", "perf_counter")
# the obs plane OWNS timing (spans, opstats, critpath, history, devprof);
# bench.py is the other sanctioned owner but lives outside quokka_tpu/ and
# is never scanned
_QK027_EXEMPT_DIRS = ("quokka_tpu/obs/",)


def _qk027_is_timer_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _dotted(node.func) in _QK027_TIMER_CALLS)


def _qk027_own_nodes(scope: ast.AST):
    """The scope's own statements/expressions, not descending into nested
    function bodies (their clock names are a different scope)."""
    stack = list(scope.body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def check_adhoc_wall_timing(tree: ast.Module, path: str, rel: str,
                            src_lines: Sequence[str]) -> List[Finding]:
    """Flags bare wall-clock deltas used for timing outside the obs plane:
    a name assigned from ``time.time()``/``time.perf_counter()`` and later
    subtracted (``t1 - t0``, ``time.perf_counter() - t0``).  A hand-rolled
    timer is invisible to the span aggregator (``obs/spans.py``), the
    flight recorder and the bench breakdown — the measurement exists only
    in whatever local variable it landed in, which is exactly how the
    engine accumulated three private timing idioms before PR 13.  Route
    durations through ``obs.span()``/``obs.spans.add()`` (they also land
    in the merged timeline) or baseline deliberate low-level sites with a
    rationale.  Deadline arithmetic (``deadline - time.monotonic()``) is
    not flagged: both operands must be clock readings."""
    r = rel.replace("\\", "/")
    base = r.rsplit("/", 1)[-1]
    if not base.startswith("qk027"):
        if ("quokka_tpu/" not in r
                or any(d in r for d in _QK027_EXEMPT_DIRS)):
            return []
    out: List[Finding] = []
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        own = list(_qk027_own_nodes(scope))
        clock_names: Set[str] = set()
        for n in own:
            if isinstance(n, ast.Assign) and _qk027_is_timer_call(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        clock_names.add(t.id)
        if not clock_names and not any(_qk027_is_timer_call(n)
                                       for n in own):
            continue

        def _clockish(x: ast.AST) -> bool:
            return (_qk027_is_timer_call(x)
                    or (isinstance(x, ast.Name) and x.id in clock_names))

        for n in own:
            if (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub)
                    and _clockish(n.left) and _clockish(n.right)):
                out.append(_mk(
                    "QK027", "adhoc-wall-timing", path, rel, n,
                    _scope_of(tree, n),
                    "bare wall-clock delta — a hand-rolled timer is "
                    "invisible to the span aggregator, the flight "
                    "recorder and the bench breakdown; route the "
                    "duration through obs.span()/obs.spans.add() "
                    "(obs/spans.py), or baseline with a rationale",
                    src_lines))
    return out


RULES = (
    check_module_level_jit,
    check_import_time_side_effects,
    check_private_api,
    check_host_sync_in_jit,
    check_unlocked_shared_state,
    check_swallowed_exceptions,
    check_bare_print,
    check_global_config_mutation,
    check_unbounded_io,
    check_adhoc_counter_dict,
    check_push_path_host_sync,
    check_raw_len_cache_key,
    check_platform_gate,
    check_unledgered_device_alloc,
    check_adhoc_operator_tally,
    check_multi_program_chain,
    check_obs_lock_blocking_io,
    check_adhoc_wall_timing,
)


def run_rules(source: str, path: str, rel: str,
              ctx: Optional[FlowContext] = None) -> List[Finding]:
    """ctx: the whole-file-set flow context built by ``lint.run_lint``;
    when absent (single-file callers, fixtures) a one-module context is
    built here so the flow-aware rules behave identically — just without
    cross-module knowledge."""
    if ctx is not None and ctx.module_table(rel) is not None:
        # reuse the context's tree: flow tables are keyed by node identity
        tree = ctx.module_table(rel).tree
    else:
        tree = ast.parse(source, filename=path)
        ctx = FlowContext()
        ctx.add_module(rel, tree)
        ctx.finalize()
    src_lines = source.splitlines()
    findings: List[Finding] = []
    for rule in RULES:
        if getattr(rule, "_needs_flow", False):
            findings.extend(rule(tree, path, rel, src_lines, ctx))
        else:
            findings.extend(rule(tree, path, rel, src_lines))
    findings.sort(key=lambda f: (f.line, f.rule))
    # occurrence-number duplicate (rule, scope, snippet) triples so baseline
    # keys are unique and stable in file order
    seen: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        k = (f.rule, f.scope, f.snippet)
        f.occurrence = seen.get(k, 0)
        seen[k] = f.occurrence + 1
    return findings
