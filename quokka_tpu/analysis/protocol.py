"""Control-store protocol verifier (rules QK014-QK017).

    python -m quokka_tpu.analysis.protocol quokka_tpu/
    python -m quokka_tpu.analysis.protocol quokka_tpu/ --matrix

The ControlStore table taxonomy (runtime/tables.py) is the contract the
recovery protocol reasons over.  This verifier extracts every store
operation site (``tset``/``tget``/``tappend``/``tape_append``/``sadd``/
``tdel``/``srem``/``tape_trim``/``drop_namespace``/``ntt_*``) into a
per-(table, key-class) writer/reader/GC matrix and statically checks the
protocol invariants over it:

  QK014  dead write / namespace escape — every written (table, key-class)
         must have a reader somewhere in the tree (``drop_namespace`` is a
         sweep, not a reader: state nobody replays is protocol rot), and
         per-query keys must go through the NamespacedStore ``_k`` wrapping
         (a raw root-store write escapes ``drop_namespace``'s sweep).
  QK015  growth needs GC — key-classes that grow with the stream (append-
         valued rows, per-seq keys, seq-membership sets) must have an
         in-run GC site (``tdel``/``srem``/``tape_trim``/``ntt_pop``);
         the end-of-query ``drop_namespace`` sweep does NOT satisfy this
         (a standing query never ends).
  QK016  lock-order acyclicity — locks wrapped by ``sanitize.maybe_
         instrument`` form a static held->acquired graph (nested ``with``
         blocks plus under-lock calls into the other lock class's
         acquiring methods); any cycle is the two-lock deadlock precursor
         the runtime recorder reports dynamically.
  QK017  checkpoint-frontier atomicity — the checkpoint commit triple
         (``LCT`` tset, ``("ckpts", ...)`` history tappend, ``IRT``
         frontier tset) must land in ONE ``store.transaction()`` block;
         a crash between torn halves leaves the rewind planner a frontier
         with no covering history entry (monotonicity breaks).

Unlike the lint plane (``analysis/lint.py``) there is NO baseline: the
verifier must run clean on the tree, and exits nonzero otherwise.  Scope:
the store's *users* — ``runtime/tables.py`` (the implementation; its
NamespacedStore delegation is checked separately for ``_k`` discipline),
``runtime/store_service.py``/``runtime/rpc.py`` (serving/client
delegation), and ``analysis/`` (this plane models the protocol, it does
not participate) are excluded from matrix extraction.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from quokka_tpu.analysis.lint import _relpath, iter_py_files
from quokka_tpu.analysis.rules import Finding

# -- store-surface taxonomy ---------------------------------------------------

_WRITE_METHODS = {"tset", "tappend", "tape_append", "sadd", "ntt_push"}
_READ_METHODS = {"tget", "titems", "tlen", "smembers", "scontains",
                 "tape_slice", "tape_len", "ntt_pop", "ntt_peek_all",
                 "ntt_len", "ntt_total"}
_GC_METHODS = {"tdel", "srem", "tape_trim", "ntt_remove_exec",
               "ntt_remove_channel", "drop_namespace"}
_TAPE_METHODS = {"tape_append", "tape_slice", "tape_len", "tape_trim"}
_NTT_METHODS = {"ntt_push", "ntt_pop", "ntt_peek_all", "ntt_len",
                "ntt_total", "ntt_remove_exec", "ntt_remove_channel"}

# receivers that denote a store handle (self.store, g.store, cs, _root, ...)
_STORE_RECEIVER = re.compile(r"(store$|^cs$|^_root$)")
# the ROOT store by name: per-query table keys must not flow through it
_ROOT_RECEIVER = re.compile(r"^root_store$")
# namespace-independent root-store surface (engine cleanup path)
_ROOT_OK_METHODS = {"drop_namespace", "namespace", "dump", "close"}

# key components that denote a per-sequence counter: rows keyed by one are
# written once per stream seq/state and grow without bound
_SEQ_NAME = re.compile(r"(^|_)(seq|s|state|pos|nxt)$|seq$")

# modules excluded from matrix extraction (see module docstring)
_EXCLUDE_REL = re.compile(
    r"quokka_tpu/(analysis/|runtime/tables\.py|runtime/store_service\.py"
    r"|runtime/rpc\.py)")

KeyClass = Tuple[str, str, Optional[int]]  # (table, subkey-head, arity)


@dataclass
class StoreOp:
    kind: str               # "write" | "read" | "gc"
    method: str
    keyclass: KeyClass      # ("LT", "ckpts", 3) / ("SWM", "*", 3) / ...
    path: str
    rel: str
    line: int
    scope: str
    snippet: str
    growth: bool = False    # write sites only: grows with the stream
    wildcard: bool = False  # titems/smembers(all)/drop_namespace: whole table


def _receiver_name(expr: ast.AST) -> Optional[str]:
    """Last name component of the call receiver: ``self.store`` -> 'store',
    ``cs`` -> 'cs', ``s.graph.store`` -> 'store'."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_seq_component(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return bool(_SEQ_NAME.search(node.id))
    if isinstance(node, ast.Attribute):
        return bool(_SEQ_NAME.search(node.attr))
    return False


def _classify_key(table: str, key: Optional[ast.AST]) -> KeyClass:
    if key is None:
        return (table, "*", None)
    if isinstance(key, ast.Tuple):
        head = key.elts[0] if key.elts else None
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return (table, head.value, len(key.elts))
        return (table, "*", len(key.elts))
    if isinstance(key, ast.Constant):
        return (table, "*", 1)
    # a Name/Attribute key may hold a tuple of any shape: unknown arity
    return (table, "*", None)


def _classes_match(write: KeyClass, other: KeyClass) -> bool:
    """Does a read/GC site of class `other` cover writes of class `write`?
    Wildcard arity (whole-table ops) covers everything in the table; a
    wildcard head on either side matches same-arity keys (variable vs
    constant tuple heads of the same shape address the same rows)."""
    if write[0] != other[0]:
        return False
    if other[2] is None or write[2] is None:
        return True
    if write[2] != other[2]:
        return False
    return write[1] == other[1] or "*" in (write[1], other[1])


class _SiteCollector(ast.NodeVisitor):
    """One file's store-op sites, with qualified enclosing scopes."""

    def __init__(self, path: str, rel: str, src_lines: List[str]):
        self.path = path
        self.rel = rel
        self.src_lines = src_lines
        self.stack: List[str] = []
        self.ops: List[StoreOp] = []

    def _scope(self) -> str:
        return ".".join(self.stack) or "<module>"

    def _snippet(self, node: ast.AST) -> str:
        i = getattr(node, "lineno", 0) - 1
        return self.src_lines[i].strip() if 0 <= i < len(self.src_lines) else ""

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        method = fn.attr
        recv = _receiver_name(fn.value)
        if recv is None:
            return
        is_store = bool(_STORE_RECEIVER.search(recv)
                        or _ROOT_RECEIVER.search(recv))
        if not is_store:
            return
        kind = ("write" if method in _WRITE_METHODS else
                "read" if method in _READ_METHODS else
                "gc" if method in _GC_METHODS else None)
        if kind is None:
            return
        op = self._classify_call(method, kind, node)
        if op is not None:
            self.ops.append(op)
        # namespace escape: per-query table traffic on the ROOT store
        if (_ROOT_RECEIVER.search(recv)
                and method not in _ROOT_OK_METHODS):
            self.ops.append(self._mk(
                "escape", method, ("<root>", "*", None), node))

    def _mk(self, kind: str, method: str, kc: KeyClass,
            node: ast.AST, **kw) -> StoreOp:
        return StoreOp(kind, method, kc, self.path, self.rel,
                       getattr(node, "lineno", 0), self._scope(),
                       self._snippet(node), **kw)

    def _classify_call(self, method: str, kind: str,
                       node: ast.Call) -> Optional[StoreOp]:
        args = node.args
        if method == "drop_namespace":
            return self._mk(kind, method, ("<all>", "*", None), node,
                            wildcard=True)
        if method in _TAPE_METHODS:
            kc = ("LT", "tape", 3)
            growth = method == "tape_append"
            return self._mk(kind, method, kc, node, growth=growth)
        if method in _NTT_METHODS:
            return self._mk(kind, method, ("NTT", "*", 1), node,
                            growth=(method == "ntt_push"))
        if not args or not (isinstance(args[0], ast.Constant)
                            and isinstance(args[0].value, str)):
            return None  # variable table name: delegation plumbing, skip
        table = args[0].value
        key = args[1] if len(args) > 1 else None
        kc = _classify_key(table, key)
        wildcard = key is None
        growth = False
        if kind == "write":
            if method == "tappend":
                growth = True
            elif method == "tset" and isinstance(key, ast.Tuple) \
                    and key.elts and _is_seq_component(key.elts[-1]):
                growth = True
            elif method == "sadd" and len(args) > 2 \
                    and _is_seq_component(args[2]):
                growth = True
        return self._mk(kind, method, kc, node, growth=growth,
                        wildcard=wildcard)


# -- QK016: static lock-order graph -------------------------------------------

# generic container-method names that would alias dict/set/list calls onto a
# lock class's surface — never edge triggers
_GENERIC_METHODS = {"get", "set", "put", "pop", "add", "items", "keys",
                    "values", "append", "update", "clear", "discard",
                    "remove", "extend", "popleft", "close"}


@dataclass
class _LockClass:
    lock_name: str
    class_name: str
    rel: str
    line: int
    # methods of the class whose body acquires the lock
    acquiring: Set[str] = field(default_factory=set)


def _find_lock_classes(trees: Sequence[Tuple[str, str, ast.Module]]
                       ) -> List[_LockClass]:
    out: List[_LockClass] = []
    for path, rel, tree in trees:
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_name = None
            line = 0
            for n in ast.walk(cls):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "maybe_instrument"
                        and n.args
                        and isinstance(n.args[0], ast.Constant)):
                    lock_name = n.args[0].value
                    line = n.lineno
                    break
            if lock_name is None:
                continue
            lc = _LockClass(lock_name, cls.name, rel, line)
            for m in cls.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if any(_acquires_self_lock(w) for w in ast.walk(m)):
                        lc.acquiring.add(m.name)
            out.append(lc)
    return out


def _acquires_self_lock(node: ast.AST) -> bool:
    """``with self._lock:`` or ``self._lock.acquire()``."""
    if isinstance(node, ast.With):
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) and e.attr == "_lock":
                return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "_lock"):
        return True
    return False


def _lock_edges(trees: Sequence[Tuple[str, str, ast.Module]],
                locks: Sequence[_LockClass]
                ) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
    """(held, acquired) -> (rel, line, scope) witness.  An edge exists when
    code inside a ``with self._lock`` body of lock class A calls a
    distinctive acquiring method of lock class B (or nests B's ``with``)."""
    by_class = {lc.class_name: lc for lc in locks}
    # distinctive method name -> owning lock, minus generic container names
    method_owner: Dict[str, _LockClass] = {}
    for lc in locks:
        for m in lc.acquiring - _GENERIC_METHODS:
            method_owner.setdefault(m, lc)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for path, rel, tree in trees:
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            holder = by_class.get(cls.name)
            if holder is None:
                continue
            for m in cls.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                for w in ast.walk(m):
                    if not (isinstance(w, ast.With)
                            and _acquires_self_lock(w)):
                        continue
                    for n in ast.walk(w):
                        if not (isinstance(n, ast.Call)
                                and isinstance(n.func, ast.Attribute)):
                            continue
                        callee = method_owner.get(n.func.attr)
                        if callee is None \
                                or callee.lock_name == holder.lock_name:
                            continue
                        edges.setdefault(
                            (holder.lock_name, callee.lock_name),
                            (rel, n.lineno, f"{cls.name}.{m.name}"))
    return edges


def _find_cycle(edges: Iterable[Tuple[str, str]]) -> Optional[List[str]]:
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
    state: Dict[str, int] = {}
    trail: List[str] = []

    def dfs(v: str) -> Optional[List[str]]:
        state[v] = 1
        trail.append(v)
        for w in graph.get(v, ()):
            if state.get(w, 0) == 1:
                return trail[trail.index(w):] + [w]
            if state.get(w, 0) == 0:
                c = dfs(w)
                if c:
                    return c
        trail.pop()
        state[v] = 2
        return None

    for v in list(graph):
        if state.get(v, 0) == 0:
            c = dfs(v)
            if c:
                return c
    return None


# -- QK017: checkpoint commit triple ------------------------------------------

def _txn_blocks(tree: ast.Module) -> List[ast.With]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            e = item.context_expr
            if (isinstance(e, ast.Call)
                    and isinstance(e.func, ast.Attribute)
                    and e.func.attr == "transaction"):
                out.append(node)
                break
    return out


def _ckpt_triple_ok(block: ast.With) -> bool:
    has_lct = has_hist = has_irt = False
    for n in ast.walk(block):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute) and n.args):
            continue
        a0 = n.args[0]
        if not (isinstance(a0, ast.Constant) and isinstance(a0.value, str)):
            continue
        if n.func.attr == "tset" and a0.value == "LCT":
            has_lct = True
        elif n.func.attr == "tset" and a0.value == "IRT":
            has_irt = True
        elif n.func.attr == "tappend" and a0.value == "LT" \
                and len(n.args) > 1 and isinstance(n.args[1], ast.Tuple) \
                and n.args[1].elts \
                and isinstance(n.args[1].elts[0], ast.Constant) \
                and n.args[1].elts[0].value == "ckpts":
            has_hist = True
    return has_lct and has_hist and has_irt


def _is_hist_rewrite(block: ast.With) -> bool:
    """A transaction that tdel's the ("ckpts", ...) history before appending
    is the GC prune pattern (drop-and-reappend of the retained suffix), not
    a new checkpoint commit — its tappends are exempt from the triple."""
    for n in ast.walk(block):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "tdel" and n.args
                and isinstance(n.args[0], ast.Constant)
                and n.args[0].value == "LT"
                and len(n.args) > 1 and isinstance(n.args[1], ast.Tuple)
                and n.args[1].elts
                and isinstance(n.args[1].elts[0], ast.Constant)
                and n.args[1].elts[0].value == "ckpts"):
            return True
    return False


def _is_ckpt_commit_site(node: ast.Call) -> Optional[str]:
    """'LCT' for a tset("LCT", ...) site, 'ckpts' for the history tappend."""
    if not (isinstance(node.func, ast.Attribute) and node.args):
        return None
    a0 = node.args[0]
    if not (isinstance(a0, ast.Constant) and isinstance(a0.value, str)):
        return None
    if node.func.attr == "tset" and a0.value == "LCT":
        return "LCT"
    if node.func.attr == "tappend" and a0.value == "LT" \
            and len(node.args) > 1 and isinstance(node.args[1], ast.Tuple) \
            and node.args[1].elts \
            and isinstance(node.args[1].elts[0], ast.Constant) \
            and node.args[1].elts[0].value == "ckpts":
        return "ckpts"
    return None


# -- NamespacedStore _k discipline (QK014 namespace-escape, tables.py side) ---

_KEYED_DELEGATES = {"tset", "tget", "tappend", "tlen", "tdel", "sadd",
                    "smembers", "scontains", "srem", "ntt_push", "ntt_pop",
                    "ntt_remove_exec", "ntt_remove_channel", "ntt_peek_all",
                    "ntt_len"}


def _check_namespace_wrapping(path: str, rel: str, tree: ast.Module,
                              src_lines: List[str]) -> List[Finding]:
    """Inside NamespacedStore, every keyed delegation to ``self._root`` must
    wrap the raw ``key`` parameter through ``self._k`` — a raw pass-through
    writes rows ``drop_namespace`` can never sweep."""
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name == "NamespacedStore"):
            continue
        for n in ast.walk(cls):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _KEYED_DELEGATES
                    and isinstance(n.func.value, ast.Attribute)
                    and n.func.value.attr == "_root"):
                continue
            raw_key = any(isinstance(a, ast.Name) and a.id == "key"
                          for a in n.args)
            wrapped = any(
                isinstance(a, ast.Call)
                and isinstance(a.func, ast.Attribute)
                and a.func.attr == "_k" for a in n.args)
            if raw_key and not wrapped:
                i = n.lineno - 1
                snip = src_lines[i].strip() if i < len(src_lines) else ""
                findings.append(Finding(
                    "QK014", "namespace-escape", path, rel, n.lineno,
                    f"NamespacedStore.{n.func.attr}",
                    f"NamespacedStore.{n.func.attr} passes the raw key to "
                    "the root store — wrap it with self._k() so "
                    "drop_namespace can sweep the row", snip))
    return findings


# -- verifier -----------------------------------------------------------------

def collect_matrix(trees: Sequence[Tuple[str, str, ast.Module, List[str]]]
                   ) -> List[StoreOp]:
    ops: List[StoreOp] = []
    for path, rel, tree, src_lines in trees:
        if _EXCLUDE_REL.search(rel):
            continue
        c = _SiteCollector(path, rel, src_lines)
        c.visit(tree)
        ops.extend(c.ops)
    return ops


def verify(paths: Sequence[str]) -> Tuple[List[Finding], List[StoreOp]]:
    trees: List[Tuple[str, str, ast.Module, List[str]]] = []
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        rel = _relpath(path)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue  # the lint plane owns QK000
        trees.append((path, rel, tree, source.splitlines()))

    ops = collect_matrix(trees)
    writes = [o for o in ops if o.kind == "write"]
    reads = [o for o in ops if o.kind == "read"]
    gcs = [o for o in ops if o.kind == "gc" and o.method != "drop_namespace"]

    # QK014a: dead writes (no reader anywhere for the key-class)
    for w in writes:
        if any(_classes_match(w.keyclass, r.keyclass) for r in reads):
            continue
        findings.append(Finding(
            "QK014", "dead-write", w.path, w.rel, w.line, w.scope,
            f"table {w.keyclass[0]!r} key-class {_fmt_kc(w.keyclass)} is "
            "written here but read nowhere in the tree — state nobody "
            "replays (drop its write, or wire up the reader)", w.snippet))

    # QK014b: root-store escapes + NamespacedStore _k discipline
    for o in ops:
        if o.kind == "escape":
            findings.append(Finding(
                "QK014", "namespace-escape", o.path, o.rel, o.line, o.scope,
                f"per-query store op {o.method!r} on the ROOT store — "
                "route it through store.namespace(query_id) so "
                "drop_namespace can sweep it", o.snippet))
    for path, rel, tree, src_lines in trees:
        if rel.endswith("runtime/tables.py"):
            findings.extend(
                _check_namespace_wrapping(path, rel, tree, src_lines))

    # QK015: growth classes need an in-run GC site
    flagged: Set[KeyClass] = set()
    for w in writes:
        if not w.growth or w.keyclass in flagged:
            continue
        if any(_classes_match(w.keyclass, g.keyclass) for g in gcs):
            continue
        flagged.add(w.keyclass)
        findings.append(Finding(
            "QK015", "growth-needs-gc", w.path, w.rel, w.line, w.scope,
            f"key-class {_fmt_kc(w.keyclass)} grows per stream "
            "seq but has no in-run GC site (tdel/srem/tape_trim) — "
            "unbounded store growth on a standing query "
            "(drop_namespace only sweeps at end-of-query)", w.snippet))

    # QK016: lock-order acyclicity (tables.py/cache.py included — the lock
    # classes ARE the implementation)
    bare = [(p, r, t) for p, r, t, _ in trees]
    locks = _find_lock_classes(bare)
    edges = _lock_edges(bare, locks)
    cycle = _find_cycle(edges.keys())
    if cycle:
        a, b = cycle[0], cycle[1]
        rel, line, scope = edges[(a, b)]
        path = next(p for p, r, _ in bare if r == rel)
        findings.append(Finding(
            "QK016", "lock-order-cycle", path, rel, line, scope,
            "lock-order cycle " + " -> ".join(cycle) + " in the static "
            "held->acquired graph — the two-lock deadlock precursor "
            "sanitize.py's recorder reports dynamically", ""))

    # QK017: checkpoint commit triple atomicity
    for path, rel, tree, src_lines in trees:
        if _EXCLUDE_REL.search(rel):
            continue
        txns = _txn_blocks(tree)
        in_ok_txn: Set[int] = set()
        in_any_txn: Set[int] = set()
        for blk in txns:
            ok = _ckpt_triple_ok(blk) or _is_hist_rewrite(blk)
            for n in ast.walk(blk):
                if isinstance(n, ast.Call) and _is_ckpt_commit_site(n):
                    in_any_txn.add(id(n))
                    if ok:
                        in_ok_txn.add(id(n))
        for n in ast.walk(tree):
            if not isinstance(n, ast.Call):
                continue
            part = _is_ckpt_commit_site(n)
            if part is None or id(n) in in_ok_txn:
                continue
            i = n.lineno - 1
            snip = src_lines[i].strip() if i < len(src_lines) else ""
            where = ("a transaction missing the rest of the triple"
                     if id(n) in in_any_txn else "no transaction at all")
            findings.append(Finding(
                "QK017", "torn-checkpoint", path, rel, n.lineno, "<module>",
                f"checkpoint commit part ({part}) lands in {where} — the "
                "LCT pointer, the (\"ckpts\", ...) history entry and the "
                "IRT frontier must commit in ONE store.transaction() or a "
                "crash tears the frontier from its covering history",
                snip))
    return findings, ops


def _fmt_kc(kc: KeyClass) -> str:
    table, head, arity = kc
    if arity is None:
        return f"{table}[*]"
    parts = ([repr(head)] if head != "*" else []) \
        + ["_"] * (arity - (head != "*"))
    return f"{table}({', '.join(parts)})"


def render_matrix(ops: Sequence[StoreOp]) -> str:
    rows: Dict[KeyClass, Dict[str, int]] = {}
    growth: Set[KeyClass] = set()
    for o in ops:
        if o.kind == "escape":
            continue
        rows.setdefault(o.keyclass, {"write": 0, "read": 0, "gc": 0})
        rows[o.keyclass][o.kind] += 1
        if o.growth:
            growth.add(o.keyclass)
    lines = [f"{'key-class':<28} {'writes':>6} {'reads':>6} {'gc':>4}  notes"]
    for kc in sorted(rows, key=lambda k: (k[0], k[1], k[2] or 0)):
        r = rows[kc]
        note = "growth" if kc in growth else ""
        lines.append(f"{_fmt_kc(kc):<28} {r['write']:>6} {r['read']:>6} "
                     f"{r['gc']:>4}  {note}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m quokka_tpu.analysis.protocol", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories (default: the installed "
                        "quokka_tpu package)")
    p.add_argument("--matrix", action="store_true",
                   help="print the writer/reader/GC matrix and exit")
    args = p.parse_args(argv)
    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]

    findings, ops = verify(paths)
    if args.matrix:
        try:
            print(render_matrix(ops))
        except BrokenPipeError:  # `--matrix | head` closing the pipe early
            sys.stderr.close()
        return 0
    for f in findings:
        print(f.render())
    if findings:
        print(f"{len(findings)} protocol violation(s) — the control-store "
              "protocol has NO baseline; fix the code", file=sys.stderr)
        return 1
    n = len({o.keyclass for o in ops if o.kind != 'escape'})
    print(f"protocol clean: {len(ops)} store-op sites across "
          f"{n} key-classes verified (QK014-QK017)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
