"""Version-guarded shims over private JAX APIs.

The package needs a handful of facts only private JAX surfaces expose (am I
inside a trace?).  Using them ad hoc is how silent breakage happens: when a
jax upgrade removes the symbol, a defensive ``except`` turns the probe into a
wrong constant answer and the bug the probe exists to avoid comes back
(round-5 verdict: ``hashtable._in_trace`` swallowing a missing
``trace_state_clean`` would silently re-enable the nested-pjit dispatch
race).  This module is the single allowed consumer of ``jax._src``/
``jax.core`` (lint rule QK003 exempts it): each shim resolves AT IMPORT TIME
against an explicit candidate list and raises ``ImportError`` with the pinned
version when none resolves — an upgrade that drops the API fails the whole
package loudly at import instead of corrupting behavior at a call site.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax


def _resolve(name: str, candidates: Sequence[Tuple[str, str]]) -> Callable:
    """First resolvable ``(module_path, attr)`` wins; none -> ImportError.

    ``module_path`` is dotted relative to the already-imported ``jax``
    package (e.g. ``"core"`` or ``"_src.core"``).
    """
    for mod_path, attr in candidates:
        obj = jax
        try:
            for part in mod_path.split("."):
                obj = getattr(obj, part)
            fn = getattr(obj, attr)
        except AttributeError:
            continue
        if callable(fn):
            return fn
    raise ImportError(
        f"jax {jax.__version__} exposes none of the known locations of "
        f"{name!r} ({['jax.' + m + '.' + a for m, a in candidates]}); "
        "quokka_tpu.analysis.compat must be taught the new location — do NOT "
        "paper over this with a default, callers rely on a correct answer "
        "(see ops/hashtable._in_trace: a wrong False re-enables a "
        "jit-dispatch race)"
    )


# True when no trace is active (top-level eager context).  Callers use the
# negation to route nested calls to plain (traceable) bodies instead of
# hitting a jit-wrapped object from inside another trace.
trace_state_clean: Callable[[], bool] = _resolve(
    "trace_state_clean",
    (
        ("core", "trace_state_clean"),
        ("_src.core", "trace_state_clean"),
    ),
)


# Size of a named mesh axis from inside a shard_map/pmap trace.  jax >= 0.5
# exposes public ``jax.lax.axis_size``; on older jax the only source is the
# axis-env frame (``jax.core.axis_frame(name).size``).  Shapes derive from
# this (bucket capacity = axis size), so a wrong/defaulted answer would
# build mis-shaped collectives — resolve loudly, never default.
if hasattr(jax.lax, "axis_size"):
    axis_size: Callable = jax.lax.axis_size
else:
    _axis_frame: Callable = _resolve(
        "axis_frame",
        (
            ("core", "axis_frame"),
            ("_src.core", "axis_frame"),
        ),
    )

    def axis_size(axis) -> int:
        frame = _axis_frame(axis)
        # 0.4.37 returns the size itself; other 0.4.x return a frame object
        return frame if isinstance(frame, int) else frame.size
