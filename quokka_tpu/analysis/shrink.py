"""Shared delta-debugging minimizer (Zeller ddmin).

Extracted from the schedule explorer (``analysis/schedex.py``) so the plan
fuzzer (``analysis/planfuzz.py``) shrinks failing op lists with the SAME
proven loop the schedule minimizer uses.  The contract both callers rely on:

- ``failing(items)`` must be a pure predicate — re-runnable, deterministic,
  and tolerant of arbitrary subsequences (schedex replays skip disabled
  actions; the plan builder skips inapplicable ops), and
- the result is 1-minimal: removing ANY single remaining element makes
  ``failing`` return False.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, TypeVar

T = TypeVar("T")


def ddmin(items: Sequence[T], failing: Callable[[List[T]], bool]) -> List[T]:
    """Smallest subsequence of ``items`` still satisfying ``failing``.

    Classic ddmin complement-removal: try dropping chunks of 1/n of the
    current sequence; on success restart with the shrunk sequence, otherwise
    halve the chunk size until single-element removals all fail — at which
    point the result is 1-minimal by construction.  ``items`` is never
    mutated; the caller's ordering is preserved."""
    cur = list(items)
    n = 2
    while len(cur) >= 2:
        chunk = max(1, len(cur) // n)
        shrunk = False
        for i in range(0, len(cur), chunk):
            cand = cur[:i] + cur[i + chunk:]
            if failing(cand):
                cur = cand
                n = max(2, n - 1)
                shrunk = True
                break
        if not shrunk:
            if chunk == 1:
                break
            n = min(len(cur), n * 2)
    return cur
