"""Runtime sanitizer mode (``QK_SANITIZE=1``).

Three instruments, all off unless the env flag is set (zero overhead on the
production path):

- **Deadlock watchdog** (``Watchdog``): every worker's main loop beats a
  per-process watchdog; when the loop stops beating for
  ``QK_SANITIZE_DEADLINE`` seconds (a dispatch blocked on a lock/pipe — the
  round-5 ``test_placement``/``test_distributed`` wedge), the watchdog
  writes a banner + faulthandler dump of EVERY thread's stack to stderr and
  exits the process with ``WATCHDOG_EXIT_CODE``.  The coordinator sees a
  dead worker within its 50 ms poll and raises — the run fails in seconds
  with stacks in hand instead of wedging to a 600 s timeout.

- **Lock-order recorder** (``maybe_instrument``): the runtime's shared locks
  (ControlStore, BatchCache) are wrapped so every acquisition records the
  held->acquired edge per thread; acquiring B while holding A after A-held-
  while-acquiring-B was seen in the other order reports a lock-order
  inversion (the classic two-lock deadlock precursor) to stderr and
  ``lock_inversions()``.

- **Recompile sentinel** (``check_no_recompiles`` / ``recompile_guard``):
  fails a run when real backend compiles happened after warmup — the
  static-shape discipline says a warmed query shape never recompiles, and a
  silent recompile is both a perf cliff and a symptom of an unstable jit
  signature.  bench.py raises on ``real_compiles_timed_runs > 0`` under
  sanitize mode.
"""

from __future__ import annotations

import contextlib
import faulthandler
import io
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Tuple

WATCHDOG_EXIT_CODE = 86  # distinctive: "the sanitizer shot the process"
_DEFAULT_DEADLINE = 120.0  # long jit compiles legitimately stall workers


def enabled() -> bool:
    return os.environ.get("QK_SANITIZE", "").strip().lower() not in (
        "", "0", "false", "no", "off")


def dump_all_stacks(stream) -> None:
    """Every thread's python stack to `stream`.  faulthandler when the
    stream is a real file (signal-safe, exactly what a wedged process
    needs); pure-python fallback for fd-less streams (pytest capture)."""
    try:
        stream.fileno()
        has_fd = True
    except (OSError, AttributeError, ValueError, io.UnsupportedOperation):
        has_fd = False
    if has_fd:
        faulthandler.dump_traceback(file=stream)
        return
    frames = sys._current_frames()
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        stream.write(f"\nThread {t.name} (id {t.ident}):\n")
        if frame is not None:
            stream.write("".join(traceback.format_stack(frame)))


def deadline_seconds() -> float:
    try:
        return float(os.environ.get("QK_SANITIZE_DEADLINE",
                                    _DEFAULT_DEADLINE))
    except ValueError:
        return _DEFAULT_DEADLINE


# ---------------------------------------------------------------------------
# Deadlock watchdog
# ---------------------------------------------------------------------------


class Watchdog:
    """Heartbeat-deadline watchdog.  ``beat()`` from the monitored loop;
    miss the deadline and the process dumps all thread stacks and exits.

    ``_exit`` is injectable for tests (default ``os._exit``: a wedged
    process cannot be trusted to unwind Python frames — some thread holds
    the lock everything is stuck on)."""

    def __init__(self, name: str, deadline: Optional[float] = None,
                 exit_code: int = WATCHDOG_EXIT_CODE,
                 _exit: Callable[[int], None] = os._exit,
                 stream=None):
        self.name = name
        self.deadline = deadline_seconds() if deadline is None else deadline
        self.exit_code = exit_code
        self._exit = _exit
        self._stream = stream
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"qk-watchdog[{name}]")

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def beat(self) -> None:
        self._last = time.monotonic()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        poll = max(0.05, min(self.deadline / 4.0, 1.0))
        while not self._stop.wait(poll):
            stalled = time.monotonic() - self._last
            if stalled <= self.deadline:
                continue
            stream = self._stream or sys.stderr
            try:
                stream.write(
                    f"\n[qk-sanitize] WATCHDOG '{self.name}' (pid "
                    f"{os.getpid()}): no progress for {stalled:.1f}s "
                    f"(deadline {self.deadline:.1f}s) — dumping all thread "
                    f"stacks and exiting {self.exit_code}\n")
                dump_all_stacks(stream)
                # the flight recorder's tail + per-thread current activity:
                # stacks say WHERE the process is stuck, the recorder says
                # WHAT it was doing on the way there (obs/recorder.py)
                with contextlib.suppress(Exception):
                    from quokka_tpu.obs import recorder as _flight

                    _flight.RECORDER.dump_text(stream, last_n=50)
                inv = lock_inversions()
                if inv:
                    stream.write(
                        f"[qk-sanitize] {len(inv)} lock-order inversion(s) "
                        f"recorded this run: {inv}\n")
                stream.flush()
            finally:
                self._exit(self.exit_code)
            return  # only reached with an injected non-exiting _exit


def start_watchdog(name: str) -> Optional[Watchdog]:
    """Sanitize-mode entry point for runtime loops: a started watchdog when
    enabled (plus faulthandler for hard crashes), else None."""
    if not enabled():
        return None
    # non-file stderr (pytest-captured streams) can refuse enable(); the
    # watchdog's explicit dump_traceback still works there
    with contextlib.suppress(Exception):
        faulthandler.enable()
    return Watchdog(name).start()


# ---------------------------------------------------------------------------
# Lock-order recorder
# ---------------------------------------------------------------------------

_order_mu = threading.Lock()
# (held, acquired) -> first-seen thread name
_order_edges: Dict[Tuple[str, str], str] = {}
_order_inversions: List[Tuple[str, str]] = []
_held = threading.local()


def _held_stack() -> List[str]:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _record_acquire(name: str) -> None:
    stack = _held_stack()
    with _order_mu:
        for h in stack:
            if h == name:  # RLock re-entry: not an ordering edge
                continue
            _order_edges.setdefault((h, name), threading.current_thread().name)
            if (name, h) in _order_edges:
                pair = (name, h) if (name, h) < (h, name) else (h, name)
                if pair not in _order_inversions:
                    _order_inversions.append(pair)
                    sys.stderr.write(
                        f"[qk-sanitize] LOCK-ORDER INVERSION: '{h}' -> "
                        f"'{name}' here, but '{name}' -> '{h}' was seen on "
                        f"thread '{_order_edges[(name, h)]}' — two-lock "
                        "deadlock precursor\n")
                    sys.stderr.flush()
    stack.append(name)


def _record_release(name: str) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            break


def lock_inversions() -> List[Tuple[str, str]]:
    with _order_mu:
        return list(_order_inversions)


def reset_lock_order() -> None:
    with _order_mu:
        _order_edges.clear()
        del _order_inversions[:]


class InstrumentedLock:
    """Wraps a Lock/RLock recording acquisition order under its name.
    Contended acquisitions (wait > _SLOW_ACQUIRE_S) additionally land in
    the flight recorder as ``lock`` events — the "lock acquire" channel of
    the merged timeline."""

    _SLOW_ACQUIRE_S = 0.005

    def __init__(self, name: str, lock):
        self.name = name
        self._lock = lock

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.monotonic()
        got = self._lock.acquire(blocking, timeout)
        if got:
            waited = time.monotonic() - t0
            if waited > self._SLOW_ACQUIRE_S:
                from quokka_tpu.obs import recorder as _flight

                _flight.RECORDER.record("lock", self.name, dur=waited)
            _record_acquire(self.name)
        return got

    def release(self) -> None:
        _record_release(self.name)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def maybe_instrument(name: str, lock):
    """Sanitize mode: wrap `lock` in the order recorder; otherwise return it
    unchanged (the production hot path pays nothing)."""
    return InstrumentedLock(name, lock) if enabled() else lock


# ---------------------------------------------------------------------------
# Recompile sentinel
# ---------------------------------------------------------------------------


class RecompileError(RuntimeError):
    """Real backend compiles happened after warmup: the static-shape /
    signature-stability discipline is broken for this run."""


def real_compiles_delta(before: Dict, after: Dict) -> int:
    """Real-compilation delta between two compilestats snapshots (persistent-
    cache hits are not real compiles — same derivation as snapshot())."""
    b = before.get("backend_compiles", 0) - before.get("cache_hits", 0)
    a = after.get("backend_compiles", 0) - after.get("cache_hits", 0)
    return max(0, a - b)


def check_no_recompiles(before: Dict, after: Dict, context: str = "",
                        force: bool = False) -> int:
    """Raise RecompileError when sanitize mode is on and real compiles
    happened between the two snapshots; returns the delta either way.
    ``force`` checks regardless of the env flag (tests, explicit gates)."""
    delta = real_compiles_delta(before, after)
    if delta > 0 and (force or enabled()):
        raise RecompileError(
            f"{delta} real backend compile(s) after warmup"
            + (f" during {context}" if context else "")
            + " — warmed query shapes must reuse their executables "
            "(compile counters: quokka_tpu/utils/compilestats.py)")
    return delta


class recompile_guard:
    """``with recompile_guard('timed runs'):`` — snapshot on entry, check on
    clean exit (no check when the body raised)."""

    def __init__(self, context: str = "", force: bool = False):
        self.context = context
        self.force = force
        self.before: Optional[Dict] = None

    def __enter__(self):
        from quokka_tpu.utils import compilestats

        self.before = compilestats.snapshot()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            from quokka_tpu.utils import compilestats

            check_no_recompiles(self.before, compilestats.snapshot(),
                                self.context, self.force)
        return False
