"""Global configuration for quokka-tpu.

Dtype and shape policy for the device kernel layer.  The reference engine
(pyquokka) runs ragged Polars batches; XLA wants static shapes, so every batch
is padded up to a "bucket" size and carries a validity mask.  Buckets are
geometric so each (kernel, bucket, dtype-signature) compiles at most once and
the compile cache stays small.

Float policy: on CPU test meshes we enable x64 and compute in float64 (exact
oracle comparisons); on TPU we keep float32 data with float64 host-side final
combines (TPU f64 is software-emulated and slow, and the MXU/VPU want 32-bit).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

# Persistent XLA compilation cache: first-compile of the fused kernels is slow
# (tens of seconds per program over a remote TPU runtime); cache executables on
# disk so they amortize across processes and queries.
def _host_fingerprint() -> str:
    """Per-backend/topology cache namespace: XLA:CPU AOT executables are
    compiled for the build host's CPU features and the cache key does NOT
    include them, so an entry written on one machine can SIGILL on another
    (observed as cpu_aot_loader 'machine type mismatch' errors when $HOME
    moves across heterogeneous hosts).  Keying the directory on the CPU
    flag set + jax version + requested platform makes a foreign host (or a
    jax upgrade, whose executable serialization format drifts) a cache
    MISS instead of a crash.  Device kind/count join the fingerprint
    lazily in runtime/compileplane.py (reading them here would initialize
    the backend at import time)."""
    import hashlib
    import platform as _plat

    feat = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feat = line
                    break
    except OSError:
        pass
    # the env-requested platform is known without initializing the backend;
    # jax.__version__ is a plain attribute
    feat += "|" + os.environ.get("JAX_PLATFORMS", "")
    feat += "|" + getattr(jax, "__version__", "")
    h = hashlib.sha256(feat.encode()).hexdigest()[:10]
    return f"{_plat.machine()}-{h}"


_cache_dir = os.environ.get("QUOKKA_JAX_CACHE_DIR", "")
if not _cache_dir:
    # Default ON for every backend: a fresh process otherwise recompiles the
    # whole kernel set (~15-20s per TPC-H query shape even on CPU; minutes
    # over the remote-TPU compile tunnel).  Opt out with
    # QUOKKA_JAX_CACHE_DIR=0.
    _cache_dir = os.path.expanduser("~/.cache/quokka_tpu_jax")
# the un-fingerprinted cache root ("" when opted out): the AOT executable
# store (runtime/compileplane.py) lives beside the XLA cache under it
CACHE_ROOT = _cache_dir if _cache_dir and _cache_dir != "0" else ""
if _cache_dir and _cache_dir != "0":
    try:
        _cache_dir = os.path.join(_cache_dir, _host_fingerprint())
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # Cache every program: the engine's per-batch kernels are individually
        # fast to compile but number in the hundreds per query shape, and the
        # cache-hit path costs ~ms.  Override with QUOKKA_JAX_CACHE_MIN_SECS.
        _min_secs = float(os.environ.get("QUOKKA_JAX_CACHE_MIN_SECS", "0"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", _min_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass

# Compile counters observe every compilation from process start (listeners
# must exist before the first jit runs; config is the package's first import).
try:
    from quokka_tpu.utils import compilestats as _compilestats

    _compilestats.ensure_registered()
except Exception:
    pass

# ---------------------------------------------------------------------------
# Padding buckets
# ---------------------------------------------------------------------------

# MIN_BUCKET / MAX_BUCKET resolve lazily (module __getattr__ below) from
# ops/sigkey — the canonical ladder.  An eager `from quokka_tpu.ops import
# sigkey` here would execute the ops package __init__ (batch, bridge, jax
# array machinery) while config is still half-initialized: the cycle only
# works as long as those modules touch config strictly at call time.


def bucket_size(n: int) -> int:
    """Smallest padding bucket that fits n rows.  Static-shape discipline:
    all kernels see bucketed lengths.  The ladder (ops/sigkey.bucket_rows)
    is pow2 with 4x rung spacing below 64Ki rows, so the compile-key space
    over small intermediates stays half the size of a pure 2x ladder."""
    from quokka_tpu.ops import sigkey

    return sigkey.bucket_rows(n)


def __getattr__(name: str):
    if name in ("MIN_BUCKET", "MAX_BUCKET"):
        from quokka_tpu.ops import sigkey

        return getattr(sigkey, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# ---------------------------------------------------------------------------
# Kernel strategy
# ---------------------------------------------------------------------------


def use_hash_tables() -> bool:
    """Whether equality-keyed group-by kernels use the device hash table
    (ops/hashtable.py) instead of the sort-based paths.  Since PR 8 this is
    a thin delegate to the kernel-strategy matrix (ops/strategy.py): env
    overrides (QUOKKA_HASH_TABLES, QK_KERNEL_STRATEGY) > persisted
    per-backend calibration > the original platform gates (on for CPU/GPU
    where scatter/gather is fast, off for TPU where random scatters
    serialize and the multi-operand sort is the idiom)."""
    from quokka_tpu.ops import strategy

    return strategy.choice("groupby") == "hashtable"


def stage_fuse_enabled() -> bool:
    """Whole-stage fusion escape hatch (ops/stagefuse.py): QK_STAGE_FUSE=0
    disables the optimizer's fuse_stages pass so a suspect plan can be
    re-run with per-operator actors.  Read dynamically (not cached at
    import) so one process can plan both variants — the fusion smoke
    compares fused vs unfused results in-process."""
    return os.environ.get("QK_STAGE_FUSE", "1") not in ("0", "false", "no")


def adapt_enabled() -> bool:
    """Runtime adaptive re-partitioning kill switch (planner/adapt.py):
    QK_ADAPT=0 disables both the plan-time eligibility pass and the
    mid-query skew trigger, so a suspect adapted plan can be re-run
    statically.  Read dynamically (not cached at import) so one process can
    run both variants — the adapt smoke compares adaptive vs static
    results in-process."""
    return os.environ.get("QK_ADAPT", "1") not in ("0", "false", "no")


def adapt_min_rows() -> int:
    """Floor on total rows delivered to a join's build edge before the
    skew trigger may fire (QK_ADAPT_MIN_ROWS).  Below this, re-partitioning
    buys nothing — the whole build fits one channel comfortably."""
    try:
        return int(os.environ.get("QK_ADAPT_MIN_ROWS", 1 << 15))
    except ValueError:
        return 1 << 15


def broadcast_bytes_threshold() -> int:
    """Measured-bytes ceiling for the cost-based broadcast-join choice
    (planner/decide.py): a build side whose MEASURED cardprofile bytes fit
    under QK_BROADCAST_BYTES is replicated to every probe channel instead
    of hash-partitioning both sides.  Only consulted when a measured figure
    exists; cold plans keep the row-estimate threshold
    (optimizer.BROADCAST_THRESHOLD)."""
    try:
        return int(os.environ.get("QK_BROADCAST_BYTES", 8 << 20))
    except ValueError:
        return 8 << 20


def replay_retry_deadline_s() -> float:
    """Upper bound on how long a recovering consumer waits for a lost
    object's producer replay before declaring the loss irrecoverable
    (QK_REPLAY_DEADLINE, runtime/engine.py).  The deadline exists so a
    producer that died holding un-replayable state fails the query loudly
    instead of wedging it forever; it is env-tunable because the right
    bound is load-dependent — a 1-core CI box replaying a long exec tape
    under kill-storm chaos legitimately needs minutes, while a test suite
    that *expects* irrecoverable losses wants the verdict in seconds."""
    try:
        return float(os.environ.get("QK_REPLAY_DEADLINE", 600.0))
    except ValueError:
        return 600.0


def use_host_asof() -> bool:
    """Whether the as-of match runs as a native sequential merge on host
    (ops/asof._asof_match_host -> native/columnar.cpp).  Thin delegate to
    the strategy matrix (ops/strategy.py) — host stays the CPU-backend
    default (np.asarray is zero-copy there); TPU *and* GPU resolve to a
    device kernel since every host column would pay a blocking d2h copy.
    QUOKKA_HOST_ASOF / QK_KERNEL_STRATEGY override; calibration can flip
    the CPU pick to the device searchsorted kernel when measured faster."""
    from quokka_tpu.ops import strategy

    return strategy.choice("asof") == "host"


# ---------------------------------------------------------------------------
# Dtype policy
# ---------------------------------------------------------------------------


def _platform() -> str:
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover
        return "cpu"


def x64_enabled() -> bool:
    return bool(jax.config.read("jax_enable_x64"))


def float_dtype():
    """float64 when x64 is on (CPU test meshes), else float32 (TPU)."""
    return jnp.float64 if x64_enabled() else jnp.float32


def int_dtype():
    return jnp.int64 if x64_enabled() else jnp.int32


# Default batch target: how many rows a reader should aim to emit per batch.
DEFAULT_BATCH_ROWS = int(os.environ.get("QUOKKA_TPU_BATCH_ROWS", 1 << 20))

# Executor/runtime defaults (mirrors the reference's exec_config knobs,
# pyquokka/df.py:63-66, rebuilt as a flat dict).
DEFAULT_EXEC_CONFIG = {
    "hbq_path": "/tmp/quokka_tpu_spill/",
    "fault_tolerance": False,
    "memory_limit": 0.25,
    "max_pipeline_batches": 30,
    "checkpoint_interval": None,
    "checkpoint_bucket": None,
    "max_pipeline": 4,
    "batch_attempt": 4,
}


# ---------------------------------------------------------------------------
# Spill tier (external sort / grace join) — reference sql_executors.py:88-188
# (SuperFastSortExecutor) and 456-515 (DiskBuildProbeJoinExecutor).
# Thresholds are ROWS accumulated before an operator switches to disk; the
# defaults keep small queries fully in memory.  Tests lower them to force the
# spill paths on tiny data.
# ---------------------------------------------------------------------------
# Shuffle data plane
# ---------------------------------------------------------------------------
# Masked-split cap: a partition split stays in masked-view mode (zero host
# syncs, shared column buffers) while n_parts * padded_len is at or below
# this; past it the one-kernel compacted split runs instead (bounds the
# downstream padded-row inflation for very wide fan-outs).
SHUFFLE_MASKED_CAP = int(os.environ.get("QUOKKA_SHUFFLE_MASKED_CAP", 1 << 25))
# Async HBQ spill (Engine.push): background threads doing the device->host
# copy + checksummed disk write off the critical path.  QK_SPILL_ASYNC=0
# restores the old synchronous spill; QK_SPILL_POOL sizes the thread pool
# (1 keeps spill-file write order identical to submission order, which the
# seeded chaos corruption streams key off); QK_SPILL_INFLIGHT bounds the
# device batches pinned by pending spills.
# streaming plane: minimum seconds between source polls of an idle standing
# query (bounds filesystem stats when no data is arriving)
STREAM_POLL_S = float(os.environ.get("QK_STREAM_POLL_S", "0.05"))
SPILL_ASYNC = os.environ.get("QK_SPILL_ASYNC", "1") not in ("0", "false", "no")
SPILL_POOL = int(os.environ.get("QK_SPILL_POOL", "1"))
SPILL_INFLIGHT = int(os.environ.get("QK_SPILL_INFLIGHT", "4"))

SPILL_SORT_ROWS = int(os.environ.get("QUOKKA_TPU_SPILL_SORT_ROWS", 1 << 22))
SPILL_MERGE_CHUNK_ROWS = int(os.environ.get("QUOKKA_TPU_SPILL_CHUNK_ROWS", 1 << 16))
SPILL_JOIN_BUILD_ROWS = int(os.environ.get("QUOKKA_TPU_SPILL_JOIN_ROWS", 1 << 22))
SPILL_JOIN_FANOUT = int(os.environ.get("QUOKKA_TPU_SPILL_JOIN_FANOUT", 8))
SPILL_DIR = os.environ.get("QUOKKA_TPU_SPILL_DIR", "/tmp/quokka_tpu_spill")
