"""ctypes bindings to the optional native C++ helper library (native/).

The library accelerates host-side columnar chores that sit off the device path:
string hashing for dictionary encoding and CSV newline-boundary scans.  Pure
Python fallbacks exist everywhere, so the package works without a compiler.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence

import numpy as np

_LIB = None
_TRIED = False


def _build_lib(native_dir: str) -> None:
    """Best-effort auto-build of the native helper on first use."""
    import subprocess

    src = os.path.join(native_dir, "columnar.cpp")
    out = os.path.join(native_dir, "libquokka_native.so")
    if not os.path.exists(src):
        return
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return  # up to date; rebuild only when the source is newer
    tmp = out + f".build-{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", tmp, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, out)  # atomic: never leave a torn .so behind
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _find_lib():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    _build_lib(os.path.join(here, "native"))
    for cand in (
        os.path.join(here, "native", "libquokka_native.so"),
        os.environ.get("QUOKKA_TPU_NATIVE_LIB", ""),
    ):
        if cand and os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.qk_fnv1a64_many.restype = None
                lib.qk_fnv1a64_many.argtypes = [
                    ctypes.c_void_p,  # concatenated utf8 bytes
                    ctypes.c_void_p,  # int64 offsets (n+1)
                    ctypes.c_int64,  # n strings
                    ctypes.c_void_p,  # out uint64[n]
                ]
                lib.qk_find_newline.restype = ctypes.c_int64
                lib.qk_find_newline.argtypes = [ctypes.c_void_p, ctypes.c_int64]
                # newer symbols may be absent from a stale/external .so (no
                # compiler to rebuild): keep the lib for the old entry points
                # and let the new consumers fall back
                try:
                    for fn in ("qk_asof_backward", "qk_asof_forward"):
                        f = getattr(lib, fn)
                        f.restype = None
                        f.argtypes = [
                            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                            ctypes.c_void_p,
                        ]
                    lib.qk_is_sorted_i64.restype = ctypes.c_int32
                    lib.qk_is_sorted_i64.argtypes = [
                        ctypes.c_void_p, ctypes.c_int64,
                    ]
                    lib._qk_has_asof = True
                except AttributeError:
                    lib._qk_has_asof = False
                _LIB = lib
            except OSError:
                _LIB = None
            break
    return _LIB


def fnv1a64_many(values: Sequence) -> Optional[np.ndarray]:
    """Hash a sequence of strings with the native lib; None if unavailable."""
    lib = _find_lib()
    if lib is None:
        return None
    encoded = [
        bytes(v) if isinstance(v, (bytes, bytearray))
        else (v if v is not None else "").encode("utf-8", errors="surrogatepass")
        for v in values
    ]
    n = len(encoded)
    offsets = np.zeros(n + 1, dtype=np.int64)
    for i, b in enumerate(encoded):
        offsets[i + 1] = offsets[i] + len(b)
    blob = b"".join(encoded)
    buf = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(0, dtype=np.uint8)
    out = np.zeros(n, dtype=np.uint64)
    lib.qk_fnv1a64_many(
        buf.ctypes.data if buf.size else 0,
        offsets.ctypes.data,
        n,
        out.ctypes.data,
    )
    # null entries hash to 0 to match the Python fallback
    for i, v in enumerate(values):
        if v is None:
            out[i] = 0
    return out


def has_asof() -> bool:
    """Whether the loaded native library provides the as-of merge symbols."""
    lib = _find_lib()
    return lib is not None and getattr(lib, "_qk_has_asof", False)


def asof_merge(t_time: np.ndarray, t_key: np.ndarray,
               q_time: np.ndarray, q_key: np.ndarray,
               direction: str = "backward") -> Optional[np.ndarray]:
    """Sequential as-of merge over host arrays (the CPU-backend fast path of
    ops/asof.asof_join).  All inputs int64 and C-contiguous; each side must
    be time-sorted ascending — the CALLER sorts/compacts first.  Returns
    int32 quote indices (-1 = unmatched) per trade, or None when the native
    library is unavailable (callers fall back to the XLA kernel)."""
    lib = _find_lib()
    if lib is None or not getattr(lib, "_qk_has_asof", False):
        return None
    nt, nq = len(t_time), len(q_time)
    out = np.empty(nt, dtype=np.int32)
    if nt == 0:
        return out
    fn = lib.qk_asof_backward if direction == "backward" else lib.qk_asof_forward
    fn(
        t_time.ctypes.data, t_key.ctypes.data, nt,
        q_time.ctypes.data if nq else 0, q_key.ctypes.data if nq else 0, nq,
        out.ctypes.data,
    )
    return out


def is_sorted_i64(a: np.ndarray) -> bool:
    lib = _find_lib()
    if lib is None or not getattr(lib, "_qk_has_asof", False) or len(a) < 2:
        return bool(np.all(a[1:] >= a[:-1])) if len(a) >= 2 else True
    return bool(lib.qk_is_sorted_i64(a.ctypes.data, len(a)))


def find_newline(data: bytes) -> int:
    """Index of first b'\\n' in data, or -1.  Native when available."""
    lib = _find_lib()
    if lib is None:
        return data.find(b"\n")
    buf = np.frombuffer(data, dtype=np.uint8)
    return int(lib.qk_find_newline(buf.ctypes.data if buf.size else 0, len(data)))
