"""Structured tracing: named spans with aggregate timings.

Replaces the reference's print_if_profile timestamp prints (pyquokka/
core.py:20-30) with accumulated span statistics that any component can emit
and the engine can report (QUOKKA_TRACE=1 prints a summary at run end).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager

ENABLED = os.environ.get("QUOKKA_TRACE", "0") not in ("0", "", "false")

_lock = threading.Lock()
_stats = defaultdict(lambda: [0, 0.0])  # name -> [count, total_seconds]


@contextmanager
def span(name: str):
    if not ENABLED:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            s = _stats[name]
            s[0] += 1
            s[1] += dt


def add(name: str, seconds: float, count: int = 1):
    if not ENABLED:
        return
    with _lock:
        s = _stats[name]
        s[0] += count
        s[1] += seconds


def summary() -> str:
    with _lock:
        rows = sorted(_stats.items(), key=lambda kv: -kv[1][1])
    lines = [f"{'span':<28}{'count':>8}{'total_s':>10}{'avg_ms':>10}"]
    for name, (n, total) in rows:
        lines.append(f"{name:<28}{n:>8}{total:>10.3f}{total / max(n,1) * 1e3:>10.2f}")
    return "\n".join(lines)


def reset():
    with _lock:
        _stats.clear()
