"""Back-compat shim: the span API moved to quokka_tpu.obs.spans.

Spans now additionally land in the flight recorder (quokka_tpu/obs/
recorder.py) so merged timelines show where time went; the QUOKKA_TRACE=1
aggregate-summary behavior is unchanged.  Import from quokka_tpu.obs in
new code.
"""

from __future__ import annotations

from quokka_tpu.obs.spans import (  # noqa: F401 — re-export surface
    add,
    enabled,
    reset,
    set_enabled,
    span,
    stats,
    summary,
)
