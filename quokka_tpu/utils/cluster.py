"""Cluster descriptions and bring-up.

Reference parity: pyquokka/utils.py — LocalCluster (utils.py:96), EC2Cluster
(utils.py:25), QuokkaClusterManager (utils.py:191, create/start/stop clusters,
copy_and_launch_flight 316).  The embedded runtime executes everything
in-process, so LocalCluster is a description object; TPUPodCluster describes a
multi-host deployment (one worker daemon per host), and QuokkaClusterManager
actually launches those daemons — over ssh for remote hosts, as local
subprocesses for loopback hosts — the role the reference's
copy_and_launch_flight plays.  Cloud *provisioning* (creating VMs: the
reference shells out to boto3) still raises with guidance; bring-up on
existing hosts is fully automated.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional


class LocalCluster:
    """Single-host execution.  n_workers == 0: all channels run in this
    process (embedded engine).  n_workers >= 1: channels spread over that many
    spawned worker processes with a served ControlStore and socket data plane
    (runtime/distributed.py) — the reference's multi-TaskManager deployment on
    one machine (pyquokka/utils.py:96 LocalCluster + core.py TaskManagers)."""

    def __init__(self, io_per_node: int = 2, exec_per_node: int = 2,
                 n_workers: int = 0, worker_tags=None):
        self.io_per_node = io_per_node
        self.exec_per_node = exec_per_node
        self.n_workers = n_workers
        # worker id -> set of string tags, consumed by
        # TaggedCustomChannelsStrategy (runtime/placement.py)
        self.worker_tags = worker_tags
        self.leader_ip = "127.0.0.1"

    @property
    def num_nodes(self) -> int:
        return 1


class TPUPodCluster:
    """Multi-host deployment: `hosts` each run one worker daemon;
    device-resident shuffles ride ICI collectives inside the slice,
    host-mediated shuffles cross DCN through the socket data plane.

    A QuokkaContext built against this serves its control store on
    `bind` (default: the coordinator's own address — not 0.0.0.0) at
    store_port and waits for len(hosts) externally-launched workers
    (runtime/distributed.run_distributed(external_workers=...)).  Launch the
    daemons yourself with worker_commands(), or let
    QuokkaClusterManager.start_cluster() execute them (ssh for remote hosts,
    subprocess for loopback) — the reference's
    QuokkaClusterManager.copy_and_launch_flight over ssh
    (pyquokka/utils.py:316).

    Every store/data-plane connection is HMAC-authenticated against the
    cluster token (runtime/rpc.py); worker_commands() embeds it."""

    def __init__(self, hosts: List[str], chips_per_host: int = 4,
                 coordinator: Optional[str] = None, store_port: int = 7997,
                 worker_tags=None, bind: Optional[str] = None,
                 remote_python: str = "python3"):
        self.hosts = hosts
        self.chips_per_host = chips_per_host
        self.coordinator = coordinator or (hosts[0] if hosts else "127.0.0.1")
        self.store_port = store_port
        self.worker_tags = worker_tags
        # interface the coordinator serves on; None = its own address
        self.bind = bind
        # interpreter on the pod hosts (the coordinator's sys.executable path
        # rarely exists remotely)
        self.remote_python = remote_python
        # consumed by context.execute_node -> run_distributed: 0 local
        # workers, every channel on an external daemon
        self.n_workers = 0

    @property
    def num_nodes(self) -> int:
        return len(self.hosts)

    @property
    def external_workers(self) -> int:
        return len(self.hosts)

    def _bare_commands(self, persist: bool = True,
                       python: Optional[str] = None) -> List[str]:
        """Launch commands WITHOUT the token (the manager supplies it
        out-of-band: env for local daemons, stdin over ssh).  `python`
        defaults per host: this interpreter for loopback hosts, the
        cluster's remote_python elsewhere."""
        flag = " --persist" if persist else ""
        out = []
        for k, host in enumerate(self.hosts):
            exe = python or (
                shlex.quote(sys.executable) if _is_local(host)
                else self.remote_python
            )
            out.append(
                f"{exe} -m quokka_tpu.runtime.worker "
                f"--store {self.coordinator}:{self.store_port} --worker-id {k}"
                + flag
            )
        return out

    def worker_commands(self, persist: bool = True) -> List[str]:
        """One launch command per host, in worker-id order, for a human (or a
        scheduler template) to run.  persist=True (the default) keeps each
        daemon alive across queries.  NOTE: embeds the cluster token for
        copy-paste convenience — anyone who can read the command line can
        join the cluster; QuokkaClusterManager.start_cluster passes the token
        out-of-band instead."""
        from quokka_tpu.runtime.rpc import default_token

        token = shlex.quote(default_token())
        return [
            f"QUOKKA_RPC_TOKEN={token} {cmd}"
            for cmd in self._bare_commands(persist)
        ]


def _is_local(host: str) -> bool:
    return host in ("localhost", "127.0.0.1", "::1", "0.0.0.0")


class QuokkaClusterManager:
    """Bring-up on existing hosts (start/stop worker daemons); cloud VM
    provisioning is not available in the embedded build."""

    def __init__(self, ssh_user: Optional[str] = None,
                 ssh_options: Optional[List[str]] = None):
        self.ssh_user = ssh_user
        self.ssh_options = ssh_options or ["-o", "StrictHostKeyChecking=no",
                                           "-o", "BatchMode=yes"]
        # id(cluster) -> {worker index -> Popen}: one manager can run
        # several clusters without clobbering handles
        self._procs: Dict[int, Dict[int, subprocess.Popen]] = {}

    def create_local_cluster(self, **kwargs) -> LocalCluster:
        return LocalCluster(**kwargs)

    # -- daemon bring-up ------------------------------------------------------
    def start_cluster(self, cluster: TPUPodCluster,
                      log_dir: Optional[str] = None) -> "TPUPodCluster":
        """Launch one worker daemon per host (reference:
        utils.py:316 copy_and_launch_flight, minus the file copy — the
        package must already be importable on each host).  Loopback hosts
        launch as local subprocesses; remote hosts over ssh (the daemon is
        left running detached with nohup).  Returns the cluster for
        chaining into QuokkaContext(cluster=...)."""
        from quokka_tpu.runtime.rpc import default_token

        token = default_token()
        cmds = cluster._bare_commands(persist=True)
        for k, (host, cmd) in enumerate(zip(cluster.hosts, cmds)):
            log = None
            try:
                if log_dir:
                    os.makedirs(log_dir, exist_ok=True)
                    log = open(os.path.join(log_dir, f"worker-{k}.log"), "ab")
                if _is_local(host):
                    env = dict(os.environ)
                    env["QUOKKA_RPC_TOKEN"] = token
                    # a loopback daemon runs this same installation: make the
                    # package importable regardless of the caller's cwd
                    pkg_root = os.path.dirname(os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__))))
                    env["PYTHONPATH"] = (
                        pkg_root + os.pathsep + env["PYTHONPATH"]
                        if env.get("PYTHONPATH") else pkg_root
                    )
                    p = subprocess.Popen(
                        shlex.split(cmd), env=env,
                        stdout=log or subprocess.DEVNULL,
                        stderr=subprocess.STDOUT,
                    )
                else:
                    # token travels on ssh stdin — never on the remote argv
                    # (ps-visible) and never interpolated into shell text
                    target = (f"{self.ssh_user}@{host}" if self.ssh_user
                              else host)
                    p = subprocess.Popen(
                        ["ssh", *self.ssh_options, target,
                         "read -r QUOKKA_RPC_TOKEN; export QUOKKA_RPC_TOKEN; "
                         f"nohup {cmd} >/tmp/quokka-worker-{k}.log 2>&1 &"],
                        stdin=subprocess.PIPE,
                        stdout=log or subprocess.DEVNULL,
                        stderr=subprocess.STDOUT,
                    )
                    p.stdin.write((token + "\n").encode())
                    p.stdin.close()
            finally:
                if log is not None:
                    log.close()  # the child keeps its inherited fd
            self._procs.setdefault(id(cluster), {})[k] = p
        return cluster

    def stop_cluster(self, cluster: TPUPodCluster) -> None:
        """Terminate THIS cluster's daemons; remote hosts get a pkill over
        ssh."""
        for k, p in self._procs.pop(id(cluster), {}).items():
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
        for k, host in enumerate(cluster.hosts):
            if not _is_local(host):
                target = f"{self.ssh_user}@{host}" if self.ssh_user else host
                subprocess.run(
                    ["ssh", *self.ssh_options, target,
                     # token boundary ( |$) so stopping worker 1 never
                     # matches 10-19 when one host runs several daemons
                     # (--persist may follow the id)
                     "pkill -f 'quokka_tpu.runtime.worker.*--worker-id "
                     f"{k}( |$)' || true"],
                    check=False,
                )

    terminate_cluster = stop_cluster

    # -- provisioning -----------------------------------------------------------
    def create_cluster(self, name: str = None, *, project: str = None,
                       zone: str = None, **kwargs):
        """Provision a TPU slice when gcloud coordinates are given (delegates
        to GCloudTPUProvisioner); otherwise explain the supported paths."""
        if name and project and zone:
            prov = GCloudTPUProvisioner(project=project, zone=zone)
            return prov.create_cluster(name, **kwargs)
        raise NotImplementedError(
            "pass name=, project=, zone= to provision a TPU VM slice via "
            "gcloud (GCloudTPUProvisioner), or construct a TPUPodCluster "
            "from existing hosts (then start_cluster launches its daemons), "
            "or use LocalCluster"
        )

    get_cluster_from_json = create_cluster


class GCloudTPUProvisioner:
    """TPU slice provisioning through the gcloud CLI — the TPU-native analog
    of the reference's boto3 EC2 cluster manager
    (pyquokka/utils.py:191-500: create_cluster / start / stop / terminate +
    IP discovery).  Where the reference calls ec2.run_instances and polls
    describe_instances, this shells out to
    `gcloud compute tpus tpu-vm create/start/stop/delete/describe` and turns
    the slice's worker endpoints into a TPUPodCluster.

    `runner` is injectable (signature of subprocess.run) so environments
    without gcloud/credentials can integration-test command construction and
    response parsing; the default runs the real CLI."""

    def __init__(self, project: str, zone: str, runner=None):
        self.project = project
        self.zone = zone
        self._run = runner or subprocess.run

    def _gcloud(self, *args, parse_json: bool = True):
        cmd = [
            "gcloud", "compute", "tpus", "tpu-vm", *args,
            f"--project={self.project}", f"--zone={self.zone}",
        ]
        if parse_json:
            cmd.append("--format=json")
        r = self._run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"gcloud failed ({' '.join(cmd[:6])}…): {r.stderr.strip()[-500:]}"
            )
        if parse_json and r.stdout.strip():
            import json

            return json.loads(r.stdout)
        return None

    def _to_cluster(self, desc: dict, internal: bool = True) -> TPUPodCluster:
        eps = desc.get("networkEndpoints") or []
        hosts = []
        for ep in eps:
            if internal:
                hosts.append(ep["ipAddress"])
            else:
                hosts.append(ep.get("accessConfig", {}).get("externalIp")
                             or ep["ipAddress"])
        if not hosts:
            raise RuntimeError(
                f"TPU {desc.get('name')!r} reports no network endpoints "
                f"(state={desc.get('state')!r})"
            )
        # worker 0's host doubles as the coordinator (control store + data
        # plane bind), matching the reference's head-node convention
        return TPUPodCluster(hosts=hosts, coordinator=hosts[0])

    def create_cluster(self, name: str, accelerator_type: str = "v5litepod-8",
                       version: str = "tpu-ubuntu2204-base",
                       spot: bool = False, internal_ips: bool = True,
                       ) -> TPUPodCluster:
        args = [
            "create", name,
            f"--accelerator-type={accelerator_type}",
            f"--version={version}",
        ]
        if spot:
            args.append("--spot")
        self._gcloud(*args, parse_json=False)
        return self.get_cluster(name, internal_ips=internal_ips)

    def get_cluster(self, name: str, internal_ips: bool = True) -> TPUPodCluster:
        desc = self._gcloud("describe", name)
        return self._to_cluster(desc, internal=internal_ips)

    def start_cluster(self, name: str, internal_ips: bool = True) -> TPUPodCluster:
        self._gcloud("start", name, parse_json=False)
        return self.get_cluster(name, internal_ips=internal_ips)

    def stop_cluster(self, name: str) -> None:
        self._gcloud("stop", name, parse_json=False)

    def terminate_cluster(self, name: str) -> None:
        self._gcloud("delete", name, "--quiet", parse_json=False)
