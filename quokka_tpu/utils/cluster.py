"""Cluster descriptions.

Reference parity: pyquokka/utils.py — LocalCluster (utils.py:96), EC2Cluster
(utils.py:25), QuokkaClusterManager (utils.py:191).  The embedded runtime
executes everything in-process, so LocalCluster is a description object; the
TPU-pod deployment path (one worker per host, chips addressed through
jax.distributed + the collective shuffle plane in quokka_tpu.parallel) is
specified here so multi-host contexts can be constructed uniformly, while
cloud provisioning (the reference shells out to boto3/ssh) is deliberately out
of scope for the embedded build and raises with guidance.
"""

from __future__ import annotations

from typing import List, Optional


class LocalCluster:
    """Single-host execution.  n_workers == 0: all channels run in this
    process (embedded engine).  n_workers >= 1: channels spread over that many
    spawned worker processes with a served ControlStore and socket data plane
    (runtime/distributed.py) — the reference's multi-TaskManager deployment on
    one machine (pyquokka/utils.py:96 LocalCluster + core.py TaskManagers)."""

    def __init__(self, io_per_node: int = 2, exec_per_node: int = 2,
                 n_workers: int = 0, worker_tags=None):
        self.io_per_node = io_per_node
        self.exec_per_node = exec_per_node
        self.n_workers = n_workers
        # worker id -> set of string tags, consumed by
        # TaggedCustomChannelsStrategy (runtime/placement.py)
        self.worker_tags = worker_tags
        self.leader_ip = "127.0.0.1"

    @property
    def num_nodes(self) -> int:
        return 1


class TPUPodCluster:
    """Multi-host deployment: `hosts` each run one worker daemon;
    device-resident shuffles ride ICI collectives inside the slice,
    host-mediated shuffles cross DCN through the socket data plane.

    A QuokkaContext built against this serves its control store on
    0.0.0.0:store_port and waits for len(hosts) externally-launched workers
    (runtime/distributed.run_distributed(external_workers=...)); launch each
    daemon with the commands from worker_commands() — the role the
    reference's QuokkaClusterManager.copy_and_launch_flight plays over ssh
    (pyquokka/utils.py:316), minus the ssh (bring your own scheduler:
    GKE/slurm/tmux).

    SECURITY: the store/data-plane RPC is unauthenticated pickle (the
    reference's open Redis/Flight trust model) — private networks only."""

    def __init__(self, hosts: List[str], chips_per_host: int = 4,
                 coordinator: Optional[str] = None, store_port: int = 7997,
                 worker_tags=None):
        self.hosts = hosts
        self.chips_per_host = chips_per_host
        self.coordinator = coordinator or (hosts[0] if hosts else "127.0.0.1")
        self.store_port = store_port
        self.worker_tags = worker_tags
        # consumed by context.execute_node -> run_distributed: 0 local
        # workers, every channel on an external daemon
        self.n_workers = 0

    @property
    def num_nodes(self) -> int:
        return len(self.hosts)

    @property
    def external_workers(self) -> int:
        return len(self.hosts)

    def worker_commands(self) -> List[str]:
        """One launch command per host, in worker-id order."""
        return [
            f"python -m quokka_tpu.runtime.worker "
            f"--store {self.coordinator}:{self.store_port} --worker-id {k}"
            for k in range(len(self.hosts))
        ]


class QuokkaClusterManager:
    """Provisioning entry points (create/start/stop clusters).  Cloud
    provisioning is not available in the embedded build."""

    def create_local_cluster(self, **kwargs) -> LocalCluster:
        return LocalCluster(**kwargs)

    def create_cluster(self, *args, **kwargs):
        raise NotImplementedError(
            "cloud cluster provisioning (EC2/GKE) is not available in the "
            "embedded build; construct a TPUPodCluster from existing hosts "
            "or use LocalCluster"
        )

    get_cluster_from_json = create_cluster
    start_cluster = create_cluster
    stop_cluster = create_cluster
    terminate_cluster = create_cluster
