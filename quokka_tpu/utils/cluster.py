"""Cluster descriptions.

Reference parity: pyquokka/utils.py — LocalCluster (utils.py:96), EC2Cluster
(utils.py:25), QuokkaClusterManager (utils.py:191).  The embedded runtime
executes everything in-process, so LocalCluster is a description object; the
TPU-pod deployment path (one worker per host, chips addressed through
jax.distributed + the collective shuffle plane in quokka_tpu.parallel) is
specified here so multi-host contexts can be constructed uniformly, while
cloud provisioning (the reference shells out to boto3/ssh) is deliberately out
of scope for the embedded build and raises with guidance.
"""

from __future__ import annotations

from typing import List, Optional


class LocalCluster:
    """Single-host execution.  n_workers == 0: all channels run in this
    process (embedded engine).  n_workers >= 1: channels spread over that many
    spawned worker processes with a served ControlStore and socket data plane
    (runtime/distributed.py) — the reference's multi-TaskManager deployment on
    one machine (pyquokka/utils.py:96 LocalCluster + core.py TaskManagers)."""

    def __init__(self, io_per_node: int = 2, exec_per_node: int = 2,
                 n_workers: int = 0, worker_tags=None):
        self.io_per_node = io_per_node
        self.exec_per_node = exec_per_node
        self.n_workers = n_workers
        # worker id -> set of string tags, consumed by
        # TaggedCustomChannelsStrategy (runtime/placement.py)
        self.worker_tags = worker_tags
        self.leader_ip = "127.0.0.1"

    @property
    def num_nodes(self) -> int:
        return 1


class TPUPodCluster:
    """Description of a multi-host TPU deployment: `hosts` run one worker
    daemon each; device-resident shuffles ride ICI collectives inside the
    slice; host-mediated shuffles cross DCN.  Constructing a QuokkaContext
    against this requires the served control store (multi-host runtime tier —
    see README roadmap)."""

    def __init__(self, hosts: List[str], chips_per_host: int = 4,
                 coordinator: Optional[str] = None):
        self.hosts = hosts
        self.chips_per_host = chips_per_host
        self.coordinator = coordinator or (hosts[0] if hosts else "127.0.0.1")

    @property
    def num_nodes(self) -> int:
        return len(self.hosts)


class QuokkaClusterManager:
    """Provisioning entry points (create/start/stop clusters).  Cloud
    provisioning is not available in the embedded build."""

    def create_local_cluster(self, **kwargs) -> LocalCluster:
        return LocalCluster(**kwargs)

    def create_cluster(self, *args, **kwargs):
        raise NotImplementedError(
            "cloud cluster provisioning (EC2/GKE) is not available in the "
            "embedded build; construct a TPUPodCluster from existing hosts "
            "or use LocalCluster"
        )

    get_cluster_from_json = create_cluster
    start_cluster = create_cluster
    stop_cluster = create_cluster
    terminate_cluster = create_cluster
