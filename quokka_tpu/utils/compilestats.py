"""Process-wide XLA compile counters, fed by jax.monitoring events.

The engine's static-shape discipline means a query shape should compile its
kernel set once and then reuse it forever — across batches within a run,
across runs within a process (jit caches), and across processes (the
persistent compilation cache, config.py).  These counters make reuse
observable: `snapshot()["backend_compiles"]` staying flat across repeated
runs IS the proof, and bench.py reports the per-phase deltas.

Counter meanings:
- backend_compiles / backend_compile_seconds: compile_or_get_cached calls —
  NOTE this event fires on persistent-cache HITS too (jax wraps the whole
  lookup-or-compile in one duration event), so real compilations are
  `real_compiles = backend_compiles - cache_hits` (snapshot derives it).
- cache_hits: persistent-cache loads that avoided a real backend compile.
- traces: jaxprs traced (cheap, happens once per in-process signature).
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_stats = {
    "backend_compiles": 0,
    "backend_compile_seconds": 0.0,
    "cache_hits": 0,
    "traces": 0,
}
_registered = False


def _on_event(event: str, **kw) -> None:
    with _lock:
        if event == "/jax/compilation_cache/cache_hits":
            _stats["cache_hits"] += 1


def _on_duration(event: str, duration_secs: float, **kw) -> None:
    with _lock:
        if event == "/jax/core/compile/backend_compile_duration":
            _stats["backend_compiles"] += 1
            _stats["backend_compile_seconds"] += duration_secs
        elif event == "/jax/core/compile/jaxpr_trace_duration":
            _stats["traces"] += 1
        else:
            return
    # compile activity in the flight recorder: merged timelines show which
    # worker paid a compile (or a persistent-cache load) and when
    try:
        from quokka_tpu.obs import recorder

        recorder.RECORDER.record(
            "compile",
            "backend_compile" if event.endswith("backend_compile_duration")
            else "trace",
            dur=duration_secs)
    except Exception:
        return  # monitoring must never break the compile path


def ensure_registered() -> None:
    global _registered
    if _registered:
        return
    _registered = True
    try:
        from jax import monitoring

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass  # older jax: counters stay at zero rather than breaking


def snapshot() -> Dict:
    ensure_registered()
    with _lock:
        out = dict(_stats)
    out["backend_compile_seconds"] = round(out["backend_compile_seconds"], 3)
    out["real_compiles"] = max(0, out["backend_compiles"] - out["cache_hits"])
    return out
