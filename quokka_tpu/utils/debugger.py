"""Post-mortem debugging: snapshot the control plane + data-plane index.

Reference parity: pyquokka/debugger.py:6-41 (dump all Redis tables + the
Flight cache index to a pickle) and Coordinator.dump_redis_state's
pre/post-recovery snapshots (coordinator.py:41-58)."""

from __future__ import annotations

import pickle
from typing import Optional


class Debugger:
    def __init__(self, graph):
        self.graph = graph

    def snapshot(self) -> dict:
        g = self.graph
        return {
            "control": g.store.dump(),
            "cache_index": g.cache.flights_info(),
            "actors": {
                a: {
                    "kind": info.kind,
                    "channels": info.channels,
                    "stage": info.stage,
                    "targets": list(info.targets),
                    "sorted": info.sorted_actor,
                }
                for a, info in g.actors.items()
            },
        }

    def dump(self, path: str) -> None:
        snap = self.snapshot()
        # tasks/partition specs aren't all picklable; stringify leaves best-effort
        with open(path, "wb") as f:
            pickle.dump(_stringify(snap), f)

    def summary(self) -> str:
        snap = self.snapshot()
        lines = [f"actors: {len(snap['actors'])}  cached objects: {len(snap['cache_index'])}"]
        for a, info in sorted(snap["actors"].items()):
            done = {
                ch for (aa, ch) in snap["control"]["DST"] if aa == a
            } if isinstance(snap["control"]["DST"], dict) else set()
            lines.append(
                f"  actor {a} ({info['kind']}, stage {info['stage']}): "
                f"{info['channels']} channels, done={sorted(done)}, "
                f"targets={info['targets']}"
            )
        return "\n".join(lines)


def _stringify(obj):
    try:
        pickle.dumps(obj)
        return obj
    except Exception:
        if isinstance(obj, dict):
            return {str(k): _stringify(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple, set)):
            return [_stringify(v) for v in obj]
        return repr(obj)
