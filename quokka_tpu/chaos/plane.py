"""The chaos plane: seeded, probabilistic, multi-layer fault injection.

The reference validated its lineage recovery protocol by MANUALLY killing
instances (fault-tolerance.md); our port's scripted injection
(``inject_failure`` / ``kill_after_inputs``) is deterministic but narrow.
This plane makes the ugly failures — dropped RPC connections, flaky store
calls, truncated/bit-flipped spill and checkpoint files, workers killed at
random task boundaries — continuous, probabilistic, and exactly
reproducible from one spec string:

    QK_CHAOS="seed=42,rpc=0.02,delay=0.05,store=0.05,corrupt=0.01,kill=1"

Grammar (comma-separated ``key=value``; unknown keys are an error so a
typo'd soak never silently runs fault-free):

    seed=N            base seed; every site derives its own RNG stream
    rpc=P             P(drop the connection) per RPC request, pre- OR
                      post-send (post-send exercises server-side dedup)
    delay=P           P(inject a 1-20 ms stall) per RPC request
    store=P           P(TransientStoreError) per control-store op, raised
                      BEFORE the request leaves the client (retry-safe)
    corrupt=P         P(truncate or bit-flip) per artifact write
    corrupt_spill=P   override for HBQ spill files only
    corrupt_ckpt=P    override for checkpoint files only
    kill=N            kill N workers (distributed: SIGKILL at an input
                      boundary; embedded: lose random exec channels at a
                      task boundary).  Requires fault_tolerance.
    kill_after=N      earliest task/input boundary for the first kill
                      (default 6)

Determinism: each injection site draws from its own ``random.Random``
seeded by ``(seed, site, role)`` — ``role`` is "main" in the coordinator/
embedded process and "worker-K" in spawned workers (set by worker_main).
Same spec => same fault plan per process role, so a failing soak run
replays by exporting the printed ``QK_CHAOS`` string.  Thread interleaving
is not controlled (it never is), but every fault is recorded in the flight
recorder (``chaos.*`` events) so a replayed run is diffable.

The plane is inert (zero overhead beyond one attribute check) unless
``QK_CHAOS`` is set or ``configure()`` is called.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_PROB_KEYS = ("rpc", "delay", "store", "corrupt", "corrupt_spill",
              "corrupt_ckpt")
_INT_KEYS = ("seed", "kill", "kill_after")
_DELAY_RANGE = (0.001, 0.020)


class ChaosSpecError(ValueError):
    """Malformed QK_CHAOS spec (unknown key, unparsable value)."""


class ChaosConfig:
    """Parsed, validated QK_CHAOS spec."""

    def __init__(self, seed: int = 0, kill: int = 0, kill_after: int = 6,
                 **probs: float):
        self.seed = int(seed)
        self.kill = int(kill)
        self.kill_after = int(kill_after)
        self.probs: Dict[str, float] = {k: 0.0 for k in _PROB_KEYS}
        # keys the spec set EXPLICITLY: corrupt_spill=0 must override a
        # nonzero corrupt= (a falsy-0.0 `or` fallback would silently ignore
        # the override)
        self._explicit = frozenset(probs)
        for k, v in probs.items():
            if k not in _PROB_KEYS:
                raise ChaosSpecError(f"unknown chaos key {k!r}")
            if not 0.0 <= float(v) <= 1.0:
                raise ChaosSpecError(f"chaos probability {k}={v} not in [0,1]")
            self.probs[k] = float(v)

    @classmethod
    def parse(cls, spec: str) -> "ChaosConfig":
        kw: Dict[str, float] = {}
        seed = kill = 0
        kill_after = 6
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ChaosSpecError(f"chaos spec item {part!r} is not k=v")
            k, _, v = part.partition("=")
            k = k.strip()
            v = v.strip()
            try:
                if k == "seed":
                    seed = int(v)
                elif k == "kill":
                    kill = int(v)
                elif k == "kill_after":
                    kill_after = int(v)
                elif k in _PROB_KEYS:
                    kw[k] = float(v)
                else:
                    raise ChaosSpecError(f"unknown chaos key {k!r}")
            except ValueError as e:
                if isinstance(e, ChaosSpecError):
                    raise
                raise ChaosSpecError(
                    f"bad chaos value {part!r}: {e}") from None
        return cls(seed=seed, kill=kill, kill_after=kill_after, **kw)

    def prob(self, site: str) -> float:
        if site == "spill":
            return (self.probs["corrupt_spill"]
                    if "corrupt_spill" in self._explicit
                    else self.probs["corrupt"])
        if site == "ckpt":
            return (self.probs["corrupt_ckpt"]
                    if "corrupt_ckpt" in self._explicit
                    else self.probs["corrupt"])
        return self.probs.get(site, 0.0)

    def render(self) -> str:
        """Canonical spec string (what a failing soak prints for replay)."""
        out = [f"seed={self.seed}"]
        for k in _PROB_KEYS:
            if self.probs[k] or k in self._explicit:
                out.append(f"{k}={self.probs[k]:g}")
        if self.kill:
            out.append(f"kill={self.kill}")
            out.append(f"kill_after={self.kill_after}")
        return ",".join(out)


class ChaosPlane:
    """Process-wide injection switchboard.  All sites consult this one
    instance (``quokka_tpu.chaos.CHAOS``); sites draw from independent
    seeded streams so adding a draw at one site never shifts another's."""

    def __init__(self):
        self._cfg: Optional[ChaosConfig] = None
        self._role = "main"
        self._rngs: Dict[str, random.Random] = {}
        self._lock = threading.Lock()
        self._loaded_env = False

    # -- configuration -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        if self._cfg is None and not self._loaded_env:
            self._load_env()
        return self._cfg is not None

    @property
    def config(self) -> Optional[ChaosConfig]:
        if self._cfg is None and not self._loaded_env:
            self._load_env()
        return self._cfg

    def _load_env(self) -> None:
        with self._lock:
            if self._loaded_env:
                return
            self._loaded_env = True
            spec = os.environ.get("QK_CHAOS", "").strip()
            if spec and spec != "0":
                self._cfg = ChaosConfig.parse(spec)

    def configure(self, spec) -> None:
        """Enable from a spec string or ChaosConfig (tests, the soak)."""
        with self._lock:
            self._cfg = (spec if isinstance(spec, ChaosConfig)
                         else ChaosConfig.parse(spec))
            self._rngs.clear()
            self._loaded_env = True

    def disable(self) -> None:
        with self._lock:
            self._cfg = None
            self._rngs.clear()
            self._loaded_env = True

    def set_role(self, role: str) -> None:
        """Per-process stream identity ("main", "worker-3", ...); spawned
        workers call this so their fault plan differs from (but is as
        reproducible as) the coordinator's."""
        with self._lock:
            self._role = role
            self._rngs.clear()

    def describe(self) -> str:
        cfg = self.config
        return "off" if cfg is None else cfg.render()

    def _rng(self, site: str) -> random.Random:
        r = self._rngs.get(site)
        if r is None:
            with self._lock:
                r = self._rngs.get(site)
                if r is None:
                    cfg = self._cfg
                    seed = 0 if cfg is None else cfg.seed
                    r = random.Random(f"{seed}:{self._role}:{site}")
                    self._rngs[site] = r
        return r

    def _record(self, site: str, label: str, **args) -> None:
        from quokka_tpu import obs

        obs.REGISTRY.counter(f"chaos.{site}").inc()
        obs.RECORDER.record(f"chaos.{site}", label, **args)

    def _roll(self, site: str, prob_site: Optional[str] = None) -> bool:
        cfg = self.config
        if cfg is None:
            return False
        p = cfg.prob(prob_site or site)
        if p <= 0.0:
            return False
        return self._rng(site).random() < p

    # -- RPC faults ----------------------------------------------------------
    def rpc_fault(self) -> Optional[str]:
        """Per-request verdict for the RPC client: None (healthy), "pre"
        (drop the connection before the request is sent) or "post" (drop it
        after send, before the response — the retried request must dedup
        server-side).  May also sleep a few ms (``delay``)."""
        if not self.enabled:
            return None
        if self._roll("delay"):
            import time

            d = self._rng("delay").uniform(*_DELAY_RANGE)
            self._record("delay", f"{d * 1e3:.1f}ms")
            time.sleep(d)
        if self._roll("rpc"):
            mode = "post" if self._rng("rpc").random() < 0.5 else "pre"
            self._record("rpc", f"drop-{mode}")
            return mode
        return None

    # -- store faults --------------------------------------------------------
    def store_fault(self, method: str) -> None:
        """Raise TransientStoreError (before the request is sent) with
        probability ``store`` — the caller's bounded retry absorbs it."""
        if self.enabled and self._roll("store"):
            from quokka_tpu.runtime.errors import TransientStoreError

            self._record("store", method)
            raise TransientStoreError(
                f"chaos: injected transient store failure on {method!r}")

    # -- artifact corruption -------------------------------------------------
    def corrupt_artifact(self, data: bytes, site: str = "spill"
                         ) -> Optional[bytes]:
        """With probability ``corrupt_{site}`` (or ``corrupt``), return a
        truncated or bit-flipped copy of the framed artifact bytes; else
        None.  The mangled bytes MUST fail integrity verification — the
        whole point is that the reader detects, quarantines and recovers."""
        if not self.enabled or not self._roll(f"corrupt-{site}", site):
            return None
        rng = self._rng(f"corrupt-{site}")
        if rng.random() < 0.5 and len(data) > 1:
            cut = rng.randrange(0, len(data) - 1)
            self._record("corrupt", f"{site}:truncate@{cut}/{len(data)}")
            return data[:cut]
        i = rng.randrange(0, len(data))
        flipped = data[:i] + bytes([data[i] ^ (1 << rng.randrange(8))]) \
            + data[i + 1:]
        self._record("corrupt", f"{site}:bitflip@{i}/{len(data)}")
        return flipped

    def corrupt_file(self, path: str, site: str) -> None:
        """File-level corruption for streamed artifacts: truncate or
        bit-flip the on-disk file in place (same probability/streams as
        ``corrupt_artifact``, without buffering the payload)."""
        if not self.enabled or not self._roll(f"corrupt-{site}", site):
            return
        rng = self._rng(f"corrupt-{site}")
        size = os.path.getsize(path)
        if size < 2:
            return
        if rng.random() < 0.5:
            cut = rng.randrange(0, size - 1)
            self._record("corrupt", f"{site}:truncate@{cut}/{size}")
            os.truncate(path, cut)
            return
        i = rng.randrange(0, size)
        with open(path, "r+b") as f:
            f.seek(i)
            byte = f.read(1)[0]
            f.seek(i)
            f.write(bytes([byte ^ (1 << rng.randrange(8))]))
        self._record("corrupt", f"{site}:bitflip@{i}/{size}")

    # -- worker / channel kills ----------------------------------------------
    def plan_worker_kills(self, worker_ids: Sequence[int]
                          ) -> List[Tuple[int, int]]:
        """Distributed runs: ``[(input_seq_threshold, worker_id), ...]`` —
        SIGKILL plan over locally-spawned workers, always leaving at least
        one survivor.  Sorted by threshold."""
        cfg = self.config
        if cfg is None or cfg.kill <= 0 or len(worker_ids) < 2:
            return []
        rng = self._rng("kill")
        n = min(cfg.kill, len(worker_ids) - 1)
        victims = rng.sample(list(worker_ids), n)
        plan = sorted(
            (cfg.kill_after + rng.randrange(0, 25), w) for w in victims
        )
        self._record("kill", f"plan={plan}")
        return plan

    def plan_embedded_failures(self, exec_channels: Sequence[Tuple[int, int]]
                               ) -> List[Tuple[int, List[Tuple[int, int]]]]:
        """Embedded engine: ``[(after_tasks, [(actor, ch), ...]), ...]`` —
        at each task-count boundary, lose those exec channels (state, queued
        tasks, cached inputs) and run the recovery protocol."""
        cfg = self.config
        if cfg is None or cfg.kill <= 0 or not exec_channels:
            return []
        rng = self._rng("kill")
        plan = []
        after = cfg.kill_after
        for _ in range(cfg.kill):
            after += rng.randrange(0, 20)
            k = min(len(exec_channels), 1 + int(rng.random() < 0.3))
            plan.append((after, sorted(rng.sample(list(exec_channels), k))))
            after += 5  # recovery gets a few tasks of headroom between kills
        self._record("kill", f"embedded plan={plan}")
        return plan

    def plan_stream_kills(self, exec_channels: Sequence[Tuple[int, int]]
                          ) -> List[Tuple[int, List[Tuple[int, int]]]]:
        """Standing queries: ``[(after_tasks, [(actor, ch), ...]), ...]`` —
        a seeded, RE-ARMING kill plan over a stream's checkpointable
        operator channels.  ``kill`` kills land at cumulative handled-task
        thresholds spread from ``kill_after`` onward, each recovered through
        the tape-replay protocol while the stream keeps flowing."""
        cfg = self.config
        if cfg is None or cfg.kill <= 0 or not exec_channels:
            return []
        rng = self._rng("stream_kill")
        plan = []
        after = cfg.kill_after
        for _ in range(cfg.kill):
            after += rng.randrange(0, 15)
            k = min(len(exec_channels), 1 + int(rng.random() < 0.25))
            plan.append((after, sorted(rng.sample(list(exec_channels), k))))
            # standing queries keep running: later kills need the stream to
            # have made real progress since the recovery
            after += 12
        self._record("kill", f"stream plan={plan}")
        return plan

    def record_kill(self, label: str) -> None:
        self._record("kill", label)


CHAOS = ChaosPlane()


def publish_env(spec: Optional[str]) -> None:
    """Publish (or clear) the chaos spec in this process's environment so
    mp-spawned worker children inherit the same seeded plan, and configure
    the local plane to match.  The soak driver is the only caller."""
    if spec:
        os.environ["QK_CHAOS"] = spec
        CHAOS.configure(spec)
    else:
        os.environ.pop("QK_CHAOS", None)
        CHAOS.disable()
