"""chaos-smoke: seeded mixed-fault soak with end-to-end integrity checks.

    python -m quokka_tpu.chaos.soak [--runs 20] [--seed BASE] [--only I]

Each run picks a fault mode (cycled deterministically), composes a QK_CHAOS
spec from its seed, executes a fixed workload under injection, and asserts
the result is BIT-EXACT against an undisturbed baseline computed once with
chaos off.  Workload values are integer-valued float64s, so sums are exact
under any execution order — "bit-exact" is a real claim, not a tolerance.

Fault modes (cycled; ``--runs 20`` covers every mode at least twice):

  mixed        embedded engine; corrupt=0.3 on every artifact write plus a
               seeded chaos kill of random exec channels
  spill-storm  EVERY spill write corrupted (corrupt_spill=1.0), no
               checkpoints, scripted kill of the consuming channels — full
               tape replay must detect every corruption (checksum), then
               recover via input-lineage re-read + live-producer rewind
  ckpt-storm   EVERY checkpoint write corrupted (corrupt_ckpt=1.0) + kill —
               restore must detect, quarantine, and rewind to an older
               checkpoint (ultimately state 0)
  service      two concurrent queries on one QueryService under
               corrupt_ckpt + per-query scripted kills — both bit-exact,
               neighbors unaffected
  adapt-kill   a zipfian build fires the mid-query skew re-partition
               (planner/adapt.py), then BOTH adapted join channels die
               with no checkpoint — the replay must re-read the journaled
               ADT routing and stay bit-exact
  distributed  2 spawned workers; RPC drops/delays + flaky store calls +
               a chaos SIGKILL of a random worker at an input boundary
  batch-resume a child service running two durable batch queries is
               SIGKILLed mid-query under corrupt_ckpt=1.0 +
               corrupt_spill=0.3; the restarted supervisor resumes both
               from their manifests — every checkpoint restore must
               detect the corruption and fall back (ultimately to input
               lineage re-reads), and both results stay bit-exact

Every injected fault and every recovery action is a flight-recorder event
(``chaos.*``, ``integrity.corrupt``, ``recover.*``, ``rpc.retry``,
``store.retry``); per-run deltas of the corresponding counters are printed.
A failing run prints its QK_CHAOS spec and an exact replay command, then
the soak exits nonzero.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time
from contextlib import contextmanager

import numpy as np
import pandas as pd
import pyarrow as pa

from quokka_tpu.chaos import publish_env

_COUNTERS = ("integrity.corrupt", "chaos.corrupt", "chaos.rpc",
             "chaos.delay", "chaos.store", "chaos.kill", "rpc.reconnect",
             "rpc.dedup_hit", "store.retry", "recover.ckpt_fallback",
             "recover.producer_rewind", "adapt.fired")


def _snap():
    from quokka_tpu import obs

    return {n: obs.REGISTRY.counter(n).value for n in _COUNTERS}


def _delta(before):
    now = _snap()
    return {n: now[n] - before[n] for n in _COUNTERS if now[n] != before[n]}


@contextmanager
def _chaos(spec):
    publish_env(spec)
    try:
        yield
    finally:
        publish_env(None)


# -- workloads (integer-valued floats: order-independent exact sums) --------


def _tables():
    r = np.random.default_rng(20260804)
    n = 20_000
    agg = pa.table({
        "k": r.integers(0, 50, n).astype(np.int64),
        "v": r.integers(0, 100, n).astype(np.float64),
    })
    left = pa.table({
        "key": r.integers(0, 200, 8000).astype(np.int64),
        "x": r.integers(0, 50, 8000).astype(np.float64),
    })
    right = pa.table({
        "key": np.arange(0, 150, dtype=np.int64),
        "y": r.integers(0, 50, 150).astype(np.float64),
    })
    # zipfian build side for the adapt-kill mode: ~90% of the build rows
    # hash to one join channel, so the planner's mid-query skew trigger
    # (planner/adapt.py) fires before the scripted kill lands
    r2 = np.random.default_rng(20260807)
    n2 = 12_000
    keys = r2.integers(0, 50, n2)
    keys[r2.random(n2) < 0.9] = 0
    skew_build = pa.table({
        "k": keys.astype(np.int64),
        "v": r2.integers(0, 100, n2).astype(np.float64),
    })
    skew_probe = pa.table({
        "pk": np.arange(0, 50, dtype=np.int64),
        "g": (np.arange(0, 50) % 5).astype(np.int64),
    })
    return agg, left, right, skew_build, skew_probe


def _ctx(opt=True, **cfg):
    # the scripted inject_failure channel ids assume the same plan shapes
    # the fault-tolerance tests pin: default optimizer for the agg query
    # (actor 1 = partial agg), optimize=False for the join (actor 2 = join)
    from quokka_tpu import QuokkaContext

    ctx = QuokkaContext(optimize=opt)
    for k, v in cfg.items():
        ctx.set_config(k, v)
    return ctx


def _q_agg(ctx, table):
    from quokka_tpu.dataset.readers import InputArrowDataset

    s = ctx.read_dataset(InputArrowDataset(table, batch_rows=1024))
    return (s.groupby("k").agg_sql("sum(v) as sv, count(*) as n")
            .collect().sort_values("k").reset_index(drop=True))


def _q_join(ctx, left, right):
    from quokka_tpu.dataset.readers import InputArrowDataset

    ls = ctx.read_dataset(InputArrowDataset(left, batch_rows=512))
    rs = ctx.read_dataset(InputArrowDataset(right, batch_rows=64))
    return (ls.join(rs, on="key").groupby("key")
            .agg_sql("sum(x * y) as t, count(*) as n")
            .collect().sort_values("key").reset_index(drop=True))


def _q_skew(ctx, probe, build):
    from quokka_tpu.dataset.readers import InputArrowDataset

    ps = ctx.read_dataset(InputArrowDataset(probe, batch_rows=64))
    bs = ctx.read_dataset(InputArrowDataset(build, batch_rows=1024))
    return (ps.join(bs, left_on="pk", right_on="k").groupby("g")
            .agg_sql("sum(v) as sv, count(*) as n")
            .collect().sort_values("g").reset_index(drop=True))


def _exact(got, want, what):
    pd.testing.assert_frame_equal(got, want, check_exact=True,
                                  check_dtype=False, obj=what)


# -- fault modes -------------------------------------------------------------
# each mode: (name, expect_detection, fn(seed, tables, baselines) -> None)


def _spec_mixed(seed):
    return f"seed={seed},corrupt=0.3,kill=1,kill_after={8 + seed % 12}"


def _mode_mixed(seed, spec, tabs, base):
    with _chaos(spec), tempfile.TemporaryDirectory() as d:
        ctx = _ctx(fault_tolerance=True, hbq_path=d,
                   checkpoint_interval=(None, 3)[seed % 2])
        _exact(_q_agg(ctx, tabs[0]), base[0], "mixed agg")


def _spec_storm(seed):
    return f"seed={seed},corrupt_spill=1.0"


def _mode_spill_storm(seed, spec, tabs, base):
    # every spill corrupt + the partial agg loses both channels with no
    # checkpoint: the full-tape replay reads (and must reject) every spill
    with _chaos(spec), tempfile.TemporaryDirectory() as d:
        ctx = _ctx(fault_tolerance=True, hbq_path=d, checkpoint_interval=None,
                   inject_failure={"after_tasks": 15 + seed % 8,
                                   "channels": [(1, 0), (1, 1)]})
        _exact(_q_agg(ctx, tabs[0]), base[0], "spill-storm agg")


def _mode_spill_storm_join(seed, spec, tabs, base):
    with _chaos(spec), tempfile.TemporaryDirectory() as d:
        ctx = _ctx(opt=False, fault_tolerance=True, hbq_path=d,
                   checkpoint_interval=None,
                   inject_failure={"after_tasks": 14 + seed % 6,
                                   "channels": [(2, 0)]})
        _exact(_q_join(ctx, tabs[1], tabs[2]), base[1], "spill-storm join")


def _spec_ckpt_storm(seed):
    return f"seed={seed},corrupt_ckpt=1.0"


def _mode_ckpt_storm(seed, spec, tabs, base):
    with _chaos(spec), tempfile.TemporaryDirectory() as d:
        ctx = _ctx(fault_tolerance=True, hbq_path=d, checkpoint_interval=3,
                   inject_failure={"after_tasks": 10 + seed % 8,
                                   "channels": [(1, seed % 2)]})
        _exact(_q_agg(ctx, tabs[0]), base[0], "ckpt-storm agg")


def _spec_service(seed):
    return f"seed={seed},corrupt_ckpt=0.5"


def _mode_service(seed, spec, tabs, base):
    from quokka_tpu.service import QueryService

    with _chaos(spec), tempfile.TemporaryDirectory() as d:
        svc = QueryService(pool_size=2, spill_dir=d,
                           exec_config={"fault_tolerance": True,
                                        "checkpoint_interval": 3})
        try:
            ctx1 = _ctx(fault_tolerance=True, checkpoint_interval=3,
                        inject_failure={"after_tasks": 10 + seed % 5,
                                        "channels": [(1, 0)]})
            ctx2 = _ctx(opt=False, fault_tolerance=True,
                        checkpoint_interval=3)
            from quokka_tpu.dataset.readers import InputArrowDataset

            s1 = (ctx1.read_dataset(InputArrowDataset(tabs[0],
                                                      batch_rows=1024))
                  .groupby("k").agg_sql("sum(v) as sv, count(*) as n"))
            ls = ctx2.read_dataset(InputArrowDataset(tabs[1], batch_rows=512))
            rs = ctx2.read_dataset(InputArrowDataset(tabs[2], batch_rows=64))
            s2 = (ls.join(rs, on="key").groupby("key")
                  .agg_sql("sum(x * y) as t, count(*) as n"))
            h1, h2 = svc.submit(s1), svc.submit(s2)
            got1 = h1.to_df().sort_values("k").reset_index(drop=True)
            got2 = h2.to_df().sort_values("key").reset_index(drop=True)
            _exact(got1, base[0], "service agg")
            _exact(got2, base[1], "service join")
        finally:
            svc.shutdown()


def _spec_adapt(seed):
    return f"seed={seed},corrupt=0.3"


def _mode_adapt_kill(seed, spec, tabs, base):
    """A mid-query skew re-partition (planner/adapt.py) must survive losing
    BOTH channels of the adapted join with no checkpoint: the ADT routing
    records are journaled before the first salted push, so the full-tape
    replay re-reads them (_adapt_refresh) and routes the replayed batches
    exactly as the adapted run did — bit-exact, no double counting of the
    replicated probe partition."""
    from quokka_tpu import obs, optimizer

    # pin the shape the scripted kill assumes: broadcast off (the join
    # must be a hash exchange for the trigger to have an edge to salt) and
    # a trigger that fires a few build batches in.  plan probe above shows
    # actor 2 = the 2-channel join exec under these knobs.
    knobs = {"QK_BROADCAST_BYTES": "1", "QK_SKEW_RATIO": "1.5",
             "QK_ADAPT_MIN_ROWS": "4000"}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    thr, optimizer.BROADCAST_THRESHOLD = optimizer.BROADCAST_THRESHOLD, 0
    fired0 = obs.REGISTRY.counter("adapt.fired").value
    try:
        with _chaos(spec), tempfile.TemporaryDirectory() as d:
            ctx = _ctx(fault_tolerance=True, hbq_path=d,
                       checkpoint_interval=None,
                       inject_failure={"after_tasks": 16 + seed % 6,
                                       "channels": [(2, 0), (2, 1)]})
            _exact(_q_skew(ctx, tabs[4], tabs[3]), base[2],
                   "adapt-kill join")
        if obs.REGISTRY.counter("adapt.fired").value - fired0 < 1:
            raise AssertionError(
                "the zipfian build never fired the skew trigger — the "
                "run recovered but proved nothing about adapted routing")
    finally:
        optimizer.BROADCAST_THRESHOLD = thr
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _spec_stream(seed):
    return f"seed={seed},kill=2,kill_after={5 + seed % 4}"


def _mode_stream(seed, spec, tabs, base):
    """Standing query under seeded kills: a continuous windowed aggregate
    over a tailed CSV takes re-arming chaos kills of its streaming operator
    mid-stream, recovers through tape replay, and its merged pane deltas
    must be BIT-EXACT vs the pandas one-shot over the same rows."""
    import os
    import threading

    from quokka_tpu import QuokkaContext
    from quokka_tpu.service import QueryService
    from quokka_tpu.streaming import TailingCsvReader, tail_window_agg

    r = np.random.default_rng(seed)
    n = 3000
    df = pd.DataFrame({
        "t": np.sort(r.integers(0, 1000, n)),
        "k": r.integers(0, 4, n),
        "v": r.integers(0, 50, n).astype(np.float64),
    })
    truth = df.assign(ws=(df.t // 100) * 100).groupby(["ws", "k"]).agg(
        s=("v", "sum"), n=("v", "count")).reset_index() \
        .sort_values(["ws", "k"]).reset_index(drop=True)
    rows = [f"{x.t},{x.k},{x.v}\n" for x in df.itertuples(index=False)]
    with _chaos(spec), tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "events.csv")
        with open(path, "w") as f:
            f.writelines(rows[:400])

        def appender():
            i = 400
            while i < n:
                j = min(i + 260, n)
                with open(path, "a") as f:
                    f.writelines(rows[i:j])
                i = j
                time.sleep(0.04)

        th = threading.Thread(target=appender, daemon=True)
        svc = QueryService(pool_size=2, spill_dir=os.path.join(d, "spill"),
                           exec_config={"fault_tolerance": True,
                                        "checkpoint_interval": 3})
        try:
            import pyarrow as _pa

            schema = _pa.schema([("t", _pa.int64()), ("k", _pa.int64()),
                                 ("v", _pa.float64())])
            ctx = QuokkaContext()
            h = svc.submit_continuous(tail_window_agg(
                ctx, TailingCsvReader(path, schema, "t"), size=100, by="k",
                aggs=[("s", "sum", "v"), ("n", "count", None)]))
            th.start()
            th.join()
            deadline = time.time() + 60
            while time.time() < deadline:
                wm = h.watermark()
                if wm is not None and wm >= float(df.t.max()):
                    break
                time.sleep(0.05)
            deltas = h.poll_deltas()
            h.stop(timeout=120)
            deltas.extend(h.poll_deltas())
            merged = {}
            for tb in deltas:
                for row in tb.to_pylist():
                    key = (row["window_start"], row["k"])
                    val = (row["s"], row["n"])
                    assert merged.get(key, val) == val, \
                        f"pane {key} re-delivered with different content"
                    merged[key] = val
            got = pd.DataFrame(
                [(ws, k, s, cn) for (ws, k), (s, cn) in merged.items()],
                columns=["ws", "k", "s", "n"],
            ).sort_values(["ws", "k"]).reset_index(drop=True)
            for c in got.columns:
                got[c] = got[c].astype(np.float64)
            want = truth.copy()
            for c in want.columns:
                want[c] = want[c].astype(np.float64)
            _exact(got, want, "stream agg")
        finally:
            svc.shutdown()


def _spec_batch_resume(seed):
    # EVERY checkpoint write corrupt (restore MUST detect, quarantine and
    # fall back regardless of seed) + 30% of spills corrupt (the resume's
    # spill verification and the replay's lineage-recompute fallback both
    # get exercised); the spec reaches the child service via QK_CHAOS
    return f"seed={seed},corrupt_ckpt=1.0,corrupt_spill=0.3"


def _mode_batch_resume(seed, spec, tabs, base):
    """The resume-smoke harness under a corruption storm: the child service
    (inheriting QK_CHAOS) corrupts every checkpoint and 30% of spills it
    writes before the SIGKILL lands; the parent-side supervisor resume then
    has to detect all of it — quarantined snapshots fall back toward state
    0, broken spills recompute from frozen input lineage — and still
    deliver both queries bit-exact vs the undisturbed one-shot runs."""
    from quokka_tpu.service import resume_smoke

    with _chaos(spec), tempfile.TemporaryDirectory() as d:
        resume_smoke.run(d, seed, log=lambda *a, **k: None)


def _spec_distributed(seed):
    return (f"seed={seed},rpc=0.03,delay=0.05,store=0.05,"
            f"kill=1,kill_after={6 + seed % 6}")


def _mode_distributed(seed, spec, tabs, base):
    from quokka_tpu.utils.cluster import LocalCluster

    with _chaos(spec):
        from quokka_tpu import QuokkaContext

        ctx = QuokkaContext(
            cluster=LocalCluster(n_workers=2),
            exec_config={"fault_tolerance": True, "checkpoint_interval": 2},
        )
        _exact(_q_agg(ctx, tabs[0]), base[0], "distributed agg")


# name, spec_fn (pure: the replay line must exist BEFORE the run can
# fail), run_fn, expect_corruption_detections
MODES = [
    ("mixed", _spec_mixed, _mode_mixed, False),
    ("spill-storm", _spec_storm, _mode_spill_storm, True),
    ("ckpt-storm", _spec_ckpt_storm, _mode_ckpt_storm, True),
    ("service", _spec_service, _mode_service, False),
    ("adapt-kill", _spec_adapt, _mode_adapt_kill, False),
    ("spill-storm-join", _spec_storm, _mode_spill_storm_join, True),
    ("ckpt-storm", _spec_ckpt_storm, _mode_ckpt_storm, True),
    # the stream, adapt-kill and batch-resume modes REPLACE existing slots
    # rather than growing the cycle: inserting an 11th entry would shift
    # every later run's (mode, seed) pairing, and the storm modes'
    # detection assertions are only validated for the seeds they get
    ("stream", _spec_stream, _mode_stream, False),
    ("distributed", _spec_distributed, _mode_distributed, False),
    ("batch-resume", _spec_batch_resume, _mode_batch_resume, True),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--runs", type=int, default=20)
    ap.add_argument("--seed", type=int, default=20260804,
                    help="base seed; run i uses seed base+i")
    ap.add_argument("--only", type=int, default=None,
                    help="replay a single run index (failure triage)")
    args = ap.parse_args(argv)

    from quokka_tpu import obs
    from quokka_tpu.obs import alerts

    # plan-shape isolation: the scripted inject_failure channel ids assume
    # the pinned cold-plan shapes (see _ctx).  The planner re-sizes
    # channels from the persisted cardinality profile, so a populated
    # developer cache — or this soak's OWN baseline runs — would shrink
    # the tiny aggs to one channel and the scripted kills would target
    # channels that don't exist.  Same discipline as tests/conftest.py.
    os.environ["QK_CARDPROFILE_DIR"] = ""
    os.environ["QK_MEMPROFILE_DIR"] = ""

    publish_env(None)  # baselines run undisturbed
    tabs = _tables()
    t0 = time.time()
    base = (_q_agg(_ctx(), tabs[0]), _q_join(_ctx(), tabs[1], tabs[2]),
            _q_skew(_ctx(), tabs[4], tabs[3]))
    print(f"[chaos-smoke] baselines in {time.time() - t0:.1f}s; "
          f"{args.runs} seeded runs, base seed {args.seed}", flush=True)

    indices = [args.only] if args.only is not None else range(args.runs)
    failures = 0
    total_detected = 0
    for i in indices:
        name, spec_fn, fn, expect_detect = MODES[i % len(MODES)]
        seed = args.seed + i
        if expect_detect:
            # storm modes also prove the ALERT plane sees the storm: two
            # back-to-back evaluations flush any pending integrity delta
            # and guarantee the rule is INACTIVE going in, so the post-run
            # evaluation below must re-fire it edge-triggered
            alerts.ENGINE.evaluate_now()
            alerts.ENGINE.evaluate_now()
        fired0 = obs.REGISTRY.counter("alert.integrity").value
        before = _snap()
        t0 = time.time()
        spec = spec_fn(seed)
        try:
            fn(seed, spec, tabs, base)
            d = _delta(before)
            detected = d.get("integrity.corrupt", 0)
            total_detected += detected
            if expect_detect and detected == 0:
                raise AssertionError(
                    "corruption was injected on every artifact write but "
                    "ZERO corruptions were detected on read — the "
                    "integrity check is not being exercised")
            if expect_detect:
                alerts.ENGINE.evaluate_now()
                fired = obs.REGISTRY.counter(
                    "alert.integrity").value - fired0
                if fired < 1:
                    raise AssertionError(
                        f"{detected} corruption(s) were detected but the "
                        "alert engine's integrity rule never fired — "
                        "/health would have slept through the storm")
                d["alert.integrity"] = fired
            print(f"[chaos-smoke] run {i:>2} {name:<16} seed={seed} "
                  f"ok in {time.time() - t0:5.1f}s  {d}", flush=True)
        except Exception as e:  # noqa: BLE001 — report, count, continue
            failures += 1
            print(f"[chaos-smoke] run {i:>2} {name:<16} seed={seed} "
                  f"FAILED in {time.time() - t0:5.1f}s: {e!r}", flush=True)
            # the replay command re-derives this exact spec from the seed
            # (no env prefix: the soak sets QK_CHAOS itself per run)
            print(f"[chaos-smoke]   spec was QK_CHAOS=\"{spec}\"; replay: "
                  f"python -m quokka_tpu.chaos.soak --only {i} "
                  f"--seed {args.seed}", flush=True)
        finally:
            publish_env(None)
    if args.only is None and total_detected == 0:
        print("[chaos-smoke] FAIL: no corruption was ever detected across "
              "the soak — integrity checks are dead", flush=True)
        return 1
    if failures:
        print(f"[chaos-smoke] {failures}/{len(list(indices))} runs FAILED",
              flush=True)
        return 1
    print(f"[chaos-smoke] all runs bit-exact; "
          f"{total_detected} corruptions detected and recovered", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
