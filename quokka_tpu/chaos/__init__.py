"""Chaos plane: seeded multi-layer fault injection (see chaos/plane.py).

Import surface::

    from quokka_tpu.chaos import CHAOS          # the process switchboard
    CHAOS.configure("seed=42,rpc=0.05,corrupt=0.02,kill=1")
    CHAOS.disable()

The soak driver lives in ``quokka_tpu.chaos.soak`` (``make chaos-smoke``).
"""

from quokka_tpu.chaos.plane import (  # noqa: F401
    CHAOS,
    ChaosConfig,
    ChaosPlane,
    ChaosSpecError,
    publish_env,
)
