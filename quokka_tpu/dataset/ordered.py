"""Sorted input readers.

Reference parity: InputSortedEC2ParquetDataset (pyquokka/dataset/
ordered_readers.py:3-150): infer global time order from Parquet row-group
statistics, assert non-overlap, and assign row groups to channels either
round-robin in time order ("stride" — channels interleave, the cache's SAT
delivery reconstructs global order) or as contiguous time ranges ("range").
"""

from __future__ import annotations

from typing import Dict, List, Optional

import pyarrow.parquet as pq

from quokka_tpu.dataset.readers import InputParquetDataset, _expand_paths


class InputSortedParquetDataset(InputParquetDataset):
    def __init__(self, path, sorted_by: str, columns=None, predicate=None,
                 mode: str = "stride"):
        super().__init__(path, columns=columns, predicate=predicate)
        self.sorted_by = sorted_by
        if mode not in ("stride", "range"):
            raise ValueError(mode)
        self.mode = mode

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        pieces = []  # (min_stat, file, rg)
        for f in _expand_paths(self.path):
            pf = pq.ParquetFile(f)
            meta = pf.metadata
            schema = pf.schema_arrow
            col_idx = {meta.row_group(0).column(i).path_in_schema: i
                       for i in range(meta.num_columns)} if meta.num_row_groups else {}
            if self.sorted_by not in col_idx:
                raise ValueError(f"sort column {self.sorted_by} not in {f}")
            for rg in range(meta.num_row_groups):
                rgm = meta.row_group(rg)
                st = rgm.column(col_idx[self.sorted_by]).statistics
                if st is None or not st.has_min_max:
                    raise ValueError(
                        f"row group {rg} of {f} lacks min/max stats on "
                        f"{self.sorted_by}; cannot order"
                    )
                if self.predicate is not None:
                    from quokka_tpu.dataset.readers import _rowgroup_prunable

                    if _rowgroup_prunable(rgm, self.predicate, schema):
                        continue
                pieces.append((st.min, st.max, f, rg))
        pieces.sort(key=lambda p: p[0])
        # assert global non-overlap (the reference does the same,
        # unordered_readers.py:351)
        for a, b in zip(pieces, pieces[1:]):
            if a[1] > b[0]:
                raise ValueError(
                    f"row groups overlap on {self.sorted_by}: "
                    f"[{a[0]}, {a[1]}] vs [{b[0]}, {b[1]}]"
                )
        lineages = [(f, rg) for _, _, f, rg in pieces]
        if self.mode == "stride":
            return {ch: lineages[ch::num_channels] for ch in range(num_channels)}
        per = (len(lineages) + num_channels - 1) // max(num_channels, 1)
        return {
            ch: lineages[ch * per : (ch + 1) * per] for ch in range(num_channels)
        }
