"""Object-store and REST readers.

The reference reads S3 with byte-range GETs for CSV (newline-boundary
refinement, pyquokka/dataset/unordered_readers.py:3-72 InputS3CSVDataset) and
threaded footer/row-group GETs for Parquet (unordered_readers.py:646-760).
Here the same designs sit behind fsspec, so one implementation serves
local files (file://), S3 (s3:// when s3fs is installed), GCS, HTTP, etc.,
and the tests drive the exact S3 code path against local files.

The REST reader mirrors the reference's crypto_dataset.py: paged HTTP GETs as
lineage units, JSON records to Arrow.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq


def resolve_fs(url: str):
    """(filesystem, path) for a URL; local paths work bare."""
    import fsspec

    try:
        fs, path = fsspec.core.url_to_fs(url)
    except ImportError as e:  # e.g. s3:// without s3fs in the image
        raise ImportError(
            f"filesystem for {url!r} needs an fsspec backend that is not "
            f"installed ({e}); local file paths and file:// always work"
        ) from None
    return fs, path


def _expand(fs, path: str) -> List[str]:
    if any(ch in path for ch in "*?["):
        return sorted(fs.glob(path))
    if fs.isdir(path):
        return sorted(p for p in fs.ls(path) if not fs.isdir(p))
    return [path]


class InputObjectCSVDataset:
    """Byte-range partitioned CSV over any fsspec filesystem.

    Lineage = (file, start, end): each channel reads its ranges with two
    range-GETs at most — the range itself plus a small tail read to finish
    the last row — and trims to newline boundaries so every row is parsed
    exactly once (the InputS3CSVDataset technique)."""

    def __init__(self, url: str, names: Optional[Sequence[str]] = None,
                 stride: int = 16 << 20, has_header: bool = True, sep: str = ","):
        self.url = url
        self.names = list(names) if names else None
        self.stride = stride
        self.has_header = has_header
        self.sep = sep
        self._schema_names: Optional[List[str]] = None
        self._arrow_schema = None  # inferred once; pins types across ranges

    @property
    def schema(self) -> List[str]:
        if self._schema_names is None:
            fs, path = resolve_fs(self.url)
            f0 = _expand(fs, path)[0]
            head = fs.open(f0, "rb").read(1 << 16)
            first = head.split(b"\n", 1)[0].decode("utf-8", "replace")
            cols = [c.strip().strip('"') for c in first.split(self.sep)]
            if self.has_header:
                self._schema_names = cols
            else:
                self._schema_names = self.names or [f"f{i}" for i in range(len(cols))]
        return self._schema_names

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        fs, path = resolve_fs(self.url)
        lineages: List[Tuple[str, int, int]] = []
        for f in _expand(fs, path):
            size = fs.size(f)
            start = 0
            while start < size:
                end = min(start + self.stride, size)
                lineages.append((f, start, end))
                start = end
        return {ch: lineages[ch::num_channels] for ch in range(num_channels)}

    def _pinned_schema(self, fs, f) -> pa.Schema:
        """Column types inferred ONCE from the file head and pinned for every
        range — per-range inference could type '123' as int in one range and
        string in another (readers.py pins the same way)."""
        if self._arrow_schema is None:
            head = fs.cat_file(f, 0, min(1 << 20, fs.size(f)))
            head = head[: head.rfind(b"\n") + 1] or head
            ro = (pacsv.ReadOptions() if self.has_header
                  else pacsv.ReadOptions(column_names=self.schema))
            t = pacsv.read_csv(
                pa.BufferReader(head), read_options=ro,
                parse_options=pacsv.ParseOptions(delimiter=self.sep),
            )
            self._arrow_schema = t.schema
        return self._arrow_schema

    def execute(self, channel: int, lineage) -> pa.Table:
        fs, _ = resolve_fs(self.url)
        f, start, end = lineage
        size = fs.size(f)
        schema = self._pinned_schema(fs, f)
        raw = fs.cat_file(f, start, min(end, size))
        if end < size:
            # FIRST extend to the end of the last row (tail reads until a
            # newline) — extending after dropping the torn head would parse a
            # foreign row's tail bytes as a row when a row spans the stride
            tail_at = end
            while True:
                chunk = fs.cat_file(f, tail_at, min(tail_at + (1 << 20), size))
                nl = chunk.find(b"\n")
                if nl >= 0:
                    raw += chunk[:nl]
                    break
                raw += chunk
                tail_at += len(chunk)
                if tail_at >= size or not chunk:
                    break
        if start > 0:
            # then drop the torn first row: it belongs to the previous range
            nl = raw.find(b"\n")
            raw = raw[nl + 1:] if nl >= 0 else b""
        names = self.schema
        if not raw.strip():
            return schema.empty_table()
        read_opts = pacsv.ReadOptions(column_names=names)
        if self.has_header and start == 0:
            read_opts = pacsv.ReadOptions()  # header row present in this range
        return pacsv.read_csv(
            pa.BufferReader(raw),
            read_options=read_opts,
            parse_options=pacsv.ParseOptions(delimiter=self.sep),
            convert_options=pacsv.ConvertOptions(
                column_types={n: schema.field(n).type for n in schema.names}
            ),
        )


class InputObjectParquetDataset:
    """Row-group partitioned Parquet over any fsspec filesystem: footer read
    per file at plan time, one row-group read per lineage, with column
    pushdown and row-group min/max skipping (unordered_readers.py:646-760)."""

    def __init__(self, url: str, columns: Optional[Sequence[str]] = None,
                 predicate=None):
        self.url = url
        self.columns = list(columns) if columns else None
        self.predicate = predicate  # conjunction usable for row-group skipping
        self._schema: Optional[pa.Schema] = None

    @property
    def schema(self) -> pa.Schema:
        if self._schema is None:
            fs, path = resolve_fs(self.url)
            f0 = _expand(fs, path)[0]
            self._schema = pq.ParquetFile(fs.open(f0, "rb")).schema_arrow
        return self._schema

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        from quokka_tpu.dataset.readers import _rowgroup_prunable

        fs, path = resolve_fs(self.url)
        lineages: List[Tuple[str, int]] = []
        for f in _expand(fs, path):
            pf = pq.ParquetFile(fs.open(f, "rb"))
            meta = pf.metadata
            schema = pf.schema_arrow
            for rg in range(meta.num_row_groups):
                if self.predicate is not None and _rowgroup_prunable(
                    meta.row_group(rg), self.predicate, schema
                ):
                    continue
                lineages.append((f, rg))
        return {ch: lineages[ch::num_channels] for ch in range(num_channels)}

    def execute(self, channel: int, lineage) -> pa.Table:
        fs, _ = resolve_fs(self.url)
        f, rg = lineage
        pf = pq.ParquetFile(fs.open(f, "rb"))
        cols = self.columns
        if cols is not None:
            cols = [c for c in cols if c in set(pf.schema_arrow.names)]
        return pf.read_row_group(rg, columns=cols)


class InputRestDataset:
    """Paged REST endpoint reader (the reference's crypto_dataset.py shape,
    GET and POST variants): lineage = one (url, params) request; JSON records
    become Arrow rows.  method="post" sends `params` as the JSON body (the
    reference's graphql/POST crypto feeds)."""

    def __init__(self, requests_list: Sequence[Tuple[str, Optional[dict]]],
                 record_path: Optional[str] = None,
                 schema: Optional[Sequence[str]] = None,
                 method: str = "get",
                 headers: Optional[dict] = None):
        if method.lower() not in ("get", "post"):
            raise ValueError(f"method must be 'get' or 'post', got {method!r}")
        self.requests_list = [(u, dict(p) if p else None) for u, p in requests_list]
        self.record_path = record_path
        self.method = method.lower()
        self.headers = dict(headers) if headers else None
        self._schema_names = list(schema) if schema else None
        self._first_page: Optional[pa.Table] = None  # plan-time fetch reuse

    @property
    def schema(self) -> Optional[List[str]]:
        if self._schema_names is None:
            # schema inference must fetch page 0; CACHE it so the runtime's
            # first lineage doesn't re-hit a rate-limited/non-idempotent API
            self._first_page = self._fetch(self.requests_list[0])
            self._schema_names = list(self._first_page.column_names)
        return self._schema_names

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        return {
            ch: self.requests_list[ch::num_channels] for ch in range(num_channels)
        }

    def execute(self, channel: int, lineage) -> pa.Table:
        url, params = lineage
        if self._first_page is not None and (url, params) == tuple(self.requests_list[0]):
            t, self._first_page = self._first_page, None
            return t
        return self._fetch((url, params))

    def _fetch(self, req) -> pa.Table:
        import requests

        url, params = req
        if self.method == "post":
            r = requests.post(url, json=params, headers=self.headers, timeout=60)
        else:
            r = requests.get(url, params=params, headers=self.headers, timeout=60)
        r.raise_for_status()
        data = r.json()
        if self.record_path is not None:
            data = data[self.record_path]
        if not isinstance(data, list):
            data = [data]
        return pa.Table.from_pylist(data)


class InputLanceDataset:
    """Lance-format reader (reference InputLanceDataset,
    pyquokka/dataset/unordered_readers.py:101-205): one lineage unit per
    fragment.  Requires the `lance` library; QuokkaContext.read_lance raises
    with the supported substitute (Parquet + IVF ANN sidecar) when it is
    absent.  Module-level so the reader pickles into distributed specs."""

    def __init__(self, path: str, columns: Optional[Sequence[str]] = None):
        self.path = path
        self._cols = list(columns) if columns else None
        self._ds = None

    def _dataset(self):
        if self._ds is None:
            import lance

            self._ds = lance.dataset(self.path)
        return self._ds

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_ds"] = None  # re-open on the worker
        return d

    @property
    def schema(self) -> List[str]:
        if self._cols:
            return list(self._cols)
        return [f.name for f in self._dataset().schema]

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        ids = [f.fragment_id for f in self._dataset().get_fragments()]
        return {ch: ids[ch::num_channels] for ch in range(num_channels)}

    def execute(self, channel: int, lineage) -> pa.Table:
        frag = self._dataset().get_fragment(lineage)
        return frag.to_table(columns=self._cols)


class InputFilesDataset:
    """Whole-file-as-rows reader: each file becomes one row of
    (filename, object-bytes) — the reference's InputDiskFilesDataset /
    InputS3FilesDataset (pyquokka/dataset/unordered_readers.py:206-272), used
    for unstructured blobs (images, documents).  `path` is a local directory,
    a glob, or any fsspec URL (s3://bucket/prefix); lineage = one batch of
    `files_per_batch` filenames, so replay re-reads exactly the lost files."""

    SCHEMA = ["filename", "object"]

    def __init__(self, path: str, files_per_batch: int = 1):
        self.path = path
        self.files_per_batch = max(1, int(files_per_batch))
        self._fs = None
        self._files: Optional[List[str]] = None

    @property
    def schema(self) -> List[str]:
        return list(self.SCHEMA)

    def _list(self) -> List[str]:
        if self._files is None:
            import os

            if "://" in self.path:
                fs, root = resolve_fs(self.path)
                self._fs = fs
                if any(ch in root for ch in "*?["):
                    files = _expand(fs, root)
                elif fs.isdir(root):
                    # a directory/prefix lists RECURSIVELY (fs.find) — a
                    # top-level-only listing would silently drop files in
                    # nested prefixes
                    files = [f for f in fs.find(root)]
                else:
                    files = _expand(fs, root)  # single object
                self._files = sorted(files)
            else:
                self._fs = None
                if os.path.isdir(self.path):
                    candidates = (
                        os.path.join(self.path, f)
                        for f in os.listdir(self.path)
                    )
                else:
                    import glob as _glob

                    candidates = _glob.glob(self.path)
                # globs can match subdirectories: only regular files are rows
                self._files = sorted(f for f in candidates if os.path.isfile(f))
            if not self._files:
                raise FileNotFoundError(f"no files match {self.path!r}")
        return self._files

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        files = self._list()
        batches = [
            files[i:i + self.files_per_batch]
            for i in range(0, len(files), self.files_per_batch)
        ]
        return {ch: batches[ch::num_channels] for ch in range(num_channels)}

    def execute(self, channel: int, lineage) -> pa.Table:
        names, blobs = [], []
        for f in lineage:
            if self._fs is not None or "://" in f:
                if self._fs is None:
                    self._fs, _ = resolve_fs(self.path)
                with self._fs.open(f, "rb") as fh:
                    blobs.append(fh.read())
            else:
                with open(f, "rb") as fh:
                    blobs.append(fh.read())
            names.append(f)
        return pa.table(
            {"filename": pa.array(names), "object": pa.array(blobs, pa.binary())}
        )
