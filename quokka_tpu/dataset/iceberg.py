"""Iceberg table reader (metadata tier).

Reference parity: QuokkaContext.read_iceberg (pyquokka/df.py:802), which
walks an Iceberg table's metadata through pyiceberg and scans the resulting
parquet file list.  pyiceberg is not in this image, so the walk is
implemented directly against the public table spec with the in-repo Avro
reader (dataset/avro.py):

    table_dir/metadata/version-hint.text     -> current metadata version
    table_dir/metadata/vN.metadata.json      -> snapshots, schemas, specs
    snapshot["manifest-list"]  (avro)        -> manifest file paths   (v2)
    snapshot["manifests"]                    -> same, inline          (v1)
    manifest (avro) entries                  -> data files + status

Data files with status DELETED(2) are dropped; the survivors feed the
existing local parquet reader (row-group channels, stats pruning, scan
cache), so predicate/projection pushdown and ANN pruning all apply
unchanged.  ``snapshot_id`` gives time travel to any retained snapshot.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from quokka_tpu.dataset import avro

STATUS_DELETED = 2


class IcebergError(ValueError):
    pass


def _local_path(uri: str, table_dir: str, location: Optional[str]) -> str:
    """Map a metadata-recorded URI to a local filesystem path.  Tables are
    commonly relocated after writing; paths under the recorded table
    ``location`` are re-rooted onto table_dir."""
    p = uri
    if p.startswith("file://"):
        p = p[len("file://"):]
    if location:
        loc = location
        if loc.startswith("file://"):
            loc = loc[len("file://"):]
        if p.startswith(loc.rstrip("/") + "/"):
            p = os.path.join(table_dir, p[len(loc.rstrip("/")) + 1:])
    if not os.path.isabs(p):
        p = os.path.join(table_dir, p)
    return p


class IcebergTable:
    def __init__(self, table_dir: str):
        self.table_dir = table_dir
        meta_dir = os.path.join(table_dir, "metadata")
        if not os.path.isdir(meta_dir):
            raise IcebergError(f"{table_dir} has no metadata/ directory")
        self.metadata = self._load_metadata(meta_dir)
        self.location = self.metadata.get("location")

    @staticmethod
    def _load_metadata(meta_dir: str) -> Dict:
        hint = os.path.join(meta_dir, "version-hint.text")
        path = None
        if os.path.exists(hint):
            with open(hint) as f:
                v = f.read().strip()
            cand = os.path.join(meta_dir, f"v{v}.metadata.json")
            if os.path.exists(cand):
                path = cand
        if path is None:
            versions = sorted(
                f for f in os.listdir(meta_dir) if f.endswith(".metadata.json")
            )
            if not versions:
                raise IcebergError(f"no *.metadata.json under {meta_dir}")
            path = os.path.join(meta_dir, versions[-1])
        with open(path) as f:
            return json.load(f)

    @property
    def snapshots(self) -> List[Dict]:
        return self.metadata.get("snapshots", [])

    @property
    def current_snapshot_id(self) -> Optional[int]:
        return self.metadata.get("current-snapshot-id")

    def snapshot(self, snapshot_id: Optional[int] = None) -> Dict:
        sid = snapshot_id if snapshot_id is not None else self.current_snapshot_id
        if sid is None or sid == -1:
            raise IcebergError("table has no current snapshot")
        for s in self.snapshots:
            if s.get("snapshot-id") == sid:
                return s
        raise IcebergError(
            f"snapshot {sid} not found (have "
            f"{[s.get('snapshot-id') for s in self.snapshots]})"
        )

    def _manifest_paths(self, snap: Dict) -> List[str]:
        if "manifest-list" in snap:  # v2 (and most v1 writers)
            mlist = _local_path(snap["manifest-list"], self.table_dir, self.location)
            records, _ = avro.read_path(mlist)
            paths = []
            for r in records:
                # Iceberg v2 manifest-list `content`: 0 = data manifests,
                # 1 = delete manifests (position/equality deletes).  Applying
                # row-level deletes is unsupported; failing loudly beats
                # scanning delete files as data.
                if int(r.get("content", 0) or 0) != 0:
                    raise IcebergError(
                        "table has row-level delete manifests (Iceberg v2 "
                        "merge-on-read); delete files are not supported"
                    )
                paths.append(
                    _local_path(r["manifest_path"], self.table_dir, self.location)
                )
            return paths
        if "manifests" in snap:  # v1 inline form
            return [
                _local_path(p, self.table_dir, self.location)
                for p in snap["manifests"]
            ]
        raise IcebergError("snapshot carries neither manifest-list nor manifests")

    def data_files(self, snapshot_id: Optional[int] = None) -> List[str]:
        """Live parquet data files of a snapshot, metadata order."""
        snap = self.snapshot(snapshot_id)
        out: List[str] = []
        for mpath in self._manifest_paths(snap):
            entries, _ = avro.read_path(mpath)
            for e in entries:
                if e.get("status") == STATUS_DELETED:
                    continue
                df = e.get("data_file") or {}
                # data_file `content`: 0 = data, 1 = position deletes,
                # 2 = equality deletes
                if int(df.get("content", 0) or 0) != 0:
                    raise IcebergError(
                        "snapshot contains row-level delete files; "
                        "delete files are not supported"
                    )
                fmt = str(df.get("file_format", "PARQUET")).upper()
                if fmt != "PARQUET":
                    raise IcebergError(f"unsupported data file format {fmt}")
                out.append(
                    _local_path(df["file_path"], self.table_dir, self.location)
                )
        return out


def data_files(table_dir: str, snapshot_id: Optional[int] = None) -> List[str]:
    return IcebergTable(table_dir).data_files(snapshot_id)
