from quokka_tpu.dataset.readers import (
    InputArrowDataset,
    InputCSVDataset,
    InputJSONDataset,
    InputParquetDataset,
)
