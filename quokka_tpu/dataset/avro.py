"""Minimal Avro Object Container File reader.

Iceberg's table metadata tier stores manifest lists and manifests as Avro
files (the reference reads them through pyiceberg, df.py:802); neither
pyiceberg nor fastavro is available in this image, so this module implements
the small subset of the Avro 1.11 spec those files need, from the public
format definition:

- container framing: magic ``Obj\\x01``, file-metadata map (schema JSON +
  codec), 16-byte sync marker, then (count, byte-size, payload, sync) blocks
- codecs: ``null`` and ``deflate`` (raw zlib, no header)
- decoding: records, unions, arrays, maps, and all primitives; enums decode
  to their symbol string, fixed to bytes.  Logical types are returned as
  their raw representation (Iceberg's readers interpret them downstream).

Writing is NOT implemented — the engine only consumes Iceberg metadata
(tests carry their own tiny spec-following encoder plus golden-byte
fixtures, so the reader is not validated against itself alone).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Tuple

MAGIC = b"Obj\x01"


class AvroError(ValueError):
    pass


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise AvroError("truncated avro data")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    # -- primitives ---------------------------------------------------------
    def long(self) -> int:
        """zigzag varint (int and long share the encoding)."""
        shift = 0
        acc = 0
        while True:
            b = self.read(1)[0]
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 70:
                raise AvroError("varint too long")
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        n = self.long()
        if n < 0:
            raise AvroError("negative bytes length")
        return self.read(n)

    def string(self) -> str:
        return self.bytes_().decode("utf-8")

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def boolean(self) -> bool:
        return self.read(1) != b"\x00"


def _decode(r: _Reader, schema) -> Any:
    """Decode one datum per the (parsed-JSON) schema."""
    if isinstance(schema, list):  # union: branch index, then value
        idx = r.long()
        if not 0 <= idx < len(schema):
            raise AvroError(f"union branch {idx} out of range")
        return _decode(r, schema[idx])
    if isinstance(schema, str):
        t = schema
    else:
        t = schema["type"]
    if t == "null":
        return None
    if t == "boolean":
        return r.boolean()
    if t in ("int", "long"):
        return r.long()
    if t == "float":
        return r.float_()
    if t == "double":
        return r.double()
    if t == "bytes":
        return r.bytes_()
    if t == "string":
        return r.string()
    if t == "record":
        out = {}
        for f in schema["fields"]:
            out[f["name"]] = _decode(r, f["type"])
        return out
    if t == "array":
        items = schema["items"]
        out_l: List[Any] = []
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:  # block with explicit byte size (skippable form)
                n = -n
                r.long()  # byte size, unused
            for _ in range(n):
                out_l.append(_decode(r, items))
        return out_l
    if t == "map":
        values = schema["values"]
        out_m: Dict[str, Any] = {}
        while True:
            n = r.long()
            if n == 0:
                break
            if n < 0:
                n = -n
                r.long()
            for _ in range(n):
                # key MUST be read before the value (RHS of a subscript
                # assignment evaluates first)
                k = r.string()
                out_m[k] = _decode(r, values)
        return out_m
    if t == "enum":
        idx = r.long()
        symbols = schema["symbols"]
        if not 0 <= idx < len(symbols):
            raise AvroError(f"enum index {idx} out of range")
        return symbols[idx]
    if t == "fixed":
        return r.read(int(schema["size"]))
    raise AvroError(f"unsupported avro type {t!r}")


def _resolve_named(schema, names: Dict[str, Any]):
    """Register and resolve named-type references (a schema may reference an
    earlier record/enum/fixed by name)."""
    if isinstance(schema, list):
        return [_resolve_named(s, names) for s in schema]
    if isinstance(schema, str):
        return names.get(schema, schema)
    t = schema.get("type")
    if t in ("record", "enum", "fixed"):
        name = schema.get("name")
        if name is not None:
            names[name] = schema
            full = schema.get("namespace")
            if full:
                names[f"{full}.{name}"] = schema
        if t == "record":
            schema = dict(schema)
            schema["fields"] = [
                {**f, "type": _resolve_named(f["type"], names)}
                for f in schema["fields"]
            ]
            names[schema["name"]] = schema
        return schema
    if t == "array":
        return {**schema, "items": _resolve_named(schema["items"], names)}
    if t == "map":
        return {**schema, "values": _resolve_named(schema["values"], names)}
    return schema


def read_file(data: bytes) -> Tuple[List[dict], dict]:
    """Decode a whole container file -> (records, file_metadata)."""
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise AvroError("not an avro object container file")
    meta_schema = {"type": "map", "values": "bytes"}
    meta = _decode(r, meta_schema)  # str keys (avro map), bytes values
    sync = r.read(16)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    schema = _resolve_named(schema, {})
    codec = meta.get("avro.codec", b"null").decode()
    records: List[dict] = []
    while not r.at_end():
        count = r.long()
        size = r.long()
        if count < 0 or size < 0:
            raise AvroError(
                f"corrupt block header: count={count} size={size}"
            )
        payload = r.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise AvroError(f"unsupported codec {codec!r}")
        br = _Reader(payload)
        for _ in range(count):
            records.append(_decode(br, schema))
        if r.read(16) != sync:
            raise AvroError("sync marker mismatch")
    return records, meta


def read_path(path: str) -> Tuple[List[dict], dict]:
    with open(path, "rb") as f:
        return read_file(f.read())
