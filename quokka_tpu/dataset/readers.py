"""Input readers.

Reader protocol (same as the reference, pyquokka/dataset/unordered_readers.py:30-42):
  get_own_state(num_channels) -> {channel: [lineage, ...]}
  execute(channel, lineage) -> pyarrow.Table
Lineage entries are small, picklable descriptions of an input slice — the unit
of deterministic re-execution for fault tolerance.

Implemented here: Parquet (per-row-group partitioning with column pushdown +
row-group min/max skipping), CSV (byte-range partitioning with newline-boundary
refinement, the technique of InputDiskCSVDataset, unordered_readers.py:273-442),
JSON-lines, and in-memory Arrow tables.
"""

from __future__ import annotations

import collections
import functools
import glob as globmod
import io
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from quokka_tpu.expression import (
    BinOp,
    ColRef,
    DateLit,
    Expr,
    InList,
    Literal,
    split_conjuncts,
)


class InputArrowDataset:
    """In-memory table split into row slices (from_arrow / from_pandas)."""

    def __init__(self, table: pa.Table, batch_rows: int = 1 << 20):
        self.table = table
        self.batch_rows = batch_rows

    @property
    def schema(self) -> pa.Schema:
        return self.table.schema

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        n = self.table.num_rows
        slices = []
        start = 0
        while start < n:
            end = min(start + self.batch_rows, n)
            slices.append((start, end - start))
            start = end
        if not slices:
            slices = [(0, 0)]
        return {ch: slices[ch::num_channels] for ch in range(num_channels)}

    def execute(self, channel: int, lineage) -> pa.Table:
        start, length = lineage
        return self.table.slice(start, length)

    def size_hint(self) -> int:
        """Estimated source bytes (query-service admission control)."""
        return self.table.nbytes


class _Readahead:
    """One-segment scan readahead: while a channel's current batch executes,
    the NEXT lineage in that channel's schedule is read on a small IO pool,
    so a cold scan overlaps disk latency with device work instead of
    alternating read-then-compute (Q1 cold scan sat at 0.13 GB/s without it).

    Reads are pure (lineage -> same bytes every time), so serving a prefetch
    changes nothing the lineage/replay machinery can observe — a mismatched
    or failed prefetch silently falls back to the synchronous read.  One slot
    per (dataset, channel); the slot table is FIFO-bounded so dead datasets
    can't pin prefetched tables forever."""

    _MAX_SLOTS = 64

    def __init__(self):
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._slots: "collections.OrderedDict" = collections.OrderedDict()

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="quokka-readahead"
                )
            return self._pool

    def take(self, ds, channel: int, lineage):
        """The prefetched table for this exact lineage, or None."""
        key = (id(ds), channel)
        with self._lock:
            ent = self._slots.pop(key, None)
        if ent is None or ent[0] != lineage:
            return None
        try:
            table = ent[1].result()
        except Exception:
            return None
        from quokka_tpu.obs.metrics import REGISTRY

        REGISTRY.counter("scan.readahead_hit").inc()
        return table

    def arm(self, ds, channel: int, lineage, read_fn) -> None:
        key = (id(ds), channel)
        fut = self._ensure_pool().submit(read_fn)
        with self._lock:
            self._slots[key] = (lineage, fut)
            while len(self._slots) > self._MAX_SLOTS:
                self._slots.popitem(last=False)


_READAHEAD = _Readahead()


def _successor_map(state: Dict[int, List]) -> Dict:
    """(channel, lineage) -> the channel's next lineage."""
    succ = {}
    for ch, pieces in state.items():
        for cur, nxt in zip(pieces, pieces[1:]):
            succ[(ch, cur)] = nxt
    return succ


def _expand_paths(path) -> List[str]:
    if isinstance(path, (list, tuple)):
        out = []
        for p in path:
            out.extend(_expand_paths(p))
        return out
    if os.path.isdir(path):
        return sorted(
            p for p in globmod.glob(os.path.join(path, "**", "*"), recursive=True)
            if os.path.isfile(p)
        )
    matches = sorted(globmod.glob(path))
    return matches if matches else [path]


class InputParquetDataset:
    """Local/posix Parquet reader: channels own (file, row_group) pairs;
    supports projection pushdown and row-group skipping from min/max stats
    (the pushdown surface of InputEC2ParquetDataset, unordered_readers.py:3-72)."""

    def __init__(self, path, columns: Optional[Sequence[str]] = None, predicate: Optional[Expr] = None):
        self.path = path
        self.columns = list(columns) if columns else None
        self.predicate = predicate  # conjunction usable for row-group skipping
        # ANN pushdown (optimizer.push_ann): (queries, nprobe) restricts the
        # scan to row groups owning the queries' closest IVF cells when an
        # .ivf.npz sidecar exists (dataset/vector.py — the Lance-index role)
        self.ann_prune = None

    @property
    def schema(self) -> pa.Schema:
        f = pq.ParquetFile(_expand_paths(self.path)[0])
        return f.schema_arrow

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        pieces = []
        for f in _expand_paths(self.path):
            keep_rgs = None
            if self.ann_prune is not None:
                from quokka_tpu.dataset.vector import prune_row_groups

                queries, nprobe = self.ann_prune
                keep = prune_row_groups(f, queries, nprobe)
                if keep is not None:
                    keep_rgs = set(int(i) for i in keep)
            pf = pq.ParquetFile(f)
            meta = pf.metadata
            schema = pf.schema_arrow
            for rg in range(meta.num_row_groups):
                if keep_rgs is not None and rg not in keep_rgs:
                    continue
                if self.predicate is not None and _rowgroup_prunable(
                    meta.row_group(rg), self.predicate, schema
                ):
                    continue
                pieces.append((f, rg))
        state = {ch: pieces[ch::num_channels] for ch in range(num_channels)}
        self._succ = _successor_map(state)
        return state

    def execute(self, channel: int, lineage) -> pa.Table:
        table = _READAHEAD.take(self, channel, lineage)
        if table is None:
            table = self._read(lineage)
        nxt = getattr(self, "_succ", {}).get((channel, lineage))
        if nxt is not None:
            _READAHEAD.arm(self, channel, nxt,
                           functools.partial(self._read, nxt))
        return table

    def _read(self, lineage) -> pa.Table:
        f, rg = lineage
        # read_dictionary: string columns whose parquet pages are already
        # dictionary-encoded come back as DictionaryArray — the bridge then
        # skips a full host-side re-encode (single-core ingest hosts care)
        pf = pq.ParquetFile(f, read_dictionary=self._dict_columns(f))
        return pf.read_row_group(rg, columns=self.columns)

    def cache_key(self, channel: int, lineage):
        """Scan-cache identity of this lineage's bytes (engine buffer pool).
        mtime_ns + size guard against serving a rewritten file."""
        f, rg = lineage
        try:
            st = os.stat(f)
        except OSError:
            return None
        return ("parquet", f, rg, st.st_mtime_ns, st.st_size,
                tuple(self.columns) if self.columns else None)

    def size_hint(self) -> int:
        """Estimated source bytes (query-service admission control): the
        on-disk footprint of every file this scan touches."""
        total = 0
        for f in _expand_paths(self.path):
            try:
                total += os.path.getsize(f)
            except OSError:
                continue
        return total

    def _dict_columns(self, f) -> List[str]:
        cached = getattr(self, "_dict_cols_cache", None)
        if cached is not None:
            return cached
        schema = pq.read_schema(f)  # footer-only read, once per dataset
        cols = [
            fld.name
            for fld in schema
            if pa.types.is_string(fld.type) or pa.types.is_large_string(fld.type)
        ]
        self._dict_cols_cache = cols
        return cols


def _rowgroup_prunable(rg_meta, predicate: Expr, schema: pa.Schema) -> bool:
    """True if row-group min/max stats prove no row satisfies the predicate."""
    stats = {}
    for i in range(rg_meta.num_columns):
        col = rg_meta.column(i)
        name = col.path_in_schema
        if col.statistics is not None and col.statistics.has_min_max:
            stats[name] = (col.statistics.min, col.statistics.max)
    for conj in split_conjuncts(predicate):
        if _conjunct_excludes(conj, stats):
            return True
    return False


def _conjunct_excludes(conj: Expr, stats) -> bool:
    if not isinstance(conj, BinOp) or conj.op not in ("<", "<=", ">", ">=", "="):
        return False
    left, right, op = conj.left, conj.right, conj.op
    if not isinstance(left, ColRef):
        left, right = right, left
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}[op]
    if not isinstance(left, ColRef) or left.name not in stats:
        return False
    if isinstance(right, DateLit):
        val = right.days
        mn, mx = stats[left.name]
        import datetime

        if isinstance(mn, datetime.date):
            mn = (mn - datetime.date(1970, 1, 1)).days
            mx = (mx - datetime.date(1970, 1, 1)).days
    elif isinstance(right, Literal) and isinstance(right.value, (int, float)):
        val = right.value
        mn, mx = stats[left.name]
        if not isinstance(mn, (int, float)):
            return False
    else:
        return False
    if op == "<":
        return mn >= val
    if op == "<=":
        return mn > val
    if op == ">":
        return mx <= val
    if op == ">=":
        return mx < val
    if op == "=":
        return val < mn or val > mx
    return False


class InputCSVDataset:
    """CSV reader with byte-range channel partitioning.  Each lineage is
    (file, start, end); ranges are refined to newline boundaries at read time:
    a non-zero start skips the (partial) first line, and the read extends past
    `end` to the next newline — so every row is read exactly once
    (technique of unordered_readers.py:273-442)."""

    def __init__(
        self,
        path,
        schema: Optional[List[str]] = None,
        has_header: bool = True,
        sep: str = ",",
        stride: int = 16 << 20,
    ):
        self.path = path
        self.names = schema
        self.has_header = has_header
        self.sep = sep
        self.stride = stride
        self._schema_cache: Optional[pa.Schema] = None

    @property
    def schema(self) -> pa.Schema:
        if self._schema_cache is None:
            f = _expand_paths(self.path)[0]
            ropts = pacsv.ReadOptions(
                column_names=None if self.has_header else self.names
            )
            head = pacsv.read_csv(
                io.BytesIO(_head_bytes(f, 1 << 20)),
                read_options=ropts,
                parse_options=pacsv.ParseOptions(delimiter=self.sep),
            )
            self._schema_cache = head.schema
        return self._schema_cache

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        pieces = []
        for f in _expand_paths(self.path):
            size = os.path.getsize(f)
            start = 0
            while start < size:
                end = min(start + self.stride, size)
                pieces.append((f, start, end))
                start = end
        state = {ch: pieces[ch::num_channels] for ch in range(num_channels)}
        self._succ = _successor_map(state)
        return state

    def size_hint(self) -> int:
        """Estimated source bytes (query-service admission control)."""
        total = 0
        for f in _expand_paths(self.path):
            try:
                total += os.path.getsize(f)
            except OSError:
                continue
        return total

    def execute(self, channel: int, lineage) -> pa.Table:
        table = _READAHEAD.take(self, channel, lineage)
        if table is None:
            table = self._read(lineage)
        nxt = getattr(self, "_succ", {}).get((channel, lineage))
        if nxt is not None:
            _READAHEAD.arm(self, channel, nxt,
                           functools.partial(self._read, nxt))
        return table

    def _read(self, lineage) -> pa.Table:
        f, start, end = lineage
        data = _read_line_range(f, start, end)
        if not data:
            return self.schema.empty_table()
        if not self.has_header and self.names is None:
            raise ValueError("headerless CSV requires an explicit schema")
        if self.has_header and start == 0:
            names = None  # the first range carries the header row itself
        else:
            names = self.names if not self.has_header else list(self.schema.names)
        table = pacsv.read_csv(
            io.BytesIO(data),
            read_options=pacsv.ReadOptions(column_names=names),
            parse_options=pacsv.ParseOptions(delimiter=self.sep),
            convert_options=pacsv.ConvertOptions(
                column_types={f.name: f.type for f in self.schema}
            ),
        )
        return table


def _read_line_range(path: str, start: int, end: int) -> bytes:
    """Read the newline-delimited rows OWNED by byte range [start, end).

    Ownership rule (each row read by exactly one range): a range owns every row
    whose first byte lies in [start, end).  A row starts at offset 0 or right
    after a newline — so the range peeks at byte start-1: if it is a newline,
    the row beginning at `start` is owned here; otherwise the torn first line
    belongs to the previous range and is skipped.  Reads extend past `end`
    only while the last owned row is incomplete."""
    size = os.path.getsize(path)
    from quokka_tpu.utils import native

    with open(path, "rb") as fh:
        if start > 0:
            fh.seek(start - 1)
            prev = fh.read(1)
            own_first = prev == b"\n"
        else:
            own_first = True
        data = fh.read(end - start)
        pos = end
        while pos < size and (not data or data[-1:] != b"\n"):
            chunk = fh.read(1 << 16)
            if not chunk:
                break
            nl = native.find_newline(chunk)
            if nl >= 0:
                data += chunk[: nl + 1]
                break
            data += chunk
            pos += len(chunk)
    if not own_first:
        nl = native.find_newline(data)
        data = data[nl + 1 :] if nl >= 0 else b""
    return data


def _head_bytes(path: str, n: int) -> bytes:
    with open(path, "rb") as fh:
        data = fh.read(n)
    # trim to last complete line so schema inference never sees a torn row
    nl = data.rfind(b"\n")
    return data[: nl + 1] if nl >= 0 else data


class InputJSONDataset:
    """JSON-lines reader (InputDiskJSONDataset equivalent,
    unordered_readers.py:445)."""

    def __init__(self, path, stride: int = 16 << 20):
        self.path = path
        self.stride = stride

    @property
    def schema(self) -> pa.Schema:
        f = _expand_paths(self.path)[0]
        return pajson.read_json(io.BytesIO(_head_bytes(f, 1 << 20))).schema

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        pieces = []
        for f in _expand_paths(self.path):
            size = os.path.getsize(f)
            start = 0
            while start < size:
                end = min(start + self.stride, size)
                pieces.append((f, start, end))
                start = end
        return {ch: pieces[ch::num_channels] for ch in range(num_channels)}

    def execute(self, channel: int, lineage) -> pa.Table:
        f, start, end = lineage
        data = _read_line_range(f, start, end)
        if not data.strip():
            return self.schema.empty_table()
        return pajson.read_json(io.BytesIO(data))
