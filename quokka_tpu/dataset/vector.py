"""Vector dataset indexing: IVF sidecar + ANN row-group pruning.

The reference reads Lance datasets and pushes approximate nearest-neighbor
search into the format's vector index (df.py:1264-1352 push_ann,
unordered_readers.py:101-205 InputLanceDataset).  Lance isn't in this image,
so the same capability is built natively over Parquet: `build_vector_index`
writes an IVF sidecar (k-means centroids + the set of cells present in each
row group; assignment runs as device matmuls), and an indexed source prunes
row groups to the query's closest `nprobe` cells.  Approximate by nature —
the optimizer only applies it when nearest_neighbors(..., approximate=True).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def sidecar_path(parquet_path: str) -> str:
    return parquet_path + ".ivf.npz"


def build_vector_index(parquet_path: str, vec_col: str, n_cells: int = 32,
                       iters: int = 8, seed: int = 0) -> str:
    """K-means the vectors (Lloyd iterations as device matmuls — assignment is
    one [n, d] @ [d, c] per pass, MXU-shaped) and record per-row-group cell
    membership.  Returns the sidecar path."""
    import jax.numpy as jnp
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(parquet_path)
    tables = [pf.read_row_group(rg, columns=[vec_col]) for rg in range(pf.metadata.num_row_groups)]
    mats = []
    for t in tables:
        arr = t.column(vec_col).combine_chunks()
        dim = arr.type.list_size
        mats.append(
            np.asarray(arr.flatten().to_numpy(zero_copy_only=False), dtype=np.float32).reshape(-1, dim)
        )
    all_vecs = np.concatenate(mats)
    n = len(all_vecs)
    n_cells = min(n_cells, n)
    r = np.random.default_rng(seed)
    cents = all_vecs[r.choice(n, n_cells, replace=False)].copy()
    x = jnp.asarray(all_vecs)
    xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-9)
    for _ in range(iters):
        c = jnp.asarray(cents)
        cn = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-9)
        assign = jnp.argmax(xn @ cn.T, axis=1)  # cosine assignment on the MXU
        a = np.asarray(assign)
        for j in range(n_cells):
            sel = all_vecs[a == j]
            if len(sel):
                cents[j] = sel.mean(axis=0)
    # per-row-group cell membership
    a = np.asarray(assign)
    rg_cells = np.zeros((len(mats), n_cells), dtype=bool)
    off = 0
    for i, m in enumerate(mats):
        rg_cells[i, np.unique(a[off:off + len(m)])] = True
        off += len(m)
    out = sidecar_path(parquet_path)
    np.savez(out, centroids=cents, rg_cells=rg_cells, vec_col=np.array([vec_col]))
    return out


def prune_row_groups(parquet_path: str, queries: np.ndarray,
                     nprobe: int) -> Optional[np.ndarray]:
    """Row-group indices that may contain any query's nprobe closest cells,
    or None when no sidecar index exists."""
    p = sidecar_path(parquet_path)
    if not os.path.exists(p):
        return None
    idx = np.load(p, allow_pickle=False)
    cents = idx["centroids"]
    rg_cells = idx["rg_cells"]
    q = np.asarray(queries, dtype=np.float32)
    qn = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-9)
    cn = cents / np.maximum(np.linalg.norm(cents, axis=1, keepdims=True), 1e-9)
    sims = qn @ cn.T  # [nq, n_cells]
    nprobe = min(nprobe, sims.shape[1])
    probed = np.unique(np.argpartition(-sims, nprobe - 1, axis=1)[:, :nprobe])
    keep = np.nonzero(rg_cells[:, probed].any(axis=1))[0]
    return keep
