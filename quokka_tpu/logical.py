"""Logical plan nodes.

Role of pyquokka/logical.py: the DataStream API builds a DAG of these; the
optimizer rewrites it; ``lower()`` emits physical actors into the runtime
TaskGraph.  Each node records its parents, output schema, and (assigned by
stage analysis) its execution stage; every consumer edge carries a TargetInfo
describing partitioning and any folded-in predicate/projection/batch functions.
"""

from __future__ import annotations

import functools

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from quokka_tpu.expression import Expr
from quokka_tpu.ops import kernels
from quokka_tpu.ops.expr_compile import AggPlan, evaluate_predicate, evaluate_to_column
from quokka_tpu.target_info import (
    BroadcastPartitioner,
    HashPartitioner,
    PassThroughPartitioner,
    RangePartitioner,
    TargetInfo,
)


class Node:
    def __init__(self, parents: List[int], schema: List[str]):
        self.parents = parents
        self.schema = schema
        self.stage = 0
        self.channels: Optional[int] = None  # None -> context default
        # build_parents: indices into self.parents whose subtree must complete
        # before this node's streaming side runs (join build sides)
        self.build_parents: List[int] = []
        self.sorted_by: Optional[List[str]] = None
        # runtime/placement.py strategy: fixes the channel count at lowering
        # and pins channels to workers in the distributed runtime
        self.placement = None

    def lower(self, ctx, graph, actor_of: Dict[int, int], node_id: int) -> None:
        raise NotImplementedError

    def derive_schema(self, parents: List[List[str]]) -> Optional[List[str]]:
        """Output columns derivable from the parents' schemas plus this
        node's own metadata (keys, expressions, rename maps, ...).

        Returns None when the DECLARED schema is the source of truth (sources
        and opaque user executors); otherwise returns the derived column list
        and raises ValueError when a parent is missing a column this node
        requires — the contract the plan verifier (analysis/planck.py QK021)
        checks node-by-node and optimizer.early_projection uses to keep
        interior schemas exact after source pruning."""
        return None

    def describe(self) -> str:
        return type(self).__name__


def _require(cols, parent: List[str], what: str) -> None:
    missing = [c for c in cols if c not in set(parent)]
    if missing:
        raise ValueError(f"{what} references columns {missing} not in input {parent}")


class SourceNode(Node):
    def __init__(self, reader, schema: List[str], sorted_by=None):
        super().__init__([], schema)
        self.reader = reader
        self.sorted_by = sorted_by
        self.predicate: Optional[Expr] = None  # pushed-down filter
        self.projection: Optional[List[str]] = None  # pushed-down column set

    def lower(self, ctx, graph, actor_of, node_id):
        reader = self.reader
        if self.predicate is not None and hasattr(reader, "predicate"):
            reader.predicate = self.predicate  # row-group pruning
        if self.projection is not None and hasattr(reader, "columns"):
            reader.columns = list(self.projection)
        actor_of[node_id] = graph.new_input_reader_node(
            reader,
            self.channels or ctx.io_channels,
            self.stage,
            self.sorted_by,
            predicate=self.predicate,
            projection=self.projection,
        )
        # plan-independent scan identity: the cardprofile records this
        # scan's measured rows/bytes under it, and the cost model
        # (planner/cost.py) looks the figure up at the NEXT plan time —
        # before any fingerprint for the next plan can exist
        from quokka_tpu.planner.cost import source_signature

        graph.actors[actor_of[node_id]].src_sig = source_signature(
            reader, self.predicate, self.projection)

    def describe(self):
        d = f"Source({type(self.reader).__name__}"
        if self.predicate is not None:
            d += f", filter={self.predicate.sql()}"
        if self.projection is not None:
            d += f", cols={self.projection}"
        return d + ")"


def _passthrough_edge():
    return TargetInfo(PassThroughPartitioner())


@dataclasses.dataclass
class SelectFn:
    """Picklable per-batch projection (executor factories must cross process
    boundaries for the multi-worker runtime)."""

    cols: List[str]

    def __call__(self, b):
        return b.select(self.cols)


@dataclasses.dataclass
class RenameFn:
    mapping: Dict[str, str]

    def __call__(self, b):
        return b.rename(self.mapping)


@dataclasses.dataclass
class WithColumnsFn:
    """Picklable with_columns map: compiles its expressions per batch."""

    exprs: Dict[str, Expr]

    def __call__(self, b):
        for name, e in self.exprs.items():
            b = b.with_column(name, evaluate_to_column(e, b))
        return b


class FilterNode(Node):
    def __init__(self, parents, schema, predicate: Expr):
        super().__init__(parents, schema)
        self.predicate = predicate

    def derive_schema(self, parents):
        _require(self.predicate.required_columns(), parents[0], "filter predicate")
        return list(parents[0])

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import UDFExecutor
        from quokka_tpu.ops.fuse import FusedPredicate

        pred = self.predicate
        actor_of[node_id] = graph.new_exec_node(
            functools.partial(UDFExecutor, FusedPredicate(pred)),
            {0: (actor_of[self.parents[0]], _passthrough_edge())},
            self.channels or ctx.exec_channels,
            self.stage,
            sorted_actor=self.sorted_by is not None,
        )

    def describe(self):
        return f"Filter({self.predicate.sql()})"


class ProjectionNode(Node):
    def __init__(self, parents, schema):
        super().__init__(parents, schema)

    def derive_schema(self, parents):
        _require(self.schema, parents[0], "projection")
        return list(self.schema)

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import UDFExecutor

        cols = list(self.schema)
        actor_of[node_id] = graph.new_exec_node(
            functools.partial(UDFExecutor, SelectFn(cols)),
            {0: (actor_of[self.parents[0]], _passthrough_edge())},
            self.channels or ctx.exec_channels,
            self.stage,
            sorted_actor=self.sorted_by is not None,
        )

    def describe(self):
        return f"Projection({self.schema})"


class MapNode(Node):
    """with_columns / rename / transform: a per-batch device function.
    ``exprs`` (when set) makes the map foldable by the optimizer.

    Every MapNode must carry EXPLICIT output-schema metadata — one of
    ``exprs`` (with_columns), ``rename`` (a column-rename map), or
    ``declared=True`` (an opaque UDF whose declared schema is trusted).  A
    bare fn with none of the three has no derivable output schema and fails
    plan verification (QK021)."""

    def __init__(self, parents, schema, fn: Callable, exprs: Optional[Dict[str, Expr]] = None,
                 rename: Optional[Dict[str, str]] = None, declared: bool = False):
        super().__init__(parents, schema)
        self.fn = fn
        self.exprs = exprs
        self.rename = rename
        self.declared = declared
        self.folded = False  # set by optimizer.fold_maps: ride the edge

    def derive_schema(self, parents):
        if self.exprs is not None:
            for k, e in self.exprs.items():
                _require(e.required_columns(), parents[0], f"map expr {k}")
            # with_column replaces in place when present, appends when new —
            # mirror DeviceBatch.with_column exactly
            return list(parents[0]) + [k for k in self.exprs if k not in set(parents[0])]
        if self.rename is not None:
            # a mapping key absent from the input is a no-op (matches
            # DeviceBatch.rename), so only the output list is derived
            return [self.rename.get(c, c) for c in parents[0]]
        if self.declared:
            return None  # opaque UDF: the declared schema is the contract
        raise ValueError("MapNode without exprs/rename/declared schema metadata")

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import UDFExecutor

        fn = self.fn
        if self.folded:
            # no actor: the map becomes a batch_func on every edge leaving
            # the parent's actor (optimizer.fold_maps guarantees this node is
            # the parent's only consumer)
            src = actor_of[self.parents[0]]
            actor_of[node_id] = src
            graph.add_pending_batch_fn(src, fn)
            return
        actor_of[node_id] = graph.new_exec_node(
            functools.partial(UDFExecutor, fn),
            {0: (actor_of[self.parents[0]], _passthrough_edge())},
            self.channels or ctx.exec_channels,
            self.stage,
            sorted_actor=self.sorted_by is not None,
        )

    def describe(self):
        label = "FoldedMap" if self.folded else "Map"
        if self.exprs:
            return f"{label}(" + ", ".join(f"{k}={v.sql()}" for k, v in self.exprs.items()) + ")"
        return f"{label}(udf)"


class StatefulNode(Node):
    """User-provided executor (stateful_transform / custom operators)."""

    def __init__(self, parents, schema, executor_factory, partitioners=None, sorted_output=None):
        super().__init__(parents, schema)
        self.executor_factory = executor_factory
        self.partitioners = partitioners or {}
        self.sorted_by = sorted_output

    def lower(self, ctx, graph, actor_of, node_id):
        sources = {}
        for i, p in enumerate(self.parents):
            part = self.partitioners.get(i, PassThroughPartitioner())
            sources[i] = (actor_of[p], TargetInfo(part))
        actor_of[node_id] = graph.new_exec_node(
            self.executor_factory,
            sources,
            self.channels or ctx.exec_channels,
            self.stage,
            sorted_actor=self.sorted_by is not None,
        )

    def describe(self):
        return "Stateful"


class AsofJoinNode(StatefulNode):
    """As-of join (OrderedStream.join_asof).  A StatefulNode for the engine
    path (SortedAsofExecutor does streaming frontier matching), but carries
    the join parameters so the mesh path can run it as one shard_map program
    (hash-shuffle both sides by the `by` keys over ICI, then the
    data-parallel sort+scan asof kernel per shard — parallel/mesh_exec.
    mesh_asof).  Reference: pyquokka/orderedstream.py:37 join_asof."""

    def __init__(self, parents, schema, executor_factory, partitioners,
                 sorted_output, *, left_on, right_on, left_by, right_by,
                 suffix, direction):
        super().__init__(parents, schema, executor_factory, partitioners,
                         sorted_output)
        self.left_on = left_on
        self.right_on = right_on
        self.left_by = list(left_by)
        self.right_by = list(right_by)
        self.suffix = suffix
        self.direction = direction

    def derive_schema(self, parents):
        _require([self.left_on] + self.left_by, parents[0], "asof left keys")
        _require([self.right_on] + self.right_by, parents[1], "asof right keys")
        rpayload = [c for c in parents[1]
                    if c not in set(self.right_by) and c != self.right_on]
        return list(parents[0]) + [
            c + self.suffix if c in set(parents[0]) else c for c in rpayload
        ]

    def describe(self):
        return f"AsofJoin({self.direction} on {self.left_on})"


class WindowAggNode(StatefulNode):
    """Window aggregation (OrderedStream.window_agg).  A StatefulNode for the
    streaming engine path, carrying window parameters so the mesh path can
    run tumbling/hopping windows as a window-id group-by in one shard_map
    (parallel/mesh_exec.mesh_window_agg).  Reference: pyquokka/datastream.py
    windowed_transform + windowtypes compilation."""

    def __init__(self, parents, schema, executor_factory, partitioners,
                 sorted_output, *, time_col, by, window, plan, trigger):
        super().__init__(parents, schema, executor_factory, partitioners,
                         sorted_output)
        self.time_col = time_col
        self.by = list(by)
        self.window = window
        self.plan = plan
        self.trigger = trigger

    def derive_schema(self, parents):
        from quokka_tpu import windows as W

        _require([self.time_col] + self.by, parents[0], "window keys")
        for name, e in self.plan.pre:
            _require(e.required_columns(), parents[0], f"window agg input {name}")
        finals = [n for n, _ in self.plan.finals]
        if isinstance(self.window, W.SlidingWindow):
            return list(parents[0]) + finals
        if isinstance(self.window, W.SessionWindow):
            extra = ["session_start", "session_end"]
        else:
            extra = ["window_start", "window_end"]
        return list(self.by) + extra + finals

    def describe(self):
        return f"WindowAgg({type(self.window).__name__})"


class ShiftNode(StatefulNode):
    """Per-key lag (OrderedStream.shift).  StatefulNode for the streaming
    engine (ShiftExecutor carries per-key tails across batches); the mesh
    path runs it as one shard_map (shuffle by key, per-shard sort + segment
    shift — parallel/mesh_exec.mesh_shift).  Reference:
    pyquokka/orderedstream.py:13."""

    def __init__(self, parents, schema, executor_factory, partitioners,
                 sorted_output, *, time_col, by, columns, n):
        super().__init__(parents, schema, executor_factory, partitioners,
                         sorted_output)
        self.time_col = time_col
        self.by = list(by)
        self.columns = list(columns)
        self.n = n

    def derive_schema(self, parents):
        _require([self.time_col] + self.by + self.columns, parents[0], "shift")
        return list(parents[0]) + [f"{c}_shifted_{self.n}" for c in self.columns]

    def describe(self):
        return f"Shift(n={self.n})"


class JoinNode(Node):
    """Binary hash join; parents[0] = probe (stream 0), parents[1] = build."""

    def __init__(self, parents, schema, left_on, right_on, how="inner", suffix="_2",
                 broadcast=False, rename=None):
        super().__init__(parents, schema)
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.how = how
        self.suffix = suffix
        self.broadcast = broadcast
        # plan-time build-column renames (so runtime behavior is stable even
        # when the optimizer prunes the clashing probe column)
        self.rename = rename
        self.build_parents = [1]
        # planner/decide.plan_adaptive_exchanges: this join's build edge may
        # be salted mid-query when the runtime observes partition skew
        # (inner non-broadcast joins only — see QK026)
        self.adapt_salt = False

    def derive_schema(self, parents):
        _require(self.left_on, parents[0], "join left keys")
        _require(self.right_on, parents[1], "join right keys")
        if self.how in ("semi", "anti"):
            return list(parents[0])
        rename = self.rename or {}
        rpayload = [c for c in parents[1] if c not in set(self.right_on)]
        return list(parents[0]) + [rename.get(c, c) for c in rpayload]

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import BuildProbeJoinExecutor

        left_on, right_on, how, suffix = self.left_on, self.right_on, self.how, self.suffix
        rename = self.rename
        out_schema = list(self.schema)
        if self.broadcast:
            edges = {
                0: (actor_of[self.parents[0]], _passthrough_edge()),
                1: (actor_of[self.parents[1]], TargetInfo(BroadcastPartitioner())),
            }
        else:
            edges = {
                0: (actor_of[self.parents[0]], TargetInfo(HashPartitioner(left_on))),
                1: (actor_of[self.parents[1]], TargetInfo(HashPartitioner(right_on))),
            }
        actor_of[node_id] = graph.new_exec_node(
            functools.partial(BuildProbeJoinExecutor,
                left_on, right_on, how, suffix, rename, out_schema=out_schema
            ),
            edges,
            self.channels or ctx.exec_channels,
            self.stage,
        )
        if not self.broadcast and getattr(self, "adapt_salt", False):
            graph.adapt_edges[(actor_of[self.parents[1]],
                               actor_of[node_id])] = {
                "probe_src": actor_of[self.parents[0]],
            }

    def describe(self):
        k = "BroadcastJoin" if self.broadcast else "HashJoin"
        return f"{k}({self.how}, {self.left_on}={self.right_on})"


class AggNode(Node):
    """Decomposed group-by aggregate: a partial-agg actor on the parent's
    channels feeds a key-hash-partitioned final-agg actor.  (The TPU-first
    replacement for batch_funcs partial agg + SQLAggExecutor concat-DuckDB.)"""

    def __init__(self, parents, schema, keys: List[str], plan: AggPlan,
                 having=None, order_by=None, limit=None):
        super().__init__(parents, schema)
        self.keys = keys
        self.plan = plan
        self.having = having
        self.order_by = order_by
        self.limit = limit

    def derive_schema(self, parents):
        _require(self.keys, parents[0], "groupby keys")
        for name, e in self.plan.pre:
            _require(e.required_columns(), parents[0], f"aggregate input {name}")
        return list(self.keys) + [
            n for n, _ in self.plan.finals if n not in set(self.keys)
        ]

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import FinalAggExecutor, PartialAggExecutor

        keys, plan = self.keys, self.plan
        having, order_by, limit = self.having, self.order_by, self.limit
        partial = graph.new_exec_node(
            functools.partial(PartialAggExecutor, keys, plan),
            {0: (actor_of[self.parents[0]], _passthrough_edge())},
            self.channels or ctx.exec_channels,
            self.stage,
        )
        n_final = (self.channels or ctx.exec_channels) if keys else 1
        part = HashPartitioner(keys) if keys else PassThroughPartitioner()
        final = graph.new_exec_node(
            functools.partial(FinalAggExecutor, keys, plan, having, order_by, limit),
            {0: (partial, TargetInfo(part))},
            n_final,
            self.stage,
        )
        if (order_by or limit is not None) and n_final > 1:
            # per-channel order/limit is local; merge to the global result
            from quokka_tpu.executors.sql_execs import SortExecutor, TopKExecutor

            names = [n for n, _ in (order_by or [])]
            desc = [d for _, d in (order_by or [])]
            if limit is not None:
                merge_factory = functools.partial(TopKExecutor, names, limit, desc)
            else:
                merge_factory = functools.partial(SortExecutor, names, desc)
            final = graph.new_exec_node(
                merge_factory,
                {0: (final, TargetInfo(PassThroughPartitioner()))},
                1,
                self.stage,
            )
        actor_of[node_id] = final

    def describe(self):
        return f"Agg(keys={self.keys}, out={[n for n, _ in self.plan.finals]})"


class FusedStageNode(Node):
    """A maximal fusible linear chain rewritten into ONE exec actor
    (optimizer.fuse_stages).  parents[0] is the chain head's main input;
    parents[1:] are the member joins' build sides in chain order.  Lowers to
    a single FusedStageExecutor actor (ops/stagefuse.py): consecutive
    filter/project/expression-map members collapse into one jitted
    elementwise program, and a tail AggNode contributes its partial half
    in-stage with the final-agg actors emitted exactly as AggNode.lower
    would."""

    def __init__(self, members: List[Node], parents: List[int],
                 schema: List[str]):
        super().__init__(parents, schema)
        self.members = members
        self.build_parents = list(range(1, len(parents)))

    def derive_schema(self, parents):
        # replay the member chain: member i's main input is member i-1's
        # derived output; join members consume build sides in chain order
        builds = iter(parents[1:])
        cur = list(parents[0])
        for m in self.members:
            if isinstance(m, JoinNode):
                cur = m.derive_schema([cur, list(next(builds))])
            else:
                d = m.derive_schema([cur])
                cur = list(m.schema) if d is None else d
        leftover = list(builds)
        if leftover:
            raise ValueError(
                f"fused stage has {len(leftover)} build inputs with no join member")
        return cur

    def describe(self):
        inner = "\n".join("  " + m.describe() for m in self.members)
        return "FusedStage(\n" + inner + "\n)"

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import (
            BuildProbeJoinExecutor,
            FinalAggExecutor,
            PartialAggExecutor,
            UDFExecutor,
        )
        from quokka_tpu.ops.stagefuse import (
            FusedElementwise,
            FusedStageExecutor,
            StageSpec,
        )

        steps: List[Tuple[str, Callable]] = []
        routing: Dict[int, Tuple[int, int]] = {}
        sources: Dict[int, Tuple[int, TargetInfo]] = {}
        builds = iter(self.parents[1:])
        elem: List[Tuple] = []
        agg: Optional[AggNode] = None

        def flush_elem():
            if elem:
                steps.append(("Elemwise", functools.partial(
                    UDFExecutor, FusedElementwise(list(elem)))))
                elem.clear()

        head = self.members[0]
        if isinstance(head, JoinNode) and not head.broadcast:
            sources[0] = (actor_of[head.parents[0]],
                          TargetInfo(HashPartitioner(head.left_on)))
        else:
            sources[0] = (actor_of[head.parents[0]], _passthrough_edge())
        for m in self.members:
            if isinstance(m, FilterNode):
                elem.append(("filter", m.predicate))
            elif isinstance(m, ProjectionNode):
                elem.append(("project", list(m.schema)))
            elif isinstance(m, MapNode) and m.exprs:
                elem.append(("map", list(m.exprs.items())))
            elif isinstance(m, MapNode):
                flush_elem()
                steps.append(("Map", functools.partial(UDFExecutor, m.fn)))
            elif isinstance(m, JoinNode):
                flush_elem()
                part = (BroadcastPartitioner() if m.broadcast
                        else HashPartitioner(m.right_on))
                stream = len(sources)
                sources[stream] = (actor_of[next(builds)], TargetInfo(part))
                routing[stream] = (len(steps), 1)
                label = "BroadcastJoin" if m.broadcast else "HashJoin"
                steps.append((label, functools.partial(
                    BuildProbeJoinExecutor, m.left_on, m.right_on, m.how,
                    m.suffix, m.rename, out_schema=list(m.schema))))
            elif isinstance(m, AggNode):
                flush_elem()
                steps.append(("PartialAgg", functools.partial(
                    PartialAggExecutor, m.keys, m.plan)))
                agg = m
            else:  # pragma: no cover - fuse_stages only admits the above
                raise TypeError(f"unfusible member {type(m).__name__}")
        flush_elem()
        fused = graph.new_exec_node(
            functools.partial(FusedStageExecutor, StageSpec(steps, routing)),
            sources,
            self.channels or ctx.exec_channels,
            self.stage,
        )
        # fuse_stages only admits a non-broadcast hash join at the chain
        # HEAD; its build is the fused actor's stream-1 source, so the
        # adaptive-exchange mark survives fusion as a runtime edge
        if (isinstance(head, JoinNode) and not head.broadcast
                and getattr(head, "adapt_salt", False) and 1 in sources):
            graph.adapt_edges[(sources[1][0], fused)] = {
                "probe_src": sources[0][0],
            }
        if agg is None:
            actor_of[node_id] = fused
            return
        # the tail agg's final half: identical actors to AggNode.lower, fed
        # by the fused stage's in-stage partials
        keys, plan = agg.keys, agg.plan
        n_final = (self.channels or ctx.exec_channels) if keys else 1
        part = HashPartitioner(keys) if keys else PassThroughPartitioner()
        final = graph.new_exec_node(
            functools.partial(FinalAggExecutor, keys, plan, agg.having,
                              agg.order_by, agg.limit),
            {0: (fused, TargetInfo(part))},
            n_final,
            self.stage,
        )
        if (agg.order_by or agg.limit is not None) and n_final > 1:
            from quokka_tpu.executors.sql_execs import SortExecutor, TopKExecutor

            names = [n for n, _ in (agg.order_by or [])]
            desc = [d for _, d in (agg.order_by or [])]
            if agg.limit is not None:
                merge_factory = functools.partial(
                    TopKExecutor, names, agg.limit, desc)
            else:
                merge_factory = functools.partial(SortExecutor, names, desc)
            final = graph.new_exec_node(
                merge_factory,
                {0: (final, TargetInfo(PassThroughPartitioner()))},
                1,
                self.stage,
            )
        actor_of[node_id] = final


class DistinctNode(Node):
    def __init__(self, parents, schema, keys):
        super().__init__(parents, schema)
        self.keys = keys

    def derive_schema(self, parents):
        _require(self.keys, parents[0], "distinct keys")
        return list(self.keys)

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import DistinctExecutor

        keys = self.keys
        actor_of[node_id] = graph.new_exec_node(
            functools.partial(DistinctExecutor, keys),
            {0: (actor_of[self.parents[0]], TargetInfo(HashPartitioner(keys)))},
            self.channels or ctx.exec_channels,
            self.stage,
        )

    def describe(self):
        return f"Distinct({self.keys})"


class TopKNode(Node):
    def __init__(self, parents, schema, by, k, descending):
        super().__init__(parents, schema)
        self.by = by
        self.k = k
        self.descending = descending

    def derive_schema(self, parents):
        _require(self.by, parents[0], "top_k keys")
        return list(parents[0])

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import TopKExecutor

        by, k, desc = self.by, self.k, self.descending
        local = graph.new_exec_node(
            functools.partial(TopKExecutor, by, k, desc),
            {0: (actor_of[self.parents[0]], _passthrough_edge())},
            self.channels or ctx.exec_channels,
            self.stage,
        )
        actor_of[node_id] = graph.new_exec_node(
            functools.partial(TopKExecutor, by, k, desc),
            {0: (local, _passthrough_edge())},
            1,
            self.stage,
        )

    def describe(self):
        return f"TopK({self.by}, k={self.k})"


class SortNode(Node):
    """Global sort.  When the upstream chain is sampleable, boundaries come
    from a sample and the sort runs range-partitioned in parallel (channel i
    owns value range i; ordered channel concat is globally sorted — the
    parallel discipline of SuperFastSortExecutor, sql_executors.py:88).
    Otherwise falls back to a single-channel blocking sort."""

    def __init__(self, parents, schema, by, descending):
        super().__init__(parents, schema)
        self.by = by
        self.descending = descending
        self.boundaries = None  # filled by the optimizer/sampling when possible

    def derive_schema(self, parents):
        _require(self.by, parents[0], "sort keys")
        return list(parents[0])

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import SortExecutor

        by, desc = self.by, self.descending
        n = self.channels or ctx.exec_channels
        if self.boundaries is not None and n > 1:
            bounds = list(self.boundaries)
            # descending: reversed range ownership keeps channel-order concat
            # equal to the requested global order
            edge = TargetInfo(
                RangePartitioner(by[0], bounds, descending=bool(desc and desc[0]))
            )
            actor_of[node_id] = graph.new_exec_node(
                functools.partial(SortExecutor, by, desc),
                {0: (actor_of[self.parents[0]], edge)},
                n,
                self.stage,
                # consumers must drain channel 0's whole range before channel
                # 1's — channel-major delivery (SAT's (seq, channel)
                # interleave breaks once a spilled sort emits multiple seqs)
                channel_major=True,
            )
        else:
            actor_of[node_id] = graph.new_exec_node(
                functools.partial(SortExecutor, by, desc),
                {0: (actor_of[self.parents[0]], _passthrough_edge())},
                1,
                self.stage,
                sorted_actor=True,
            )
        self.sorted_by = list(by)

    def describe(self):
        par = f", parallel x{self.channels or '?'}" if self.boundaries else ""
        return f"Sort({self.by}{par})"


class SinkNode(Node):
    """Blocking collect target (DataSetNode in the reference)."""

    def __init__(self, parents, schema):
        super().__init__(parents, schema)

    def derive_schema(self, parents):
        # the sink SELECTS its declared columns (SelectingStorageExecutor);
        # a superset input is legal, a missing column is not
        _require(self.schema, parents[0], "collect")
        return list(self.schema)

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import SelectingStorageExecutor

        actor_of[node_id] = graph.new_exec_node(
            functools.partial(SelectingStorageExecutor, list(self.schema)),
            {0: (actor_of[self.parents[0]], _passthrough_edge())},
            1,
            self.stage,
            blocking=True,
        )

    def describe(self):
        return "Collect"
