"""Device join kernels.

TPU has no pointer-chasing hash tables, so joins are rank-based (SURVEY.md
"Hard parts" #3): concatenate probe+build key limbs, compute dense ranks via a
multi-operand sort (one XLA sort), then match rows that share a rank.  Two
paths:

- ``hash_join_pk``: build side has unique keys (the common TPC-H case —
  dimension/PK build sides).  Output is probe-aligned and mask-based: no host
  sync, stays fully on device.
- ``hash_join_general``: many-to-many.  Output size is computed on device and
  synced to the host once per batch to pick the output bucket, then a jitted
  expansion kernel gathers (probe_idx, build_idx) pairs.

Reference behavior being matched: BuildProbeJoinExecutor semantics
(pyquokka/executors/sql_executors.py:325-378) — inner/left/semi/anti.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from quokka_tpu import config
from quokka_tpu.ops import kernels
from quokka_tpu.runtime import compileplane
from quokka_tpu.ops.batch import (
    DeviceBatch, NumCol, StrCol, gather_columns, key_limbs, null_mask, with_nulls,
)
from quokka_tpu.ops.kernels import dense_rank


def _nonnull_valid(batch: DeviceBatch, keys) -> jax.Array:
    """Rows with any null join key never match (SQL null-join semantics)."""
    v = batch.valid
    for k in keys:
        v = v & ~null_mask(batch.columns[k])
    return v


@jax.jit
def _count_true(mask: jax.Array):
    return jnp.sum(mask.astype(jnp.int32))


def _concat_limbs(probe: DeviceBatch, build: DeviceBatch, probe_keys, build_keys):
    lp = key_limbs(probe, probe_keys)
    lb = key_limbs(build, build_keys)
    assert len(lp) == len(lb), "join key column types must match"
    limbs = [jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(lp, lb)]
    valid = jnp.concatenate(
        [_nonnull_valid(probe, probe_keys), _nonnull_valid(build, build_keys)]
    )
    return limbs, valid


@jax.jit
def _sort_build_keys(limbs: Tuple[jax.Array, ...], valid: jax.Array):
    """Sort the build side's key limbs once (invalid/null-key rows last).
    Returns (sorted_limbs, perm, n_valid) for binary-search probing."""
    n = valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    s = lax.sort([inv, *limbs, iota], num_keys=1 + len(limbs))
    return tuple(s[1:-1]), s[-1], jnp.sum(valid.astype(jnp.int32))


def _lex_lt_eq(a: Tuple[jax.Array, ...], b: Tuple[jax.Array, ...]):
    """Elementwise lexicographic (a < b, a == b) over limb tuples."""
    lt = jnp.zeros(a[0].shape, dtype=bool)
    eq = jnp.ones(a[0].shape, dtype=bool)
    for x, y in zip(a, b):
        lt = lt | (eq & (x < y))
        eq = eq & (x == y)
    return lt, eq


@functools.partial(jax.jit, static_argnames=("steps",))
def _pk_probe_sorted(sorted_limbs, perm, n_valid, probe_limbs, probe_ok,
                     steps: int):
    """Probe a PRESORTED build with a vectorized lexicographic lower-bound:
    `steps` unrolled halvings, each one gather per limb — ~20 p-sized gathers
    instead of re-sorting probe+build jointly per batch (the dominant join
    cost at scale; a 2M-row multi-operand sort is ~100x a 1M gather)."""
    p = probe_limbs[0].shape[0]
    lo = jnp.zeros(p, dtype=jnp.int32)
    hi = jnp.broadcast_to(n_valid.astype(jnp.int32), (p,))
    for _ in range(steps):
        mid = (lo + hi) >> 1
        mk = tuple(l[mid] for l in sorted_limbs)
        lt, _ = _lex_lt_eq(mk, probe_limbs)  # build[mid] < probe row
        go = lo < hi
        lo = jnp.where(go & lt, mid + 1, lo)
        hi = jnp.where(go & ~lt, mid, hi)
    pos = jnp.clip(lo, 0, perm.shape[0] - 1)
    mk = tuple(l[pos] for l in sorted_limbs)
    _, eq = _lex_lt_eq(mk, probe_limbs)
    matched = probe_ok & eq & (lo < n_valid)
    # ties in the build sort kept original order (iota operand), so perm[pos]
    # is the smallest original build index of the key — same pick as
    # _pk_match's segment-min
    build_idx = jnp.clip(perm[pos], 0, perm.shape[0] - 1)
    return build_idx, matched


def _build_sorted_cached(build: DeviceBatch, build_keys: Sequence[str]):
    """Sorted-key view of a build table, cached ON the batch object: the
    probe executor joins the same finalized build against every probe batch
    (sql_execs.BuildProbeJoinExecutor), so the sort is paid once."""
    cache = getattr(build, "_pk_sorted_cache", None)
    if cache is None:
        cache = build._pk_sorted_cache = {}
    key = tuple(build_keys)
    hit = cache.get(key)
    if hit is None:
        limbs = key_limbs(build, build_keys)
        ok = _nonnull_valid(build, build_keys)
        hit = cache[key] = compileplane.aot_kernel_call(
            "sort_build_keys", _sort_build_keys, (tuple(limbs), ok))
    return hit


@functools.partial(jax.jit, static_argnames=("p",))
def _pk_match(limbs: Tuple[jax.Array, ...], valid: jax.Array, p: int):
    n = valid.shape[0]
    ranks, _ = dense_rank(limbs, valid)
    rp, rb = ranks[:p], ranks[p:]
    vp, vb = valid[:p], valid[p:]
    b = n - p
    iota_b = jnp.arange(b, dtype=jnp.int32)
    first = jnp.full(n, b, dtype=jnp.int32).at[rb].min(jnp.where(vb, iota_b, b))
    cnt = jax.ops.segment_sum(vb.astype(jnp.int32), rb, num_segments=n)
    build_idx = jnp.clip(first[rp], 0, b - 1)
    matched = vp & (cnt[rp] > 0)
    return build_idx, matched


def hash_join_pk(
    probe: DeviceBatch,
    build: DeviceBatch,
    probe_keys: Sequence[str],
    build_keys: Sequence[str],
    how: str = "inner",
    build_payload: Sequence[str] = (),
) -> DeviceBatch:
    """Join where build keys are unique.  Probe-aligned; the probe path has
    no host sync.  The cached build pays ONE scalar d2h per build batch (the
    hash-table convergence check, hashtable.build_table) — a diverged build
    is remembered on the batch and every probe takes the sort path."""
    from quokka_tpu.ops import strategy as kstrategy

    probe_limbs = key_limbs(probe, probe_keys)
    probe_ok = _nonnull_valid(probe, probe_keys)
    use_tables = kstrategy.choice("join_build") == "hashtable"
    if use_tables:
        # hashtable is imported at module scope by kernels (imported above):
        # a first-import inside an active trace once mis-primed jit dispatch
        from quokka_tpu.ops import hashtable

        try:
            table = hashtable.build_table(
                build, build_keys, key_limbs,
                lambda: _nonnull_valid(build, build_keys),
            )
        except hashtable.HashTableConvergenceError:
            # unplaced build rows would alias slot 0's key: take the sort
            # path for this build batch instead of joining wrong
            use_tables = False
        else:
            assert len(probe_limbs) == len(table.raw_dtypes), \
                "join key column types must match"
            build_idx, matched = hashtable.pk_probe(
                table, probe_limbs, probe_ok)
            kstrategy.note_used("join_build", "hashtable")
    if not use_tables:
        kstrategy.note_used("join_build", "sort")
        sorted_limbs, perm, n_valid = _build_sorted_cached(build, build_keys)
        assert len(probe_limbs) == len(sorted_limbs), \
            "join key column types must match"
        steps = max(1, int(np.ceil(np.log2(max(2, build.padded_len)))) + 1)
        build_idx, matched = compileplane.aot_kernel_call(
            "pk_probe_sorted", _pk_probe_sorted,
            (tuple(sorted_limbs), perm, n_valid,
             tuple(l.astype(s.dtype)
                   for l, s in zip(probe_limbs, sorted_limbs)),
             probe_ok),
            (steps,),
        )
    if how == "semi":
        return kernels.apply_mask(probe, matched)
    if how == "anti":
        return kernels.apply_mask(probe, probe.valid & ~matched)
    cols = dict(probe.columns)
    for name, taken in gather_columns(
        {n: build.columns[n] for n in build_payload}, build_idx
    ).items():
        if how == "left":
            taken = with_nulls(taken, ~matched)
        cols[name] = taken
    if how == "inner":
        out_valid = matched
    elif how == "left":
        out_valid = probe.valid
    else:
        raise ValueError(f"how={how}")
    # start the output count's async host copy now: downstream consumers
    # (partial agg, storage filters, concat compaction) read it batches
    # later, when it has long landed — instead of paying a fresh device
    # round trip each
    return DeviceBatch(cols, out_valid, None, probe.sorted_by).note_count(
        _count_true(out_valid))


@functools.partial(jax.jit, static_argnames=("p",))
def _mm_plan(limbs: Tuple[jax.Array, ...], valid: jax.Array, p: int):
    n = valid.shape[0]
    ranks, _ = dense_rank(limbs, valid)
    rp, rb = ranks[:p], ranks[p:]
    vp, vb = valid[:p], valid[p:]
    b = n - p
    cnt = jax.ops.segment_sum(vb.astype(jnp.int32), rb, num_segments=n)
    # build rows grouped by rank: sort build positions by rank
    iota_b = jnp.arange(b, dtype=jnp.int32)
    inv = (~vb).astype(jnp.int32)
    _, _, build_pos_sorted = lax.sort([inv, rb, iota_b], num_keys=2)
    offsets = jnp.cumsum(cnt) - cnt  # start of each rank's run in the sorted build
    match_count = jnp.where(vp, cnt[rp], 0)
    total = jnp.sum(match_count)
    return match_count, total, offsets, build_pos_sorted, rp


@functools.partial(jax.jit, static_argnames=("out_padded",))
def _mm_expand(match_count, offsets, build_pos_sorted, rp, total, out_padded: int):
    p = match_count.shape[0]
    cum = jnp.cumsum(match_count)
    j = jnp.arange(out_padded, dtype=jnp.int32)
    probe_idx = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    probe_idx = jnp.clip(probe_idx, 0, p - 1)
    start = cum[probe_idx] - match_count[probe_idx]
    k = j - start
    bpos = offsets[rp[probe_idx]] + k
    bpos = jnp.clip(bpos, 0, build_pos_sorted.shape[0] - 1)
    build_idx = build_pos_sorted[bpos]
    out_valid = j < total
    return probe_idx, build_idx, out_valid


def mm_plan_for(limbs, valid, p: int, how: str, probe_valid=None):
    """Shared many-to-many planning for the embedded AND mesh join paths:
    per-probe match counts (left joins get a synthetic row for unmatched
    probes), total output rows, and the sorted-build expansion tables."""
    match_count, total, offsets, build_pos_sorted, rp = \
        compileplane.aot_kernel_call(
            "mm_plan", _mm_plan, (tuple(limbs), valid), (p,))
    if how == "left":
        pv = valid[:p] if probe_valid is None else probe_valid
        match_count = jnp.where(pv & (match_count == 0), 1, match_count)
        total = jnp.sum(match_count)
    return match_count, total, offsets, build_pos_sorted, rp


def mm_unmatched(limbs, valid, p: int, probe_idx, match_count):
    """Output-aligned mask of left-join rows with no real build match."""
    return (match_count[probe_idx] == 1) & _is_unmatched_gather(
        tuple(limbs), valid, p, probe_idx
    )


def hash_join_general(
    probe: DeviceBatch,
    build: DeviceBatch,
    probe_keys: Sequence[str],
    build_keys: Sequence[str],
    how: str = "inner",
    build_payload: Sequence[str] = (),
) -> DeviceBatch:
    """Many-to-many join.  One host sync per batch for the output bucket."""
    p = probe.padded_len
    limbs, valid = _concat_limbs(probe, build, probe_keys, build_keys)
    if how in ("semi", "anti"):
        match_count, *_ = _mm_plan(tuple(limbs), valid, p)
        matched = match_count > 0
        mask = matched if how == "semi" else (probe.valid & ~matched)
        return kernels.apply_mask(probe, mask)
    match_count, total, offsets, build_pos_sorted, rp = mm_plan_for(
        limbs, valid, p, how, probe_valid=probe.valid
    )
    ntotal = int(total)  # host sync: pick output bucket
    out_padded = config.bucket_size(ntotal)
    probe_idx, build_idx, out_valid = compileplane.aot_kernel_call(
        "mm_expand", _mm_expand,
        (match_count, offsets, build_pos_sorted, rp, total), (out_padded,)
    )
    cols = gather_columns(probe.columns, probe_idx)
    unmatched = None
    if how == "left":
        unmatched = mm_unmatched(limbs, valid, p, probe_idx, match_count)
    for name, taken in gather_columns(
        {n: build.columns[n] for n in build_payload}, build_idx
    ).items():
        if how == "left":
            taken = with_nulls(taken, unmatched)
        cols[name] = taken
    # out_valid = (iota < total) for BOTH inner and left (mm_plan_for's
    # left adjustment feeds total), so the host count is exact either way
    return DeviceBatch(cols, out_valid, ntotal, None)


@functools.partial(jax.jit, static_argnames=("p",))
def _is_unmatched_gather(limbs, valid, p, probe_idx):
    ranks, _ = dense_rank(tuple(limbs), valid)
    rp, rb = ranks[:p], ranks[p:]
    vp, vb = valid[:p], valid[p:]
    n = valid.shape[0]
    cnt = jax.ops.segment_sum(vb.astype(jnp.int32), rb, num_segments=n)
    # dense_rank gives invalid (incl. null-key) probe rows an arbitrary rank —
    # they must read as unmatched regardless of that rank's build count
    return ((cnt[rp] == 0) | ~vp)[probe_idx]


@jax.jit
def _distinct_from_table(tbl, ok):
    """(# placed keys, # insertable rows) from a converged hash table."""
    from quokka_tpu.ops import hashtable

    return (jnp.sum((tbl != hashtable.EMPTY).astype(jnp.int32)),
            jnp.sum(ok.astype(jnp.int32)))


@jax.jit
def _sorted_has_dup(sorted_limbs, n_valid):
    """Any adjacent equal key pair within the valid prefix of a build sort."""
    dup = jnp.zeros((), dtype=bool)
    eq = jnp.ones(sorted_limbs[0].shape[0], dtype=bool)
    for limb in sorted_limbs:
        eq = eq & (limb == jnp.roll(limb, 1))
    iota = jnp.arange(sorted_limbs[0].shape[0], dtype=jnp.int32)
    dup = jnp.any(eq & (iota >= 1) & (iota < n_valid))
    return dup, n_valid


def build_keys_unique(build: DeviceBatch, build_keys: Sequence[str]) -> bool:
    """Host-synced check whether the build side is PK-unique (decides fast
    path).  Called once per finalized build table, not per probe batch.

    Answered from the SAME cached structure the probe will use — the device
    hash table (distinct == placed slots) or the cached build sort (any
    adjacent equal pair) — instead of a fresh dense-rank sort over the
    build, so the check is nearly free and the probe cache is warm before
    the first probe batch arrives.  Null-key rows match the dense-rank
    semantics this replaces: all nulls collapse into one key, so uniqueness
    additionally requires at most one null/NaN-key row."""
    from quokka_tpu.ops import strategy as kstrategy

    nvalid = build.count_valid()
    if kstrategy.choice("join_build") == "hashtable":
        from quokka_tpu.ops import hashtable

        try:
            table = hashtable.build_table(
                build, build_keys, key_limbs,
                lambda: _nonnull_valid(build, build_keys),
            )
        except hashtable.HashTableConvergenceError:
            table = None  # diverged build: the sort fallback below decides
        if table is not None:
            raw = key_limbs(build, build_keys)
            ok = _nonnull_valid(build, build_keys) & ~hashtable.nan_rows(raw)
            distinct, n_ok = _distinct_from_table(table.tbl, ok)
            distinct, n_ok = int(distinct), int(n_ok)
            return distinct == n_ok and nvalid - n_ok <= 1
    sorted_limbs, _perm, n_ok_dev = _build_sorted_cached(build, build_keys)
    dup, n_ok = _sorted_has_dup(tuple(sorted_limbs), n_ok_dev)
    return (not bool(dup)) and nvalid - int(n_ok) <= 1
