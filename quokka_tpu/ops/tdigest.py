"""Merging t-digest: a MERGEABLE quantile sketch.

Replaces the round-1 reservoir sampler (VERDICT r1: "mergeable quantile
sketches" — the reference uses ldbpy's t-digest).  Per-channel digests merge
EXACTLY at the combine stage instead of averaging per-channel quantiles, so
multi-channel results don't depend on how rows were partitioned.

Standard merging-digest construction (Dunning & Ertl): centroids kept sorted
by mean; a pass merges neighbors while the k1 scale function allows, giving
O(compression) centroids with fine resolution at the tails.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import numpy as np


def _k1(q: float, compression: float) -> float:
    q = min(1.0, max(0.0, q))
    return compression / (2.0 * math.pi) * math.asin(2.0 * q - 1.0)


class TDigest:
    def __init__(self, compression: float = 200.0,
                 means: np.ndarray = None, weights: np.ndarray = None):
        self.compression = float(compression)
        self.means = np.zeros(0) if means is None else np.asarray(means, dtype=np.float64)
        self.weights = np.zeros(0) if weights is None else np.asarray(weights, dtype=np.float64)

    # -- building -------------------------------------------------------------
    def add(self, values: np.ndarray) -> None:
        v = np.asarray(values, dtype=np.float64)
        v = v[~np.isnan(v)]
        if len(v) == 0:
            return
        cap = int(4 * self.compression)
        if len(v) > 2 * cap:
            # big chunks pre-bucket VECTORIZED (sort + reduceat over
            # equal-count slices) so the sequential merge loop in _compress
            # only ever sees O(compression) centroids, not O(rows)
            v = np.sort(v)
            edges = np.linspace(0, len(v), cap + 1).astype(np.int64)
            starts = edges[:-1]
            counts = np.diff(edges).astype(np.float64)
            sums = np.add.reduceat(v, starts)
            means = sums / counts
            self.means = np.concatenate([self.means, means])
            self.weights = np.concatenate([self.weights, counts])
        else:
            self.means = np.concatenate([self.means, v])
            self.weights = np.concatenate([self.weights, np.ones(len(v))])
        if len(self.means) > 8 * self.compression:
            self._compress()

    def merge(self, other: "TDigest") -> None:
        self.means = np.concatenate([self.means, other.means])
        self.weights = np.concatenate([self.weights, other.weights])
        self._compress()

    def _compress(self) -> None:
        if len(self.means) == 0:
            return
        order = np.argsort(self.means, kind="stable")
        m, w = self.means[order], self.weights[order]
        total = w.sum()
        out_m, out_w = [m[0]], [w[0]]
        w_before = 0.0
        k_lo = _k1(0.0, self.compression)
        for i in range(1, len(m)):
            q_up = (w_before + out_w[-1] + w[i]) / total
            if _k1(q_up, self.compression) - k_lo <= 1.0:
                # merge into the current centroid (weighted mean)
                nw = out_w[-1] + w[i]
                out_m[-1] += (m[i] - out_m[-1]) * (w[i] / nw)
                out_w[-1] = nw
            else:
                w_before += out_w[-1]
                k_lo = _k1(w_before / total, self.compression)
                out_m.append(m[i])
                out_w.append(w[i])
        self.means = np.asarray(out_m)
        self.weights = np.asarray(out_w)

    # -- querying -------------------------------------------------------------
    def quantile(self, q: float) -> float:
        self._compress()
        if len(self.means) == 0:
            return float("nan")
        if len(self.means) == 1:
            return float(self.means[0])
        w = self.weights
        total = w.sum()
        # centroid midpoints in cumulative-weight space
        cum = np.cumsum(w) - w / 2.0
        target = q * total
        if target <= cum[0]:
            return float(self.means[0])
        if target >= cum[-1]:
            return float(self.means[-1])
        i = np.searchsorted(cum, target) - 1
        frac = (target - cum[i]) / max(cum[i + 1] - cum[i], 1e-12)
        return float(self.means[i] + frac * (self.means[i + 1] - self.means[i]))

    # -- serialization (travels through the shuffle as two float columns) -----
    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        self._compress()
        return self.means, self.weights

    @classmethod
    def from_arrays(cls, means, weights, compression: float = 200.0) -> "TDigest":
        return cls(compression, means, weights)
