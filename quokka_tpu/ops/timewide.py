"""Two-limb (wide int64) ordering/arithmetic helpers.

Without x64, int64/ns-timestamp columns live on device as two int32 limbs
(hi = value >> 32, lo_sortable = (value & 0xFFFFFFFF) - 2**31) so that signed
lexicographic (hi, lo_sortable) order equals numeric order (ops/bridge.py).
This module centralises every operation that must respect both limbs:

- widen_limbs / scalar_limbs: uniform limb views of narrow cols & host ints
- not_limbs: exact order-reversal (int64 bitwise NOT == per-limb NOT)
- limb comparisons for range partitioning
- host_i64: exact host int64 view of a column
- rebase_narrow / add_base: exact rebase of a wide time column onto an int32
  window relative to a host base (the "rescaled epoch" strategy for the
  streaming time-series tier; raises when the stream span overflows int32)

Reference counterpart: pyquokka's executors operate on host Polars int64
columns directly (ts_executors.py); here the 64-bit arithmetic must be
explicit because the device path is 32-bit.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quokka_tpu.ops.batch import DeviceBatch, NumCol

_SIGN = np.uint32(0x80000000)


def _bitcast(x, dt):
    return jax.lax.bitcast_convert_type(x, dt)


def widen_limbs(col: NumCol) -> Tuple[jax.Array, jax.Array]:
    """(hi, lo_sortable) int32 limb view of any integer-kind NumCol."""
    if col.hi is not None:
        return col.hi, col.data
    d = col.data
    if jnp.issubdtype(d.dtype, jnp.floating):
        raise TypeError("widen_limbs on float column")
    if d.dtype == jnp.int64:
        # narrow int64 storage (x64 mode): split exactly — the old int32
        # cast silently truncated ns-epoch timestamps
        hi = (d >> jnp.int64(32)).astype(jnp.int32)
        lo_u = (d & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        lo = _bitcast(lo_u ^ _SIGN, jnp.int32)
        return hi, lo
    d = d.astype(jnp.int32)
    hi = jnp.where(d < 0, jnp.int32(-1), jnp.int32(0))
    lo = _bitcast(_bitcast(d, jnp.uint32) ^ _SIGN, jnp.int32)
    return hi, lo


def not_limbs(limbs: Tuple[jax.Array, jax.Array]) -> Tuple[jax.Array, jax.Array]:
    """Per-limb bitwise NOT == int64 bitwise NOT (~v = -v-1): exact strictly
    decreasing remap, used to run 'forward' asof on a backward kernel."""
    hi, lo = limbs
    return ~hi, ~lo


def scalar_limbs(v: int) -> Tuple[np.int32, np.int32]:
    """Limb encoding of a host int (arbitrary precision, sign-correct)."""
    v = int(v)
    return np.int32(v >> 32), np.int32((v & 0xFFFFFFFF) - 2**31)


def limb_le_scalar_count(col: NumCol, boundaries) -> jax.Array:
    """searchsorted(boundaries, col, side='right') for a possibly-wide column:
    per row, the count of boundaries <= value."""
    hi, lo = widen_limbs(col)
    bl = [scalar_limbs(b) for b in boundaries]
    bhi = jnp.asarray(np.array([h for h, _ in bl], dtype=np.int32))
    blo = jnp.asarray(np.array([l for _, l in bl], dtype=np.int32))
    le = (bhi[None, :] < hi[:, None]) | (
        (bhi[None, :] == hi[:, None]) & (blo[None, :] <= lo[:, None])
    )
    return jnp.sum(le, axis=1).astype(jnp.int32)


def host_max_i64(col: NumCol, valid) -> int:
    """Exact int64 max over valid rows via two device reduces (no bulk pull).
    Caller must ensure at least one valid row."""
    hi, lo = widen_limbs(col)
    neg = jnp.int32(-(2**31))
    mh = jnp.max(jnp.where(valid, hi, neg))
    ml = jnp.max(jnp.where(valid & (hi == mh), lo, neg))
    return int(mh) * 2**32 + int(ml) + 2**31


def host_min_i64(col: NumCol, valid) -> int:
    """Exact int64 min over valid rows (mirror of host_max_i64)."""
    hi, lo = widen_limbs(col)
    pos = jnp.int32(2**31 - 1)
    mh = jnp.min(jnp.where(valid, hi, pos))
    ml = jnp.min(jnp.where(valid & (hi == mh), lo, pos))
    return int(mh) * 2**32 + int(ml) + 2**31


def cmp_scalar(col: NumCol, v: int, op: str) -> jax.Array:
    """Elementwise comparison of a possibly-wide int column against a host int."""
    hi, lo = widen_limbs(col)
    vhi, vlo = scalar_limbs(v)
    eq = (hi == vhi) & (lo == vlo)
    lt = (hi < vhi) | ((hi == vhi) & (lo < vlo))
    return {
        "=": eq, "!=": ~eq, "<": lt, "<=": lt | eq, ">": ~(lt | eq), ">=": ~lt,
    }[op]


def host_i64(col: NumCol, valid) -> np.ndarray:
    """Exact int64 host values of the valid rows (one device->host sync)."""
    mask = np.asarray(valid)
    if col.hi is not None:
        hi = np.asarray(col.hi)[mask].astype(np.int64)
        lo = np.asarray(col.data)[mask].astype(np.int64) + 2**31
        return (hi << np.int64(32)) | lo
    return np.asarray(col.data)[mask].astype(np.int64)


def rebase_narrow(col: NumCol, valid, base: int, headroom: int = 0) -> NumCol:
    """value - base as an int32 'i' column.  Exact: raises if any valid value
    falls outside [0, 2**31 - headroom) relative to base — the caller keeps
    `headroom` so later window arithmetic (t + size) cannot overflow."""
    hi, lo = widen_limbs(col)
    bhi, blo = scalar_limbs(base)
    lo_u = _bitcast(lo, jnp.uint32) ^ _SIGN        # true unsigned low limb
    blo_u = np.uint32((int(base) & 0xFFFFFFFF))
    diff_lo = lo_u - blo_u                          # wraps mod 2^32
    borrow = (lo_u < blo_u).astype(jnp.int32)
    diff_hi = hi - jnp.int32(int(base) >> 32) - borrow
    rel = _bitcast(diff_lo, jnp.int32)
    limit = jnp.int32(2**31 - 1 - int(headroom))
    ok = (diff_hi == 0) & (rel >= 0) & (rel <= limit)
    if not bool(jnp.all(ok | ~valid)):
        unit = f" {col.unit}" if col.unit else ""
        raise ValueError(
            f"time column spans more than 2^31{unit} units within one stream "
            f"(base={base}); cast to a coarser unit (e.g. ms/s) or enable x64"
        )
    return NumCol(jnp.where(valid, rel, 0), "i")


def add_base(data, base: Optional[int], kind: str, unit: Optional[str]) -> NumCol:
    """Inverse of rebase_narrow: int32 relative values + host base -> NumCol
    (wide if the absolute values need 64 bits)."""
    data = data.astype(jnp.int32)
    if not base:
        return NumCol(data, kind, unit=unit)
    lo_u = _bitcast(data, jnp.uint32)               # data >= 0 so low limb == data
    blo_u = np.uint32(int(base) & 0xFFFFFFFF)
    sum_lo = lo_u + blo_u                            # wraps mod 2^32
    carry = (sum_lo < lo_u).astype(jnp.int32)
    hi = jnp.int32(int(base) >> 32) + carry
    lo = _bitcast(sum_lo ^ _SIGN, jnp.int32)
    return NumCol(lo, kind, hi=hi, unit=unit)
