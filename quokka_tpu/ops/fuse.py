"""Whole-pipeline fusion: run a batch's expression+aggregate work as ONE
jitted XLA program.

Why: per-op jit dispatch costs dominate on TPU (each call is a host->device
round trip; over a remote runtime each is milliseconds).  XLA wants one big
program it can fuse (SURVEY.md build plan: "let XLA fuse — don't hand-schedule").

Two-phase design:
- HOST PREPASS (per batch): anything that depends on string dictionary VALUES
  (LIKE/contains/equality masks, in-lists, string transforms) is evaluated
  once over the (small) dictionary and gathered by code into a device array,
  which becomes an extra input column.  The expression tree is rewritten to
  reference these bound columns.  Key string columns contribute their hash
  limb arrays the same way.
- TRACED PHASE: the rewritten, now purely-numeric expression graph plus the
  sort/segment group-by runs inside a single jit, cached per
  (padded_len, column signature, plan id).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from quokka_tpu.expression import (
    Agg,
    Alias,
    BinOp,
    Case,
    Cast,
    ColRef,
    DateLit,
    DtField,
    Expr,
    Func,
    InList,
    IntervalLit,
    IsNull,
    Literal,
    StrOp,
    UnaryOp,
    _rebuild,
)
import numpy as np

from quokka_tpu import config
from quokka_tpu.ops import expr_compile, kernels, sigkey
from quokka_tpu.ops import strategy as kstrategy
from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol, gather_columns
from quokka_tpu.runtime import compileplane


def _is_string_dependent(e: Expr, batch: DeviceBatch) -> bool:
    """Does evaluating e require dictionary VALUES (host data)?"""
    if isinstance(e, (StrOp,)):
        return True
    if _is_string_cast(e):
        return True
    if isinstance(e, UnaryOp) and e.op == "not":
        # bind the whole NOT subtree, not just its string child: evaluate()'s
        # 3VL null guard lives inside the NOT handling, and `not __bound`
        # would re-invert null rows back to True
        return _is_string_dependent(e.operand, batch)
    if isinstance(e, InList):
        return _refs_string(e.expr, batch)
    if isinstance(e, IsNull):
        return _refs_string(e.expr, batch)
    if isinstance(e, BinOp) and e.op in ("=", "!=", "<", "<=", ">", ">="):
        if _refs_string(e.left, batch) or _refs_string(e.right, batch):
            return True
    return False


def _is_string_cast(e: Expr) -> bool:
    """cast(x as varchar) builds a dictionary on the HOST — it can never run
    inside a traced (fused) program, even over numeric inputs."""
    return isinstance(e, Cast) and e.to.startswith(("varchar", "string", "text"))


def _refs_string(e: Expr, batch: DeviceBatch) -> bool:
    if isinstance(e, ColRef):
        return isinstance(batch.columns.get(e.name), StrCol)
    if isinstance(e, Literal):
        return isinstance(e.value, str)
    if _is_string_cast(e):
        return True
    return any(_refs_string(c, batch) for c in e.children())


class Prepass:
    """Rewrites expressions against a concrete batch: string-dependent
    subtrees are evaluated NOW (host dict work + one gather) and replaced by
    references to bound device columns."""

    def __init__(self, batch: DeviceBatch):
        self.batch = batch
        self.bound: Dict[str, jnp.ndarray] = {}
        self._memo: Dict[str, str] = {}

    def rewrite(self, e: Expr) -> Expr:
        if isinstance(e, Alias):
            return Alias(self.rewrite(e.expr), e.name)
        if _is_string_dependent(e, self.batch):
            return ColRef(self._bind(e))
        kids = e.children()
        if not kids:
            return e
        return _rebuild(e, [self.rewrite(k) for k in kids])

    def _bind(self, e: Expr) -> str:
        key = e.sql()
        if key in self._memo:
            return self._memo[key]
        col = expr_compile.evaluate_to_column(e, self.batch)
        if isinstance(col, StrCol):
            # string-valued transform: bind its hash limbs? not needed for
            # numeric pipelines; fall back to codes (equality-safe only within
            # this batch) — callers needing more go through the unfused path
            raise expr_compile.CompileError("string-valued expr in fused pipeline")
        name = f"__b{len(self.bound)}"
        self.bound[name] = col.data
        self._memo[key] = name
        return name


class _ShimBatch:
    """Duck-typed DeviceBatch over traced arrays for expr_compile.evaluate."""

    def __init__(self, columns: Dict[str, object], padded_len: int, valid):
        self.columns = columns
        self._padded = padded_len
        self.valid = valid

    @property
    def padded_len(self):
        return self._padded

    @property
    def names(self):
        return list(self.columns.keys())


# Fused programs are cached GLOBALLY by full structural signature so separate
# executor instances (and separate queries) reuse the same jitted callable —
# jax's trace cache is keyed by function identity, so per-instance closures
# would recompile on every query.  The dict is the compile plane's program
# store: signatures derive through ops/sigkey (canonical ladder, normalized
# column signatures) and misses resolve through compileplane.acquire, which
# loads a persisted executable when one exists and AOT-compiles otherwise.
_FUSED_PROGRAMS: Dict[Tuple, object] = compileplane.PROGRAMS


def _dispatch_program(sig, builder, args):
    """Hot-path program dispatch: one dict get per batch; misses go through
    the compile plane (persisted-executable load, else explicit AOT
    compile + background persist).  A pre-warmed executable whose shapes
    drift from this call rebuilds in place instead of erroring."""
    fn = _FUSED_PROGRAMS.get(sig)
    if fn is None:
        fn = compileplane.acquire(sig, builder, args)
    else:
        # record the use under the current plan even on a warm hit (a new
        # plan reusing another's programs must still prewarm them all)
        compileplane.note_program(sig)
    from quokka_tpu.obs import devprof

    # charge the program's static flops/bytes to the current operator
    devprof.on_dispatch(sig)
    try:
        return fn(*args)
    except compileplane.AotMismatch:
        fn = builder()
        _FUSED_PROGRAMS[sig] = fn
        return fn(*args)


# Small-key group-by: the one-hot operand the MXU matmul contracts over is
# materialized n x (B+1); bound its footprint so a big batch can't blow HBM.
_SMALL_GROUPBY_MAX_BUCKETS = 256
_SMALL_GROUPBY_MAX_BYTES = 512 << 20


class FusedPartialAgg:
    """One-jit partial group-by-aggregate, compiled per batch signature.

    Two strategies inside the jit:
    - SMALL-KEY FAST PATH: when every group key is a dictionary-encoded string
      and the product of dictionary sizes is tiny (TPC-H Q1's
      returnflag x linestatus = a dozen groups), the group id is computed
      directly from the codes and float sums/counts reduce via ONE
      one-hot matmul on the MXU — no sort, and the output batch is a
      256-row bucket instead of the input's padded length (so everything
      downstream — shuffle, concat, recombine — shrinks by ~4000x).
    - GENERAL PATH: multi-operand lax.sort on key limbs + contiguous segment
      reduces (random-order scatter-adds serialize badly on TPU)."""

    def __init__(self, keys: List[str], plan):
        self.keys = keys
        self.plan = plan

    def _small_dims(self, batch: DeviceBatch, use_tables: bool):
        """Per-key bucket counts (dict size + a null slot) when the small-key
        path applies, else None.  Dims are CANONICALIZED to the next power
        of two: raw dictionary sizes vary per file/batch, and keying the
        fused program on the exact size would recompile the whole small-key
        program every time a scan chunk's dictionary grows by one entry —
        the bucket ladder discipline, applied to the signature space."""
        if not self.keys:
            return None
        if not all(isinstance(batch.columns[k], StrCol) for k in self.keys):
            return None
        if not all(op in ("sum", "count") for _, op, _ in self.plan.partials):
            return None
        dims = tuple(
            _pow2(len(batch.columns[k].dictionary.values) + 1)
            for k in self.keys
        )
        n_buckets = int(np.prod(dims)) + 1  # + the invalid-row dump bucket
        itemsize = 8 if config.x64_enabled() else 4
        if n_buckets > _SMALL_GROUPBY_MAX_BUCKETS:
            return None
        if not use_tables:
            # matmul-strategy gates only: the scatter strategy materializes
            # no n x B one-hot and accumulates exactly
            if batch.padded_len * n_buckets * itemsize > _SMALL_GROUPBY_MAX_BYTES:
                return None
            # float32 matmul accumulation is exact only up to 2^24: beyond
            # that, counts (and integer-valued sums) can silently lose units
            if not config.x64_enabled() and batch.padded_len > (1 << 24):
                return None
        return dims

    def __call__(self, batch: DeviceBatch) -> DeviceBatch:
        pre = Prepass(batch)
        pre_exprs = [(name, pre.rewrite(e)) for name, e in self.plan.pre]
        # inputs: numeric columns referenced + bound columns + key limbs
        needed = set()
        for _, e in pre_exprs:
            needed |= e.required_columns()
        num_inputs = {}
        for n in sorted(needed):
            c = batch.columns.get(n)
            if c is None:
                continue  # bound column
            assert isinstance(c, NumCol), n
            num_inputs[n] = c
        # the group-by strategy is resolved ONCE per dispatch and baked
        # into the program signature (ops/strategy.py); a warm program's
        # choice is recorded as having run without re-tracing
        gb_choice = kstrategy.choice("groupby")
        use_tables = gb_choice == "hashtable"
        dims = self._small_dims(batch, use_tables)
        if dims is not None:
            kstrategy.note_used("groupby", gb_choice)
            return self._call_small(batch, pre, pre_exprs, num_inputs, dims,
                                    use_tables)
        key_limbs: List[jnp.ndarray] = []
        for k in self.keys:
            c = batch.columns[k]
            if isinstance(c, StrCol):
                # within one batch, dictionary codes ARE the key identity:
                # one limb instead of two hash limbs (cross-batch identity is
                # restored at recombine time via hash limbs on the small
                # partial batches)
                key_limbs.append(c.codes)
            else:
                if c.hi is not None:
                    key_limbs.append(c.hi)
                key_limbs.append(c.data)
        sig = sigkey.make_key(
            "partial_agg",
            sigkey.batch_sig(batch, list(num_inputs)),
            tuple(sorted(pre.bound)),
            tuple(str(l.dtype) for l in key_limbs),
            tuple((n, e.sql()) for n, e in pre_exprs),
            tuple((p, op, tmp) for p, op, tmp in self.plan.partials),
            bool(self.keys),
            use_tables,  # strategy is baked into the program
        )
        kstrategy.note_used("groupby", gb_choice)
        builder = lambda: self._build(  # noqa: E731 — deferred to cache miss
            pre_exprs, list(num_inputs), sorted(pre.bound), len(key_limbs))
        return self._invoke(
            sig, builder, batch, pre, num_inputs, tuple(key_limbs),
            batch.padded_len,
        )

    def _invoke(self, sig, builder, batch, pre, num_inputs, key_arrays,
                out_pad):
        """Shared dispatch tail: run the fused program and assemble the
        partial-aggregate output batch (used by both strategies)."""
        hi_arrays = tuple(
            c.hi if c.hi is not None else jnp.zeros(0, jnp.int32)
            for c in num_inputs.values()
        )
        outs = _dispatch_program(sig, builder, (
            tuple(c.data for c in num_inputs.values()),
            hi_arrays,
            tuple(pre.bound[k] for k in sorted(pre.bound)),
            key_arrays,
            batch.valid,
        ))
        *agg_arrays, rep, num = outs
        cols = gather_columns({k: batch.columns[k] for k in self.keys}, rep)
        for (pname, _, _), arr in zip(self.plan.partials, agg_arrays):
            cols[pname] = NumCol(
                arr, "f" if jnp.issubdtype(arr.dtype, jnp.floating) else "i"
            )
        gvalid = jnp.arange(out_pad) < num
        return DeviceBatch(cols, gvalid, None, None).note_count(num)

    def _build(self, pre_exprs, num_names, bound_names, n_limbs):
        plan = self.plan
        has_keys = bool(self.keys)

        @jax.jit
        def fused(num_arrays, hi_arrays, bound_arrays, limbs, valid):
            n = valid.shape[0]
            cols = {}
            for name, arr, hi in zip(num_names, num_arrays, hi_arrays):
                cols[name] = NumCol(arr, _infer_kind(arr), hi=hi if hi.shape[0] else None)
            for name, arr in zip(bound_names, bound_arrays):
                cols[name] = NumCol(arr, _infer_kind(arr))
            shim = _ShimBatch(cols, n, valid)
            pre_cols = {}
            for name, e in pre_exprs:
                pre_cols[name] = expr_compile.evaluate_to_column(e, shim)
            arrays = tuple(
                pre_cols[tmp].data if tmp is not None else jnp.zeros(n, jnp.int32)
                for (_, _, tmp) in plan.partials
            )
            ops = tuple(op for (_, op, _) in plan.partials)
            if has_keys:
                outs, counts, rep, num = kernels.groupby_limbs(
                    tuple(limbs), arrays, ops, valid
                )
            else:
                ranks = jnp.zeros(n, dtype=jnp.int32)
                num = jnp.minimum(jnp.sum(valid), 1).astype(jnp.int32)
                outs, counts, rep = kernels._segment_aggs(ranks, valid, arrays, ops)
            return (*outs, rep, num)

        return fused

    def _call_small(self, batch, pre, pre_exprs, num_inputs, dims,
                    use_tables: bool):
        codes = tuple(batch.columns[k].codes for k in self.keys)
        out_pad = config.bucket_size(int(np.prod(dims)))
        sig = sigkey.make_key(
            "partial_agg_small",
            sigkey.batch_sig(batch, list(num_inputs)),
            tuple(sorted(pre.bound)),
            dims,
            tuple((n, e.sql()) for n, e in pre_exprs),
            tuple((p, op, tmp) for p, op, tmp in self.plan.partials),
            use_tables,  # strategy is baked into the program
        )
        builder = lambda: self._build_small(  # noqa: E731 — on cache miss
            pre_exprs, list(num_inputs), sorted(pre.bound), dims, out_pad,
            use_tables)
        return self._invoke(sig, builder, batch, pre, num_inputs, codes,
                            out_pad)

    def _build_small(self, pre_exprs, num_names, bound_names, dims, out_pad,
                     use_tables: bool):
        plan = self.plan
        n_groups = int(np.prod(dims))
        strides = []
        s = 1
        for d in reversed(dims):
            strides.append(s)
            s *= d
        strides = tuple(reversed(strides))
        if use_tables:
            # CPU/GPU: scatter segment-sums by bucket id — no n x B one-hot,
            # exact accumulation, and none of the matmul memory gates.  TPU
            # keeps the one-hot matmul (the MXU reduces all agg columns in
            # one pass; random scatters serialize there).
            return self._build_small_scatter(
                pre_exprs, num_names, bound_names, strides, n_groups, out_pad
            )

        @jax.jit
        def fused(num_arrays, hi_arrays, bound_arrays, codes, valid):
            n = valid.shape[0]
            cols = {}
            for name, arr, hi in zip(num_names, num_arrays, hi_arrays):
                cols[name] = NumCol(
                    arr, _infer_kind(arr), hi=hi if hi.shape[0] else None
                )
            for name, arr in zip(bound_names, bound_arrays):
                cols[name] = NumCol(arr, _infer_kind(arr))
            shim = _ShimBatch(cols, n, valid)
            pre_cols = {}
            for name, e in pre_exprs:
                pre_cols[name] = expr_compile.evaluate_to_column(e, shim)
            gid = jnp.zeros(n, dtype=jnp.int32)
            for c, st in zip(codes, strides):
                # code -1 = null -> slot 0 of that key (SQL: nulls form one group)
                gid = gid + (c.astype(jnp.int32) + 1) * jnp.int32(st)
            gid = jnp.where(valid, gid, jnp.int32(n_groups))  # dump bucket
            fdt = config.float_dtype()
            onehot = gid[:, None] == jnp.arange(n_groups + 1, dtype=jnp.int32)[None, :]
            mat_cols = []  # columns reduced by the one matmul
            seg_results = {}  # partial idx -> bucket array (integer sums)
            for j, (pname, op, tmp) in enumerate(plan.partials):
                if op == "count":
                    mat_cols.append((j, valid.astype(fdt)))
                    continue
                v = pre_cols[tmp].data
                if jnp.issubdtype(v.dtype, jnp.floating):
                    # invalid (padded) rows may hold NaN garbage, which would
                    # poison the whole bucket column through NaN * 0
                    mat_cols.append(
                        (j, jnp.where(valid, v, jnp.zeros((), v.dtype)))
                    )
                else:
                    # integer sums stay exact via a (rare) segment reduce
                    x = jnp.where(valid, v, jnp.zeros((), v.dtype))
                    seg = jax.ops.segment_sum(x, gid, num_segments=n_groups + 1)
                    seg_results[j] = seg[:n_groups]
            sums = None
            if mat_cols:
                stacked = jnp.stack([c for _, c in mat_cols], axis=1)
                # HIGHEST: the TPU MXU's default f32 matmul truncates operands
                # to bf16 (~8 mantissa bits) — sums must keep f32 precision to
                # match the segment-reduce path
                sums = jnp.matmul(
                    onehot.astype(fdt).T, stacked,
                    precision=jax.lax.Precision.HIGHEST,
                )[:n_groups]
            iota = jnp.arange(n, dtype=jnp.int32)
            rep_b = jnp.min(
                jnp.where(onehot[:, :n_groups], iota[:, None], jnp.int32(n)),
                axis=0,
            )
            live = rep_b < n
            num = jnp.sum(live.astype(jnp.int32))
            bidx = jnp.arange(n_groups, dtype=jnp.int32)
            order = jnp.argsort(jnp.where(live, bidx, jnp.int32(n_groups) + bidx))
            outs = []
            k = 0
            for j, (pname, op, tmp) in enumerate(plan.partials):
                if j in seg_results:
                    arr = seg_results[j]
                else:
                    arr = sums[:, k]
                    k += 1
                    if op == "count":
                        # counts <= n <= 2**24 are exact in float32
                        arr = arr.astype(jnp.int32)
                arr = arr[order]
                outs.append(_pad_tail(arr, out_pad))
            rep_d = jnp.minimum(rep_b[order], jnp.int32(n - 1))
            return (*outs, _pad_tail(rep_d, out_pad), num)

        return fused

    def _build_small_scatter(self, pre_exprs, num_names, bound_names,
                             strides, n_groups, out_pad):
        """Scatter strategy of the small-key fast path: identical contract
        and bucket-id scheme as the matmul strategy, but every aggregate is
        one segment reduce over (n_groups + 1) buckets."""
        plan = self.plan

        @jax.jit
        def fused(num_arrays, hi_arrays, bound_arrays, codes, valid):
            n = valid.shape[0]
            cols = {}
            for name, arr, hi in zip(num_names, num_arrays, hi_arrays):
                cols[name] = NumCol(
                    arr, _infer_kind(arr), hi=hi if hi.shape[0] else None
                )
            for name, arr in zip(bound_names, bound_arrays):
                cols[name] = NumCol(arr, _infer_kind(arr))
            shim = _ShimBatch(cols, n, valid)
            pre_cols = {}
            for name, e in pre_exprs:
                pre_cols[name] = expr_compile.evaluate_to_column(e, shim)
            gid = jnp.zeros(n, dtype=jnp.int32)
            for c, st in zip(codes, strides):
                # code -1 = null -> slot 0 of that key (SQL: nulls form one group)
                gid = gid + (c.astype(jnp.int32) + 1) * jnp.int32(st)
            gid = jnp.where(valid, gid, jnp.int32(n_groups))  # dump bucket
            iota = jnp.arange(n, dtype=jnp.int32)
            rep_b = jax.ops.segment_min(
                jnp.where(valid, iota, jnp.int32(n)), gid,
                num_segments=n_groups + 1,
            )[:n_groups]
            live = rep_b < n
            num = jnp.sum(live.astype(jnp.int32))
            bidx = jnp.arange(n_groups, dtype=jnp.int32)
            order = jnp.argsort(jnp.where(live, bidx, jnp.int32(n_groups) + bidx))
            outs = []
            for pname, op, tmp in plan.partials:
                if op == "count":
                    x = valid.astype(jnp.int32)
                else:
                    v = pre_cols[tmp].data
                    x = jnp.where(valid, v, jnp.zeros((), v.dtype))
                arr = jax.ops.segment_sum(x, gid, num_segments=n_groups + 1)
                outs.append(_pad_tail(arr[:n_groups][order], out_pad))
            rep_d = jnp.minimum(rep_b[order], jnp.int32(n - 1))
            return (*outs, _pad_tail(rep_d, out_pad), num)

        return fused


def _pow2(n: int) -> int:
    return sigkey.pow2_dim(n)


def _pad_tail(arr, padded):
    from quokka_tpu.ops.bridge import _pad_device

    return _pad_device(arr, padded)


def _infer_kind(arr):
    if arr.dtype == jnp.bool_:
        return "b"
    if jnp.issubdtype(arr.dtype, jnp.floating):
        return "f"
    return "i"


class FusedPredicate:
    """One-jit filter mask evaluation (plus prepass-bound string masks)."""

    def __init__(self, expr: Expr):
        self.expr = expr

    def __call__(self, batch: DeviceBatch) -> DeviceBatch:
        pre = Prepass(batch)
        try:
            e = pre.rewrite(self.expr)
        except expr_compile.CompileError:
            mask = expr_compile.evaluate_predicate(self.expr, batch)
            return kernels.apply_mask(batch, mask)
        needed = sorted(
            n for n in e.required_columns() if n in batch.columns
        )
        num_inputs = {}
        ok = True
        for n in needed:
            c = batch.columns[n]
            if not isinstance(c, NumCol) or c.hi is not None:
                ok = False
                break
            num_inputs[n] = c
        if not ok:
            mask = expr_compile.evaluate_predicate(self.expr, batch)
            return kernels.apply_mask(batch, mask)
        sig = sigkey.make_key(
            "predicate",
            sigkey.batch_sig(batch, list(num_inputs)),
            tuple(sorted(pre.bound)),
            e.sql(),
        )

        def builder():
            names, bnames = list(num_inputs), sorted(pre.bound)

            @jax.jit
            def fused(arrays, barrays, valid):
                cols = {}
                for name, arr in zip(names, arrays):
                    cols[name] = NumCol(arr, _infer_kind(arr))
                for name, arr in zip(bnames, barrays):
                    cols[name] = NumCol(arr, _infer_kind(arr))
                shim = _ShimBatch(cols, valid.shape[0], valid)
                m = valid & expr_compile.evaluate_predicate(e, shim)
                return m, jnp.sum(m.astype(jnp.int32))

            return fused

        mask, num = _dispatch_program(sig, builder, (
            tuple(num_inputs[n].data for n in num_inputs),
            tuple(pre.bound[k] for k in sorted(pre.bound)),
            batch.valid,
        ))
        return DeviceBatch(batch.columns, mask, None, batch.sorted_by).note_count(num)
