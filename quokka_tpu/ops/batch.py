"""Device-resident columnar batch.

The unit of data flowing through the engine.  Where the reference keeps Polars
DataFrames on the host (pyquokka/core.py push/execute paths), quokka-tpu keeps
batches as dicts of padded ``jax.Array`` columns plus a validity mask, so every
relational kernel (filter/project/hash/agg/join) is a jitted XLA program with
static shapes.

Strings are dictionary-encoded at ingest: the device sees only int32 codes; the
dictionary (small: unique values) stays on the host together with 64-bit FNV
hashes split into two uint32 limbs (TPU-native — no 64-bit ints needed on
device).  Predicates on strings are evaluated once on the dictionary host-side
and gathered by code on device; joins/groupbys on strings use the hash limbs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quokka_tpu import config

# ---------------------------------------------------------------------------
# String dictionaries
# ---------------------------------------------------------------------------

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a64(s) -> int:
    """Stable 64-bit FNV-1a hash (process-independent, unlike Python hash()).
    Accepts str or bytes (binary dictionary values hash their raw bytes)."""
    h = _FNV_OFFSET
    data = (
        s if isinstance(s, (bytes, bytearray))
        else s.encode("utf-8", errors="surrogatepass")
    )
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def _hash_strings(values: Sequence) -> np.ndarray:
    try:
        from quokka_tpu.utils import native  # C++ fast path if built

        out = native.fnv1a64_many(values)
        if out is not None:
            return out
    except Exception:
        pass
    return np.array([fnv1a64(v) if v is not None else 0 for v in values], dtype=np.uint64)


class StringDict:
    """Host-side dictionary for a string (or binary) column: values + 64-bit
    hashes as two uint32 limb arrays (device-friendly).  `binary` marks a
    bytes-valued dictionary (whole-file blob columns) so device_to_arrow
    round-trips to pa.binary instead of pa.string."""

    def __init__(self, values: np.ndarray, binary: Optional[bool] = None):
        # values: np object array of unique strings/bytes (may contain None)
        vals = np.asarray(values, dtype=object)
        if len(vals) == 0:
            # invariant: a dictionary is never empty.  All-invalid batches
            # get one null slot so every consumer can gather by clamped code
            # without special-casing zero-length host arrays.
            vals = np.array([None], dtype=object)
        self.values = vals
        if binary is None:
            # value sniff is a fallback only: an ALL-NULL dictionary can't be
            # sniffed, so producers that know the arrow type (bridge) pass
            # the flag explicitly to keep binary columns binary across
            # all-null batches
            binary = next(
                (isinstance(v, (bytes, bytearray)) for v in vals if v is not None),
                False,
            )
        self.binary = bool(binary)
        self._h64: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.values)

    @property
    def h64(self) -> np.ndarray:
        if self._h64 is None:
            self._h64 = _hash_strings(self.values)
        return self._h64

    @property
    def hash_hi(self) -> np.ndarray:
        return (self.h64 >> np.uint64(32)).astype(np.uint32).astype(np.int32)

    @property
    def hash_lo(self) -> np.ndarray:
        return (self.h64 & np.uint64(0xFFFFFFFF)).astype(np.uint32).astype(np.int32)

    def code_of(self, literal: str) -> int:
        """Code of a literal in this dictionary, or -1 if absent."""
        hits = np.nonzero(self.values == literal)[0]
        return int(hits[0]) if len(hits) else -1

    @property
    def none_entries(self) -> Optional[np.ndarray]:
        """Bool mask of None (null) entries, or None when there are none."""
        if not hasattr(self, "_none_entries"):
            m = np.array([x is None for x in self.values], dtype=bool)
            self._none_entries = m if m.any() else None
        return self._none_entries


# ---------------------------------------------------------------------------
# Columns
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NumCol:
    """Numeric / boolean / date / timestamp column on device.

    kind: 'f' float, 'i' int, 'b' bool, 'd' date32 (days), 't' timestamp.
    ``hi`` is the optional high 32-bit limb for wide integers/timestamps when
    running without x64 (TPU): value = hi * 2^32 + uint32(data).
    """

    data: jax.Array
    kind: str = "f"
    hi: Optional[jax.Array] = None
    unit: Optional[str] = None  # timestamp unit ('s','ms','us','ns')

    @property
    def padded_len(self) -> int:
        return self.data.shape[0]

    def take(self, idx: jax.Array) -> "NumCol":
        return NumCol(
            self.data[idx], self.kind, None if self.hi is None else self.hi[idx], self.unit
        )


@dataclasses.dataclass
class StrCol:
    """Dictionary-encoded string column: int32 codes on device, dict on host."""

    codes: jax.Array
    dictionary: StringDict

    @property
    def padded_len(self) -> int:
        return self.codes.shape[0]

    def hash_limbs(self):
        """Two int32 device arrays (hi, lo) of the 64-bit value hash per row.
        Null rows (code < 0) get the hash of null, (0, 0) — same pair
        _hash_strings assigns to None dictionary entries — so all nulls land
        in one group for groupby/sort instead of aliasing the last entry."""
        c = jnp.maximum(self.codes, 0)
        isnull = self.codes < 0
        hi = jnp.where(isnull, 0, jnp.asarray(self.dictionary.hash_hi)[c])
        lo = jnp.where(isnull, 0, jnp.asarray(self.dictionary.hash_lo)[c])
        return hi, lo

    def take(self, idx: jax.Array) -> "StrCol":
        return StrCol(self.codes[idx], self.dictionary)


@dataclasses.dataclass
class VecCol:
    """Fixed-width vector (embedding) column: [rows, dim] device array.
    Bridge target for arrow fixed_size_list<float> columns; the payload of
    vector search (top-k cosine runs as a matmul on the MXU)."""

    data: jax.Array  # [padded_rows, dim]

    @property
    def padded_len(self) -> int:
        return self.data.shape[0]

    @property
    def dim(self) -> int:
        return self.data.shape[1]

    def take(self, idx: jax.Array) -> "VecCol":
        return VecCol(self.data[idx])


Column = object  # NumCol | StrCol | VecCol


# ---------------------------------------------------------------------------
# Null representation (sentinel encoding)
#
# The reference carries Polars/Arrow validity bitmaps; device batches instead
# reserve one value per kind as NULL and map it back to a real Arrow null at
# the device->host boundary (bridge.device_to_arrow):
#   floats            NaN
#   narrow int/date   INT32_MIN (INT64_MIN under x64)
#   wide int/ts       INT64_MIN (both limbs == INT32_MIN under the lo-2^31
#                     encoding)
#   strings           dictionary code -1
#   bools             no null (ingest fills False); nulled bools upcast to 'i'
# Consequences (documented divergence): INT_MIN as real data reads as null,
# and nulls sort first (smallest) rather than Polars' nulls-last.
# ---------------------------------------------------------------------------

NULL_I32 = -(2**31)
NULL_I64 = -(2**63)


def _int_sentinel(dtype):
    return NULL_I64 if dtype == jnp.int64 else NULL_I32


def null_mask(col) -> jax.Array:
    """Per-row null mask for any column kind."""
    if isinstance(col, StrCol):
        isnull = col.codes < 0
        none = col.dictionary.none_entries
        if none is not None:
            isnull = isnull | jnp.asarray(none)[jnp.maximum(col.codes, 0)]
        return isnull
    if isinstance(col, VecCol):
        return jnp.zeros(col.padded_len, dtype=bool)
    if col.kind == "f":
        return jnp.isnan(col.data)
    if col.kind == "b":
        return jnp.zeros(col.padded_len, dtype=bool)
    if col.hi is not None:
        return (col.hi == NULL_I32) & (col.data == NULL_I32)
    return col.data == _int_sentinel(col.data.dtype)


def with_nulls(col, null_where: jax.Array):
    """Return `col` with rows where `null_where` marked null (sentinel)."""
    if isinstance(col, StrCol):
        return StrCol(jnp.where(null_where, -1, col.codes), col.dictionary)
    if isinstance(col, VecCol):
        return VecCol(jnp.where(null_where[:, None], 0.0, col.data))
    if col.kind == "f":
        return NumCol(jnp.where(null_where, jnp.nan, col.data), "f", unit=col.unit)
    if col.kind == "b":
        # bools have no spare value: upcast to int (0/1/NULL)
        data = jnp.where(null_where, NULL_I32, col.data.astype(jnp.int32))
        return NumCol(data, "i")
    if col.hi is not None:
        return NumCol(
            jnp.where(null_where, jnp.int32(NULL_I32), col.data),
            col.kind,
            hi=jnp.where(null_where, jnp.int32(NULL_I32), col.hi),
            unit=col.unit,
        )
    sent = _int_sentinel(col.data.dtype)
    return NumCol(jnp.where(null_where, sent, col.data), col.kind, unit=col.unit)


# ---------------------------------------------------------------------------
# Batch
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DeviceBatch:
    """A padded columnar batch.  ``valid`` marks live rows; all kernels must
    respect it.  ``nrows`` is the host-known live count when available (None
    after device-side filtering until a sync).  ``nrows_dev`` is an optional
    device scalar of the live count whose host copy was started asynchronously
    at creation — ``count_valid()`` then blocks on an (almost always already
    finished) transfer instead of paying a full device round trip."""

    columns: Dict[str, Column]
    valid: jax.Array  # bool[padded]
    nrows: Optional[int] = None
    sorted_by: Optional[List[str]] = None  # ordered-stream metadata
    nrows_dev: Optional[jax.Array] = None

    @property
    def padded_len(self) -> int:
        return self.valid.shape[0]

    @property
    def names(self) -> List[str]:
        return list(self.columns.keys())

    def count_valid(self) -> int:
        if self.nrows is None:
            from quokka_tpu.obs import spans as tracing

            src = self.nrows_dev if self.nrows_dev is not None else jnp.sum(self.valid)
            with tracing.span("count_valid.block"):
                self.nrows = int(src)
        return self.nrows

    def note_count(self, num: jax.Array) -> "DeviceBatch":
        """Record a device scalar as this batch's live count and start its
        async device->host copy (free to read later)."""
        try:
            num.copy_to_host_async()
        except Exception:
            pass  # tracers / numpy scalars: count stays device-lazy
        self.nrows_dev = num
        return self

    def select(self, names: Sequence[str]) -> "DeviceBatch":
        return DeviceBatch(
            {n: self.columns[n] for n in names}, self.valid, self.nrows,
            self.sorted_by, self.nrows_dev,
        )

    def drop(self, names: Sequence[str]) -> "DeviceBatch":
        keep = [n for n in self.columns if n not in set(names)]
        return self.select(keep)

    def rename(self, mapping: Dict[str, str]) -> "DeviceBatch":
        return DeviceBatch(
            {mapping.get(n, n): c for n, c in self.columns.items()},
            self.valid,
            self.nrows,
            self.sorted_by,
            self.nrows_dev,
        )

    def with_column(self, name: str, col: Column) -> "DeviceBatch":
        cols = dict(self.columns)
        cols[name] = col
        return DeviceBatch(cols, self.valid, self.nrows, self.sorted_by, self.nrows_dev)

    def take(self, idx: jax.Array, valid: jax.Array, nrows: Optional[int]) -> "DeviceBatch":
        cols = gather_columns(self.columns, idx)
        return DeviceBatch(cols, valid, nrows, self.sorted_by)


@jax.jit
def _gather_all(arrays, idx):
    """One compiled program gathering EVERY column at once: eager per-column
    `a[idx]` costs a separate dispatch (and bounds-check chain) per array —
    the dominant cost of wide-row takes in the engine's join path."""
    return tuple(a[idx] for a in arrays)


def gather_columns(columns: Dict[str, "Column"], idx: jax.Array) -> Dict[str, "Column"]:
    """Row-gather a whole column dict through a single fused XLA program."""
    arrays: List[jax.Array] = []
    for c in columns.values():
        if isinstance(c, StrCol):
            arrays.append(c.codes)
        elif isinstance(c, VecCol):
            arrays.append(c.data)
        else:
            if c.hi is not None:
                arrays.append(c.hi)
            arrays.append(c.data)
    from quokka_tpu.runtime import compileplane

    gathered = iter(compileplane.aot_kernel_call(
        "gather", _gather_all, (tuple(arrays), idx)))
    out: Dict[str, Column] = {}
    for n, c in columns.items():
        if isinstance(c, StrCol):
            out[n] = StrCol(next(gathered), c.dictionary)
        elif isinstance(c, VecCol):
            out[n] = VecCol(next(gathered))
        else:
            hi = next(gathered) if c.hi is not None else None
            out[n] = NumCol(next(gathered), c.kind, hi=hi, unit=c.unit)
    return out


def key_limbs(batch: DeviceBatch, cols: Sequence[str]) -> List[jax.Array]:
    """Flatten key columns into a list of 32-bit (or native-width) integer/float
    arrays usable as lexicographic sort keys and equality keys.  Strings become
    their two hash limbs; wide ints contribute (hi, lo)."""
    limbs: List[jax.Array] = []
    for name in cols:
        c = batch.columns[name]
        if isinstance(c, StrCol):
            hi, lo = c.hash_limbs()
            limbs.append(hi)
            limbs.append(lo)
        else:
            if c.hi is not None:
                limbs.append(c.hi)
            limbs.append(c.data)
    return limbs
