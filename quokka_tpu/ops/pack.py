"""Coalesced host<->device transfers.

Every batch crosses the host/device boundary as ONE buffer in each direction.
Per-buffer transfer cost on TPU runtimes is dominated by round-trip latency
(and on tunneled dev runtimes it is milliseconds per call), so the bridge
never moves columns individually: all column arrays of a batch are packed
into a single uint8 buffer host-side, shipped with one ``jax.device_put``,
and sliced back into typed arrays by one jitted unpack program (bitcasts are
free on device).  The reverse direction symmetrically packs all columns (plus
the validity mask) into one uint8 array on device and issues one
device->host read.

Wire narrowing: integer columns whose value range fits 8/16 bits travel as
offset-encoded uint8/uint16 and are widened back on device (the bias rides
in the packed buffer, so the unpack program is reused across batches); float
columns with few distinct values (TPC-H's 2-decimal discounts/taxes, rates,
flags) travel as uint8/uint16 codes plus a small value table and are
re-gathered on device.  This typically halves the wire bytes — which matters
because host->device bandwidth, not device compute, is the scan bottleneck
(SURVEY.md §7 hard part 4: host<->device transfer amortization).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_ALIGN = 8
# below this many elements a min/max or distinct scan costs more than it saves
_NARROW_MIN_ELEMS = 4096
# float columns: sample-distinct cutoff before paying for a full unique()
_FLOAT_DICT_SAMPLE_DISTINCT = 200
_FLOAT_DICT_MAX = 65535


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _int_narrow_plan(arr: np.ndarray):
    """(wire_dtype, bias) for an integer array, or (arr.dtype, None)."""
    mn = int(arr.min())
    mx = int(arr.max())
    width = mx - mn
    if width <= 0xFF:
        return np.dtype(np.uint8), mn
    if width <= 0xFFFF:
        return np.dtype(np.uint16), mn
    return arr.dtype, None


def _float_dict_plan(flat: np.ndarray):
    """(codes, value_table) when the column is low-cardinality, else None.
    Detection is a cheap host sample; the encode itself runs in Arrow C++
    (~10ms/1M rows) — host CPU is precious (single-core ingest hosts)."""
    stride = max(1, flat.size // 4096)
    sample = flat[::stride][:4096]
    # equal_nan collapses NaNs into one entry (numpy >= 1.24 default True)
    if np.unique(sample).size > _FLOAT_DICT_SAMPLE_DISTINCT:
        return None
    import pyarrow as pa
    import pyarrow.compute as pc

    enc = pc.dictionary_encode(pa.array(flat))
    uniq = enc.dictionary.to_numpy(zero_copy_only=False).astype(flat.dtype)
    if uniq.size > _FLOAT_DICT_MAX or uniq.size == 0:
        return None
    wdt = np.uint8 if uniq.size <= 0xFF else np.uint16
    codes = enc.indices.to_numpy(zero_copy_only=False).astype(wdt)
    # pad the table to a power-of-two length so the unpack program's layout
    # (part of its compile key) is stable across batches with slightly
    # different distinct counts
    tlen = max(16, 1 << (int(uniq.size - 1).bit_length()))
    if tlen > uniq.size:
        uniq = np.concatenate([uniq, np.full(tlen - uniq.size, uniq[-1], uniq.dtype)])
    return codes, uniq


# ---------------------------------------------------------------------------
# host -> device
# ---------------------------------------------------------------------------

# layout entry: (offset, n_elems, wire_dtype_str, target_dtype_str,
#                aux_offset_or_None, trailing_dims, aux_len)
# aux is a bias scalar (ints), a gather table (floats), or the live-row
# count (the "__valid__" pseudo-leaf).
_UNPACK_PROGRAMS: Dict[Tuple, object] = {}


def _build_unpack(layout: Tuple, total: int):
    @jax.jit
    def unpack(buf):
        outs = []
        for (off, n, wire, target, aux_off, trailing, aux_len) in layout:
            if wire == "__valid__":
                # validity mask materialized on device from the live-row
                # count embedded in the buffer: 4 bytes on the wire instead
                # of one byte per row
                braw = lax.slice(buf, (aux_off,), (aux_off + 4,))
                cnt = lax.bitcast_convert_type(braw.reshape(1, 4), jnp.int32)[0]
                outs.append(jnp.arange(n, dtype=jnp.int32) < cnt)
                continue
            wdt = jnp.dtype(wire)
            tdt = jnp.dtype(target) if target != "bool" else jnp.dtype(jnp.bool_)
            isz = wdt.itemsize
            raw = lax.slice(buf, (off,), (off + n * isz,))
            if isz == 1:
                arr = lax.bitcast_convert_type(raw, wdt)
            else:
                arr = lax.bitcast_convert_type(raw.reshape(n, isz), wdt)
            if target == "bool":
                arr = arr != 0
            elif aux_off is not None and jnp.issubdtype(tdt, jnp.floating):
                # low-cardinality float: codes -> gather from the value table
                tsz = tdt.itemsize
                traw = lax.slice(buf, (aux_off,), (aux_off + aux_len * tsz,))
                table = lax.bitcast_convert_type(traw.reshape(aux_len, tsz), tdt)
                arr = table[arr.astype(jnp.int32)]
            elif wire != target:
                arr = arr.astype(tdt)
                if aux_off is not None:
                    bsz = tdt.itemsize
                    braw = lax.slice(buf, (aux_off,), (aux_off + bsz,))
                    bias = lax.bitcast_convert_type(braw.reshape(1, bsz), tdt)[0]
                    arr = arr + bias
            if trailing:
                arr = arr.reshape((n // int(np.prod(trailing)),) + trailing)
            outs.append(arr)
        return tuple(outs)

    return unpack


class ValidCount:
    """Marker leaf for pack_put: becomes a bool[padded] validity mask computed
    on device as ``arange(padded) < nrows`` (only the count crosses the wire)."""

    def __init__(self, padded: int, nrows: int):
        self.padded = padded
        self.nrows = nrows


def pack_put(leaves: Sequence) -> List[jax.Array]:
    """Transfer numpy arrays to device as one buffer; returns device arrays
    with the original dtypes/shapes (bools stay bool, narrowed ints/floats
    widened back).  ``ValidCount`` leaves come back as device bool masks."""
    if not leaves:
        return []
    offset = 0
    layout = []
    auxes = []  # (layout_index, aux_numpy_array)
    views = []
    for arr in leaves:
        if isinstance(arr, ValidCount):
            layout.append([0, arr.padded, "__valid__", "bool", None, (), 0])
            auxes.append((len(layout) - 1, np.array([arr.nrows], dtype=np.int32)))
            continue
        arr = np.ascontiguousarray(arr)
        trailing = tuple(arr.shape[1:])
        flat = arr.reshape(-1)
        n = flat.size
        target = "bool" if arr.dtype == np.bool_ else str(arr.dtype)
        aux = None
        if arr.dtype == np.bool_:
            wire_arr = flat.view(np.uint8)
            wire = "uint8"
        elif arr.dtype in (np.int32, np.int64) and n >= _NARROW_MIN_ELEMS:
            wdt, bias = _int_narrow_plan(flat)
            if bias is not None:
                wire_arr = (flat - bias).astype(wdt)
                aux = np.array([bias], dtype=arr.dtype)
            else:
                wire_arr = flat
            wire = str(wdt)
        elif arr.dtype in (np.float32, np.float64) and n >= _NARROW_MIN_ELEMS:
            plan = _float_dict_plan(flat)
            if plan is not None:
                wire_arr, aux = plan
                wire = str(wire_arr.dtype)
            else:
                wire_arr = flat
                wire = target
        else:
            wire_arr = flat
            wire = target
        off = offset
        offset = _align(off + wire_arr.nbytes)
        views.append((off, wire_arr))
        layout.append([off, n, wire, target, None, trailing,
                       0 if aux is None else len(aux)])
        if aux is not None:
            auxes.append((len(layout) - 1, aux))
    for idx, aval in auxes:
        off = offset
        offset = _align(off + aval.nbytes)
        views.append((off, aval.view(np.uint8)))
        layout[idx][4] = off
    total = offset if offset else _ALIGN
    buf = np.zeros(total, dtype=np.uint8)
    for off, v in views:
        buf[off : off + v.nbytes] = v.view(np.uint8)
    key = (tuple(tuple(e) for e in layout), total)
    prog = _UNPACK_PROGRAMS.get(key)
    if prog is None:
        prog = _build_unpack(key[0], total)
        _UNPACK_PROGRAMS[key] = prog
    dbuf = jax.device_put(buf)
    return list(prog(dbuf))


# ---------------------------------------------------------------------------
# device -> host
# ---------------------------------------------------------------------------

_PACK_PROGRAMS: Dict[Tuple, object] = {}


def _build_pack(sig: Tuple):
    @jax.jit
    def pack(arrays):
        parts = []
        for a in arrays:
            if a.dtype == jnp.bool_:
                a = a.astype(jnp.uint8)
            flat = a.reshape(-1)
            if flat.dtype.itemsize == 1:
                raw = lax.bitcast_convert_type(flat, jnp.uint8)
            else:
                raw = lax.bitcast_convert_type(flat, jnp.uint8).reshape(-1)
            parts.append(raw)
        return jnp.concatenate(parts) if parts else jnp.zeros(0, jnp.uint8)

    return pack


def get_packed(arrays: Sequence[jax.Array]) -> List[np.ndarray]:
    """Read device arrays back to host as one transfer; returns numpy arrays
    with the original dtypes/shapes."""
    if not arrays:
        return []
    # pure-numpy arrays (already host) pass through
    if all(isinstance(a, np.ndarray) for a in arrays):
        return [np.asarray(a) for a in arrays]
    sig = tuple((str(a.dtype), tuple(a.shape)) for a in arrays)
    prog = _PACK_PROGRAMS.get(sig)
    if prog is None:
        prog = _build_pack(sig)
        _PACK_PROGRAMS[sig] = prog
    buf = np.asarray(prog(tuple(jnp.asarray(a) for a in arrays)))
    outs = []
    off = 0
    for dt, shape in sig:
        npdt = np.dtype(np.bool_) if dt == "bool" else np.dtype(dt)
        n = int(np.prod(shape)) if shape else 1
        nbytes = n * (1 if dt == "bool" else npdt.itemsize)
        raw = buf[off : off + nbytes]
        if dt == "bool":
            arr = raw.view(np.uint8).astype(np.bool_)
        else:
            arr = np.frombuffer(raw.tobytes(), dtype=npdt, count=n)
        outs.append(arr.reshape(shape))
        off += nbytes
    return outs
