"""Coalesced, compile-stable host<->device transfers.

Every batch crosses the host/device boundary with ONE runtime call in each
direction: ``jax.device_put`` of the whole list of (narrowed) column arrays,
and ``jax.device_get`` of the whole list coming back.  Decoding back to the
logical dtypes happens in one small jitted elementwise program per *layout*
(astype + bias add, table gathers, ``arange < count`` for validity).

Design note — why a list of typed arrays and not one byte buffer: the first
cut of this module packed all columns into a single uint8 buffer and sliced/
bitcast it apart on device.  That unpack program is compile-hostile on TPU
(uint8 reshapes + bitcasts across lane tiling): a single 7-column/1M-row
layout took ~400 s of XLA compile over the dev tunnel, and because the
layout (offsets, widths) changed whenever a batch's value ranges changed,
queries recompiled it repeatedly.  A pytree ``device_put`` costs the same
single RPC, and the decode program here is plain elementwise/gather code
that compiles in ~1 s.

Wire narrowing (kept from the first cut): integer columns whose value range
fits 8/16 bits travel as offset-encoded uint8/uint16 and are widened back on
device (the bias rides as a tiny data array, NOT in the compile key); float
columns with few distinct values (TPC-H's 2-decimal discounts/taxes, rates)
travel as uint8/uint16 codes plus a small value table and are re-gathered on
device.  This typically halves wire bytes — host->device bandwidth, not
device compute, is the scan bottleneck (SURVEY.md §7 hard part 4).

Narrowing decisions are STICKY per batch-signature (dtypes + shapes): the
first batch picks each column's wire format and later batches conform,
widening the plan monotonically (at most two recompiles per column ever)
when a batch's range no longer fits.  This keeps the decode program's
compile key stable across batches — the property whose absence caused the
pathological recompiles above.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# below this many elements a min/max or distinct scan costs more than it saves
_NARROW_MIN_ELEMS = 4096
# float columns: sample-distinct cutoff before paying for a full unique()
_FLOAT_DICT_SAMPLE_DISTINCT = 200
_FLOAT_DICT_MAX = 65535

_WIDTH = {"uint8": 0, "uint16": 1}  # narrowing lattice; full width = 2


def _int_wire_needed(mn: int, mx: int) -> str:
    width = mx - mn
    if width <= 0xFF:
        return "uint8"
    if width <= 0xFFFF:
        return "uint16"
    return "full"


class ValidCount:
    """Marker leaf for pack_put: becomes a bool[padded] validity mask computed
    on device as ``arange(padded) < nrows`` (only the count crosses the wire)."""

    def __init__(self, padded: int, nrows: int):
        self.padded = padded
        self.nrows = nrows


class _IntPlan:
    __slots__ = ("wire",)

    def __init__(self, wire: str):
        self.wire = wire  # "uint8" | "uint16" | "full"


class _FloatPlan:
    __slots__ = ("mode", "tlen")

    def __init__(self, mode: str, tlen: int = 0):
        self.mode = mode  # "dict" | "full"
        self.tlen = tlen  # power-of-two table length when mode == "dict"


# batch signature -> per-leaf sticky plans
_PLANS: Dict[Tuple, List] = {}
# decode layout -> jitted program
_DECODE_PROGRAMS: Dict[Tuple, object] = {}


def _float_dict_encode(flat: np.ndarray, plan: Optional[_FloatPlan]):
    """Dictionary-encode a float column per the (possibly new) sticky plan.
    Returns (codes, table, plan) or (None, None, full_plan)."""
    if plan is not None and plan.mode == "full":
        return None, None, plan
    if plan is None:
        # cheap host sample decides whether to pay for a full encode at all
        stride = max(1, flat.size // 4096)
        sample = flat[::stride][:4096]
        if np.unique(sample).size > _FLOAT_DICT_SAMPLE_DISTINCT:
            return None, None, _FloatPlan("full")
    import pyarrow as pa
    import pyarrow.compute as pc

    enc = pc.dictionary_encode(pa.array(flat))
    uniq = enc.dictionary.to_numpy(zero_copy_only=False).astype(flat.dtype)
    if uniq.size > _FLOAT_DICT_MAX or uniq.size == 0:
        return None, None, _FloatPlan("full")
    tlen = max(16, 1 << (int(uniq.size - 1).bit_length()))
    if plan is None:
        plan = _FloatPlan("dict", tlen)
    elif tlen > plan.tlen:
        plan = _FloatPlan("dict", tlen)  # grow monotonically (recompile once)
    wdt = np.uint8 if plan.tlen <= 256 else np.uint16
    codes = enc.indices.to_numpy(zero_copy_only=False).astype(wdt)
    if plan.tlen > uniq.size:
        uniq = np.concatenate(
            [uniq, np.full(plan.tlen - uniq.size, uniq[-1], uniq.dtype)]
        )
    if codes.nbytes + uniq.nbytes >= flat.nbytes:
        # a stream that STARTED low-cardinality can drift high-cardinality;
        # once codes+table stop saving wire bytes, stop paying the encode on
        # every future batch too (sticky degrade, one recompile)
        return None, None, _FloatPlan("full")
    return codes, uniq, plan


def _build_decode(layout: Tuple):
    """One jitted program decoding the whole wire list back to logical arrays.
    Elementwise widen/bias, small table gathers, and arange<count masks only —
    nothing layout-hostile; compile cost is ~1 s and the key (``layout``) is
    stable across batches thanks to sticky plans."""

    @jax.jit
    def decode(wires):
        outs = []
        i = 0
        for spec in layout:
            kind = spec[0]
            if kind == "valid":
                _, padded = spec
                cnt = wires[i][0]
                outs.append(jnp.arange(padded, dtype=jnp.int32) < cnt)
                i += 1
            elif kind == "bool":
                _, shape = spec
                outs.append((wires[i] != 0).reshape(shape))
                i += 1
            elif kind == "widen":
                _, target, shape = spec
                arr = wires[i].astype(jnp.dtype(target)) + wires[i + 1][0]
                outs.append(arr.reshape(shape))
                i += 2
            elif kind == "dict":
                _, shape = spec
                codes, table = wires[i], wires[i + 1]
                outs.append(table[codes.astype(jnp.int32)].reshape(shape))
                i += 2
            else:  # pass
                outs.append(wires[i])
                i += 1
        return tuple(outs)

    return decode


def pack_put(leaves: Sequence) -> List[jax.Array]:
    """Transfer numpy arrays to device with one ``device_put``; returns device
    arrays with the original dtypes/shapes (bools stay bool, narrowed
    ints/floats widened back).  ``ValidCount`` leaves come back as device bool
    masks."""
    if not leaves:
        return []
    items = []
    sig = []
    for arr in leaves:
        if isinstance(arr, ValidCount):
            sig.append(("__valid__", arr.padded))
            items.append(arr)
        else:
            arr = np.ascontiguousarray(arr)
            sig.append((str(arr.dtype), arr.shape))
            items.append(arr)
    sig = tuple(sig)
    plans = _PLANS.setdefault(sig, [None] * len(items))

    wires: List[np.ndarray] = []
    layout: List[Tuple] = []
    for idx, arr in enumerate(items):
        if isinstance(arr, ValidCount):
            wires.append(np.array([arr.nrows], dtype=np.int32))
            layout.append(("valid", arr.padded))
            continue
        shape = arr.shape
        flat = arr.reshape(-1)
        n = flat.size
        if arr.dtype == np.bool_:
            wires.append(flat.view(np.uint8))
            layout.append(("bool", shape))
            continue
        if arr.dtype in (np.int32, np.int64) and n >= _NARROW_MIN_ELEMS:
            plan: Optional[_IntPlan] = plans[idx]
            mn = int(flat.min())
            mx = int(flat.max())
            needed = _int_wire_needed(mn, mx)
            if plan is None:
                plan = _IntPlan(needed)
            elif needed == "full" or (
                plan.wire != "full" and _WIDTH[needed] > _WIDTH[plan.wire]
            ):
                plan = _IntPlan(needed)  # widen monotonically
            plans[idx] = plan
            if plan.wire != "full":
                wdt = np.dtype(plan.wire)
                wires.append((flat - mn).astype(wdt))
                wires.append(np.array([mn], dtype=arr.dtype))
                layout.append(("widen", str(arr.dtype), shape))
                continue
            wires.append(arr)
            layout.append(("pass", str(arr.dtype), shape))
            continue
        if arr.dtype in (np.float32, np.float64) and n >= _NARROW_MIN_ELEMS:
            codes, table, plan = _float_dict_encode(flat, plans[idx])
            plans[idx] = plan
            if codes is not None:
                wires.append(codes)
                wires.append(table)
                layout.append(("dict", shape))
                continue
            wires.append(arr)
            layout.append(("pass", str(arr.dtype), shape))
            continue
        wires.append(arr)
        layout.append(("pass", str(arr.dtype), shape))

    # keyed by layout alone: the program is a function of the layout, and
    # jax.jit re-traces per input dtype/shape signature under one wrapper
    key = tuple(layout)
    prog = _DECODE_PROGRAMS.get(key)
    if prog is None:
        prog = _build_decode(key)
        _DECODE_PROGRAMS[key] = prog
    dwires = jax.device_put(wires)
    return list(prog(dwires))


def get_packed(arrays: Sequence) -> List[np.ndarray]:
    """Read device arrays back to host in one ``device_get`` (transfers are
    started async first so the runtime can pipeline them); returns numpy
    arrays with the original dtypes/shapes.  No device program is involved —
    the d2h direction must never pay a compile."""
    if not arrays:
        return []
    if all(isinstance(a, np.ndarray) for a in arrays):
        return [np.asarray(a) for a in arrays]
    for a in arrays:
        try:
            a.copy_to_host_async()
        except AttributeError:
            pass
    return [np.asarray(a) for a in jax.device_get(list(arrays))]
