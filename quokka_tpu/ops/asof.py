"""As-of join kernel.

The reference's SortedAsofExecutor walks trade/quote frontiers sequentially
per batch (pyquokka/executors/ts_executors.py:324-383).  The TPU formulation is
data-parallel: concatenate both sides, sort once by (key, time, side), then a
segmented fill-forward scan (jax.lax.associative_scan) carries the most recent
quote position within each key segment onto every trade row.  One sort + one
log-depth scan — no sequential loop.

Direction 'backward' matches quotes with time <= trade time (quotes sort before
trades on ties); 'forward' is the mirror (run on negated times).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from quokka_tpu import config
from quokka_tpu.ops import kernels
from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol, key_limbs
from quokka_tpu.ops.kernels import dense_rank


def _seg_fill_forward(values: jax.Array, seg_start: jax.Array) -> jax.Array:
    """Within each segment (seg_start marks first element), running max of
    `values` — used to propagate the latest quote position forward."""

    def combine(a, b):
        av, as_ = a
        bv, bs = b
        v = jnp.where(bs, bv, jnp.maximum(av, bv))
        return v, as_ | bs

    out, _ = lax.associative_scan(combine, (values, seg_start))
    return out


@functools.partial(jax.jit, static_argnames=("t", "forward_ties"))
def _asof_match(limbs: Tuple[jax.Array, ...], times: Tuple[jax.Array, ...],
                is_trade: jax.Array, valid: jax.Array, t: int,
                forward_ties: bool = False):
    """Returns per-trade-row (quote_row_idx, matched) for backward asof.
    Arrays are the concatenation [trades | quotes]; `t` = trade padded len.
    `times` is one array for narrow/float time columns, or (hi, lo) limbs for
    wide int64/ns timestamps (limb lexicographic order == numeric order).

    Tie-break among quotes sharing (key, time): the scan takes the quote at
    the MAX sorted position, so the iota tie key orders equal quotes by
    original index — ascending for backward (pandas/polars pick the LAST
    tied quote) and descending (`forward_ties`, on the caller's negated
    times) so forward picks the FIRST tied quote, matching pandas and the
    native host merge."""
    n = valid.shape[0]
    ranks, _ = dense_rank(limbs, valid)
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    # sort by (validity, key rank, time, side): quotes (0) before trades (1)
    # at equal times -> backward asof includes same-timestamp quotes
    side = is_trade.astype(jnp.int32)
    tie = -iota if forward_ties else iota
    nk = 2 + len(times)
    sorted_ops = lax.sort([inv, ranks, *times, side, tie, iota],
                          num_keys=nk + 2)
    perm = sorted_ops[-1]
    valid_s = sorted_ops[0] == 0
    ranks_s = sorted_ops[1]
    side_s = sorted_ops[nk]
    seg_start = (ranks_s != jnp.roll(ranks_s, 1)) | (iota == 0)
    quote_pos = jnp.where(valid_s & (side_s == 0), iota, -1)
    last_quote_pos = _seg_fill_forward(quote_pos, seg_start)
    # for each sorted position, the original row of the latest quote <= here
    quote_orig = perm[jnp.clip(last_quote_pos, 0, n - 1)]
    matched_s = valid_s & (side_s == 1) & (last_quote_pos >= 0)
    # scatter back to original (concat) positions
    match_orig = jnp.zeros(n, dtype=jnp.int32).at[perm].set(quote_orig)
    matched = jnp.zeros(n, dtype=bool).at[perm].set(matched_s)
    return match_orig[:t], matched[:t]


# ---------------------------------------------------------------------------
# Host fast path (CPU backend): the as-of match is a textbook O(n+m)
# sequential merge; XLA:CPU's variadic sort makes the device kernel ~340
# ns/row while the native walk (native/columnar.cpp qk_asof_backward) runs at
# memory speed.  On the CPU backend np.asarray of a device array is a
# zero-copy view, so "host" costs no transfer.  TPU keeps the sort+scan
# kernel (config.use_host_asof() gates, QUOKKA_HOST_ASOF overrides).
# ---------------------------------------------------------------------------


def _np_time64(col: NumCol) -> np.ndarray:
    """Order-preserving int64 view of a time column on host.  NOTE: float
    columns map through an IEEE bit trick, so the result is only comparable
    against another float column's encoding — _asof_match_host bails when
    the two sides' dtype families differ."""
    d = np.asarray(col.data)
    if col.hi is not None:
        from quokka_tpu.ops import bridge

        return bridge._limbs_to_int64(np.asarray(col.hi), d)
    if d.dtype.kind == "f":
        # IEEE total-order bit trick: non-negative floats' bit patterns are
        # already ordered non-negative ints; negatives flip their low 63
        # bits (sign kept) to reverse magnitude order while staying below
        # every positive
        bits = np.ascontiguousarray(d.astype(np.float64)).view(np.int64)
        return np.where(bits < 0, bits ^ np.int64(0x7FFFFFFFFFFFFFFF), bits)
    return d.astype(np.int64)


def _time_family(col: NumCol) -> str:
    if col.hi is not None:
        return "i"
    return "f" if np.asarray(col.data).dtype.kind == "f" else "i"


def _np_key64(batch: DeviceBatch, by: Sequence[str]) -> "np.ndarray | None":
    """Exact int64 key per row from <=2 int32 limbs (or one int64 limb).
    Returns None when the key shape doesn't pack exactly — caller falls back
    to the device kernel."""
    if not by:
        return np.zeros(batch.padded_len, dtype=np.int64)
    limbs = [np.asarray(l) for l in key_limbs(batch, list(by))]
    if any(l.dtype.kind == "f" for l in limbs):
        return None
    if len(limbs) == 1:
        return limbs[0].astype(np.int64)
    if len(limbs) == 2 and all(l.dtype.itemsize <= 4 for l in limbs):
        return (limbs[0].astype(np.int64) << 32) | limbs[1].astype(
            np.uint32
        ).astype(np.int64)
    return None


def _asof_match_host(trades, quotes, left_on, right_on, left_by, right_by,
                     direction):
    """(quote_idx, matched) as numpy arrays aligned to trade rows, or None
    when the native library / key shape doesn't support the fast path."""
    from quokka_tpu.utils import native

    if not native.has_asof():
        return None  # skip all host prep when the merge can't run anyway
    if _time_family(trades.columns[left_on]) != _time_family(
            quotes.columns[right_on]):
        return None  # int vs float encodings are not mutually comparable
    tk = _np_key64(trades, left_by)
    qk = _np_key64(quotes, right_by)
    if tk is None or qk is None:
        return None
    tt = _np_time64(trades.columns[left_on])
    qt = _np_time64(quotes.columns[right_on])
    tv = np.asarray(trades.valid)
    qv = np.asarray(quotes.valid)
    tidx = np.flatnonzero(tv)
    qidx = np.flatnonzero(qv)
    tt, tk = np.ascontiguousarray(tt[tidx]), np.ascontiguousarray(tk[tidx])
    qt, qk = np.ascontiguousarray(qt[qidx]), np.ascontiguousarray(qk[qidx])
    if not native.is_sorted_i64(tt):
        order = np.argsort(tt, kind="stable")
        tidx, tt, tk = tidx[order], np.ascontiguousarray(tt[order]), \
            np.ascontiguousarray(tk[order])
    if not native.is_sorted_i64(qt):
        order = np.argsort(qt, kind="stable")
        qidx, qt, qk = qidx[order], np.ascontiguousarray(qt[order]), \
            np.ascontiguousarray(qk[order])
    res = native.asof_merge(tt, tk, qt, qk, direction)
    if res is None:
        return None
    quote_idx = np.zeros(trades.padded_len, dtype=np.int32)
    matched = np.zeros(trades.padded_len, dtype=bool)
    hit = res >= 0
    quote_idx[tidx[hit]] = qidx[res[hit]].astype(np.int32)
    matched[tidx[hit]] = True
    return quote_idx, matched


def asof_join(
    trades: DeviceBatch,
    quotes: DeviceBatch,
    left_on: str,
    right_on: str,
    left_by: Sequence[str],
    right_by: Sequence[str],
    payload: Sequence[str],
    direction: str = "backward",
) -> DeviceBatch:
    """Probe-aligned asof join: each valid trade row gains the payload of its
    most recent quote (per key).  Unmatched trades keep NaN/zero payload and a
    false mask is NOT applied (matches polars join_asof semantics: unmatched
    rows survive with null payload — floats become NaN)."""
    t = trades.padded_len
    if direction not in ("backward", "forward"):
        raise ValueError(direction)
    host = None
    if config.use_host_asof():
        host = _asof_match_host(
            trades, quotes, left_on, right_on, left_by, right_by, direction
        )
    if host is not None:
        quote_idx = jnp.asarray(host[0])
        matched = jnp.asarray(host[1])
    else:
        lt = key_limbs(trades, list(left_by)) if left_by else []
        lq = key_limbs(quotes, list(right_by)) if right_by else []
        if left_by:
            limbs = [jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(lt, lq)]
        else:
            limbs = [jnp.zeros(t + quotes.padded_len, dtype=jnp.int32)]
        tc = trades.columns[left_on]
        qc = quotes.columns[right_on]
        if tc.hi is not None or qc.hi is not None:
            from quokka_tpu.ops import timewide

            tl, ql = timewide.widen_limbs(tc), timewide.widen_limbs(qc)
            if direction == "forward":
                tl, ql = timewide.not_limbs(tl), timewide.not_limbs(ql)
            times = tuple(jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(tl, ql))
        else:
            t_time, q_time = tc.data, qc.data
            if direction == "forward":
                t_time, q_time = -t_time, -q_time
            times = (jnp.concatenate([t_time, q_time.astype(t_time.dtype)]),)
        is_trade = jnp.concatenate(
            [jnp.ones(t, dtype=bool), jnp.zeros(quotes.padded_len, dtype=bool)]
        )
        valid = jnp.concatenate([trades.valid, quotes.valid])
        match_orig, matched = _asof_match(
            tuple(limbs), times, is_trade, valid, t,
            forward_ties=(direction == "forward"),
        )
        quote_idx = jnp.clip(match_orig - t, 0, quotes.padded_len - 1)
    cols = dict(trades.columns)
    from quokka_tpu.ops.batch import with_nulls

    for name in payload:
        c = quotes.columns[name]
        taken = c.take(quote_idx)
        cols[name] = with_nulls(taken, ~matched)
    cols["__asof_matched__"] = NumCol(matched, "b")
    return DeviceBatch(cols, trades.valid, trades.nrows, trades.sorted_by)
