"""As-of join kernel.

The reference's SortedAsofExecutor walks trade/quote frontiers sequentially
per batch (pyquokka/executors/ts_executors.py:324-383).  The TPU formulation is
data-parallel: concatenate both sides, sort once by (key, time, side), then a
segmented fill-forward scan (jax.lax.associative_scan) carries the most recent
quote position within each key segment onto every trade row.  One sort + one
log-depth scan — no sequential loop.

Direction 'backward' matches quotes with time <= trade time (quotes sort before
trades on ties); 'forward' is the mirror (run on negated times).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from quokka_tpu.ops import kernels
from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol, key_limbs
from quokka_tpu.ops.kernels import dense_rank


def _seg_fill_forward(values: jax.Array, seg_start: jax.Array) -> jax.Array:
    """Within each segment (seg_start marks first element), running max of
    `values` — used to propagate the latest quote position forward."""

    def combine(a, b):
        av, as_ = a
        bv, bs = b
        v = jnp.where(bs, bv, jnp.maximum(av, bv))
        return v, as_ | bs

    out, _ = lax.associative_scan(combine, (values, seg_start))
    return out


@functools.partial(jax.jit, static_argnames=("t",))
def _asof_match(limbs: Tuple[jax.Array, ...], times: Tuple[jax.Array, ...],
                is_trade: jax.Array, valid: jax.Array, t: int):
    """Returns per-trade-row (quote_row_idx, matched) for backward asof.
    Arrays are the concatenation [trades | quotes]; `t` = trade padded len.
    `times` is one array for narrow/float time columns, or (hi, lo) limbs for
    wide int64/ns timestamps (limb lexicographic order == numeric order)."""
    n = valid.shape[0]
    ranks, _ = dense_rank(limbs, valid)
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    # sort by (validity, key rank, time, side): quotes (0) before trades (1)
    # at equal times -> backward asof includes same-timestamp quotes
    side = is_trade.astype(jnp.int32)
    nk = 2 + len(times)
    sorted_ops = lax.sort([inv, ranks, *times, side, iota], num_keys=nk + 1)
    perm = sorted_ops[-1]
    valid_s = sorted_ops[0] == 0
    ranks_s = sorted_ops[1]
    side_s = sorted_ops[nk]
    seg_start = (ranks_s != jnp.roll(ranks_s, 1)) | (iota == 0)
    quote_pos = jnp.where(valid_s & (side_s == 0), iota, -1)
    last_quote_pos = _seg_fill_forward(quote_pos, seg_start)
    # for each sorted position, the original row of the latest quote <= here
    quote_orig = perm[jnp.clip(last_quote_pos, 0, n - 1)]
    matched_s = valid_s & (side_s == 1) & (last_quote_pos >= 0)
    # scatter back to original (concat) positions
    match_orig = jnp.zeros(n, dtype=jnp.int32).at[perm].set(quote_orig)
    matched = jnp.zeros(n, dtype=bool).at[perm].set(matched_s)
    return match_orig[:t], matched[:t]


def asof_join(
    trades: DeviceBatch,
    quotes: DeviceBatch,
    left_on: str,
    right_on: str,
    left_by: Sequence[str],
    right_by: Sequence[str],
    payload: Sequence[str],
    direction: str = "backward",
) -> DeviceBatch:
    """Probe-aligned asof join: each valid trade row gains the payload of its
    most recent quote (per key).  Unmatched trades keep NaN/zero payload and a
    false mask is NOT applied (matches polars join_asof semantics: unmatched
    rows survive with null payload — floats become NaN)."""
    t = trades.padded_len
    lt = key_limbs(trades, list(left_by)) if left_by else []
    lq = key_limbs(quotes, list(right_by)) if right_by else []
    if left_by:
        limbs = [jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(lt, lq)]
    else:
        limbs = [jnp.zeros(t + quotes.padded_len, dtype=jnp.int32)]
    if direction not in ("backward", "forward"):
        raise ValueError(direction)
    tc = trades.columns[left_on]
    qc = quotes.columns[right_on]
    if tc.hi is not None or qc.hi is not None:
        from quokka_tpu.ops import timewide

        tl, ql = timewide.widen_limbs(tc), timewide.widen_limbs(qc)
        if direction == "forward":
            tl, ql = timewide.not_limbs(tl), timewide.not_limbs(ql)
        times = tuple(jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(tl, ql))
    else:
        t_time, q_time = tc.data, qc.data
        if direction == "forward":
            t_time, q_time = -t_time, -q_time
        times = (jnp.concatenate([t_time, q_time.astype(t_time.dtype)]),)
    is_trade = jnp.concatenate(
        [jnp.ones(t, dtype=bool), jnp.zeros(quotes.padded_len, dtype=bool)]
    )
    valid = jnp.concatenate([trades.valid, quotes.valid])
    match_orig, matched = _asof_match(tuple(limbs), times, is_trade, valid, t)
    quote_idx = jnp.clip(match_orig - t, 0, quotes.padded_len - 1)
    cols = dict(trades.columns)
    from quokka_tpu.ops.batch import with_nulls

    for name in payload:
        c = quotes.columns[name]
        taken = c.take(quote_idx)
        cols[name] = with_nulls(taken, ~matched)
    cols["__asof_matched__"] = NumCol(matched, "b")
    return DeviceBatch(cols, trades.valid, trades.nrows, trades.sorted_by)
