"""As-of join kernels.

The reference's SortedAsofExecutor walks trade/quote frontiers sequentially
per batch (pyquokka/executors/ts_executors.py:324-383).  Three strategies
(ops/strategy.py picks per backend; each records what actually ran):

- ``sort``: concatenate both sides, sort once by (key, time, side), then a
  segmented fill-forward scan (jax.lax.associative_scan) carries the most
  recent quote position within each key segment onto every trade row.  One
  sort + one log-depth scan — no sequential loop.
- ``searchsorted``: sort ONLY the quotes by (key, time) — cached on the
  quote batch, so repeated flushes against an unchanged buffer pay it once —
  and resolve every trade with a vectorized lexicographic binary search
  (upper bound for backward, lower bound for forward).  ~log2(q) gathers per
  limb instead of an (n+m)-row multi-operand sort per flush, and no
  concat-sized intermediates.  Fully device-resident: the accelerator
  default.
- ``host``: the native O(n+m) sequential merge (native/columnar.cpp),
  profitable only where np.asarray of a device array is zero-copy (CPU).

Direction 'backward' matches quotes with time <= trade time (quotes sort
before trades on ties); 'forward' is the mirror.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from quokka_tpu import config
from quokka_tpu.ops import kernels
from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol, key_limbs
from quokka_tpu.ops.kernels import dense_rank


def _seg_fill_forward(values: jax.Array, seg_start: jax.Array) -> jax.Array:
    """Within each segment (seg_start marks first element), running max of
    `values` — used to propagate the latest quote position forward."""

    def combine(a, b):
        av, as_ = a
        bv, bs = b
        v = jnp.where(bs, bv, jnp.maximum(av, bv))
        return v, as_ | bs

    out, _ = lax.associative_scan(combine, (values, seg_start))
    return out


@functools.partial(jax.jit, static_argnames=("t", "forward_ties"))
def _asof_match(limbs: Tuple[jax.Array, ...], times: Tuple[jax.Array, ...],
                is_trade: jax.Array, valid: jax.Array, t: int,
                forward_ties: bool = False):
    """Returns per-trade-row (quote_row_idx, matched) for backward asof.
    Arrays are the concatenation [trades | quotes]; `t` = trade padded len.
    `times` is one array for narrow/float time columns, or (hi, lo) limbs for
    wide int64/ns timestamps (limb lexicographic order == numeric order).

    Tie-break among quotes sharing (key, time): the scan takes the quote at
    the MAX sorted position, so the iota tie key orders equal quotes by
    original index — ascending for backward (pandas/polars pick the LAST
    tied quote) and descending (`forward_ties`, on the caller's negated
    times) so forward picks the FIRST tied quote, matching pandas and the
    native host merge."""
    n = valid.shape[0]
    ranks, _ = dense_rank(limbs, valid)
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    # sort by (validity, key rank, time, side): quotes (0) before trades (1)
    # at equal times -> backward asof includes same-timestamp quotes
    side = is_trade.astype(jnp.int32)
    tie = -iota if forward_ties else iota
    nk = 2 + len(times)
    sorted_ops = lax.sort([inv, ranks, *times, side, tie, iota],
                          num_keys=nk + 2)
    perm = sorted_ops[-1]
    valid_s = sorted_ops[0] == 0
    ranks_s = sorted_ops[1]
    side_s = sorted_ops[nk]
    seg_start = (ranks_s != jnp.roll(ranks_s, 1)) | (iota == 0)
    quote_pos = jnp.where(valid_s & (side_s == 0), iota, -1)
    last_quote_pos = _seg_fill_forward(quote_pos, seg_start)
    # for each sorted position, the original row of the latest quote <= here
    quote_orig = perm[jnp.clip(last_quote_pos, 0, n - 1)]
    matched_s = valid_s & (side_s == 1) & (last_quote_pos >= 0)
    # scatter back to original (concat) positions
    match_orig = jnp.zeros(n, dtype=jnp.int32).at[perm].set(quote_orig)
    matched = jnp.zeros(n, dtype=bool).at[perm].set(matched_s)
    return match_orig[:t], matched[:t]


# ---------------------------------------------------------------------------
# searchsorted strategy: cached quote-side (key, time) sort + vectorized
# lexicographic binary search per trade row.
# ---------------------------------------------------------------------------


def _lex_lt_eq(a: Tuple[jax.Array, ...], b: Tuple[jax.Array, ...]):
    """Elementwise lexicographic (a < b, a == b) over limb tuples (the same
    comparator join._pk_probe_sorted uses)."""
    lt = jnp.zeros(a[0].shape, dtype=bool)
    eq = jnp.ones(a[0].shape, dtype=bool)
    for x, y in zip(a, b):
        lt = lt | (eq & (x < y))
        eq = eq & (x == y)
    return lt, eq


@jax.jit
def _ss_sort_quotes(ops: Tuple[jax.Array, ...], valid: jax.Array):
    """Sort the quote side once by (validity, key limbs..., time limbs...);
    returns (sorted_ops, perm, n_valid).  Invalid rows sort last; ties keep
    original order (iota operand), so among equal (key, time) quotes sorted
    position order == original order — the tie-break both directions rely
    on."""
    n = valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    s = lax.sort([inv, *ops, iota], num_keys=1 + len(ops))
    return tuple(s[1:-1]), s[-1], jnp.sum(valid.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("steps", "upper", "nkey"))
def _ss_probe(sorted_ops: Tuple[jax.Array, ...], perm: jax.Array,
              n_valid: jax.Array, probe_ops: Tuple[jax.Array, ...],
              probe_valid: jax.Array, steps: int, upper: bool, nkey: int):
    """Per-trade binary search over the sorted quotes.  ``upper`` (backward
    asof): upper bound of (key, time) minus one — the LAST quote with key ==
    k and time <= t (among exact (key, time) ties the last original index,
    pandas semantics).  Lower bound (forward): the FIRST quote with key == k
    and time >= t.  Returns (original quote row idx clipped, matched)."""
    p = probe_ops[0].shape[0]
    nq = sorted_ops[0].shape[0]
    lo = jnp.zeros(p, dtype=jnp.int32)
    hi = jnp.broadcast_to(n_valid.astype(jnp.int32), (p,))
    for _ in range(steps):
        mid = (lo + hi) >> 1
        mk = tuple(l[mid] for l in sorted_ops)
        lt, eq = _lex_lt_eq(mk, probe_ops)
        cond = (lt | eq) if upper else lt  # quote[mid] <= probe vs < probe
        go = lo < hi
        lo = jnp.where(go & cond, mid + 1, lo)
        hi = jnp.where(go & ~cond, mid, hi)
    pos = lo - 1 if upper else lo
    in_range = (pos >= 0) & (pos < n_valid)
    cpos = jnp.clip(pos, 0, nq - 1)
    keq = jnp.ones(p, dtype=bool)
    for s_l, p_l in zip(sorted_ops[:nkey], probe_ops[:nkey]):
        keq = keq & (s_l[cpos] == p_l)
    matched = probe_valid & in_range & keq
    return jnp.clip(perm[cpos], 0, nq - 1), matched


def _ss_quote_sorted(quotes: DeviceBatch, right_on: str,
                     right_by: Sequence[str], wide: bool, time_dtype):
    """(sorted_ops, perm, n_valid, nkey) for a quote batch, cached ON the
    batch object (the streaming executor probes the same buffer on every
    flush until new quotes concat into a fresh object — same discipline as
    join._build_sorted_cached).  Both directions share one cache entry: the
    search side decides backward vs forward, not the sort.  ``time_dtype``
    (the TRADE side's time dtype, None when wide) is applied to the quote
    time limb BEFORE sorting — the same quote->trade cast the sort kernel
    applies pre-sort, so mixed-dtype comparisons and within-tie ordering
    stay bit-identical to that path (probe-side casts would truncate the
    trade times instead)."""
    from quokka_tpu.runtime import compileplane

    cache = getattr(quotes, "_asof_ss_cache", None)
    if cache is None:
        cache = quotes._asof_ss_cache = {}
    key = (tuple(right_by), right_on, wide, str(time_dtype))
    hit = cache.get(key)
    if hit is None:
        ql = key_limbs(quotes, list(right_by)) if right_by else []
        qc = quotes.columns[right_on]
        if wide:
            from quokka_tpu.ops import timewide

            qt = tuple(timewide.widen_limbs(qc))
        else:
            qt = (qc.data.astype(time_dtype),)
        ops = tuple(ql) + qt
        sorted_ops, perm, n_valid = compileplane.aot_kernel_call(
            "asof_ss_sort", _ss_sort_quotes, (ops, quotes.valid))
        hit = cache[key] = (sorted_ops, perm, n_valid, len(ql))
    return hit


def _asof_match_searchsorted(trades: DeviceBatch, quotes: DeviceBatch,
                             left_on: str, right_on: str,
                             left_by: Sequence[str],
                             right_by: Sequence[str], direction: str):
    """(quote_idx, matched) aligned to trade rows, fully on device."""
    from quokka_tpu.runtime import compileplane

    tc = trades.columns[left_on]
    qc = quotes.columns[right_on]
    wide = tc.hi is not None or qc.hi is not None
    sorted_ops, perm, n_valid, nkey = _ss_quote_sorted(
        quotes, right_on, list(right_by), wide,
        None if wide else tc.data.dtype)
    lt = key_limbs(trades, list(left_by)) if left_by else []
    assert len(lt) == nkey, "asof by-key column types must match"
    if wide:
        from quokka_tpu.ops import timewide

        tt = tuple(timewide.widen_limbs(tc))
    else:
        tt = (tc.data,)
    probe_ops = tuple(
        l.astype(s.dtype) for l, s in zip(tuple(lt) + tt, sorted_ops)
    )
    steps = max(1, int(np.ceil(np.log2(max(2, quotes.padded_len)))) + 1)
    return compileplane.aot_kernel_call(
        "asof_ss_probe", _ss_probe,
        (sorted_ops, perm, n_valid, probe_ops, trades.valid),
        (steps, direction == "backward", nkey),
    )


# ---------------------------------------------------------------------------
# Host fast path (CPU backend): the as-of match is a textbook O(n+m)
# sequential merge; XLA:CPU's variadic sort makes the device kernel ~340
# ns/row while the native walk (native/columnar.cpp qk_asof_backward) runs at
# memory speed.  On the CPU backend np.asarray of a device array is a
# zero-copy view, so "host" costs no transfer.  TPU keeps the sort+scan
# kernel (config.use_host_asof() gates, QUOKKA_HOST_ASOF overrides).
# ---------------------------------------------------------------------------


def _np_time64(col: NumCol) -> np.ndarray:
    """Order-preserving int64 view of a time column on host.  NOTE: float
    columns map through an IEEE bit trick, so the result is only comparable
    against another float column's encoding — _asof_match_host bails when
    the two sides' dtype families differ."""
    d = np.asarray(col.data)
    if col.hi is not None:
        from quokka_tpu.ops import bridge

        return bridge._limbs_to_int64(np.asarray(col.hi), d)
    if d.dtype.kind == "f":
        # IEEE total-order bit trick: non-negative floats' bit patterns are
        # already ordered non-negative ints; negatives flip their low 63
        # bits (sign kept) to reverse magnitude order while staying below
        # every positive
        bits = np.ascontiguousarray(d.astype(np.float64)).view(np.int64)
        return np.where(bits < 0, bits ^ np.int64(0x7FFFFFFFFFFFFFFF), bits)
    return d.astype(np.int64)


def _time_family(col: NumCol) -> str:
    if col.hi is not None:
        return "i"
    return "f" if np.asarray(col.data).dtype.kind == "f" else "i"


def _np_key64(batch: DeviceBatch, by: Sequence[str]) -> "np.ndarray | None":
    """Exact int64 key per row from <=2 int32 limbs (or one int64 limb).
    Returns None when the key shape doesn't pack exactly — caller falls back
    to the device kernel."""
    if not by:
        return np.zeros(batch.padded_len, dtype=np.int64)
    limbs = [np.asarray(l) for l in key_limbs(batch, list(by))]
    if any(l.dtype.kind == "f" for l in limbs):
        return None
    if len(limbs) == 1:
        return limbs[0].astype(np.int64)
    if len(limbs) == 2 and all(l.dtype.itemsize <= 4 for l in limbs):
        return (limbs[0].astype(np.int64) << 32) | limbs[1].astype(
            np.uint32
        ).astype(np.int64)
    return None


def _asof_match_host(trades, quotes, left_on, right_on, left_by, right_by,
                     direction):
    """(quote_idx, matched) as numpy arrays aligned to trade rows, or None
    when the native library / key shape doesn't support the fast path."""
    from quokka_tpu.utils import native

    if not native.has_asof():
        return None  # skip all host prep when the merge can't run anyway
    if _time_family(trades.columns[left_on]) != _time_family(
            quotes.columns[right_on]):
        return None  # int vs float encodings are not mutually comparable
    tk = _np_key64(trades, left_by)
    qk = _np_key64(quotes, right_by)
    if tk is None or qk is None:
        return None
    tt = _np_time64(trades.columns[left_on])
    qt = _np_time64(quotes.columns[right_on])
    tv = np.asarray(trades.valid)
    qv = np.asarray(quotes.valid)
    tidx = np.flatnonzero(tv)
    qidx = np.flatnonzero(qv)
    tt, tk = np.ascontiguousarray(tt[tidx]), np.ascontiguousarray(tk[tidx])
    qt, qk = np.ascontiguousarray(qt[qidx]), np.ascontiguousarray(qk[qidx])
    if not native.is_sorted_i64(tt):
        order = np.argsort(tt, kind="stable")
        tidx, tt, tk = tidx[order], np.ascontiguousarray(tt[order]), \
            np.ascontiguousarray(tk[order])
    if not native.is_sorted_i64(qt):
        order = np.argsort(qt, kind="stable")
        qidx, qt, qk = qidx[order], np.ascontiguousarray(qt[order]), \
            np.ascontiguousarray(qk[order])
    res = native.asof_merge(tt, tk, qt, qk, direction)
    if res is None:
        return None
    quote_idx = np.zeros(trades.padded_len, dtype=np.int32)
    matched = np.zeros(trades.padded_len, dtype=bool)
    hit = res >= 0
    quote_idx[tidx[hit]] = qidx[res[hit]].astype(np.int32)
    matched[tidx[hit]] = True
    return quote_idx, matched


def asof_join(
    trades: DeviceBatch,
    quotes: DeviceBatch,
    left_on: str,
    right_on: str,
    left_by: Sequence[str],
    right_by: Sequence[str],
    payload: Sequence[str],
    direction: str = "backward",
    strategy: "str | None" = None,
) -> DeviceBatch:
    """Probe-aligned asof join: each valid trade row gains the payload of its
    most recent quote (per key).  Unmatched trades keep NaN/zero payload and a
    false mask is NOT applied (matches polars join_asof semantics: unmatched
    rows survive with null payload — floats become NaN).

    ``strategy`` forces a kernel ("host"/"sort"/"searchsorted"); None
    consults the per-backend matrix (ops/strategy.py).  A host pick that the
    native library / key shape declines falls back to the device
    searchsorted kernel — never a wrong answer, and the fallback is what
    gets recorded as having run."""
    from quokka_tpu.ops import strategy as kstrategy

    t = trades.padded_len
    if direction not in ("backward", "forward"):
        raise ValueError(direction)
    pick = strategy or kstrategy.choice("asof")
    host = None
    if pick == "host":
        host = _asof_match_host(
            trades, quotes, left_on, right_on, left_by, right_by, direction
        )
        if host is None:
            pick = "searchsorted"  # native lib/key shape declined
    if host is not None:
        quote_idx = jnp.asarray(host[0])
        matched = jnp.asarray(host[1])
        kstrategy.note_used("asof", "host")
    elif pick == "searchsorted":
        quote_idx, matched = _asof_match_searchsorted(
            trades, quotes, left_on, right_on, left_by, right_by, direction
        )
        kstrategy.note_used("asof", "searchsorted")
    else:
        kstrategy.note_used("asof", "sort")
        lt = key_limbs(trades, list(left_by)) if left_by else []
        lq = key_limbs(quotes, list(right_by)) if right_by else []
        if left_by:
            limbs = [jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(lt, lq)]
        else:
            limbs = [jnp.zeros(t + quotes.padded_len, dtype=jnp.int32)]
        tc = trades.columns[left_on]
        qc = quotes.columns[right_on]
        if tc.hi is not None or qc.hi is not None:
            from quokka_tpu.ops import timewide

            tl, ql = timewide.widen_limbs(tc), timewide.widen_limbs(qc)
            if direction == "forward":
                tl, ql = timewide.not_limbs(tl), timewide.not_limbs(ql)
            times = tuple(jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(tl, ql))
        else:
            t_time, q_time = tc.data, qc.data
            if direction == "forward":
                t_time, q_time = -t_time, -q_time
            times = (jnp.concatenate([t_time, q_time.astype(t_time.dtype)]),)
        is_trade = jnp.concatenate(
            [jnp.ones(t, dtype=bool), jnp.zeros(quotes.padded_len, dtype=bool)]
        )
        valid = jnp.concatenate([trades.valid, quotes.valid])
        match_orig, matched = _asof_match(
            tuple(limbs), times, is_trade, valid, t,
            forward_ties=(direction == "forward"),
        )
        quote_idx = jnp.clip(match_orig - t, 0, quotes.padded_len - 1)
    cols = dict(trades.columns)
    from quokka_tpu.ops.batch import with_nulls

    for name in payload:
        c = quotes.columns[name]
        taken = c.take(quote_idx)
        cols[name] = with_nulls(taken, ~matched)
    cols["__asof_matched__"] = NumCol(matched, "b")
    return DeviceBatch(cols, trades.valid, trades.nrows, trades.sorted_by)
