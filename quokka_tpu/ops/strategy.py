"""Per-backend, per-operator kernel-strategy matrix.

The engine has more than one implementation of its hot relational kernels —
sort-based and hash-table group-by/join, host-native and two device as-of
kernels, masked and compacted shuffle splits — and which one wins is a
property of the BACKEND (scatter throughput, sort cost, d2h latency), not of
the query.  Until PR 8 the picks were scattered platform gates in config.py
("hash tables off on TPU", "host asof on CPU"), which meant the benched path
on one backend could be a path another backend never runs (VERDICT r5
finding #2).  This module is now the one place a kernel strategy is decided,
and the decision is MEASURED, not asserted:

- ``choice(op)`` resolves an operator's strategy:
    1. ``QK_KERNEL_STRATEGY="op=choice,..."`` — forced override (tests,
       experiments).  Unknown ops/choices raise: a forced choice that
       silently no-ops is how wrong benchmarks happen.
    2. legacy envs ``QUOKKA_HASH_TABLES`` (group-by + join build) and
       ``QUOKKA_HOST_ASOF`` (asof), kept working verbatim.
    3. a persisted calibration profile for THIS backend fingerprint
       (``calibrate()`` micro-times every candidate kernel on live arrays
       and stores the winners under ``<cache>/strategy/<fingerprint>.json``).
       A foreign fingerprint — different platform, device kind/count, jax —
       is ignored entirely, never partially applied.
    4. static per-platform safe defaults (the pre-PR-8 gates).

- ``note_used(op, choice)`` records what actually RAN (dispatch sites call
  it), feeding ``strategy.<op>.<choice>`` counters and the per-query
  ``detail.strategy`` map bench.py emits; ``bench.py --check`` fails when a
  benched line records a choice its platform gates off
  (``invalid_for_platform``).

Operators and choices:

  groupby     sort | hashtable      (kernels.sorted_groupby vs
                                     hashtable.hash_groupby)
  join_build  sort | hashtable      (join._pk_probe_sorted vs
                                     hashtable build_table/pk_probe)
  asof        host | sort | searchsorted
                                    (native O(n+m) host merge vs the
                                     concat+sort+scan device kernel vs the
                                     cached-quote-sort device binary search)
  asof_probe  eager | coalesced     (per-dispatch asof flushes vs probe-side
                                     trade batches coalesced through the
                                     cap-aware _coalesce bucketed path so
                                     each flush's joint sort amortizes)
  shuffle     masked | compacted    (kernels.split_by_partition modes)

This module and config.py are the ONLY places allowed to probe the platform
(lint rule QK013): a platform string check anywhere else is a scattered gate
waiting to diverge from the matrix.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from quokka_tpu import config

OPS: Dict[str, Tuple[str, ...]] = {
    "groupby": ("sort", "hashtable"),
    "join_build": ("sort", "hashtable"),
    "asof": ("host", "sort", "searchsorted"),
    "asof_probe": ("eager", "coalesced"),
    "shuffle": ("masked", "compacted"),
}

# The pre-calibration safe defaults — the argued per-platform gates that
# config.use_hash_tables()/use_host_asof() used to hard-code.  CPU/GPU:
# scatter/gather fast, sorts slow -> tables; TPU: random scatters
# serialize, multi-operand sort is the idiom.  Host asof only where
# np.asarray is zero-copy (CPU); accelerators get the device searchsorted
# merge so the benched path needs no host round trip.
_PLATFORM_DEFAULTS: Dict[str, Dict[str, str]] = {
    "cpu": {"groupby": "hashtable", "join_build": "hashtable",
            "asof": "host", "asof_probe": "coalesced", "shuffle": "masked"},
    "gpu": {"groupby": "hashtable", "join_build": "hashtable",
            "asof": "searchsorted", "asof_probe": "coalesced",
            "shuffle": "masked"},
    "tpu": {"groupby": "sort", "join_build": "sort",
            "asof": "searchsorted", "asof_probe": "coalesced",
            "shuffle": "masked"},
}
_PLATFORM_DEFAULTS["cuda"] = _PLATFORM_DEFAULTS["gpu"]
_PLATFORM_DEFAULTS["rocm"] = _PLATFORM_DEFAULTS["gpu"]
_FALLBACK_DEFAULTS = {"groupby": "sort", "join_build": "sort",
                      "asof": "sort", "asof_probe": "coalesced",
                      "shuffle": "masked"}

_CALIB_VERSION = 1

_lock = threading.Lock()
# parsed QK_KERNEL_STRATEGY cache, keyed by the raw env string so tests that
# monkeypatch the env see their change on the next call
_env_cache: Tuple[Optional[str], Dict[str, str]] = (None, {})
# loaded-or-computed calibration choices for THIS process's backend;
# _calib_state: "unloaded" | "loaded" (None result = no usable profile)
_calibrated: Optional[Dict[str, str]] = None
_calib_state = "unloaded"

_used_lock = threading.Lock()
_used: Dict[str, list] = {}


class StrategyError(ValueError):
    """Malformed QK_KERNEL_STRATEGY / unknown operator or choice."""


def _validate(op: str, choice_: str, origin: str) -> None:
    if op not in OPS:
        raise StrategyError(
            f"{origin}: unknown operator {op!r} (known: {sorted(OPS)})")
    if choice_ not in OPS[op]:
        raise StrategyError(
            f"{origin}: unknown choice {choice_!r} for {op!r} "
            f"(known: {OPS[op]})")


def _env_overrides() -> Dict[str, str]:
    raw = os.environ.get("QK_KERNEL_STRATEGY")
    global _env_cache
    cached_raw, cached = _env_cache
    if raw == cached_raw:
        return cached
    parsed: Dict[str, str] = {}
    if raw:
        for item in raw.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise StrategyError(
                    f"QK_KERNEL_STRATEGY: expected op=choice, got {item!r}")
            op, _, ch = item.partition("=")
            op, ch = op.strip(), ch.strip()
            _validate(op, ch, "QK_KERNEL_STRATEGY")
            parsed[op] = ch
    _env_cache = (raw, parsed)
    return parsed


def _legacy_env(op: str) -> Optional[str]:
    """QUOKKA_HASH_TABLES / QUOKKA_HOST_ASOF keep their documented meaning."""
    if op in ("groupby", "join_build"):
        v = os.environ.get("QUOKKA_HASH_TABLES", "auto").lower()
        if v in ("1", "true", "yes", "on"):
            return "hashtable"
        if v in ("0", "false", "no", "off"):
            return "sort"
        return None
    if op == "asof":
        v = os.environ.get("QUOKKA_HOST_ASOF", "auto").lower()
        if v in ("1", "true", "yes", "on"):
            return "host"
        if v in ("0", "false", "no", "off"):
            # "no host walk" — take the backend's device pick
            dev = _calibrated_choice(op) or _default(op)
            return dev if dev != "host" else "searchsorted"
        return None
    return None


def _default(op: str) -> str:
    plat = config._platform()
    return _PLATFORM_DEFAULTS.get(plat, _FALLBACK_DEFAULTS)[op]


# ---------------------------------------------------------------------------
# persisted calibration
# ---------------------------------------------------------------------------


def _dir() -> Optional[str]:
    """Calibration profile directory; None disables persistence (and
    loading).  QK_STRATEGY_DIR="" explicitly disables — tests set this so a
    developer box's calibration can never change test behavior."""
    d = os.environ.get("QK_STRATEGY_DIR")
    if d is not None:
        return d or None
    if not config.CACHE_ROOT:
        return None
    return os.path.join(config.CACHE_ROOT, "strategy")


def _fingerprint() -> str:
    from quokka_tpu.runtime import compileplane

    return compileplane.backend_fingerprint()


def _profile_path() -> Optional[str]:
    d = _dir()
    if d is None:
        return None
    return os.path.join(d, f"{_fingerprint()}.json")


def _load_profile() -> Optional[Dict[str, str]]:
    """Choices from the persisted profile for THIS fingerprint, else None.
    A corrupt file or a foreign fingerprint inside it is ignored wholesale —
    safe defaults beat a half-trusted profile."""
    path = _profile_path()
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            prof = json.load(f)
        if not isinstance(prof, dict):
            return None
        if prof.get("version") != _CALIB_VERSION:
            return None
        if prof.get("fingerprint") != _fingerprint():
            return None
        choices = prof.get("choices")
        if not isinstance(choices, dict):
            return None
        for op, ch in choices.items():
            _validate(op, ch, path)
        return dict(choices)
    except (OSError, ValueError, StrategyError):
        from quokka_tpu.obs import diag

        diag(f"strategy: ignoring unusable calibration profile {path}")
        return None


def _calibrated_choice(op: str) -> Optional[str]:
    global _calibrated, _calib_state
    with _lock:
        if _calib_state == "unloaded":
            _calibrated = _load_profile()
            _calib_state = "loaded"
        return None if _calibrated is None else _calibrated.get(op)


def reset() -> None:
    """Forget cached env parses and the loaded calibration profile (tests)."""
    global _env_cache, _calibrated, _calib_state
    with _lock:
        _env_cache = (None, {})
        _calibrated = None
        _calib_state = "unloaded"


# ---------------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------------


def resolve(op: str) -> Tuple[str, str]:
    """(choice, source) for an operator; source is one of
    "env" | "legacy-env" | "calibrated" | "default"."""
    if op not in OPS:
        raise StrategyError(f"unknown operator {op!r} (known: {sorted(OPS)})")
    env = _env_overrides()
    if op in env:
        return env[op], "env"
    legacy = _legacy_env(op)
    if legacy is not None:
        return legacy, "legacy-env"
    cal = _calibrated_choice(op)
    if cal is not None:
        return cal, "calibrated"
    return _default(op), "default"


def choice(op: str) -> str:
    return resolve(op)[0]


def choices() -> Dict[str, str]:
    return {op: resolve(op)[0] for op in OPS}


def sources() -> Dict[str, str]:
    return {op: resolve(op)[1] for op in OPS}


# ---------------------------------------------------------------------------
# what actually ran (bench honesty)
# ---------------------------------------------------------------------------


def note_used(op: str, ran: str) -> None:
    """Record that a dispatch site actually executed `ran` for `op` — the
    fallback paths (diverged hash build, missing native lib) report the
    kernel that ran, not the one the matrix asked for.  Every distinct
    kernel is kept (a mesh query's timed shard kernel and its
    coordinator-side recombine may legitimately differ): the snapshot must
    name them all, not whichever dispatched last."""
    with _used_lock:
        ops_ran = _used.setdefault(op, [])
        if ran not in ops_ran:
            ops_ran.append(ran)
            from quokka_tpu import obs

            obs.REGISTRY.counter(f"strategy.{op}.{ran}").inc()


def used_snapshot() -> Dict[str, str]:
    """{op: choice} of what ran since the last reset; when more than one
    kernel ran for an op the value is every choice sorted and '+'-joined
    (e.g. ``groupby: "hashtable+sort"``)."""
    with _used_lock:
        return {op: "+".join(sorted(v)) for op, v in _used.items()}


def reset_used() -> None:
    with _used_lock:
        _used.clear()


def invalid_for_platform(platform: str, op: str,
                         ran: str) -> Optional[str]:
    """Why a recorded (op, choice) could never be the production path on
    `platform`, or None when it is legitimate.  ``ran`` may be a '+'-joined
    multi-value from used_snapshot; every component must be runnable.  This
    is the bench --check honesty gate: the r5 verdict's top finding was a
    benched host-asof that a TPU will never run."""
    parts = ran.split("+") if ran else [ran]
    if op not in OPS or any(p not in OPS.get(op, ()) for p in parts):
        return (f"unknown strategy {op}={ran!r} — the bench recorded a "
                "choice the matrix does not define")
    if op == "asof" and "host" in parts and platform != "cpu":
        return ("host-native asof is a CPU-only fast path (each time/key/"
                f"valid column pays a blocking d2h copy on {platform}); a "
                f"{platform} deployment never runs it, so timing it says "
                "nothing about that backend")
    return None


# ---------------------------------------------------------------------------
# calibration microbench
# ---------------------------------------------------------------------------


def _time_best(fn, reps: int) -> float:
    fn()  # warm: compiles + first-dispatch costs are not the steady state
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _calib_batches(rows: int):
    """Synthetic batches shared by the shuffle/asof candidates."""
    import numpy as np
    import pyarrow as pa

    from quokka_tpu.ops import bridge

    r = np.random.default_rng(11)
    n_sym = 64
    tt = np.sort(r.integers(0, 1 << 20, rows)).astype(np.int64)
    qt = np.sort(r.integers(0, 1 << 20, 2 * rows)).astype(np.int64)
    trades = bridge.arrow_to_device(pa.table({
        "time": tt, "sym": r.integers(0, n_sym, rows).astype(np.int64),
        "size": r.integers(1, 500, rows).astype(np.int64)}))
    quotes = bridge.arrow_to_device(pa.table({
        "time": qt, "sym": r.integers(0, n_sym, 2 * rows).astype(np.int64),
        "bid": r.uniform(10, 500, 2 * rows)}))
    return trades, quotes


def calibrate(rows: Optional[int] = None, reps: int = 3,
              persist: bool = True) -> Dict[str, object]:
    """Micro-time every candidate kernel on live device arrays and pick the
    winners; persists (atomically) under the backend fingerprint and
    installs the result in-process.  Returns {"choices", "timings_s",
    "fingerprint", "rows"}.  One-time per backend: ``ensure_calibrated``
    answers from the persisted profile on every later run."""
    import jax.numpy as jnp
    import numpy as np

    from quokka_tpu.ops import asof as asof_ops
    from quokka_tpu.ops import hashtable, join as join_ops, kernels

    if rows is None:
        env = os.environ.get("QK_STRATEGY_CALIB_ROWS")
        if env:
            rows = int(env)
        else:
            # prefer measured cardinalities (obs/opstats.py cardprofile):
            # probe at the batch sizes real plans on this backend actually
            # produced, not a fixed guess.  Clamped — the calibration matrix
            # times dozens of candidates and must stay sub-second-ish.
            from quokka_tpu.obs import opstats

            measured = opstats.measured_calib_rows()
            rows = min(max(int(measured), 1 << 12), 1 << 20) \
                if measured else (1 << 16)
    rows = int(rows)
    r = np.random.default_rng(7)
    timings: Dict[str, Dict[str, float]] = {}

    # group-by: one int32 key limb, medium cardinality, one summed column
    limbs = (jnp.asarray(r.integers(0, rows // 16, rows).astype(np.int32)),)
    vals = (jnp.asarray(r.uniform(0, 1, rows).astype(np.float32)),)
    valid = jnp.ones(rows, dtype=bool)
    timings["groupby"] = {
        "sort": _time_best(
            lambda: kernels.sorted_groupby(limbs, vals, ("sum",), valid)[
                0][0].block_until_ready(), reps),
        "hashtable": _time_best(
            lambda: hashtable._hash_groupby_jit(
                limbs, vals, ("sum",), valid,
                hashtable.capbits_for(rows))[0][0].block_until_ready(), reps),
    }

    # join build+probe: unique build keys, probe twice the build size
    bl = (jnp.asarray(r.permutation(rows).astype(np.int32)),)
    pl = (jnp.asarray(r.integers(0, rows, 2 * rows).astype(np.int32)),)
    bok = jnp.ones(rows, dtype=bool)
    pok = jnp.ones(2 * rows, dtype=bool)
    steps = max(1, int(np.ceil(np.log2(max(2, rows)))) + 1)

    def _join_sort():
        sl, perm, nv = join_ops._sort_build_keys(bl, bok)
        out = join_ops._pk_probe_sorted(sl, perm, nv, pl, pok, steps)
        out[1].block_until_ready()

    def _join_ht():
        capbits = hashtable.capbits_for(rows)
        cl = hashtable.canonical_limbs(bl, nan_unique=False)
        _, tbl, _ = hashtable._insert_jit(cl, bok, capbits)
        out = hashtable._probe_jit(
            tbl, cl, hashtable.canonical_limbs(pl, nan_unique=False), pok,
            capbits)
        out[1].block_until_ready()

    timings["join_build"] = {
        "sort": _time_best(_join_sort, reps),
        "hashtable": _time_best(_join_ht, reps),
    }

    # asof + shuffle work on real DeviceBatches through the public entries
    trades, quotes = _calib_batches(rows)
    asof_t: Dict[str, float] = {}
    for cand in OPS["asof"]:
        def _run(c=cand):
            # pay the quote-sort cost every rep (the executor's buffer
            # grows between flushes, so the cached sort rarely carries)
            quotes.__dict__.pop("_asof_ss_cache", None)
            out = asof_ops.asof_join(
                trades, quotes, "time", "time", ["sym"], ["sym"], ["bid"],
                strategy=c)
            out.columns["bid"].data.block_until_ready()

        try:
            if cand == "host":
                from quokka_tpu.utils import native

                if not native.has_asof() or config._platform() != "cpu":
                    continue
            asof_t[cand] = _time_best(_run, reps)
        except Exception:  # noqa: BLE001 — a missing candidate is a skip
            continue
    timings["asof"] = asof_t

    # shuffle is timed for the profile's information but NEVER picked by
    # calibration: the masked/compacted tradeoff is a PIPELINE property —
    # masked split counts ride asynchronous d2h copies that consumers read
    # batches later, while the compacted plan's counts readback BLOCKS the
    # push path (shuffle.host_syncs).  A standalone microbench observes
    # only kernel walls, so it flips to compacted on noise margins and
    # reintroduces the per-split pipeline drain PR 6 removed (measured: a
    # 1.4% microbench "win" cost the SF1 join queries ~3x in transfer
    # stalls).  The masked default + SHUFFLE_MASKED_CAP heuristic stands;
    # QK_KERNEL_STRATEGY=shuffle=compacted remains for experiments.
    n_parts = 8
    pids = kernels.partition_ids(trades, ["sym"], n_parts)

    def _shuffle(compact: bool):
        parts = kernels.split_by_partition(trades, pids, n_parts,
                                           compact=compact)
        if not compact:
            parts = [kernels.compact(p) for p in parts]  # consumer densify
        parts[-1].valid.block_until_ready()

    timings["shuffle"] = {
        "masked": _time_best(lambda: _shuffle(False), reps),
        "compacted": _time_best(lambda: _shuffle(True), reps),
    }

    # asof_probe is likewise never calibrated: the eager/coalesced tradeoff
    # is the asof executor's flush cadence under a live stream, which a
    # standalone kernel microbench cannot observe — the coalesced default
    # stands, QK_KERNEL_STRATEGY=asof_probe=eager remains for experiments.
    picks: Dict[str, str] = {}
    for op, t in timings.items():
        if t and op != "shuffle":
            picks[op] = min(t, key=t.get)
    result = {
        "version": _CALIB_VERSION,
        "fingerprint": _fingerprint(),
        "platform": config._platform(),
        "rows": rows,
        "choices": picks,
        "timings_s": {op: {c: round(v, 6) for c, v in t.items()}
                      for op, t in timings.items()},
    }
    global _calibrated, _calib_state
    with _lock:
        _calibrated = dict(picks)
        _calib_state = "loaded"
    if persist:
        path = _profile_path()
        if path is not None:
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(result, f, indent=2, sort_keys=True)
                os.replace(tmp, path)
            except OSError:
                from quokka_tpu.obs import diag

                diag(f"strategy: could not persist calibration to {path}")
    return result


def ensure_calibrated(rows: Optional[int] = None) -> Dict[str, str]:
    """Load the persisted profile for this backend, calibrating once if none
    exists.  QK_STRATEGY_CALIBRATE=0 skips the (potentially multi-second)
    microbench and leaves the platform defaults in charge."""
    loaded = _calibrated_choice("groupby")  # forces one load attempt
    with _lock:
        have = _calibrated is not None
    del loaded
    if have:
        with _lock:
            return dict(_calibrated or {})
    if os.environ.get("QK_STRATEGY_CALIBRATE", "1") in ("0", "false", "no"):
        return {}
    return dict(calibrate(rows=rows)["choices"])
