from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol, StringDict, key_limbs
from quokka_tpu.ops.bridge import arrow_to_device, concat_batches, device_to_arrow, to_pandas
