"""Device hash table for equality-keyed kernels (hash group-by, PK join probe).

The sort-based kernels (`kernels.sorted_groupby`, `join._pk_probe_sorted`)
remain the default on TPU, where random-order scatters serialize badly and
the multi-operand sort is the idiomatic grouping primitive (SURVEY.md "Hard
parts" #3).  On CPU/GPU backends the opposite holds: XLA scatter/gather are
fast and an O(n) table pass beats the O(n log n) sort by 3-10x on the
high-cardinality group-bys that dominate TPC-H Q3-class queries (measured:
1M-row 3-operand lax.sort ~485 ms vs insert+segment ~175 ms on one CPU core).
`config.use_hash_tables()` picks per backend; env QUOKKA_HASH_TABLES=1|0
overrides.

Design: open addressing over a power-of-two capacity with a double-hash odd
stride.  The insert loop runs all rows in lockstep (`lax.while_loop`); each
round every unplaced row scatter-mins its row id into its current candidate
slot, then reads the slot back: the winner is placed, rows whose key equals
the occupant's key are placed on the same slot (duplicate keys CONVERGE —
the slot doubles as a group id), and everyone else steps by its key's
stride.  Rows of equal keys share hash, stride and therefore probe sequence,
so they always meet the same occupant and can never split into two groups.
The scatter-min makes the winner (and thus the whole table) deterministic —
a replay of the same batch reproduces byte-identical groups, which the
lineage tape asserts (runtime/engine.py replay-determinism checks).

Reference parity: this plays the role of the in-memory hash structures
polars uses inside the reference's groupby/join executors
(pyquokka/executors/sql_executors.py:325-378) — here as a pure XLA program.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from quokka_tpu.analysis import compat

EMPTY = jnp.int32(2**31 - 1)


class HashTableConvergenceError(RuntimeError):
    """The lockstep insert failed to place every valid row (load factor or
    probe-chain pathology).  Callers fall back to the sort-based kernels —
    never proceed: unplaced rows silently alias slot 0's group."""

_M1 = jnp.uint32(0x85EBCA6B)
_M2 = jnp.uint32(0xC2B2AE35)
_M3 = jnp.uint32(0x9E3779B1)


def capbits_for(n: int) -> int:
    """Capacity exponent giving load factor <= 0.5 (min 256 slots)."""
    bits = 8
    while (1 << bits) < 2 * max(n, 1):
        bits += 1
    return bits


def canonical_limbs(limbs: Sequence[jax.Array],
                    nan_unique: bool = True) -> Tuple[jax.Array, ...]:
    """Equality-preserving int32 form of key limbs.  64-bit limbs (the x64
    CPU regime stores ints as one int64 limb and floats as float64) expand
    to TWO int32 limbs each — truncating would silently merge keys that
    differ only above bit 31.

    Float limbs are bitcast after canonicalizing -0.0 to +0.0 (IEEE == says
    they are one key; their bit patterns differ).  NaN handling follows the
    sort path's IEEE-compare semantics (NaN != NaN):

    - group-by (`nan_unique=True`): each NaN row must become its own group,
      so every float limb carries a companion limb that is 0 for non-NaN
      rows and a per-row unique id for NaN rows (a full int32 limb — a
      NaN-space bit pattern would overflow the 23-bit mantissa at
      MAX_BUCKET-sized batches).  Spreading NaNs across slots also breaks up
      what would otherwise be one giant shared probe chain.
    - join (`nan_unique=False`): NaN keys never match ANY row, including
      other NaNs; callers must mask NaN rows out of validity (`nan_rows`).
    """
    out = []
    for l in limbs:
        if jnp.issubdtype(l.dtype, jnp.floating):
            if l.dtype == jnp.float64:
                f = jnp.where(l == 0.0, jnp.float64(0.0), l)
                isnan = jnp.isnan(l)
                f = jnp.where(isnan, jnp.float64(jnp.nan), f)  # one NaN pattern
                pair = lax.bitcast_convert_type(f, jnp.int32)  # [..., 2]
                out.append(pair[..., 0])
                out.append(pair[..., 1])
            else:
                f = l.astype(jnp.float32)
                f = jnp.where(f == 0.0, jnp.float32(0.0), f)
                isnan = jnp.isnan(f)
                f = jnp.where(isnan, jnp.float32(jnp.nan), f)
                out.append(lax.bitcast_convert_type(f, jnp.int32))
            if nan_unique:
                rid = jnp.arange(l.shape[0], dtype=jnp.int32)
                out.append(jnp.where(isnan, rid + 1, jnp.int32(0)))
        elif l.dtype == jnp.int32:
            out.append(l)
        elif l.dtype in (jnp.int64, jnp.uint64):
            u = l.astype(jnp.uint64)
            out.append((u >> 32).astype(jnp.int32))
            out.append(u.astype(jnp.uint32).astype(jnp.int32))
        else:
            out.append(l.astype(jnp.int32))
    return tuple(out)


def nan_rows(limbs: Sequence[jax.Array]) -> jax.Array:
    """Rows with a NaN in any float limb (excluded from join matching)."""
    m = jnp.zeros(limbs[0].shape, dtype=bool)
    for l in limbs:
        if jnp.issubdtype(l.dtype, jnp.floating):
            m = m | jnp.isnan(l)
    return m


def _hash_stride(limbs: Tuple[jax.Array, ...], mask: int):
    h = jnp.full(limbs[0].shape, jnp.uint32(0x9747B28C))
    for l in limbs:
        h = (h ^ l.astype(jnp.uint32)) * _M3
        h ^= h >> 16
    h = (h ^ (h >> 13)) * _M1
    h = (h ^ (h >> 16)) * _M2
    slot = (h ^ (h >> 15)) & jnp.uint32(mask)
    stride = ((h >> 7) | jnp.uint32(1)) & jnp.uint32(mask)  # odd: full cycle
    return slot, stride


def _eq_at(limbs: Tuple[jax.Array, ...], idx: jax.Array,
           other: Tuple[jax.Array, ...]) -> jax.Array:
    eq = jnp.ones(idx.shape, dtype=bool)
    for l, o in zip(limbs, other):
        eq = eq & (l[idx] == o)
    return eq


_RID_BITS = 24  # rid < 2^24 always holds: config.MAX_BUCKET == 1 << 24
_RID_MASK = (1 << _RID_BITS) - 1


def _in_trace() -> bool:
    """True while tracing inside another jit.  The table kernels are called
    both nested (FusedPartialAgg's fused program, mesh programs) and at top
    level (executors); routing traced calls to the PLAIN bodies — which
    trace to the identical jaxpr a nested pjit would inline — sidesteps a
    jit-dispatch race observed when the engine's threads hit the same pjit
    object from both contexts (spurious 'Execution supplied N buffers but
    compiled program expected M buffers' on the 1-core CPU backend).

    The probe goes through the version-guarded shim: a jax upgrade that
    moves the private API fails the package at import (analysis/compat.py)
    instead of a swallowed exception silently answering False — which would
    re-enable the dispatch race this helper exists to avoid."""
    return not compat.trace_state_clean()


def _insert_body(limbs: Tuple[jax.Array, ...], valid: jax.Array, capbits: int):
    """Insert all valid rows; returns (slot_for_row, table, converged).

    slot_for_row[i] is the slot holding row i's key (all equal keys share
    it); table[s] packs (claim_round << 24 | row_id) for the row that
    claimed slot s, or EMPTY.  Use `table_rid` to decode.  Invalid rows get
    slot 0 — callers mask by `valid`.  `converged` is a scalar bool: every
    valid row placed before the round cap — when False the unplaced rows'
    myslot=0 silently aliases slot 0's group, so untraced callers MUST
    check it and fall back to the sort path (build_table raises
    HashTableConvergenceError; hash_groupby reruns sorted_groupby).  With
    load <= 0.5 and full-cycle double hashing non-convergence is
    astronomically unlikely — but its failure mode is silent wrong
    results, which is exactly what must never fail silently.

    The scatter must be claim-stable: a plain scatter-min of row ids would
    let a LATER round's smaller rid clobber an earlier claim, breaking the
    open-addressing invariant that slots a row probed past stay occupied
    (observed as ~2% of keys silently vanishing from the table).  Packing
    the round number above the rid makes earlier claims always win; ties
    within a round resolve to the smallest rid, so the table — and every
    group id derived from it — is deterministic.  Rounds saturate at 126
    (prio must stay below EMPTY); with load <= 0.5 and double hashing,
    probe chains are ~6-10 rounds in practice.
    """
    cap = 1 << capbits
    mask = cap - 1
    n = valid.shape[0]
    slot0, stride = _hash_stride(limbs, mask)
    rid = jnp.arange(n, dtype=jnp.int32)

    def body(c):
        tbl, slot, placed, myslot, it = c
        active = ~placed
        prio = (jnp.minimum(it, 126) << _RID_BITS) | rid
        cand = jnp.where(active, slot, jnp.uint32(0)).astype(jnp.int32)
        tbl = tbl.at[cand].min(jnp.where(active, prio, EMPTY))
        occ_prio = tbl[slot.astype(jnp.int32)]
        occ_row = jnp.clip(occ_prio & _RID_MASK, 0, n - 1)
        same = (occ_prio != EMPTY) & _eq_at(limbs, occ_row, limbs)
        newly = active & ((occ_prio == prio) | same)
        myslot = jnp.where(newly, slot.astype(jnp.int32), myslot)
        placed = placed | newly
        slot = jnp.where(placed, slot, (slot + stride) & jnp.uint32(mask))
        return tbl, slot, placed, myslot, it + 1

    def cond(c):
        return (~c[2].all()) & (c[4] < 2 * cap)

    tbl = jnp.full(cap, EMPTY)
    init = (tbl, slot0, ~valid, jnp.zeros(n, dtype=jnp.int32), jnp.int32(0))
    tbl, _, placed, myslot, _ = lax.while_loop(cond, body, init)
    return myslot, tbl, placed.all()


_insert_jit = functools.partial(jax.jit, static_argnames=("capbits",))(_insert_body)


def _insert(limbs, valid, capbits: int):
    """(myslot, table, converged).  Traced calls cannot host-check the
    converged flag; it stays an array for the caller's program (build_table,
    the only untraced consumer, checks it and raises)."""
    if _in_trace():
        return _insert_body(limbs, valid, capbits)
    from quokka_tpu.runtime import compileplane

    return compileplane.aot_kernel_call(
        "ht_insert", _insert_jit, (limbs, valid), (capbits,))


def table_rid(tbl: jax.Array) -> jax.Array:
    """Decode a table's packed entries to row ids (EMPTY stays EMPTY)."""
    return jnp.where(tbl == EMPTY, EMPTY, tbl & _RID_MASK)


def _probe_body(table: jax.Array, build_limbs: Tuple[jax.Array, ...],
                probe_limbs: Tuple[jax.Array, ...], probe_ok: jax.Array,
                capbits: int):
    """Walk each probe row's sequence until its key or an empty slot.
    Returns (build_idx clipped to range, matched)."""
    mask = (1 << capbits) - 1
    slot0, stride = _hash_stride(probe_limbs, mask)
    p = probe_ok.shape[0]
    b = max(build_limbs[0].shape[0], 1)

    def body(c):
        slot, done, res, ok = c
        entry = table[slot.astype(jnp.int32)]
        empty = entry == EMPTY
        rid = entry & _RID_MASK
        hit = (~empty) & _eq_at(build_limbs, jnp.clip(rid, 0, b - 1), probe_limbs)
        res = jnp.where(hit & ~done, rid, res)
        ok = ok | (hit & ~done)
        done = done | hit | empty
        slot = jnp.where(done, slot, (slot + stride) & jnp.uint32(mask))
        return slot, done, res, ok

    def cond(c):
        return ~c[1].all()

    init = (slot0, ~probe_ok, jnp.zeros(p, dtype=jnp.int32),
            jnp.zeros(p, dtype=bool))
    _, _, res, ok = lax.while_loop(cond, body, init)
    return jnp.clip(res, 0, b - 1), ok & probe_ok


_probe_jit = functools.partial(jax.jit, static_argnames=("capbits",))(_probe_body)


def _probe(table, build_limbs, probe_limbs, probe_ok, capbits: int):
    if _in_trace():
        return _probe_body(table, build_limbs, probe_limbs, probe_ok, capbits)
    from quokka_tpu.runtime import compileplane

    return compileplane.aot_kernel_call(
        "ht_probe", _probe_jit, (table, build_limbs, probe_limbs, probe_ok),
        (capbits,))


def hash_groupby(limbs: Tuple[jax.Array, ...], arrays: Tuple[jax.Array, ...],
                 ops: Tuple[str, ...], valid: jax.Array):
    """Drop-in for `kernels.sorted_groupby` — same (outs, counts, rep, num)
    contract, except group ids come out in hash order rather than key order
    (no consumer depends on group order; ORDER BY is an explicit node).

    Non-convergence of the insert (silent wrong groups otherwise): untraced
    calls check the flag on host — one scalar d2h sync per batch, the price
    of never answering wrong — and rerun through the sort path; traced
    calls (fused/mesh programs) cannot host-branch, so they accept the
    residual risk documented on `_insert_body` — the executors' untraced
    batches are where the table strategy actually runs today."""
    capbits = capbits_for(valid.shape[0])
    if _in_trace():
        outs, counts, rep, num, _ = _hash_groupby_body(
            tuple(limbs), tuple(arrays), ops, valid, capbits)
        return outs, counts, rep, num
    from quokka_tpu.ops import strategy as kstrategy

    outs, counts, rep, num, converged = _hash_groupby_jit(
        tuple(limbs), tuple(arrays), ops, valid, capbits)
    if not bool(converged):
        from quokka_tpu.ops import kernels

        kstrategy.note_used("groupby", "sort")  # the fallback is what ran
        return kernels.sorted_groupby(tuple(limbs), tuple(arrays), ops, valid)
    kstrategy.note_used("groupby", "hashtable")
    return outs, counts, rep, num


def _hash_groupby_body(limbs, arrays, ops, valid, capbits):
    from quokka_tpu.ops import kernels

    climbs = canonical_limbs(limbs)
    myslot, tbl, converged = _insert_body(climbs, valid, capbits)
    flag = (tbl != EMPTY).astype(jnp.int32)
    rank_of_slot = jnp.cumsum(flag) - flag
    ranks = rank_of_slot[myslot]
    num = jnp.sum(flag)
    outs, counts, rep = kernels._segment_aggs_body(ranks, valid, arrays, ops)
    return tuple(outs), counts, rep, num, converged


_hash_groupby_jit = functools.partial(
    jax.jit, static_argnames=("ops", "capbits")
)(_hash_groupby_body)


class _TableCache:
    """Hash table of a finalized build batch, cached on the batch object
    (same discipline as join._build_sorted_cached: one build serves every
    probe batch, so the insert — and the build-side null-mask work — is
    paid once, on the cache miss only)."""

    __slots__ = ("tbl", "limbs", "raw_dtypes", "capbits")

    def __init__(self, tbl, limbs, raw_dtypes, capbits):
        self.tbl = tbl
        self.limbs = limbs
        self.raw_dtypes = raw_dtypes
        self.capbits = capbits


# negative-cache sentinel: a diverged build is remembered on the batch so a
# long probe stream does not re-run the whole failed insert loop per probe
_DIVERGED = object()


def build_table(build, build_keys: Sequence[str], key_limbs_fn,
                valid_fn) -> _TableCache:
    cache = getattr(build, "_ht_cache", None)
    if cache is None:
        cache = build._ht_cache = {}
    key = tuple(build_keys)
    hit = cache.get(key)
    if hit is _DIVERGED:
        raise HashTableConvergenceError(
            "hash-table build previously failed to converge for this build "
            "batch (cached); take the sort-based probe")
    if hit is None:
        raw = key_limbs_fn(build, build_keys)
        limbs = canonical_limbs(raw, nan_unique=False)
        capbits = capbits_for(build.padded_len)
        _, tbl, converged = _insert(limbs, valid_fn() & ~nan_rows(raw),
                                    capbits)
        if not bool(converged):
            cache[key] = _DIVERGED
            raise HashTableConvergenceError(
                f"hash-table build did not place every row "
                f"(capbits={capbits}, n={build.padded_len}); caller must "
                "fall back to the sort-based probe")
        hit = cache[key] = _TableCache(
            tbl, limbs, tuple(l.dtype for l in raw), capbits
        )
    return hit


def pk_probe(table: _TableCache, probe_limbs: Sequence[jax.Array],
             probe_ok: jax.Array):
    """PK-join probe against a cached build table: (build_idx, matched).
    Equal-key build rows converged on one slot holding the SMALLEST build
    row id — the same pick as the sort path's segment-min.  Probe limbs are
    coerced to the build's raw limb dtypes first (the sort path's
    `astype(s.dtype)` discipline), so an int probe key matches a float
    build key by value."""
    coerced = [l.astype(dt) for l, dt in zip(probe_limbs, table.raw_dtypes)]
    climbs = canonical_limbs(coerced, nan_unique=False)
    ok = probe_ok & ~nan_rows(coerced)
    return _probe(table.tbl, table.limbs, climbs, ok, table.capbits)
