"""Arrow <-> device bridge (+ device batch concat).

Converts pyarrow Tables (what readers produce and writers consume) into
DeviceBatch (what kernels consume).  Mirrors the role Polars conversion plays
at pyquokka/core.py:287-299 (batch arrives -> to polars -> executor), but the
target is padded jax Arrays with dictionary-encoded strings.

Wide integers (int64 / timestamps) without x64: stored as two int32 limbs
(hi = arithmetic >> 32, lo = low 32 bits with the sign bit flipped so that
signed-int32 lexicographic (hi, lo) order equals numeric order).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from quokka_tpu import config
from quokka_tpu.ops import pack
from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol, StringDict, VecCol

_I32_MIN = -(2**31)
_I32_MAX = 2**31 - 1


def _pad(arr: np.ndarray, padded: int, fill=0) -> np.ndarray:
    n = len(arr)
    if n == padded:
        return arr
    out = np.full(padded, fill, dtype=arr.dtype)
    out[:n] = arr
    return out


def _wide_int_limbs(vals: np.ndarray, padded: int):
    """Split int64 numpy values into (hi, lo_sortable) int32 limbs.

    lo_sortable = lo - 2**31 (sign-bit flip), so signed (hi, lo_sortable)
    lexicographic order equals numeric int64 order for every value —
    including when the low 32 bits straddle 2**31.
    """
    hi = (vals >> np.int64(32)).astype(np.int32)
    lo = (vals & np.int64(0xFFFFFFFF)).astype(np.int64)
    lo_sortable = (lo - 2**31).astype(np.int32)
    return _pad(hi, padded), _pad(lo_sortable, padded)


def _limbs_to_int64(hi: np.ndarray, lo_sortable: np.ndarray) -> np.ndarray:
    lo = lo_sortable.astype(np.int64) + 2**31
    return (hi.astype(np.int64) << np.int64(32)) | lo


def _ints_to_col(vals: np.ndarray, padded: int, kind: str, unit=None, nullm=None) -> NumCol:
    """nullm: optional bool mask of null rows (vals are 0-filled there); nulls
    become the kind's sentinel (batch.NULL_I32 / NULL_I64)."""
    from quokka_tpu.ops.batch import NULL_I32, NULL_I64

    vals = np.ascontiguousarray(vals)
    if config.x64_enabled():
        v = vals.astype(np.int64)
        if nullm is not None:
            v = np.where(nullm, np.int64(NULL_I64), v)
        return NumCol(_pad(v, padded), kind, unit=unit)
    if vals.size == 0 or (vals.min() >= _I32_MIN and vals.max() <= _I32_MAX):
        v = vals.astype(np.int32)
        if nullm is not None:
            v = np.where(nullm, np.int32(NULL_I32), v)
        return NumCol(_pad(v, padded), kind, unit=unit)
    v = vals.astype(np.int64)
    if nullm is not None:
        v = np.where(nullm, np.int64(NULL_I64), v)  # limbs: (NULL_I32, NULL_I32)
    hi, lo = _wide_int_limbs(v, padded)
    return NumCol(lo, kind, hi=hi, unit=unit)


def arrow_column_to_device(arr: pa.ChunkedArray, padded: int):
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    if pa.types.is_dictionary(t):
        idx = arr.indices
        if idx.null_count:
            idx = pc.fill_null(idx, -1)  # null rows -> code -1
        codes = idx.to_numpy(zero_copy_only=False).astype(np.int32)
        values = arr.dictionary.to_pylist()
        # the arrow value type decides binary-ness — a value sniff would
        # misclassify an all-null batch of a binary column as string
        is_bin = pa.types.is_binary(t.value_type) or pa.types.is_large_binary(
            t.value_type
        )
        return StrCol(
            _pad(codes, padded),
            StringDict(np.array(values, dtype=object), binary=is_bin),
        )
    if (
        pa.types.is_string(t) or pa.types.is_large_string(t)
        or pa.types.is_binary(t) or pa.types.is_large_binary(t)
    ):
        # binary columns (whole-file blobs) dictionary-encode like strings:
        # bytes stay on the host dictionary, int32 codes go on device
        enc = pc.dictionary_encode(arr)
        if isinstance(enc, pa.ChunkedArray):
            enc = enc.combine_chunks()
        return arrow_column_to_device(enc, padded)
    if pa.types.is_fixed_size_list(t):
        # must run before fill_null (lists can't fill with a scalar) and must
        # not rely on flatten() alone — it drops null slots, misaligning rows;
        # null rows become zero vectors explicitly
        dim = t.list_size
        valid_np = arr.is_valid().to_numpy(zero_copy_only=False)
        flat = arr.flatten().to_numpy(zero_copy_only=False).astype(config.float_dtype())
        out = np.zeros((padded, dim), dtype=flat.dtype)
        out[np.nonzero(valid_np)[0]] = flat.reshape(-1, dim)
        return VecCol(out)
    from quokka_tpu.ops.batch import NULL_I32

    nullm = None
    if arr.null_count:
        # nulls become kind sentinels (NaN / INT_MIN / code -1) — real Arrow
        # nulls again at device_to_arrow.  Bools have no spare value: False.
        nullm = np.logical_not(arr.is_valid().to_numpy(zero_copy_only=False))
        arr = pc.fill_null(arr, float("nan") if pa.types.is_floating(t) else 0)
    if pa.types.is_boolean(t):
        vals = arr.to_numpy(zero_copy_only=False).astype(np.bool_)
        return NumCol(_pad(vals, padded, fill=False), "b")
    if pa.types.is_date32(t):
        vals = arr.cast(pa.int32()).to_numpy(zero_copy_only=False).astype(np.int32)
        if nullm is not None:
            vals = np.where(nullm, np.int32(NULL_I32), vals)
        return NumCol(_pad(vals, padded), "d")
    if pa.types.is_date64(t):
        vals = arr.cast(pa.timestamp("ms")).cast(pa.int64()).to_numpy(zero_copy_only=False)
        vals = (vals // 86400000).astype(np.int32)
        if nullm is not None:
            vals = np.where(nullm, np.int32(NULL_I32), vals)
        return NumCol(_pad(vals, padded), "d")
    if pa.types.is_timestamp(t):
        vals = arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
        return _ints_to_col(vals, padded, "t", unit=t.unit, nullm=nullm)
    if pa.types.is_decimal(t):
        vals = arr.cast(pa.float64()).to_numpy(zero_copy_only=False)
        vals = vals.astype(config.float_dtype())
        if nullm is not None:
            vals = np.where(nullm, np.nan, vals)
        return NumCol(_pad(vals, padded), "f")
    if pa.types.is_integer(t):
        vals = arr.to_numpy(zero_copy_only=False)
        return _ints_to_col(vals, padded, "i", nullm=nullm)
    if pa.types.is_floating(t):
        vals = arr.to_numpy(zero_copy_only=False).astype(config.float_dtype())
        return NumCol(_pad(vals, padded), "f")
    raise NotImplementedError(f"arrow type {t} not supported on device yet")


def arrow_to_device(table: pa.Table, sorted_by: Optional[List[str]] = None) -> DeviceBatch:
    n = table.num_rows
    padded = config.bucket_size(n)
    cols = {name: arrow_column_to_device(table.column(name), padded) for name in table.column_names}
    return host_cols_to_device(cols, n, padded, sorted_by)


def host_cols_to_device(
    cols, n: int, padded: int, sorted_by: Optional[List[str]] = None
) -> DeviceBatch:
    """Move numpy-backed columns to device as ONE packed transfer."""
    leaves: List[np.ndarray] = [pack.ValidCount(padded, n)]
    slots = []  # (col, attr)
    for col in cols.values():
        if isinstance(col, StrCol):
            leaves.append(col.codes)
            slots.append((col, "codes"))
        elif isinstance(col, VecCol):
            leaves.append(col.data)
            slots.append((col, "data"))
        else:
            leaves.append(col.data)
            slots.append((col, "data"))
            if col.hi is not None:
                leaves.append(col.hi)
                slots.append((col, "hi"))
    device = pack.pack_put(leaves)
    valid = device[0]
    for (col, attr), arr in zip(slots, device[1:]):
        setattr(col, attr, arr)
    return DeviceBatch(cols, valid, nrows=n, sorted_by=sorted_by)


def device_to_arrow(batch: DeviceBatch) -> pa.Table:
    """Sync a batch to the host as a compacted Arrow table (valid rows only).
    All columns + the validity mask come back in ONE device->host transfer."""
    leaves = [batch.valid]
    slots = []
    for col in batch.columns.values():
        if isinstance(col, StrCol):
            leaves.append(col.codes)
            slots.append(1)
        elif isinstance(col, VecCol):
            leaves.append(col.data)
            slots.append(1)
        else:
            leaves.append(col.data)
            if col.hi is not None:
                leaves.append(col.hi)
                slots.append(2)
            else:
                slots.append(1)
    host = pack.get_packed(leaves)
    mask = np.asarray(host[0])
    host_cols = {}
    i = 1
    for (name, col), width in zip(batch.columns.items(), slots):
        if width == 2:
            host_cols[name] = (host[i], host[i + 1])
        else:
            host_cols[name] = (host[i], None)
        i += width
    arrays = []
    names = []
    for name, col in batch.columns.items():
        h_data, h_hi = host_cols[name]
        names.append(name)
        if isinstance(col, VecCol):
            mat = h_data[mask]
            flat = pa.array(mat.reshape(-1))
            arrays.append(
                pa.FixedSizeListArray.from_arrays(flat, col.dim)
            )
        elif isinstance(col, StrCol):
            codes = h_data[mask]
            vals = col.dictionary.values
            out = np.empty(len(codes), dtype=object)
            for i, c in enumerate(codes):
                out[i] = vals[c] if 0 <= c < len(vals) else None
            typ = pa.binary() if col.dictionary.binary else pa.string()
            arrays.append(pa.array(out, type=typ))
        else:
            from quokka_tpu.ops.batch import NULL_I32, NULL_I64

            data = h_data[mask]
            if col.hi is not None:
                hi = h_hi[mask]
                v64 = _limbs_to_int64(hi, data)
                nullm = v64 == NULL_I64
                nullm = nullm if nullm.any() else None
                if col.kind == "t":
                    arrays.append(
                        pa.array(v64, mask=nullm).cast(pa.timestamp(col.unit or "us"))
                    )
                else:
                    arrays.append(pa.array(v64, type=pa.int64(), mask=nullm))
            elif col.kind == "d":
                d32 = data.astype(np.int32)
                nullm = d32 == np.int32(NULL_I32)
                nullm = nullm if nullm.any() else None
                arrays.append(pa.array(d32, mask=nullm).cast(pa.date32()))
            elif col.kind in ("i", "t"):
                sent = NULL_I64 if data.dtype == np.int64 else NULL_I32
                nullm = data == sent
                nullm = nullm if nullm.any() else None
                if col.kind == "t":
                    arrays.append(
                        pa.array(data.astype(np.int64), mask=nullm).cast(
                            pa.timestamp(col.unit or "us")
                        )
                    )
                else:
                    arrays.append(pa.array(data, mask=nullm))
            elif col.kind == "b":
                arrays.append(pa.array(data.astype(np.bool_)))
            else:
                arrays.append(pa.array(data))
    return pa.table(arrays, names=names)


def merge_dicts(dicts: Sequence[StringDict]):
    """Merge string dictionaries; returns (merged StringDict, [remap arrays])."""
    if len(dicts) == 1:
        return dicts[0], [None]
    all_vals = np.concatenate([d.values for d in dicts])
    # np.unique on object arrays with None fails; substitute sentinel.
    # Uniqueness keys are str() reprs (injective per column type); merged
    # values are the ORIGINAL objects so bytes dictionaries survive intact.
    sent = "\x00__null__"
    flat = np.array([sent if v is None else v for v in all_vals], dtype=object)
    uniq, first_idx, inverse = np.unique(
        flat.astype(str), return_index=True, return_inverse=True
    )
    merged_vals = np.array(
        [None if flat[i] == sent else all_vals[i] for i in first_idx],
        dtype=object,
    )
    merged = StringDict(merged_vals, binary=any(d.binary for d in dicts))
    remaps = []
    off = 0
    for d in dicts:
        remaps.append(inverse[off : off + len(d)].astype(np.int32))
        off += len(d)
    return merged, remaps


def concat_batches(batches: Sequence[DeviceBatch]) -> DeviceBatch:
    """Concatenate same-schema batches into one padded batch (host-coordinated:
    dictionaries merge on host, data stays on device).

    When any batch's live count is unknown host-side, the concat runs fully
    on device with NO sync: padded regions are concatenated as-is (validity
    masks included) instead of compacting first.  The result is looser-packed
    but avoids a blocking device round trip per input batch."""
    if len(batches) == 1:
        return batches[0]
    # resolve counts that are nearly free first: host-known nrows, or an
    # async-copied device count that has normally landed by concat time
    unresolved = 0
    for b in batches:
        if b.nrows is None:
            if b.nrows_dev is not None:
                b.count_valid()
            else:
                unresolved += 1
    if unresolved:
        if sum(b.padded_len for b in batches) > config.MAX_BUCKET:
            # sparse concat would blow past the bucket cap on padded length
            # alone; pay the blocking counts and compact instead
            for b in batches:
                b.count_valid()
        else:
            return _concat_batches_device(batches)
    names = batches[0].names
    total = sum(b.count_valid() for b in batches)
    padded = config.bucket_size(total)
    fused = _try_fused_concat(batches, total, padded)
    if fused is not None:
        return fused
    # compact each batch first (gather valid rows), then concat + pad
    from quokka_tpu.ops import kernels

    compacted = [kernels.compact(b) for b in batches]
    counts = [b.count_valid() for b in compacted]
    out_cols = {}
    for name in names:
        cols = [b.columns[name] for b in compacted]
        if isinstance(cols[0], StrCol):
            merged, remaps = merge_dicts([c.dictionary for c in cols])
            code_parts = []
            for c, remap, cnt in zip(cols, remaps, counts):
                codes = c.codes[:cnt]
                if remap is not None:
                    # null rows carry code -1: keep them null (a bare gather
                    # would clamp -1 onto dictionary entry 0)
                    remapped = jnp.asarray(remap)[jnp.maximum(codes, 0)]
                    codes = jnp.where(codes < 0, -1, remapped)
                code_parts.append(codes)
            codes = _pad_device(jnp.concatenate(code_parts), padded)
            out_cols[name] = StrCol(codes, merged)
        elif isinstance(cols[0], VecCol):
            data = jnp.concatenate([c.data[:cnt] for c, cnt in zip(cols, counts)])
            if data.shape[0] < padded:
                data = jnp.pad(data, ((0, padded - data.shape[0]), (0, 0)))
            out_cols[name] = VecCol(data[:padded])
        else:
            cols = _align_limbs(cols)
            data = jnp.concatenate([c.data[:cnt] for c, cnt in zip(cols, counts)])
            data = _pad_device(data, padded)
            hi = None
            if cols[0].hi is not None:
                hi = _pad_device(
                    jnp.concatenate([c.hi[:cnt] for c, cnt in zip(cols, counts)]), padded
                )
            out_cols[name] = NumCol(data, cols[0].kind, hi=hi, unit=cols[0].unit)
    valid = jnp.arange(padded) < total
    sorted_by = batches[0].sorted_by
    return DeviceBatch(out_cols, valid, nrows=total, sorted_by=sorted_by)


@functools.partial(jax.jit, static_argnames=("out_padded",))
def _fused_concat_kernel(part_arrays, valids, out_padded: int):
    """One XLA program for the whole compact-concat: stack validity, gather
    the live rows of every column to the front of one bucketed output.
    ``part_arrays``: per column, the tuple of per-part arrays.  Replaces the
    eager per-part compact + per-column concat chain (dozens of dispatches
    and intermediate buffers per call) that dominated the vectorized
    probe/aggregate pipelines' host overhead."""
    vcat = jnp.concatenate(valids)
    idx = jnp.nonzero(vcat, size=out_padded, fill_value=0)[0]
    live = jnp.arange(out_padded) < jnp.sum(vcat.astype(jnp.int32))
    outs = []
    for arrays in part_arrays:
        g = jnp.concatenate(arrays)[idx]
        # zero the invalid tail (nonzero's fill duplicates row 0 there):
        # downstream sort-segmented kernels key off raw limb values and a
        # duplicated real key could extend a segment into the padding
        m = live if g.ndim == 1 else live[:, None]
        outs.append(jnp.where(m, g, jnp.zeros((), g.dtype)))
    return tuple(outs), live


def _try_fused_concat(batches, total: int, padded: int):
    """Fused compact-concat when every column concatenates as plain device
    arrays: NumCol limbs align, StrCol codes remap on host first (dict
    merge), VecCol joins the fast path via its 2D data.  Returns None when
    a column mix needs the general path."""
    names = batches[0].names
    per_col = []  # (name, kind-tuple) with per-part arrays
    str_meta = {}
    for name in names:
        cols = [b.columns[name] for b in batches]
        if isinstance(cols[0], StrCol):
            merged, remaps = merge_dicts([c.dictionary for c in cols])
            parts = []
            for c, remap in zip(cols, remaps):
                codes = c.codes
                if remap is not None:
                    remapped = jnp.asarray(remap)[jnp.maximum(codes, 0)]
                    codes = jnp.where(codes < 0, -1, remapped)
                parts.append(codes)
            per_col.append((name, "str", tuple(parts)))
            str_meta[name] = merged
        elif isinstance(cols[0], VecCol):
            if len({c.dim for c in cols}) != 1:
                return None
            per_col.append((name, "vec", tuple(c.data for c in cols)))
        else:
            cols = _align_limbs(cols)
            if len({c.data.dtype for c in cols}) != 1:
                return None  # mixed narrow dtypes: general path promotes
            per_col.append((name, "num", tuple(c.data for c in cols)))
            if cols[0].hi is not None:
                per_col.append((name + "\0hi", "hi",
                                tuple(c.hi for c in cols)))
            str_meta[name] = cols[0]  # aligned kind/unit source
    valids = tuple(jnp.asarray(b.valid) for b in batches)
    from quokka_tpu.runtime import compileplane

    outs, valid = compileplane.aot_kernel_call(
        "fused_concat", _fused_concat_kernel,
        (tuple(arrs for (_n, _k, arrs) in per_col), valids), (padded,))
    out_cols = {}
    it = iter(zip(per_col, outs))
    pending_hi = {}
    for (name, kind, _arrs), arr in it:
        if kind == "str":
            out_cols[name] = StrCol(arr, str_meta[name])
        elif kind == "vec":
            out_cols[name] = VecCol(arr)
        elif kind == "hi":
            pending_hi[name[:-3]] = arr
        else:
            src = str_meta[name]
            out_cols[name] = NumCol(arr, src.kind, unit=src.unit)
    for name, hi in pending_hi.items():
        c = out_cols[name]
        out_cols[name] = NumCol(c.data, c.kind, hi=hi, unit=c.unit)
    return DeviceBatch(out_cols, valid, nrows=total,
                       sorted_by=batches[0].sorted_by)


def _concat_batches_device(batches: Sequence[DeviceBatch]) -> DeviceBatch:
    """Sync-free concat: stack full padded regions + validity masks."""
    names = batches[0].names
    total_padded = config.bucket_size(sum(b.padded_len for b in batches))
    out_cols = {}
    for name in names:
        cols = [b.columns[name] for b in batches]
        if isinstance(cols[0], StrCol):
            merged, remaps = merge_dicts([c.dictionary for c in cols])
            code_parts = []
            for c, remap in zip(cols, remaps):
                codes = c.codes
                if remap is not None:
                    remapped = jnp.asarray(remap)[jnp.maximum(codes, 0)]
                    codes = jnp.where(codes < 0, -1, remapped)
                code_parts.append(codes)
            out_cols[name] = StrCol(
                _pad_device(jnp.concatenate(code_parts), total_padded), merged
            )
        elif isinstance(cols[0], VecCol):
            data = jnp.concatenate([c.data for c in cols])
            if data.shape[0] < total_padded:
                data = jnp.pad(data, ((0, total_padded - data.shape[0]), (0, 0)))
            out_cols[name] = VecCol(data[:total_padded])
        else:
            cols = _align_limbs(cols)
            data = _pad_device(jnp.concatenate([c.data for c in cols]), total_padded)
            hi = None
            if cols[0].hi is not None:
                hi = _pad_device(jnp.concatenate([c.hi for c in cols]), total_padded)
            out_cols[name] = NumCol(data, cols[0].kind, hi=hi, unit=cols[0].unit)
    valid = _pad_device(
        jnp.concatenate([jnp.asarray(b.valid) for b in batches]), total_padded
    )  # zero-fill: padded tail rows are invalid
    sorted_by = batches[0].sorted_by
    return DeviceBatch(out_cols, valid, nrows=None, sorted_by=sorted_by)


def _align_limbs(cols: Sequence[NumCol]) -> Sequence[NumCol]:
    """Promote plain-int32 columns to the two-limb representation when ANY
    sibling batch carries limbs.  _ints_to_col picks int32 vs limbs per batch
    from that batch's value range, so a stream can legitimately mix the two —
    concatenating a biased lo_sortable limb with plain values (and dropping
    hi) would silently corrupt every wide row."""
    if all(c.hi is None for c in cols) or all(c.hi is not None for c in cols):
        return cols
    from quokka_tpu.ops.batch import NULL_I32
    from quokka_tpu.ops.timewide import widen_limbs

    out = []
    for c in cols:
        if c.hi is not None:
            out.append(c)
            continue
        hi, lo = widen_limbs(c)
        # the plain-int32 null sentinel must become the wide null sentinel
        # (hi, lo) == (NULL_I32, NULL_I32), not the numeric value -2**31
        isnull = c.data == NULL_I32
        hi = jnp.where(isnull, jnp.int32(NULL_I32), hi)
        lo = jnp.where(isnull, jnp.int32(NULL_I32), lo)
        out.append(NumCol(lo, c.kind, hi=hi, unit=c.unit))
    return out


def _pad_device(arr, padded):
    n = arr.shape[0]
    if n == padded:
        return arr
    if n > padded:
        return arr[:padded]
    return jnp.pad(arr, (0, padded - n))


def to_pandas(batch_or_table):
    t = batch_or_table
    if isinstance(t, DeviceBatch):
        t = device_to_arrow(t)
    return t.to_pandas()
