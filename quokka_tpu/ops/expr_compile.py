"""Lower expression ASTs onto DeviceBatch columns as jnp computations.

Replaces the reference's dual path of sqlglot->polars `evaluate`
(pyquokka/sql_utils.py:86) and "give up and run DuckDB SQL" (pyquokka/
core.py:156-163): here there is exactly one compile path and it emits JAX ops,
so filters/projections fuse into the surrounding jitted kernel.

String rules (TPU-first): predicates and transforms evaluate on the host over
the (small) dictionary once, then a device gather by code applies them to all
rows.  Date math runs on int32 days with the civil-calendar algorithm
vectorized in jnp.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from quokka_tpu import config
from quokka_tpu.expression import (
    Agg,
    Alias,
    BinOp,
    Case,
    Cast,
    ColRef,
    DateLit,
    DtField,
    Expr,
    Func,
    InList,
    IntervalLit,
    IsNull,
    Literal,
    StrOp,
    UnaryOp,
)
from quokka_tpu.ops.batch import (
    NULL_I32,
    DeviceBatch,
    NumCol,
    StrCol,
    StringDict,
    null_mask,
)


class CompileError(Exception):
    pass


Value = object  # NumCol | StrCol | python scalar | IntervalLit


def evaluate(e: Expr, batch: DeviceBatch):
    """Evaluate an expression against a batch -> NumCol / StrCol / scalar."""
    if isinstance(e, Alias):
        return evaluate(e.expr, batch)
    if isinstance(e, ColRef):
        if e.name not in batch.columns:
            raise CompileError(f"unknown column {e.name}; have {batch.names}")
        return batch.columns[e.name]
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, DateLit):
        return _DateScalar(e.days)
    if isinstance(e, IntervalLit):
        return e
    if isinstance(e, BinOp):
        return _binop(e.op, evaluate(e.left, batch), evaluate(e.right, batch))
    if isinstance(e, UnaryOp):
        if e.op == "not":
            # push NOT into comparisons (op flip / De Morgan) so SQL 3VL holds:
            # NOT (x = 5) with x null must be false, not ~false
            pushed = _negate_expr(e.operand)
            if pushed is not None:
                return evaluate(pushed, batch)
            res = ~_as_bool(evaluate(e.operand, batch))
            # fallback invert (LIKE/contains/...): still exclude null operands
            if isinstance(e.operand, StrOp):
                v = evaluate(e.operand.expr, batch)
                if isinstance(v, (NumCol, StrCol)):
                    res = res & ~null_mask(v)
            return NumCol(res, "b")
        v = evaluate(e.operand, batch)
        if e.op == "-":
            if isinstance(v, NumCol):
                return NumCol(-v.data, v.kind)
            return -v
        raise CompileError(e.op)
    if isinstance(e, Case):
        return _case(e, batch)
    if isinstance(e, InList):
        return _in_list(e, batch)
    if isinstance(e, IsNull):
        return _is_null(e, batch)
    if isinstance(e, StrOp):
        return _str_op(e, batch)
    if isinstance(e, DtField):
        return _dt_field(e, batch)
    if isinstance(e, Cast):
        return _cast(e, batch)
    if isinstance(e, Func):
        return _func(e, batch)
    if isinstance(e, Agg):
        raise CompileError("aggregate expression used in a scalar context")
    raise CompileError(f"cannot compile {type(e).__name__}")


def evaluate_predicate(e: Expr, batch: DeviceBatch) -> jnp.ndarray:
    return _as_bool(evaluate(e, batch))


def evaluate_to_column(e: Expr, batch: DeviceBatch):
    return value_to_column(evaluate(e, batch), batch)


def value_to_column(v, batch: DeviceBatch):
    if isinstance(v, (NumCol, StrCol)):
        return v
    if isinstance(v, _DateScalar):
        return NumCol(jnp.full(batch.padded_len, v.days, dtype=jnp.int32), "d")
    if isinstance(v, str):
        return StrCol(
            jnp.zeros(batch.padded_len, dtype=jnp.int32),
            StringDict(np.array([v], dtype=object)),
        )
    if isinstance(v, bool):
        return NumCol(jnp.full(batch.padded_len, v, dtype=jnp.bool_), "b")
    if isinstance(v, int):
        return NumCol(jnp.full(batch.padded_len, v, dtype=config.int_dtype()), "i")
    if isinstance(v, float):
        return NumCol(jnp.full(batch.padded_len, v, dtype=config.float_dtype()), "f")
    raise CompileError(f"cannot materialize {type(v)} as a column")


class _DateScalar:
    __slots__ = ("days",)

    def __init__(self, days: int):
        self.days = days


# ---------------------------------------------------------------------------
# coercion helpers
# ---------------------------------------------------------------------------


def _as_bool(v) -> jnp.ndarray:
    if isinstance(v, NumCol):
        return v.data.astype(jnp.bool_) if v.data.dtype != jnp.bool_ else v.data
    if isinstance(v, bool):
        return jnp.asarray(v)
    raise CompileError(f"expected boolean, got {type(v)}")


def _numeric_data(v):
    if isinstance(v, NumCol):
        if v.hi is not None:
            raise CompileError("arithmetic on wide ints requires x64 (CPU) mode")
        return v.data
    if isinstance(v, _DateScalar):
        return v.days
    if isinstance(v, (int, float, bool)):
        return v
    raise CompileError(f"expected numeric, got {type(v)}")


def _result_kind(a, b, op):
    ka = a.kind if isinstance(a, NumCol) else _scalar_kind(a)
    kb = b.kind if isinstance(b, NumCol) else _scalar_kind(b)
    if op == "/":
        return "f"
    if "d" in (ka, kb) and op in ("+", "-"):
        # date - date -> int days; date +/- interval -> date
        if ka == "d" and kb == "d":
            return "i"
        return "d"
    if "f" in (ka, kb):
        return "f"
    return "i"


def _scalar_kind(v):
    if isinstance(v, _DateScalar):
        return "d"
    if isinstance(v, bool):
        return "b"
    if isinstance(v, int):
        return "i"
    if isinstance(v, float):
        return "f"
    return "?"


_CMP = {"=", "!=", "<", "<=", ">", ">="}


def _binop(op, a, b):
    if op in ("and", "or"):
        xa, xb = _as_bool(a), _as_bool(b)
        return NumCol(xa & xb if op == "and" else xa | xb, "b")

    # string comparisons -> dictionary trick / hash equality
    if isinstance(a, StrCol) or isinstance(b, StrCol):
        return _string_compare(op, a, b)

    # interval arithmetic on dates
    if isinstance(b, IntervalLit):
        return _date_interval(op, a, b)
    if isinstance(a, IntervalLit):
        raise CompileError("interval must be on the right-hand side")

    # wide-int comparisons (two-limb)
    wa = isinstance(a, NumCol) and a.hi is not None
    wb = isinstance(b, NumCol) and b.hi is not None
    if (wa or wb) and op in _CMP:
        return _wide_compare(op, a, b)

    da, db = _numeric_data(a), _numeric_data(b)
    if op in _CMP:
        fn = {
            "=": lambda x, y: x == y,
            "!=": lambda x, y: x != y,
            "<": lambda x, y: x < y,
            "<=": lambda x, y: x <= y,
            ">": lambda x, y: x > y,
            ">=": lambda x, y: x >= y,
        }[op]
        res = fn(da, db)
        # SQL three-valued logic: a null operand makes the predicate false
        for side in (a, b):
            if isinstance(side, NumCol) and side.kind in ("i", "d", "t", "f"):
                res = res & ~null_mask(side)
        return NumCol(res, "b")

    kind = _result_kind(a, b, op)
    if op == "+":
        out = da + db
    elif op == "-":
        out = da - db
    elif op == "*":
        out = da * db
    elif op == "/":
        fa = jnp.asarray(da, dtype=config.float_dtype()) if not isinstance(da, (int, float)) else da
        fb = jnp.asarray(db, dtype=config.float_dtype()) if not isinstance(db, (int, float)) else db
        out = fa / fb
    elif op == "//":
        out = da // db
    elif op == "%":
        out = da % db
    else:
        raise CompileError(f"binop {op}")
    out = jnp.asarray(out)
    # arithmetic would destroy int sentinels (INT_MIN + 1 is no longer null):
    # re-mark the result null wherever a sentinel-kind operand was null
    nulls = None
    for side in (a, b):
        if isinstance(side, NumCol) and side.kind in ("i", "d", "t"):
            nm = null_mask(side)
            nulls = nm if nulls is None else nulls | nm
    if nulls is not None:
        if kind == "f" or jnp.issubdtype(out.dtype, jnp.floating):
            out = jnp.where(nulls, jnp.nan, out)
        else:
            out = jnp.where(nulls, jnp.iinfo(out.dtype).min, out)
    return NumCol(out, kind)


def _days_in_month(y, m):
    """Vectorized month lengths with Gregorian leap years."""
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                          dtype=jnp.int32)
    leap = ((y % 4 == 0) & ((y % 100 != 0) | (y % 400 == 0))).astype(jnp.int32)
    return lengths[m - 1] + jnp.where(m == 2, leap, 0)


def _add_months_days(days, delta_months):
    """date(days since epoch) + N calendar months, day-of-month clamped to the
    target month's length (SQL interval-month semantics)."""
    y, m, d = _civil_from_days(days)
    mt = y * 12 + (m - 1) + delta_months
    y2 = jnp.floor_divide(mt, 12)
    m2 = mt - y2 * 12 + 1
    d2 = jnp.minimum(d, _days_in_month(y2, m2))
    return _days_from_civil(y2, m2, d2)


def _add_months_scalar(days: int, delta_months: int) -> int:
    import calendar
    import datetime

    dt = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(days))
    mt = dt.year * 12 + (dt.month - 1) + int(delta_months)
    y2, m2 = mt // 12, mt % 12 + 1
    d2 = min(dt.day, calendar.monthrange(y2, m2)[1])
    return (datetime.date(y2, m2, d2) - datetime.date(1970, 1, 1)).days


def _date_interval(op, a, iv: IntervalLit):
    if iv.months:
        delta_m = -iv.months if op == "-" else iv.months
        if iv.micros:
            raise CompileError("mixed month+day intervals")
        if isinstance(a, _DateScalar):
            return _DateScalar(_add_months_scalar(a.days, delta_m))
        if isinstance(a, NumCol) and a.kind == "d":
            out = _add_months_days(a.data, delta_m).astype(jnp.int32)
            nm = null_mask(a)  # civil math would turn the sentinel into a date
            return NumCol(jnp.where(nm, jnp.int32(NULL_I32), out), "d")
        raise CompileError("month/year interval arithmetic on non-date")
    if not isinstance(a, NumCol):
        if isinstance(a, _DateScalar):
            d = a.days + (iv.days if op == "+" else -iv.days)
            return _DateScalar(d)
        raise CompileError("interval arithmetic on non-date")
    if a.kind == "d":
        delta = iv.days
    elif a.kind == "t":
        delta = _micros_to_unit(iv.micros, a.unit or "us")
    else:
        raise CompileError(f"interval arithmetic on kind {a.kind}")
    if op == "-":
        delta = -delta
    if a.hi is not None:
        raise CompileError("interval arithmetic on wide timestamps requires x64")
    return NumCol(a.data + delta, a.kind, unit=a.unit)


def _micros_to_unit(micros: int, unit: str) -> int:
    scale = {"s": 1_000_000, "ms": 1_000, "us": 1, "ns": 1 / 1000}[unit]
    return int(micros / scale)


def _wide_compare(op, a, b):
    def limbs(v):
        if isinstance(v, NumCol):
            if v.hi is not None:
                return v.hi, v.data
            # narrow col vs wide: widen
            hi = jnp.where(v.data < 0, -1, 0).astype(v.data.dtype)
            lo = _lo_sortable_from_narrow(v.data)
            return hi, lo
        val = int(v.days if isinstance(v, _DateScalar) else v)
        hi = np.int32(val >> 32)
        lo_u = np.uint32(val & 0xFFFFFFFF)
        lo = np.int32(int(lo_u) - 2**31)
        return hi, lo

    ahi, alo = limbs(a)
    bhi, blo = limbs(b)
    eq = (ahi == bhi) & (alo == blo)
    lt = (ahi < bhi) | ((ahi == bhi) & (alo < blo))
    table = {
        "=": eq,
        "!=": ~eq,
        "<": lt,
        "<=": lt | eq,
        ">": ~(lt | eq),
        ">=": ~lt,
    }
    res = table[op]
    for side in (a, b):
        if isinstance(side, NumCol):
            res = res & ~null_mask(side)
    return NumCol(res, "b")


def _lo_sortable_from_narrow(x):
    u = x.astype(jnp.uint32)
    return (u ^ jnp.uint32(0x80000000)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------


def _dict_gather(col: StrCol, host_values: np.ndarray, kind: str) -> NumCol:
    """Evaluate something per-dictionary-entry on host, gather by code.
    Null rows (code < 0) yield False for predicates, NULL sentinel for ints."""
    g = jnp.asarray(host_values)[jnp.maximum(col.codes, 0)]
    isnull = col.codes < 0
    if kind == "b":
        g = g & ~isnull
    elif kind == "f":
        g = jnp.where(isnull, jnp.nan, g)
    else:
        sent = NULL_I32 if g.dtype != jnp.int64 else -(2**63)
        g = jnp.where(isnull, sent, g)
    return NumCol(g, kind)


def _notnone(d: StringDict) -> np.ndarray:
    """Host mask of dictionary entries that are real strings (None = null).
    Reuses the cached StringDict.none_entries mask — no per-batch host loop."""
    none = d.none_entries
    if none is None:
        return np.ones(len(d), dtype=bool)
    return ~none


def _string_compare(op, a, b):
    if isinstance(a, str) and isinstance(b, StrCol):
        a, b, op = b, a, _flip(op)
    if isinstance(a, StrCol) and isinstance(b, str):
        vals = a.dictionary.values.astype(str)
        nn = _notnone(a.dictionary)  # null strings never match (3VL)
        if op == "=":
            return _dict_gather(a, (vals == b) & nn, "b")
        if op == "!=":
            return _dict_gather(a, (vals != b) & nn, "b")
        cmp = {"<": vals < b, "<=": vals <= b, ">": vals > b, ">=": vals >= b}[op]
        return _dict_gather(a, cmp & nn, "b")
    if isinstance(a, StrCol) and isinstance(b, StrCol):
        if op not in ("=", "!="):
            raise CompileError("ordering comparison between two string columns (todo)")
        ahi, alo = a.hash_limbs()
        bhi, blo = b.hash_limbs()
        eq = (ahi == bhi) & (alo == blo)
        out = eq if op == "=" else ~eq
        out = out & ~null_mask(a) & ~null_mask(b)
        return NumCol(out, "b")
    raise CompileError(f"string comparison {type(a)} {op} {type(b)}")


def _flip(op):
    return {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}[op]


_NEG_CMP = {"=": "!=", "!=": "=", "<": ">=", "<=": ">", ">": "<=", ">=": "<"}


def _negate_expr(e: Expr) -> Optional[Expr]:
    """Push a logical NOT one level down, or None if it can't be pushed.
    Negated comparisons keep their null guard (null operand -> false), which a
    plain bitwise invert would wrongly turn into true (SQL three-valued logic)."""
    if isinstance(e, BinOp):
        if e.op in _NEG_CMP:
            return BinOp(_NEG_CMP[e.op], e.left, e.right)
        if e.op in ("and", "or"):
            la, lb = _negate_expr(e.left), _negate_expr(e.right)
            if la is None:
                la = UnaryOp("not", e.left)
            if lb is None:
                lb = UnaryOp("not", e.right)
            return BinOp("or" if e.op == "and" else "and", la, lb)
        return None
    if isinstance(e, UnaryOp) and e.op == "not":
        return e.operand
    if isinstance(e, IsNull):
        return IsNull(e.expr, negated=not e.negated)
    if isinstance(e, InList):
        return InList(e.expr, e.values, negated=not e.negated)
    return None


def _like_to_regex(pat: str) -> str:
    out = []
    for ch in pat:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def _str_op(e: StrOp, batch: DeviceBatch):
    v = evaluate(e.expr, batch)
    if not isinstance(v, StrCol):
        raise CompileError(f"str op {e.op} on non-string")
    vals = v.dictionary.values
    svals = vals.astype(str)
    if e.op == "like":
        rx = re.compile(_like_to_regex(e.args[0]))
        mask = np.array([bool(rx.match(s)) for s in svals])
        return _dict_gather(v, mask, "b")
    if e.op == "contains":
        return _dict_gather(v, np.char.find(svals, e.args[0]) >= 0, "b")
    if e.op == "starts_with":
        return _dict_gather(v, np.char.startswith(svals, e.args[0]), "b")
    if e.op == "ends_with":
        return _dict_gather(v, np.char.endswith(svals, e.args[0]), "b")
    if e.op == "length":
        return _dict_gather(v, np.char.str_len(svals).astype(np.int32), "i")
    if e.op == "hash":
        hi = jnp.asarray(v.dictionary.hash_hi)[jnp.maximum(v.codes, 0)]
        return NumCol(jnp.where(v.codes < 0, 0, hi), "i")
    # string -> string transforms: rewrite the dictionary, keep codes
    if e.op == "lower":
        return StrCol(v.codes, StringDict(np.char.lower(svals).astype(object)))
    if e.op == "upper":
        return StrCol(v.codes, StringDict(np.char.upper(svals).astype(object)))
    if e.op == "strip":
        return StrCol(v.codes, StringDict(np.char.strip(svals).astype(object)))
    if e.op == "slice":
        off, length = e.args[0], e.args[1]
        if length is None:
            new = np.array([s[off:] for s in svals], dtype=object)
        else:
            new = np.array([s[off : off + int(length)] for s in svals], dtype=object)
        return StrCol(v.codes, StringDict(new))
    if e.op == "json_extract":
        import json

        path = e.args[0].lstrip("$.")

        def get(s):
            try:
                return str(json.loads(s).get(path))
            except Exception:
                return None

        new = np.array([get(s) for s in svals], dtype=object)
        return StrCol(v.codes, StringDict(new))
    raise CompileError(f"str op {e.op}")


def _in_list(e: InList, batch: DeviceBatch):
    v = evaluate(e.expr, batch)
    if isinstance(v, StrCol):
        mask = np.isin(v.dictionary.values.astype(str), [str(x) for x in e.values])
        mask = mask & _notnone(v.dictionary)
        out = _dict_gather(v, mask, "b")
    else:
        data = _numeric_data(v)
        acc = jnp.zeros_like(data, dtype=jnp.bool_)
        for val in e.values:
            acc = acc | (data == val)
        out = NumCol(acc, "b")
    if e.negated:
        out = NumCol(~out.data, "b")
    # null operand: both IN and NOT IN are null -> false under 3VL
    if isinstance(v, (NumCol, StrCol)):
        out = NumCol(out.data & ~null_mask(v), "b")
    return out


def _is_null(e: IsNull, batch: DeviceBatch):
    v = evaluate(e.expr, batch)
    if isinstance(v, (StrCol, NumCol)):
        out = NumCol(null_mask(v), "b")
    else:
        out = NumCol(jnp.zeros(batch.padded_len, dtype=jnp.bool_), "b")
    if e.negated:
        out = NumCol(~out.data, "b")
    return out


# ---------------------------------------------------------------------------
# dates
# ---------------------------------------------------------------------------


def _civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day); Hinnant's algorithm in
    pure int32 jnp ops."""
    z = days + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _ts_to_seconds(col: NumCol):
    scale = {"s": 1, "ms": 1_000, "us": 1_000_000, "ns": 1_000_000_000}[col.unit or "us"]
    if col.hi is not None:
        raise CompileError("timestamp field extraction on wide ints requires x64")
    return col.data // scale


def _dt_field(e: DtField, batch: DeviceBatch):
    v = evaluate(e.expr, batch)
    if not isinstance(v, NumCol) or v.kind not in ("d", "t"):
        raise CompileError(f"extract({e.field}) on non-temporal column")
    if v.kind == "d":
        days = v.data
        secs_in_day = None
    else:
        secs = _ts_to_seconds(v)
        days = jnp.floor_divide(secs, 86400)
        secs_in_day = secs - days * 86400
    f = e.field
    if f in ("year", "month", "day"):
        y, m, d = _civil_from_days(days)
        out = {"year": y, "month": m, "day": d}[f]
        return NumCol(out.astype(jnp.int32), "i")
    if f == "weekday":
        return NumCol(((days + 4) % 7).astype(jnp.int32), "i")  # 0=Sunday
    if secs_in_day is None:
        raise CompileError(f"extract({f}) from a date")
    if f == "hour":
        return NumCol((secs_in_day // 3600).astype(jnp.int32), "i")
    if f == "minute":
        return NumCol(((secs_in_day // 60) % 60).astype(jnp.int32), "i")
    if f == "second":
        return NumCol((secs_in_day % 60).astype(jnp.int32), "i")
    raise CompileError(f"extract field {f}")


# ---------------------------------------------------------------------------
# misc scalar funcs
# ---------------------------------------------------------------------------


def _case(e: Case, batch: DeviceBatch):
    # string-valued CASE: any string branch routes to the dictionary path
    raw_vals = [evaluate(v, batch) for _, v in e.whens]
    raw_default = evaluate(e.default, batch) if e.default is not None else None
    if any(isinstance(v, (StrCol, str)) for v in raw_vals + [raw_default]):
        return _case_string(e, batch, raw_vals, raw_default)
    # numeric path: reuse the already-evaluated branch values (a second
    # evaluate() would re-run every branch subtree on device)
    default = (
        value_to_column(raw_default, batch)
        if raw_default is not None
        else NumCol(jnp.full(batch.padded_len, jnp.nan, dtype=config.float_dtype()), "f")
    )
    conds = [evaluate_predicate(cond, batch) for cond, _ in e.whens]
    vals = [value_to_column(v, batch) for v in raw_vals]
    # promote all branches to a common dtype before any where()
    dtype = jnp.result_type(default.data, *(v.data for v in vals))
    out = default.data.astype(dtype)
    kind = "f" if jnp.issubdtype(dtype, jnp.floating) else default.kind
    for c, vcol in zip(reversed(conds), reversed(vals)):
        out = jnp.where(c, vcol.data.astype(dtype), out)
    return NumCol(out, kind)


def _case_string(e: Case, batch: DeviceBatch, raw_vals, raw_default):
    """String-valued CASE: merge the branch dictionaries, pick codes with
    nested where (the string work stays host-side over small dictionaries;
    per-row selection is int32 code arithmetic on device)."""
    from quokka_tpu.ops import bridge

    n = batch.padded_len
    branches = list(raw_vals) + ([raw_default] if raw_default is not None else [])
    dicts = []
    for v in branches:
        if isinstance(v, StrCol):
            dicts.append(v.dictionary)
        elif isinstance(v, str):
            dicts.append(StringDict(np.array([v], dtype=object)))
        elif v is None:
            dicts.append(StringDict(np.array([None], dtype=object)))
        else:
            raise CompileError("CASE mixes string and non-string branches")
    merged, remaps = bridge.merge_dicts(dicts)

    def codes_of(v, remap):
        if isinstance(v, StrCol):
            if remap is None:
                return v.codes
            g = jnp.asarray(remap)[jnp.maximum(v.codes, 0)]
            return jnp.where(v.codes < 0, -1, g)
        code = 0 if remap is None else int(remap[0])
        return jnp.full(n, code, dtype=jnp.int32)

    if raw_default is not None:
        out = codes_of(raw_default, remaps[-1])
    else:
        out = jnp.full(n, -1, dtype=jnp.int32)  # ELSE missing -> null
    conds = [evaluate_predicate(c, batch) for c, _ in e.whens]
    for cond, v, remap in zip(reversed(conds), reversed(raw_vals),
                              reversed(remaps[: len(raw_vals)])):
        out = jnp.where(cond, codes_of(v, remap), out)
    return StrCol(out, merged)


def _cast(e: Cast, batch: DeviceBatch):
    v = evaluate(e.expr, batch)
    to = e.to
    if to.startswith(("double", "float", "real", "decimal", "numeric")):
        if isinstance(v, (int, float)):
            return float(v)
        if isinstance(v, StrCol):
            vals = np.array(
                [float(x) if x not in (None, "") else np.nan for x in v.dictionary.values]
            )
            return _dict_gather(v, vals.astype(np.float64 if config.x64_enabled() else np.float32), "f")
        return NumCol(v.data.astype(config.float_dtype()), "f")
    if to.startswith(("int", "bigint", "smallint", "tinyint")):
        if isinstance(v, (int, float)):
            return int(v)
        return NumCol(v.data.astype(config.int_dtype()), "i")
    if to.startswith("bool"):
        return NumCol(_as_bool(v), "b")
    if to.startswith("date"):
        if isinstance(v, str):
            return _DateScalar(DateLit(v).days)
        if isinstance(v, NumCol) and v.kind == "t":
            secs = _ts_to_seconds(v)
            return NumCol((secs // 86400).astype(jnp.int32), "d")
        if isinstance(v, NumCol):
            return NumCol(v.data.astype(jnp.int32), "d")
    if to.startswith(("varchar", "string", "text")):
        return _cast_to_string(v, batch)
    raise CompileError(f"cast to {to}")


def _cast_to_string(v, batch: DeviceBatch) -> StrCol:
    """Numeric/date -> dictionary-encoded string.  Costs one host sync per
    batch (string materialization is host work by design); distinct values
    become the dictionary, rows gather by code."""
    if isinstance(v, StrCol):
        return v
    if isinstance(v, str):
        return StrCol(
            jnp.zeros(batch.padded_len, dtype=jnp.int32),
            StringDict(np.array([v], dtype=object)),
        )
    if isinstance(v, bool):
        # match the bool COLUMN stringification ("true"/"false"), not str(True)
        return StrCol(
            jnp.zeros(batch.padded_len, dtype=jnp.int32),
            StringDict(np.array(["true" if v else "false"], dtype=object)),
        )
    if isinstance(v, (int, float)):
        return StrCol(
            jnp.zeros(batch.padded_len, dtype=jnp.int32),
            StringDict(np.array([str(v)], dtype=object)),
        )
    if not isinstance(v, NumCol):
        raise CompileError(f"cast to string from {type(v).__name__}")
    from quokka_tpu.ops import timewide
    from quokka_tpu.ops.batch import null_mask

    # stringify only VALID, non-null rows: padded/invalid slots hold garbage
    # that would bloat the dictionary and waste host time
    valid = np.asarray(batch.valid)
    nm = np.asarray(null_mask(v))
    live = valid & ~nm
    idx = np.nonzero(live)[0]
    if v.kind == "d":
        days = np.asarray(v.data)[idx].astype("datetime64[D]")
        host = np.array([str(x) for x in days], dtype=object)
    elif v.kind == "t" or v.hi is not None:
        vals = timewide.host_i64(v, jnp.asarray(live))
        if v.kind == "t":
            unit = v.unit or "us"
            host = np.array(
                [str(x) for x in vals.astype(f"datetime64[{unit}]")], dtype=object
            )
        else:
            host = np.array([str(int(x)) for x in vals], dtype=object)
    elif v.kind == "b":
        host = np.array(
            ["true" if x else "false" for x in np.asarray(v.data)[idx]], dtype=object
        )
    else:
        data = np.asarray(v.data)[idx]
        if v.kind == "f":
            host = np.array([str(float(x)) for x in data], dtype=object)
        else:
            host = np.array([str(int(x)) for x in data], dtype=object)
    uniq, live_codes = np.unique(host, return_inverse=True)
    codes = np.full(batch.padded_len, -1, dtype=np.int32)
    codes[idx] = live_codes.astype(np.int32)
    return StrCol(jnp.asarray(codes), StringDict(uniq.astype(object)))


def _func(e: Func, batch: DeviceBatch):
    name = e.name
    args = [evaluate(a, batch) for a in e.args]

    def num(i):
        return _numeric_data(args[i])

    if name in ("__nn0", "__nnhigh", "__nnlow", "__nncount"):
        # internal null-skipping wrappers injected by AggPlan.rewrite: replace
        # nulls with the aggregate's identity element before the kernel agg
        v = args[0]
        if not isinstance(v, (NumCol, StrCol)):
            if name == "__nncount":
                return NumCol(jnp.ones(batch.padded_len, dtype=jnp.int32), "i")
            return v
        nm = null_mask(v)
        if name == "__nncount":
            return NumCol((~nm).astype(jnp.int32), "i")
        if isinstance(v, StrCol):
            raise CompileError("numeric aggregate over a string column")
        if v.hi is not None:
            raise CompileError("aggregate over wide ints requires x64")
        if v.kind == "f":
            repl = {"__nn0": 0.0, "__nnhigh": jnp.inf, "__nnlow": -jnp.inf}[name]
        else:
            ii = jnp.iinfo(v.data.dtype)
            repl = {"__nn0": 0, "__nnhigh": ii.max, "__nnlow": ii.min}[name]
        return NumCol(jnp.where(nm, repl, v.data), v.kind, unit=v.unit)

    if name == "abs":
        return NumCol(jnp.abs(num(0)), _kind_of(args[0]))
    if name == "round":
        nd = int(args[1]) if len(args) > 1 else 0
        return NumCol(jnp.round(num(0), nd), "f")
    if name == "sqrt":
        return NumCol(jnp.sqrt(jnp.asarray(num(0), config.float_dtype())), "f")
    if name == "exp":
        return NumCol(jnp.exp(jnp.asarray(num(0), config.float_dtype())), "f")
    if name in ("ln", "log"):
        return NumCol(jnp.log(jnp.asarray(num(0), config.float_dtype())), "f")
    if name == "floor":
        return NumCol(jnp.floor(num(0)), "f")
    if name == "ceil":
        return NumCol(jnp.ceil(num(0)), "f")
    if name == "power":
        return NumCol(jnp.power(jnp.asarray(num(0), config.float_dtype()), num(1)), "f")
    if name == "sign":
        return NumCol(jnp.sign(num(0)), _kind_of(args[0]))
    if name in ("sin", "cos"):
        f = jnp.sin if name == "sin" else jnp.cos
        return NumCol(f(jnp.asarray(num(0), config.float_dtype())), "f")
    if name == "coalesce":
        v = args[0]
        if not isinstance(v, NumCol):
            return v  # scalar first arg is never null
        if v.hi is not None:
            raise CompileError("coalesce on wide ints requires x64")
        kind = v.kind
        out = v.data
        for i in range(1, len(args)):
            # sentinel-aware: detect nulls of the CURRENT accumulator (NaN for
            # floats, INT_MIN for int kinds), not just NaN
            nm = null_mask(NumCol(out, kind))
            nxt = args[i]
            nxt_data = nxt.data if isinstance(nxt, NumCol) else nxt
            if isinstance(nxt, NumCol) and nxt.kind == "f" and kind != "f":
                out = out.astype(config.float_dtype())
                kind = "f"
                nm = jnp.isnan(out) | nm.astype(bool)
            out = jnp.where(nm, nxt_data, out)
        return NumCol(jnp.asarray(out), kind)
    if name in ("greatest", "least"):
        f = jnp.maximum if name == "greatest" else jnp.minimum
        out = num(0)
        for i in range(1, len(args)):
            out = f(out, num(i))
        return NumCol(jnp.asarray(out), _kind_of(args[0]))
    if name == "date_trunc":
        every = args[0]
        v = args[1]
        if not isinstance(v, NumCol):
            raise CompileError("date_trunc on scalar")
        if v.kind == "d" and every in ("month", "year"):
            y, m, _ = _civil_from_days(v.data)
            if every == "year":
                m = jnp.ones_like(m)
            return NumCol(_days_from_civil(y, m, jnp.ones_like(m)), "d")
        raise CompileError(f"date_trunc {every} on kind {v.kind}")
    raise CompileError(f"function {name}")


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _kind_of(v):
    if isinstance(v, NumCol):
        return v.kind
    return _scalar_kind(v)


# ---------------------------------------------------------------------------
# aggregation decomposition (partial -> final), mirroring the semantics of
# pyquokka/sql_utils.py:299-412 parse_multiple_aggregations
# ---------------------------------------------------------------------------


class AggPlan:
    """Decomposed aggregation:
    - pre: [(tmp_name, Expr)]           per-batch scalar columns to compute
    - partials: [(pname, op, tmp|None)] kernel aggs over (keys, tmp columns)
    - recombine: [(pname, op)]          how to merge partial results
    - finals: [(out_name, Expr over partial names)]
    """

    def __init__(self):
        self.pre: List[Tuple[str, Expr]] = []
        self.partials: List[Tuple[str, str, Optional[str]]] = []
        self.recombine: List[Tuple[str, str]] = []
        self.finals: List[Tuple[str, Expr]] = []
        self._memo: Dict[str, str] = {}

    def _tmp(self, e: Expr) -> str:
        key = "pre:" + e.sql()
        if key in self._memo:
            return self._memo[key]
        name = f"__pre_{len(self.pre)}"
        self.pre.append((name, e))
        self._memo[key] = name
        return name

    def _partial(self, op: str, arg: Optional[Expr]) -> str:
        key = f"agg:{op}:{arg.sql() if arg is not None else '*'}"
        if key in self._memo:
            return self._memo[key]
        name = f"__agg_{len(self.partials)}"
        tmp = self._tmp(arg) if arg is not None else None
        self.partials.append((name, op, tmp))
        self.recombine.append((name, {"count": "sum"}.get(op, op)))
        self._memo[key] = name
        return name

    def rewrite(self, e: Expr) -> Expr:
        if isinstance(e, Agg):
            if e.distinct:
                raise CompileError("count(distinct) requires the holistic agg path")
            # null skipping: wrap args so nulls become the agg's identity and
            # count(col) counts only non-null rows (SQL semantics)
            def nn_count(arg):
                if arg is None:
                    return ColRef(self._partial("count", None))
                return ColRef(self._partial("sum", Func("__nncount", [arg])))

            if e.op == "sum":
                return ColRef(self._partial("sum", Func("__nn0", [e.arg])))
            if e.op == "min":
                return ColRef(self._partial("min", Func("__nnhigh", [e.arg])))
            if e.op == "max":
                return ColRef(self._partial("max", Func("__nnlow", [e.arg])))
            if e.op == "count":
                return nn_count(e.arg)
            if e.op == "avg":
                s = ColRef(self._partial("sum", Func("__nn0", [e.arg])))
                c = nn_count(e.arg)
                return BinOp("/", s, c)
            if e.op in ("stddev", "var"):
                x = Func("__nn0", [e.arg])
                s1 = ColRef(self._partial("sum", x))
                s2 = ColRef(self._partial("sum", BinOp("*", x, x)))
                c = nn_count(e.arg)
                mean = BinOp("/", s1, c)
                var = BinOp("-", BinOp("/", s2, c), BinOp("*", mean, mean))
                if e.op == "var":
                    return var
                return Func("sqrt", [var])
            raise CompileError(f"aggregate {e.op}")
        kids = e.children()
        if not kids:
            return e
        from quokka_tpu.expression import _rebuild

        return _rebuild(e, [self.rewrite(k) for k in kids])


def plan_aggregation(outputs: Sequence[Expr]) -> AggPlan:
    """outputs: Alias-wrapped expressions containing Agg nodes."""
    plan = AggPlan()
    for i, e in enumerate(outputs):
        name = e.name if isinstance(e, Alias) else f"col{i}"
        inner = e.expr if isinstance(e, Alias) else e
        plan.finals.append((name, plan.rewrite(inner)))
    return plan
