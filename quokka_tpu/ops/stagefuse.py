"""Whole-stage fusion: run a maximal linear operator chain as ONE exec actor.

Why: per-operator dispatch tax dominates Q3/Q5 — every filter→project→probe→
partial-agg hop used to round-trip through a separate task dispatch, a store
push, and a re-densify on the consumer side.  The optimizer's ``fuse_stages``
pass (optimizer.py) rewrites single-consumer, same-placement, non-blocking
chains into one ``FusedStageNode`` which lowers to ONE actor running a
``FusedStageExecutor``: a producer's output feeds the next operator in-process
with zero intermediate batch materialization, zero extra bridge/densify, and
zero added host syncs (Flare's whole-stage compilation, TQP's tensor-runtime
lowering — ROADMAP item 1).

Two layers:

- ``FusedElementwise``: consecutive filter/project/expression-map members
  collapse into ONE jitted program through the existing ops/fuse.py prepass +
  compile-plane machinery (sigkey-canonicalized signature, AOT-persisted,
  pre-warmable).  The output keeps the input's columns with a lazily-applied
  combined mask (the FusedPredicate discipline) — no densify between members.
- ``FusedStageExecutor``: the actor-level chain container.  Stream 0 cascades
  through the member executors; build streams (join builds) route to their
  owning member.  Lineage, checkpoint, and tape boundaries sit at STAGE
  granularity: the stage checkpoints as one unit (a list of member snapshots)
  and the engine's tape records stage-level inputs/outputs, so chaos/recovery
  replay stays bit-exact.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from quokka_tpu.expression import Expr, substitute_columns
from quokka_tpu.ops import expr_compile, kernels, sigkey
from quokka_tpu.ops.batch import DeviceBatch, NumCol
from quokka_tpu.ops.fuse import (
    Prepass,
    _dispatch_program,
    _infer_kind,
    _refs_string,
    _ShimBatch,
)
from quokka_tpu.executors.base import Executor


class FusedElementwise:
    """Picklable fused filter/project/map pipeline: ONE jit program per batch
    signature computes the combined row mask plus every derived column.

    ``steps`` is the chain segment in execution order:
      ("filter", Expr) | ("project", [cols]) | ("map", [(name, Expr), ...])
    Map/filter expressions are inlined at plan time (later steps substitute
    earlier map definitions), so the program evaluates everything against the
    ORIGINAL input columns — filters and maps commute freely because masks
    only ever narrow ``valid`` and expressions are evaluated over all lanes
    anyway (the engine-wide padded-lane discipline)."""

    def __init__(self, steps: Sequence[Tuple]):
        self.steps = [tuple(s) for s in steps]
        env: Dict[str, Expr] = {}
        conjuncts: List[Expr] = []
        visible: Optional[List[str]] = None  # None -> passthrough-all
        for kind, payload in self.steps:
            if kind == "filter":
                conjuncts.append(substitute_columns(payload, env))
            elif kind == "map":
                for name, e in payload:
                    env[name] = substitute_columns(e, env)
                if visible is not None:
                    visible += [n for n, _ in payload if n not in visible]
            elif kind == "project":
                visible = list(payload)
            else:  # pragma: no cover - plan construction bug
                raise ValueError(f"unknown stagefuse step {kind!r}")
        self._env = env
        self._conjuncts = conjuncts
        self._visible = visible
        # computed outputs the program must produce (projection may drop some)
        names = visible if visible is not None else list(env)
        self._outputs = [(n, env[n]) for n in names if n in env]

    def sql(self) -> str:
        """Stable structural text (compile-plane fingerprints stop recursing
        at sql(); without this, deep factory nesting would hit _describe's
        depth cutoff and stop discriminating between elementwise pipelines)."""
        parts = []
        for kind, payload in self.steps:
            if kind == "filter":
                parts.append(f"filter:{payload.sql()}")
            elif kind == "map":
                parts.append(
                    "map:" + ",".join(f"{n}={e.sql()}" for n, e in payload))
            else:
                parts.append("project:" + ",".join(payload))
        return "elemwise[" + ";".join(parts) + "]"

    # -- sequential fallback (string-valued exprs, wide-int inputs) ----------
    def _sequential(self, batch: DeviceBatch) -> DeviceBatch:
        b = batch
        for kind, payload in self.steps:
            if kind == "filter":
                mask = expr_compile.evaluate_predicate(payload, b)
                b = kernels.apply_mask(b, mask)
            elif kind == "map":
                for name, e in payload:
                    b = b.with_column(name, expr_compile.evaluate_to_column(e, b))
            else:
                b = b.select([c for c in payload if c in b.columns])
        return b

    def __call__(self, batch: DeviceBatch) -> DeviceBatch:
        pre = Prepass(batch)
        try:
            conjuncts = [pre.rewrite(e) for e in self._conjuncts]
            outputs = [(n, pre.rewrite(e)) for n, e in self._outputs]
        except expr_compile.CompileError:
            return self._sequential(batch)
        if any(_refs_string(e, batch) for e in conjuncts) or any(
                _refs_string(e, batch) for _, e in outputs):
            # string material survived the rewrite (e.g. CASE with string
            # branches): evaluating it builds a host dictionary, which can
            # never happen inside a trace — run the per-step path
            return self._sequential(batch)
        needed = set()
        for e in conjuncts:
            needed |= e.required_columns()
        for _, e in outputs:
            needed |= e.required_columns()
        num_inputs: Dict[str, NumCol] = {}
        for n in sorted(needed):
            c = batch.columns.get(n)
            if c is None:
                continue  # prepass-bound column
            if not isinstance(c, NumCol) or c.hi is not None:
                # wide-int / string inputs: the per-step executors handle
                # them; identical values either way (masks are exact)
                return self._sequential(batch)
            num_inputs[n] = c
        sig = sigkey.make_key(
            "stage_elemwise",
            sigkey.batch_sig(batch, list(num_inputs)),
            tuple(sorted(pre.bound)),
            tuple(e.sql() for e in conjuncts),
            tuple((n, e.sql()) for n, e in outputs),
        )

        def builder():
            names, bnames = list(num_inputs), sorted(pre.bound)

            @jax.jit
            def fused(arrays, barrays, valid):
                cols = {}
                for name, arr in zip(names, arrays):
                    cols[name] = NumCol(arr, _infer_kind(arr))
                for name, arr in zip(bnames, barrays):
                    cols[name] = NumCol(arr, _infer_kind(arr))
                shim = _ShimBatch(cols, valid.shape[0], valid)
                m = valid
                for e in conjuncts:
                    m = m & expr_compile.evaluate_predicate(e, shim)
                outs = []
                for _, e in outputs:
                    c = expr_compile.evaluate_to_column(e, shim)
                    outs.append((c.data,
                                 c.hi if c.hi is not None
                                 else jnp.zeros(0, jnp.int32)))
                return m, jnp.sum(m.astype(jnp.int32)), tuple(outs)

            return fused

        try:
            mask, num, out_arrays = _dispatch_program(sig, builder, (
                tuple(num_inputs[n].data for n in num_inputs),
                tuple(pre.bound[k] for k in sorted(pre.bound)),
                batch.valid,
            ))
        except expr_compile.CompileError:
            # an expression form evaluate() supports eagerly but not under
            # trace — identical values either way, just per-step dispatch
            return self._sequential(batch)
        computed = {}
        for (name, _), (arr, hi) in zip(outputs, out_arrays):
            computed[name] = NumCol(arr, _infer_kind(arr),
                                    hi=hi if hi.shape[0] else None)
        if self._visible is None:
            # with_column replaces in place: a recomputed existing column
            # keeps its position, new names append in definition order
            names = list(batch.columns)
            names += [n for n in computed if n not in batch.columns]
        else:
            names = self._visible
        cols = {}
        for n in names:
            cols[n] = computed[n] if n in computed else batch.columns[n]
        sorted_by = batch.sorted_by
        if sorted_by is not None and not all(s in cols for s in sorted_by):
            sorted_by = None
        return DeviceBatch(cols, mask, None, sorted_by).note_count(num)


class StageSpec:
    """Picklable description of a fused stage: the member executor steps in
    chain order plus the fused-actor stream routing.  Exposes sql() so the
    plan fingerprint captures the FULL chain structure."""

    def __init__(self, steps: Sequence[Tuple[str, Callable]],
                 routing: Dict[int, Tuple[int, int]]):
        self.steps = [tuple(s) for s in steps]
        self.routing = dict(routing)

    def sql(self) -> str:
        from quokka_tpu.runtime.compileplane import _describe

        parts = [f"{label}:{_describe(factory)}" for label, factory in self.steps]
        routes = ",".join(f"{s}->{m}.{ss}"
                          for s, (m, ss) in sorted(self.routing.items()))
        return "stage[" + ";".join(parts) + "|" + routes + "]"


class FusedStageExecutor(Executor):
    """One actor running a whole fused stage.  Stream 0 (the chain's main
    input) cascades through every member; build streams route to their owning
    join member.  Emission decisions stay content-deterministic — each member
    already decides emits without inspecting device data, and the cascade is
    a pure function of those decisions — so tape replay at stage granularity
    reproduces the exact emit sequence."""

    # one fused dispatch does the work of the whole member chain: drain a
    # wider slice of the ready queue per task than the per-operator default
    # so the interior joins/aggs run over bigger coalesced wholes
    MAX_PIPELINE_BATCHES = 32

    def __init__(self, spec: StageSpec):
        self.spec = spec
        self.steps = [factory() for _, factory in spec.steps]
        self.labels = [label for label, _ in spec.steps]
        self.routing = spec.routing
        self.OP_NAME = "FusedStage[" + ">".join(self.labels) + "]"

    @property
    def SUPPORTS_CHECKPOINT(self) -> bool:
        # the stage checkpoints as ONE unit; that is only sound when every
        # member either snapshots real state or carries none at all.  Reading
        # the members' flags per call keeps runtime downgrades visible (the
        # grace join flips its instance flag off when it enters disk mode).
        return all(
            getattr(m, "SUPPORTS_CHECKPOINT", False)
            or getattr(m, "STATELESS", False)
            for m in self.steps
        )

    def _note_rows(self, idx: int, out: Optional[DeviceBatch]) -> None:
        """Per-logical-operator row accounting on the fused actor's opstats
        record (host-known rows only — never a device sync)."""
        if out is None:
            return
        from quokka_tpu.obs import opstats

        rows = out.nrows if out.nrows is not None else out.padded_len
        opstats.note(**{f"fused{idx}_{self.labels[idx]}_rows": rows})

    def _cascade(self, start: int, out: Optional[DeviceBatch],
                 channel: int) -> Optional[DeviceBatch]:
        for i in range(start, len(self.steps)):
            if out is None:
                return None
            out = self.steps[i].execute([out], 0, channel)
            self._note_rows(i, out)
        return out

    def execute(self, batches, stream_id, channel):
        idx, sub_stream = self.routing.get(stream_id, (0, 0))
        if sub_stream == 0:
            from quokka_tpu.obs.metrics import REGISTRY

            REGISTRY.counter("stagefuse.exec").inc()
        out = self.steps[idx].execute(batches, sub_stream, channel)
        self._note_rows(idx, out)
        return self._cascade(idx + 1, out, channel)

    def source_done(self, stream_id, channel):
        idx, sub_stream = self.routing.get(stream_id, (0, 0))
        out = self.steps[idx].source_done(sub_stream, channel)
        self._note_rows(idx, out)
        return self._cascade(idx + 1, out, channel)

    def done(self, channel):
        # interior members learn "main input exhausted" here: each member's
        # done() output feeds the remaining chain before the next member
        # finalizes, preserving per-operator flush order exactly as the
        # unfused actor pipeline would have delivered it
        pending: List[DeviceBatch] = []
        for i, m in enumerate(self.steps):
            outs: List[DeviceBatch] = []
            for b in pending:
                o = m.execute([b], 0, channel)
                self._note_rows(i, o)
                if o is not None:
                    outs.append(o)
            d = m.done(channel)
            if d is not None:
                for o in ([d] if isinstance(d, DeviceBatch) else d):
                    if o is not None:
                        self._note_rows(i, o)
                        outs.append(o)
            pending = outs
        return pending or None

    def checkpoint(self):
        return [
            m.checkpoint() if getattr(m, "SUPPORTS_CHECKPOINT", False) else None
            for m in self.steps
        ]

    def restore(self, state) -> None:
        if not state:
            return
        for m, s in zip(self.steps, state):
            if s is not None:
                m.restore(s)
