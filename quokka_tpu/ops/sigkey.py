"""Canonical jit-cache signature derivation — the ONE place cache-key
dimensions come from.

Every fused/AOT program in the engine is cached by a structural signature
(padded length, column dtypes, expression text, strategy flags).  BENCH_r05
showed that space fragmenting: 11-15 real compiles per join query during
warmup, because each call site derived its own key from raw batch
properties — one program per 2x padded-length rung, per redundant
kind-char, per exact dictionary size.  This module collapses the key space:

- ``bucket_rows(n)``: the padded-length bucket ladder.  All rungs are
  powers of two (mesh sharding divides by them), but below ``LADDER_KNEE``
  rungs are spaced 4x apart instead of 2x: small intermediates (probe
  slices, partial aggregates, shuffle partitions) are sub-millisecond to
  process at any of those sizes, so the extra padding is free while the
  rung count — and with it the number of distinct compiled programs —
  halves at the small end.  Above the knee rungs stay 2x: padding waste is
  real memory there.  ``QUOKKA_SIG_LADDER=pow2`` restores the legacy pure
  2x ladder.
- ``pow2_dim(n)``: canonical key-space dimensions (dictionary sizes, hash
  buckets) — raw sizes vary per file/batch and would recompile the
  program every time a dictionary grows by one entry.
- ``batch_sig(batch, names)`` / ``col_sig``: the canonical per-column
  signature.  The column ``kind`` char is deliberately absent: traced
  programs rebuild kinds from dtypes (``fuse._infer_kind``), so date vs
  int32 columns compile to the same program and must share a key.
- ``aval_sig(args)``: canonical (shape, dtype) tuple over a pytree of
  arrays — the key half for AOT-compiled kernels (runtime/compileplane).
- ``make_key(kind, *parts)``: assembles the final hashable key AND records
  it in the process-wide ledger, so signature cardinality is observable
  (tests pin a per-query budget; lint QK012 bans keys built from raw
  lengths anywhere else).

No jax import: this module is on the config import path (config.bucket_size
delegates to ``bucket_rows``) and must stay dependency-light.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Sequence, Tuple

MIN_BUCKET = 256
MAX_BUCKET = 1 << 24
# below the knee, ladder rungs are spaced 4x (LADDER_STEP bits); above it 2x
LADDER_KNEE = 1 << 16
LADDER_STEP = 2

_PURE_POW2 = os.environ.get("QUOKKA_SIG_LADDER", "").lower() == "pow2"


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, int(n - 1)).bit_length()


def bucket_rows(n: int) -> int:
    """Smallest ladder bucket that fits n rows.  All rungs are powers of
    two; rungs below LADDER_KNEE come every LADDER_STEP doublings so the
    small-shape compile space stays small."""
    if n <= MIN_BUCKET:
        return MIN_BUCKET
    b = _pow2_ceil(n)
    if b > MAX_BUCKET:
        raise ValueError(f"batch of {n} rows exceeds max bucket {MAX_BUCKET}")
    if _PURE_POW2 or b >= LADDER_KNEE:
        return b
    # snap up to the next rung: rung exponents are MIN_BUCKET's exponent
    # plus a multiple of LADDER_STEP
    base = MIN_BUCKET.bit_length() - 1
    over = (b.bit_length() - 1) - base
    rung = base + ((over + LADDER_STEP - 1) // LADDER_STEP) * LADDER_STEP
    return min(1 << rung, LADDER_KNEE)


def pow2_dim(n: int) -> int:
    """Canonical key-space dimension (dictionary size, bucket count):
    next power of two, so growth recompiles O(log) times, not O(n)."""
    return _pow2_ceil(n)


def col_sig(name: str, col) -> Tuple:
    """Canonical per-column signature: dtype + wide-limb presence decide
    the traced program; the kind char does not (kinds are re-inferred from
    dtypes inside the trace) and exact dictionary contents never do."""
    # StrCol duck-type: dictionary-encoded codes
    if hasattr(col, "codes"):
        return (name, "str")
    return (name, str(col.data.dtype), col.hi is not None)


def batch_sig(batch, names: Sequence[str]) -> Tuple:
    """Structural signature of a batch restricted to ``names`` — padded
    length (already on the canonical ladder by construction) plus each
    column's canonical signature."""
    return (batch.padded_len,) + tuple(
        col_sig(n, batch.columns[n]) for n in names
    )


def aval_sig(args) -> Tuple:
    """Canonical (shape, dtype) signature over a nested tuple of arrays —
    the shape half of an AOT kernel key.  Non-array leaves (ints, bools,
    strings: static parameters) pass through as themselves."""
    if isinstance(args, (tuple, list)):
        return tuple(aval_sig(a) for a in args)
    shape = getattr(args, "shape", None)
    dtype = getattr(args, "dtype", None)
    if shape is None or dtype is None:
        return args
    return (tuple(shape), str(dtype))


# ---------------------------------------------------------------------------
# signature ledger: every distinct program key, by kind — makes cache-key
# cardinality observable (tests pin a budget; bench/prewarm read it)
# ---------------------------------------------------------------------------

_ledger_lock = threading.Lock()
_LEDGER: Dict[str, set] = {}


def make_key(kind: str, *parts) -> Tuple:
    """Assemble a program cache key and record it in the ledger.  Hot
    path (steady-state kernel dispatch) is a lock-free membership probe —
    dict/set reads are GIL-atomic and the sets only grow; the lock is
    taken only for a genuinely new key."""
    key = (kind,) + tuple(parts)
    s = _LEDGER.get(kind)
    if s is None or key not in s:
        with _ledger_lock:
            _LEDGER.setdefault(kind, set()).add(key)
    return key


def ledger_counts() -> Dict[str, int]:
    """{kind: distinct keys recorded since reset} — the cardinality the
    compile plane exists to keep small."""
    with _ledger_lock:
        return {k: len(v) for k, v in _LEDGER.items()}


def ledger_keys(kind: str) -> Tuple:
    with _ledger_lock:
        return tuple(_LEDGER.get(kind, ()))


def reset_ledger() -> None:
    with _ledger_lock:
        _LEDGER.clear()
