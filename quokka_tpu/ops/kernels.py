"""Jitted relational kernels over DeviceBatch.

Design notes (TPU-first):
- Every kernel is static-shape: batches are padded to buckets (config.bucket_size)
  and carry a validity mask.  Filtering flips mask bits; compaction (which needs
  a host sync for the live count) happens only at batch boundaries (shuffle,
  output), mirroring where the reference engine synchronizes anyway.
- Group-by uses a sort + segment-reduce plan ("dense rank"): sort rows by key
  limbs, mark group starts, prefix-sum to get dense segment ids, then
  jax.ops.segment_* with num_segments = padded length.  This replaces the
  hash-table group-bys Polars does on CPU (SURVEY.md section 2.2) with a plan that
  maps onto XLA's sort and scatter-add, which tile well on TPU.
- Multi-column / string / wide-int keys are lists of 32-bit "limbs"
  (ops/batch.key_limbs); lexicographic multi-operand lax.sort handles them
  without 64-bit device ints.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from quokka_tpu import config
from quokka_tpu.ops import hashtable
from quokka_tpu.ops.batch import DeviceBatch, NumCol, StrCol, gather_columns, key_limbs

# ---------------------------------------------------------------------------
# masking / compaction
# ---------------------------------------------------------------------------


def _aot(kind, jit_fn, args, statics=()):
    """Kernel dispatch through the compile plane (persisted executables,
    canonical aval keys); inlines untouched inside traces."""
    from quokka_tpu.runtime import compileplane

    return compileplane.aot_kernel_call(kind, jit_fn, args, statics)


def apply_mask(batch: DeviceBatch, mask: jax.Array) -> DeviceBatch:
    new_valid, num = _aot("mask_count", _mask_and_count, (batch.valid, mask))
    return DeviceBatch(batch.columns, new_valid, None, batch.sorted_by).note_count(num)


@jax.jit
def _mask_and_count(valid, mask):
    v = valid & mask
    return v, jnp.sum(v.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("out_size",))
def _compact_idx(valid, out_size):
    idx = jnp.nonzero(valid, size=out_size, fill_value=0)[0]
    return idx


def compact(batch: DeviceBatch) -> DeviceBatch:
    """Gather valid rows to the front and shrink to the smallest bucket.
    Costs one host sync for the live count."""
    n = batch.count_valid()
    padded = config.bucket_size(n)
    if n == batch.padded_len and padded == batch.padded_len:
        return batch
    idx = _aot("compact_idx", _compact_idx, (batch.valid,), (padded,))
    valid = jnp.arange(padded) < n
    return batch.take(idx, valid, n)


def compact_if_large(batch: DeviceBatch, threshold: int = 1 << 16) -> DeviceBatch:
    """Compact only when the padded region is big enough to matter.  Small
    batches pass through uncompacted — their blocking live-count read (a full
    host round trip) costs far more than the slack rows they carry."""
    if batch.padded_len <= threshold:
        return batch
    return compact(batch)


def head(batch: DeviceBatch, k: int) -> DeviceBatch:
    b = compact(batch)
    n = min(b.count_valid(), k)
    padded = config.bucket_size(n)
    idx = jnp.arange(padded)
    return b.take(idx, idx < n, n)


# ---------------------------------------------------------------------------
# sort-key limbs (order-preserving, unlike hash limbs)
# ---------------------------------------------------------------------------


def sort_limbs(batch: DeviceBatch, cols: Sequence[str], descending=None) -> List[jax.Array]:
    """Limbs whose ascending lexicographic order == the requested column order.
    Strings map codes -> dictionary-rank (host argsort of the dict), so string
    sorts are true lexicographic sorts, not hash-order."""
    if descending is None:
        descending = [False] * len(cols)
    limbs: List[jax.Array] = []
    for name, desc in zip(cols, descending):
        c = batch.columns[name]
        if isinstance(c, StrCol):
            order = np.argsort(c.dictionary.values.astype(str), kind="stable")
            rank = np.empty(len(order), dtype=np.int32)
            rank[order] = np.arange(len(order), dtype=np.int32)
            limb = jnp.asarray(rank)[jnp.maximum(c.codes, 0)]
            # nulls (code -1) sort first ascending (rank -1 < all real ranks)
            limb = jnp.where(c.codes < 0, -1, limb)
            limbs.append(~limb if desc else limb)
        else:
            parts = []
            if c.hi is not None:
                parts.append(c.hi)
            parts.append(c.data)
            for p in parts:
                if desc:
                    if jnp.issubdtype(p.dtype, jnp.floating):
                        p = -p
                    elif p.dtype == jnp.bool_:
                        p = ~p
                    else:
                        p = ~p  # bitwise-not reverses signed-int order, no overflow
                limbs.append(p)
    return limbs


# ---------------------------------------------------------------------------
# dense rank (the group-by / join workhorse)
# ---------------------------------------------------------------------------


@jax.jit
def _dense_rank_impl(limbs: Tuple[jax.Array, ...], valid: jax.Array):
    n = valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    sorted_ops = lax.sort([inv, *limbs, iota], num_keys=1 + len(limbs))
    perm = sorted_ops[-1]
    valid_sorted = sorted_ops[0] == 0
    changed = jnp.zeros(n, dtype=bool)
    for limb_sorted in sorted_ops[1:-1]:
        changed = changed | (limb_sorted != jnp.roll(limb_sorted, 1))
    starts = valid_sorted & (changed | (iota == 0))
    ranks_sorted = jnp.cumsum(starts.astype(jnp.int32)) - 1
    ranks_sorted = jnp.maximum(ranks_sorted, 0)
    num = jnp.max(jnp.where(valid_sorted, ranks_sorted, -1)) + 1
    ranks = jnp.zeros(n, dtype=jnp.int32).at[perm].set(ranks_sorted)
    return ranks, num


def dense_rank(limbs: Sequence[jax.Array], valid: jax.Array):
    """Dense 0..k-1 ids such that two valid rows share an id iff their key limbs
    are equal.  Invalid rows get an arbitrary id; callers must mask."""
    return _dense_rank_impl(tuple(limbs), valid)


# ---------------------------------------------------------------------------
# group-by aggregate
# ---------------------------------------------------------------------------

AGG_OPS = ("sum", "count", "min", "max", "mean", "first")


@functools.partial(jax.jit, static_argnames=("ops",))
def sorted_groupby(limbs: Tuple[jax.Array, ...], arrays: Tuple[jax.Array, ...],
                   ops: Tuple[str, ...], valid: jax.Array):
    """Group-by-aggregate in sorted segment order.

    One multi-operand sort, then segment reductions over CONTIGUOUS segments
    (indices_are_sorted=True) — this avoids random-order scatter-adds, which
    serialize badly on TPU.  Returns (agg_outputs, counts, rep_indices, num):
    outputs indexed by dense rank, `rep` maps rank -> an original row index
    holding the group's key values."""
    n = valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    sorted_ops = lax.sort([inv, *limbs, iota], num_keys=1 + len(limbs))
    perm = sorted_ops[-1]
    valid_s = sorted_ops[0] == 0
    changed = jnp.zeros(n, dtype=bool)
    for limb_sorted in sorted_ops[1:-1]:
        changed = changed | (limb_sorted != jnp.roll(limb_sorted, 1))
    starts = valid_s & (changed | (iota == 0))
    ranks_sorted = jnp.maximum(jnp.cumsum(starts.astype(jnp.int32)) - 1, 0)
    num = jnp.max(jnp.where(valid_s, ranks_sorted, -1)) + 1
    counts = jax.ops.segment_sum(
        valid_s.astype(jnp.int32), ranks_sorted, num_segments=n, indices_are_sorted=True
    )
    rep = jax.ops.segment_min(
        jnp.where(valid_s, perm, n - 1), ranks_sorted, num_segments=n,
        indices_are_sorted=True,
    )
    outs = []
    for arr, op in zip(arrays, ops):
        arr_s = arr[perm]
        if op == "count":
            if jnp.issubdtype(arr.dtype, jnp.floating):
                c = jax.ops.segment_sum(
                    (valid_s & ~jnp.isnan(arr_s)).astype(jnp.int32),
                    ranks_sorted, num_segments=n, indices_are_sorted=True,
                )
            else:
                c = counts
            outs.append(c)
        elif op == "sum":
            x = jnp.where(valid_s, arr_s, jnp.zeros((), arr.dtype))
            outs.append(jax.ops.segment_sum(x, ranks_sorted, num_segments=n,
                                            indices_are_sorted=True))
        elif op == "mean":
            x = jnp.where(valid_s, arr_s, jnp.zeros((), arr.dtype))
            s = jax.ops.segment_sum(x, ranks_sorted, num_segments=n,
                                    indices_are_sorted=True)
            outs.append(s / jnp.maximum(counts, 1).astype(s.dtype))
        elif op == "min":
            x = jnp.where(valid_s, arr_s, _max_sentinel(arr.dtype))
            outs.append(jax.ops.segment_min(x, ranks_sorted, num_segments=n,
                                            indices_are_sorted=True))
        elif op == "max":
            x = jnp.where(valid_s, arr_s, _min_sentinel(arr.dtype))
            outs.append(jax.ops.segment_max(x, ranks_sorted, num_segments=n,
                                            indices_are_sorted=True))
        elif op == "first":
            outs.append(arr[rep])
        else:
            raise ValueError(f"unknown agg {op}")
    return tuple(outs), counts, rep, num


def _segment_aggs_body(ranks, valid, arrays: Tuple[jax.Array, ...],
                       ops: Tuple[str, ...]):
    n = ranks.shape[0]
    outs = []
    counts = jax.ops.segment_sum(valid.astype(jnp.int32), ranks, num_segments=n)
    iota = jnp.arange(n, dtype=jnp.int32)
    rep = jnp.full(n, n - 1, dtype=jnp.int32).at[ranks].min(jnp.where(valid, iota, n - 1))
    for arr, op in zip(arrays, ops):
        if op == "count":
            if arr is not None and jnp.issubdtype(arr.dtype, jnp.floating):
                c = jax.ops.segment_sum(
                    (valid & ~jnp.isnan(arr)).astype(jnp.int32), ranks, num_segments=n
                )
            else:
                c = counts
            outs.append(c)
        elif op == "sum":
            x = jnp.where(valid, arr, jnp.zeros((), arr.dtype))
            outs.append(jax.ops.segment_sum(x, ranks, num_segments=n))
        elif op == "mean":
            x = jnp.where(valid, arr, jnp.zeros((), arr.dtype))
            s = jax.ops.segment_sum(x, ranks, num_segments=n)
            outs.append(s / jnp.maximum(counts, 1).astype(s.dtype))
        elif op == "min":
            big = _max_sentinel(arr.dtype)
            x = jnp.where(valid, arr, big)
            outs.append(jax.ops.segment_min(x, ranks, num_segments=n))
        elif op == "max":
            small = _min_sentinel(arr.dtype)
            x = jnp.where(valid, arr, small)
            outs.append(jax.ops.segment_max(x, ranks, num_segments=n))
        elif op == "first":
            outs.append(arr[rep])
        else:
            raise ValueError(f"unknown agg {op}")
    return outs, counts, rep


_segment_aggs_jit = functools.partial(jax.jit, static_argnames=("ops",))(
    _segment_aggs_body
)


def _segment_aggs(ranks, valid, arrays, ops):
    """Jitted at top level, plain body while tracing (see
    hashtable._in_trace for the dispatch-race rationale)."""
    fn = _segment_aggs_body if hashtable._in_trace() else _segment_aggs_jit
    return fn(ranks, valid, tuple(arrays), tuple(ops))


def _max_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)


def _min_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype)


def groupby_limbs(limbs: Tuple[jax.Array, ...], arrays: Tuple[jax.Array, ...],
                  ops: Tuple[str, ...], valid: jax.Array):
    """Group rows by key limbs: the single strategy-dispatch point for every
    group-by consumer (here, FusedPartialAgg).  The per-backend matrix
    (ops/strategy.py) picks hash table vs multi-operand sort; hash_groupby
    itself records a sort fallback when the insert diverges."""
    from quokka_tpu.ops import strategy as kstrategy

    if kstrategy.choice("groupby") == "hashtable":
        return hashtable.hash_groupby(tuple(limbs), arrays, ops, valid)
    kstrategy.note_used("groupby", "sort")
    return sorted_groupby(tuple(limbs), arrays, ops, valid)


def groupby_aggregate(
    batch: DeviceBatch,
    keys: Sequence[str],
    aggs: Sequence[Tuple[str, str, Optional[jax.Array]]],
) -> DeviceBatch:
    """aggs: list of (output_name, op, input_array_or_None_for_count).
    Returns a grouped batch (padded to input size; compact() to shrink)."""
    n = batch.padded_len
    arrays = tuple(
        a if a is not None else jnp.zeros(n, dtype=jnp.int32) for (_, _, a) in aggs
    )
    ops = tuple(op for (_, op, _) in aggs)
    if keys:
        limbs = key_limbs(batch, keys)
        outs, counts, rep, num = groupby_limbs(tuple(limbs), arrays, ops, batch.valid)
    else:
        ranks = jnp.zeros(n, dtype=jnp.int32)
        num = jnp.minimum(jnp.sum(batch.valid), 1).astype(jnp.int32)
        outs, counts, rep = _segment_aggs(ranks, batch.valid, arrays, ops)

    cols = gather_columns({k: batch.columns[k] for k in keys}, rep)
    for (name, _, _), arr in zip(aggs, outs):
        cols[name] = NumCol(arr, "f" if jnp.issubdtype(arr.dtype, jnp.floating) else "i")
    group_valid = jnp.arange(n) < num
    return DeviceBatch(cols, group_valid, None, None).note_count(num)


def distinct(batch: DeviceBatch, keys: Sequence[str]) -> DeviceBatch:
    g = groupby_aggregate(batch, list(keys), [])
    return g.select(list(keys))


# ---------------------------------------------------------------------------
# sort / top-k
# ---------------------------------------------------------------------------


@jax.jit
def _sort_perm(limbs: Tuple[jax.Array, ...], valid: jax.Array):
    n = valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    out = lax.sort([inv, *limbs, iota], num_keys=1 + len(limbs))
    return out[-1]


def sort_batch(batch: DeviceBatch, by: Sequence[str], descending=None) -> DeviceBatch:
    limbs = sort_limbs(batch, by, descending)
    perm = _aot("sort_perm", _sort_perm, (tuple(limbs), batch.valid))
    out = batch.take(perm, batch.valid, batch.nrows)
    # valid rows are now contiguous at the front; derive the mask on device
    # (a host count here would cost a full round trip per sort) and start the
    # count's async host copy so a later compact/head is sync-free
    out.valid, n = _aot("prefix_mask", _prefix_mask, (batch.valid,))
    out.nrows = batch.nrows
    out.sorted_by = list(by)
    return out.note_count(n)


@jax.jit
def _prefix_mask(valid):
    n = jnp.sum(valid.astype(jnp.int32))
    return jnp.arange(valid.shape[0], dtype=jnp.int32) < n, n


def top_k(batch: DeviceBatch, by: Sequence[str], k: int, descending=None) -> DeviceBatch:
    s = sort_batch(batch, by, descending)
    return head(s, k)


# ---------------------------------------------------------------------------
# hash partition (shuffle)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_parts",))
def _partition_ids(limbs: Tuple[jax.Array, ...], n_parts: int):
    h = jnp.zeros(limbs[0].shape[0], dtype=jnp.uint32)
    for limb in limbs:
        if jnp.issubdtype(limb.dtype, jnp.floating):
            limb = limb.astype(jnp.int32)
        elif limb.dtype == jnp.bool_:
            limb = limb.astype(jnp.int32)
        u = limb.astype(jnp.uint32) if limb.dtype != jnp.int64 else limb.astype(jnp.uint32)
        h = h * jnp.uint32(0x9E3779B1) + u
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
    return (h % jnp.uint32(n_parts)).astype(jnp.int32)


def partition_ids(batch: DeviceBatch, keys: Sequence[str], n_parts: int) -> jax.Array:
    limbs = key_limbs(batch, keys)
    return _aot("partition_ids", _partition_ids, (tuple(limbs),), (n_parts,))


@functools.partial(jax.jit, static_argnames=("n_parts",))
def _split_masks(part_ids, valid, n_parts: int):
    """ONE dispatch producing every partition's validity mask plus its live
    count (the masked-split fast path used to dispatch one apply_mask kernel
    per partition)."""
    masks = tuple((part_ids == p) & valid for p in range(n_parts))
    counts = tuple(jnp.sum(m.astype(jnp.int32)) for m in masks)
    return masks, counts


@functools.partial(jax.jit, static_argnames=("n_parts",))
def _partition_plan(part_ids, valid, n_parts: int):
    """ONE dispatch planning a compacted split: a stable permutation grouping
    valid rows by partition id (invalid rows last), per-partition counts and
    start offsets.  Every partition is then a window of ``perm`` — no
    per-partition nonzero scans over the full batch."""
    n = valid.shape[0]
    pid = jnp.where(valid, part_ids, jnp.int32(n_parts))
    counts = jnp.bincount(pid, length=n_parts + 1)[:n_parts]
    iota = jnp.arange(n, dtype=jnp.int32)
    perm = lax.sort([pid.astype(jnp.int32), iota], num_keys=2)[-1]
    offsets = jnp.cumsum(counts) - counts
    return perm, counts, offsets


@functools.partial(jax.jit, static_argnames=("out_size",))
def _part_window(perm, offset, count, out_size: int):
    """Row indices + validity of one partition's window of the plan perm."""
    pos = offset + jnp.arange(out_size, dtype=jnp.int32)
    idx = perm[jnp.clip(pos, 0, perm.shape[0] - 1)]
    return idx, jnp.arange(out_size, dtype=jnp.int32) < count


# Per-query attribution for push-path host syncs: the engine enters a scope
# carrying its ONCE-RESOLVED per-query counter (a creating registry lookup
# here would resurrect a GC'd per-query instrument after TaskGraph.cleanup,
# and diffing the global counter would cross-attribute concurrent queries).
_SYNC_SCOPE = threading.local()


@contextlib.contextmanager
def shuffle_sync_scope(counter):
    prev = getattr(_SYNC_SCOPE, "counter", None)
    _SYNC_SCOPE.counter = counter
    try:
        yield
    finally:
        _SYNC_SCOPE.counter = prev


def _shuffle_sync() -> None:
    """Count a blocking host readback on the shuffle path (the shuffle-smoke
    sentinel asserts this stays flat in steady state)."""
    from quokka_tpu import obs

    obs.REGISTRY.counter("shuffle.host_syncs").inc()
    c = getattr(_SYNC_SCOPE, "counter", None)
    if c is not None:
        c.inc()


def split_by_partition(batch: DeviceBatch, part_ids: jax.Array, n_parts: int,
                       compact: Optional[bool] = None):
    """Split a batch into n per-partition batches.

    Default (masked) mode: parts are VIEWS over the parent's column arrays —
    one fused kernel produces every partition's mask and live count, columns
    are shared (no copies, no gathers) and the counts' host copies start
    asynchronously (note_count), so the push path pays ZERO blocking host
    syncs.  Consumers compact/concat when the counts have long landed.

    Compacted mode (``compact=True``, or auto past SHUFFLE_MASKED_CAP total
    padded rows): one segmented-sort plan kernel groups rows by partition,
    then each partition is a window-gather at its own bucket — n_parts
    window gathers instead of n_parts full-batch nonzero scans, and ONE
    counts readback whose async host copy starts at plan dispatch.  Buckets
    are UNIFORM across partitions when skew allows, so every downstream
    consumer sees one shape per split instead of one per partition."""
    if n_parts == 1:
        return [batch]
    if compact is None:
        from quokka_tpu.ops import strategy as kstrategy

        if kstrategy.choice("shuffle") == "compacted":
            # calibrated-compacted backends still skip the plan kernel on
            # small batches, where its counts readback dominates
            compact = batch.padded_len > (1 << 16)
        else:
            compact = (batch.padded_len > (1 << 16)
                       and n_parts * batch.padded_len > config.SHUFFLE_MASKED_CAP)
        kstrategy.note_used("shuffle", "compacted" if compact else "masked")
    if not compact:
        masks, counts = _aot("split_masks", _split_masks,
                             (part_ids, batch.valid), (n_parts,))
        return [
            DeviceBatch(batch.columns, m, None, batch.sorted_by).note_count(c)
            for m, c in zip(masks, counts)
        ]
    perm, counts, offsets = _aot("partition_plan", _partition_plan,
                                 (part_ids, batch.valid), (n_parts,))
    with contextlib.suppress(Exception):  # numpy-backed arrays lack it
        counts.copy_to_host_async()
    _shuffle_sync()
    host_counts = np.asarray(counts)  # overlaps the plan kernel's execution
    max_count = int(host_counts.max()) if n_parts else 0
    uniform = config.bucket_size(max_count)
    total = int(host_counts.sum())
    # uniform buckets collapse the downstream shape space to ONE per split;
    # skewed splits fall back to per-partition buckets so device memory
    # stays proportional to the data
    use_uniform = n_parts * uniform <= 2 * config.bucket_size(max(total, 1))
    out = []
    for p in range(n_parts):
        cnt = int(host_counts[p])
        padded = uniform if use_uniform else config.bucket_size(cnt)
        idx, valid = _aot("part_window", _part_window,
                          (perm, offsets[p], counts[p]), (padded,))
        out.append(batch.take(idx, valid, cnt))
    return out


# ---------------------------------------------------------------------------
# whole-batch reductions
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("op",))
def reduce_array(arr: jax.Array, valid: jax.Array, op: str):
    if op == "sum":
        return jnp.sum(jnp.where(valid, arr, jnp.zeros((), arr.dtype)))
    if op == "count":
        return jnp.sum(valid.astype(jnp.int64 if config.x64_enabled() else jnp.int32))
    if op == "min":
        return jnp.min(jnp.where(valid, arr, _max_sentinel(arr.dtype)))
    if op == "max":
        return jnp.max(jnp.where(valid, arr, _min_sentinel(arr.dtype)))
    raise ValueError(op)
