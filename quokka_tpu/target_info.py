"""Per-edge routing spec: how one operator's output reaches another.

Equivalent of the reference's TargetInfo + Partitioner taxonomy
(pyquokka/target_info.py:4-72).  A TargetInfo hangs on every logical-plan edge
and carries: the partitioner, a post-operator predicate, a projection, and
batch functions folded in by the optimizer.  At lowering time the runtime turns
it into a concrete device partition function (predicate mask -> batch_funcs ->
partition -> projection, same order as pyquokka/core.py:300-313).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence

from quokka_tpu.expression import Expr


class Partitioner:
    pass


@dataclasses.dataclass
class PassThroughPartitioner(Partitioner):
    """Source channel i feeds target channel i % n (no data movement when
    channel counts match)."""


@dataclasses.dataclass
class BroadcastPartitioner(Partitioner):
    """Every batch goes to every target channel."""


@dataclasses.dataclass
class HashPartitioner(Partitioner):
    keys: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class RangePartitioner(Partitioner):
    key: str = ""
    boundaries: List = dataclasses.field(default_factory=list)  # n-1 split points
    descending: bool = False  # channel 0 owns the HIGHEST range when set


@dataclasses.dataclass
class FunctionPartitioner(Partitioner):
    fn: Optional[Callable] = None  # fn(batch, src_channel, num_target_channels) -> {ch: batch}


@dataclasses.dataclass
class TargetInfo:
    partitioner: Partitioner
    predicate: Optional[Expr] = None
    projection: Optional[Sequence[str]] = None
    batch_funcs: List[Callable] = dataclasses.field(default_factory=list)

    def and_predicate(self, pred: Expr) -> "TargetInfo":
        from quokka_tpu.expression import conjoin

        newp = pred if self.predicate is None else conjoin([self.predicate, pred])
        return TargetInfo(self.partitioner, newp, self.projection, list(self.batch_funcs))
