"""quokka-tpu: a TPU-native, push-based, pipelined distributed query engine.

Capabilities modeled on marsupialtail/quokka (see SURVEY.md): a lazy
Polars-like DataStream API over a streaming task runtime with lineage-based
fault tolerance — with per-batch columnar compute rebuilt as JAX/XLA kernels
on TPU instead of Polars/DuckDB on CPU.
"""

__version__ = "0.1.0"

from quokka_tpu.context import QuokkaContext
from quokka_tpu.datastream import DataStream, GroupedDataStream, OrderedStream
from quokka_tpu.expression import col, date, interval, lit, when
from quokka_tpu.runtime.placement import (
    CustomChannelsStrategy,
    DatasetStrategy,
    PlacementStrategy,
    SingleChannelStrategy,
    TaggedCustomChannelsStrategy,
)


def __getattr__(name):
    # lazy: the query service pulls in threading/admission machinery most
    # one-shot users never touch
    if name in ("QueryService", "QueryHandle"):
        from quokka_tpu import service

        return getattr(service, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

