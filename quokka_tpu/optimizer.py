"""Logical-plan optimizer.

Pass lineup mirrors the reference driver (pyquokka/df.py:887-907):
  1. push_filters      — predicate pushdown per CNF conjunct, through
                         projections/maps/joins down into source readers
                         (df.py:1029-1139 + parquet pushdown)
  2. early_projection  — column-requirement analysis; prunes the column set
                         each source actually reads (df.py:1141-1262)
  3. choose_broadcast  — catalog-estimated small build sides switch their
                         shuffle join to a broadcast join (the cardinality
                         role of df.py:1401-1513's join ordering)
Stage assignment (df.py:1530-1621) runs afterwards in context._assign_stages.
All passes are pure rewrites of the node dict; nodes a rewrite disconnects
are garbage-collected between passes (pass_pipeline), so the dict always
holds exactly the live graph.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from quokka_tpu import logical
from quokka_tpu.expression import Expr, conjoin, rename_columns, split_conjuncts, substitute_columns

BROADCAST_THRESHOLD = 65_536  # build rows below this skip the probe-side shuffle


def pass_pipeline(exec_channels: int = 2):
    """The canonical pass lineup as (name, fn(sub, sink_id)) pairs — the
    unit of pass-level verification (analysis/planck.py) and of the plan
    fuzzer's pass-subset differential (analysis/planfuzz.py)."""
    def wrap(fn):
        def run(sub, sid):
            fn(sub, sid)
            # rewrites leave disconnected leftovers behind (a pushed
            # filter's original node); collect them so the plan dict holds
            # exactly the live graph — EXPLAIN and the plan verifier scan it
            live = _reachable(sub, sid)
            for nid in set(sub) - set(live):
                del sub[nid]
            # structural passes may stale interior schemas (a swapped
            # filter, a pruned source): re-derive so declared stays exact
            _recompute_schemas(sub, live)

        return run

    # cost-fed passes live in planner/decide.py; imported lazily because
    # decide consumes this module's chain-walk and catalog helpers
    from quokka_tpu.planner import decide

    return [
        (name, wrap(fn))
        for name, fn in [
            ("push_filters", push_filters),
            ("early_projection", early_projection),
            ("reorder_joins", decide.reorder_joins_cost),
            ("choose_broadcast", decide.choose_broadcast_cost),
            ("size_channels",
             lambda sub, sid: decide.size_channels(sub, sid, exec_channels)),
            ("plan_adaptive_exchanges", decide.plan_adaptive_exchanges),
            ("plan_parallel_sorts",
             lambda sub, sid: plan_parallel_sorts(sub, sid, exec_channels)),
            ("push_ann", push_ann),
            ("fold_maps", fold_maps),
            ("fuse_stages", fuse_stages),
        ]
    ]


def optimize(sub: Dict[int, logical.Node], sink_id: int,
             exec_channels: int = 2) -> int:
    """Run the full pass pipeline.  Under QK_PLAN_VERIFY=1 every pass's
    (before, after) pair is checked against the plan invariants QK021-QK024;
    a violation raises PlanInvariantError naming the pass and the offending
    node (never on the push path — this is all plan-time)."""
    from quokka_tpu.analysis import planck

    verify = planck.enabled()
    if verify:
        planck.verify_plan(sub, sink_id, where="pre-optimize")
    for name, fn in pass_pipeline(exec_channels):
        before = planck.digest(sub, sink_id) if verify else None
        fn(sub, sink_id)
        if verify:
            planck.verify_pass(sub, sink_id, name, before)
    if verify:
        planck.finish_plan()
    return sink_id


def push_ann(sub: Dict[int, logical.Node], sink_id: int) -> None:
    """Approximate nearest-neighbor pushdown (df.py:1264-1352 push_ann):
    an opted-in nearest_neighbors over an IVF-indexed Parquet source prunes
    the scan to row groups owning the queries' closest cells."""
    # readers are shared with the user's plan object: reset first so a prior
    # approximate query can't leak pruning into a later exact one
    for nid in _reachable(sub, sink_id):
        node = sub[nid]
        if isinstance(node, logical.SourceNode) and hasattr(node.reader, "ann_prune"):
            node.reader.ann_prune = None
    cons = _consumers(sub, sink_id)
    for nid in _reachable(sub, sink_id):
        node = sub[nid]
        info = getattr(node, "ann_info", None)
        if info is None:
            continue
        # the walked chain (including the source) must feed ONLY this ANN
        # branch — pruning a shared source would drop rows from exact branches
        cur_id = node.parents[0]
        ok = True
        guard = 0
        while guard < 16:
            guard += 1
            if len(cons.get(cur_id, [])) > 1:
                ok = False
                break
            cur = sub[cur_id]
            if isinstance(cur, (logical.ProjectionNode, logical.FilterNode)):
                cur_id = cur.parents[0]
                continue
            break
        if not ok:
            continue
        cur = sub[cur_id]
        if isinstance(cur, logical.SourceNode) and hasattr(cur.reader, "ann_prune"):
            cur.reader.ann_prune = (info["queries"], info["nprobe"])


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _consumers(sub: Dict[int, logical.Node], sink_id: int) -> Dict[int, List[int]]:
    cons: Dict[int, List[int]] = {nid: [] for nid in _reachable(sub, sink_id)}
    for nid in list(cons):
        for p in sub[nid].parents:
            cons[p].append(nid)
    return cons


def _reachable(sub: Dict[int, logical.Node], sink_id: int) -> List[int]:
    out, seen = [], set()

    def rec(nid):
        if nid in seen:
            return
        seen.add(nid)
        for p in sub[nid].parents:
            rec(p)
        out.append(nid)

    rec(sink_id)
    return out


def _relink(sub, sink_id, old: int, new: int) -> None:
    """Point every consumer of `old` at `new` (removing `old` from the plan)."""
    for nid in _reachable(sub, sink_id):
        node = sub[nid]
        node.parents = [new if p == old else p for p in node.parents]


# ---------------------------------------------------------------------------
# 1. predicate pushdown
# ---------------------------------------------------------------------------


def push_filters(sub: Dict[int, logical.Node], sink_id: int) -> None:
    changed = True
    rounds = 0
    while changed and rounds < 100:
        changed = False
        rounds += 1
        for nid in _reachable(sub, sink_id):
            node = sub.get(nid)
            if not isinstance(node, logical.FilterNode):
                continue
            cons = _consumers(sub, sink_id)
            if not cons.get(nid):
                continue  # a root filter cannot be removed after its push
            parent = sub[node.parents[0]]
            if _try_push_one(sub, sink_id, nid, node, node.parents[0], parent, cons):
                changed = True
                break


def _try_push_one(sub, sink_id, fid, fnode, pid, parent, cons) -> bool:
    pred = fnode.predicate
    parent_shared = len(cons.get(pid, [])) > 1

    if isinstance(parent, logical.FilterNode):
        parent_pred = parent.predicate
        if parent_shared:
            return False
        fnode.predicate = conjoin([parent_pred, pred])
        fnode.parents = list(parent.parents)
        return True

    if isinstance(parent, logical.SourceNode):
        if parent_shared:
            return False
        parent.predicate = (
            pred if parent.predicate is None else conjoin([parent.predicate, pred])
        )
        _relink(sub, sink_id, fid, pid)
        return True

    if isinstance(parent, (logical.ProjectionNode, logical.SortNode, logical.DistinctNode)):
        if parent_shared:
            return False
        # swap: filter below, parent above.  The filter now sees the
        # parent's INPUT: inherit that node's order metadata (pushing below
        # a sort means the filter's input is no longer sorted — QK024)
        fnode.parents = list(parent.parents)
        fnode.sorted_by = _copy_order(sub[fnode.parents[0]])
        parent.parents = [fid]
        _relink_except(sub, sink_id, fid, pid, skip=pid)
        return True

    if isinstance(parent, logical.MapNode) and parent.exprs is not None:
        if parent_shared:
            return False
        new_pred = substitute_columns(pred, parent.exprs)
        fnode.predicate = new_pred
        fnode.parents = list(parent.parents)
        fnode.sorted_by = _copy_order(sub[fnode.parents[0]])
        parent.parents = [fid]
        _relink_except(sub, sink_id, fid, pid, skip=pid)
        return True

    if isinstance(parent, logical.JoinNode):
        left_schema = set(sub[parent.parents[0]].schema)
        right = sub[parent.parents[1]]
        rename = parent.rename or {}
        unsuffix = {}
        for c in right.schema:
            if c in set(parent.right_on):
                continue
            unsuffix[rename.get(c, c)] = c
        remaining = []
        pushed = False
        for conj in split_conjuncts(pred):
            req = conj.required_columns()
            if req <= left_schema and parent.how in ("inner", "left", "semi", "anti"):
                _insert_filter_above(sub, parent, 0, conj)
                pushed = True
            elif req <= set(unsuffix) and parent.how == "inner":
                _insert_filter_above(sub, parent, 1, rename_columns(conj, unsuffix))
                pushed = True
            else:
                remaining.append(conj)
        if not pushed:
            return False
        if remaining:
            fnode.predicate = conjoin(remaining)
        else:
            _relink(sub, sink_id, fid, pid)
        return True

    return False


def _copy_order(node: logical.Node):
    return list(node.sorted_by) if node.sorted_by is not None else None


def _relink_except(sub, sink_id, fid, pid, skip):
    """After swapping filter below `pid`: consumers of fid (other than pid)
    should now consume pid."""
    for nid in _reachable(sub, sink_id):
        if nid in (fid, skip):
            continue
        node = sub[nid]
        node.parents = [pid if p == fid else p for p in node.parents]


def _insert_filter_above(sub, join_node: logical.JoinNode, side: int, conj: Expr):
    parent_id = join_node.parents[side]
    new_id = max(sub) + 1
    sub[new_id] = logical.FilterNode([parent_id], list(sub[parent_id].schema), conj)
    join_node.parents[side] = new_id


# ---------------------------------------------------------------------------
# 2. early projection
# ---------------------------------------------------------------------------


def early_projection(sub: Dict[int, logical.Node], sink_id: int) -> None:
    order = _reachable(sub, sink_id)
    req: Dict[int, Set[str]] = {nid: set() for nid in order}
    req[sink_id] = set(sub[sink_id].schema)
    for nid in reversed(order):
        node = sub[nid]
        need = req[nid] | set()
        if isinstance(node, logical.SinkNode):
            need = set(node.schema)
        # a with_columns output nobody consumes must be PRUNED, not just
        # skipped in the requirement walk: the runtime map computes every
        # expr it carries, so its inputs would otherwise need to survive
        # source pruning (planfuzz-found: dead expr over a pruned column)
        if isinstance(node, logical.MapNode) and node.exprs is not None \
                and any(k not in need for k in node.exprs):
            node.exprs = {k: e for k, e in node.exprs.items() if k in need}
            node.fn = logical.WithColumnsFn(node.exprs)
        for i, pid in enumerate(node.parents):
            req[pid] |= _needed_from_parent(sub, node, i, need)
    for nid in order:
        node = sub[nid]
        if isinstance(node, logical.SourceNode):
            keep = set(req[nid])
            if node.predicate is not None:
                keep |= node.predicate.required_columns()
            # a sorted source's order columns stay readable: downstream
            # ordered operators key off them and the plan invariant
            # (QK024) requires sorted_by ⊆ schema
            keep |= set(node.sorted_by or [])
            needed = [c for c in node.schema if c in keep]
            if 0 < len(needed) < len(node.schema):
                node.projection = needed
                node.schema = needed
    _recompute_schemas(sub, order)


def _recompute_schemas(sub: Dict[int, logical.Node], order: List[int]) -> None:
    """Re-derive interior output schemas after source pruning, so every
    node's declared schema stays EXACTLY what the runtime will produce
    (planck QK021 checks declared == derived).  Nodes whose declared schema
    is the source of truth (sources, opaque UDFs) return None and keep it;
    a derivation error here is left for the plan verifier to report."""
    for nid in order:
        node = sub[nid]
        if not node.parents:
            continue
        try:
            d = node.derive_schema([list(sub[p].schema) for p in node.parents])
        except (ValueError, KeyError):
            continue
        if d is not None:
            node.schema = d


def _needed_from_parent(sub, node: logical.Node, i: int, need: Set[str]) -> Set[str]:
    parent_schema = set(sub[node.parents[i]].schema)
    if isinstance(node, logical.FilterNode):
        return (need | node.predicate.required_columns()) & parent_schema
    if isinstance(node, logical.ProjectionNode):
        return set(node.schema) & parent_schema
    if isinstance(node, logical.MapNode):
        if node.exprs is None:
            return parent_schema  # opaque UDF: keep everything
        out = set()
        for c in need:
            if c in node.exprs:
                out |= node.exprs[c].required_columns()
            else:
                out.add(c)
        return out & parent_schema
    if isinstance(node, logical.AggNode):
        out = set(node.keys)
        for _, e in node.plan.pre:
            out |= e.required_columns()
        return out & parent_schema
    if isinstance(node, logical.JoinNode):
        if i == 0:
            return ((need & parent_schema) | set(node.left_on)) & parent_schema
        right = sub[node.parents[1]]
        rename = node.rename or {}
        out = set(node.right_on)
        for c in right.schema:
            if rename.get(c, c) in need:
                out.add(c)
        return out & parent_schema
    if isinstance(node, (logical.SortNode, logical.TopKNode)):
        return (need | set(node.by)) & parent_schema
    if isinstance(node, logical.DistinctNode):
        return set(node.keys) & parent_schema
    if isinstance(node, logical.StatefulNode):
        return parent_schema
    return need & parent_schema if need else parent_schema


# ---------------------------------------------------------------------------
# 3. broadcast join selection
# ---------------------------------------------------------------------------


_CATALOG = None


def _get_catalog():
    from quokka_tpu.catalog import Catalog

    global _CATALOG
    if _CATALOG is None:
        _CATALOG = Catalog()
    return _CATALOG


def choose_broadcast(sub: Dict[int, logical.Node], sink_id: int) -> None:
    cat = _get_catalog()
    for nid in _reachable(sub, sink_id):
        node = sub[nid]
        if not isinstance(node, logical.JoinNode) or node.broadcast:
            continue
        if node.how not in ("inner", "semi", "anti", "left"):
            continue
        est = _estimate_subtree(sub, node.parents[1], cat)
        if est is not None and est <= BROADCAST_THRESHOLD:
            node.broadcast = True


def fold_maps(sub: Dict[int, logical.Node], sink_id: int) -> None:
    """Fold expression-only MapNodes into their consumer edges
    (df.py:1354-1399 fold_map): instead of a separate actor hop, the map runs
    as a TargetInfo.batch_func inside the producer's partition function
    (engine executes batch_funcs at push time, runtime/engine.py).  Safe only
    when the map's parent has no OTHER consumer — the map rides every edge
    leaving the parent's actor."""
    cons = _consumers(sub, sink_id)
    for nid in _reachable(sub, sink_id):
        node = sub.get(nid)
        if not isinstance(node, logical.MapNode) or node.exprs is None:
            continue
        if getattr(node, "folded", False):
            continue
        pid = node.parents[0]
        if len(cons.get(pid, [])) != 1:
            continue
        parent = sub[pid]
        if isinstance(parent, logical.SourceNode):
            continue  # the source predicate path already fuses; keep readers lean
        node.folded = True


def _fusible_member(node: logical.Node) -> bool:
    """May this node live inside a fused stage?  Non-blocking, streaming,
    unordered, placement-free operators only — exactly the set
    FusedStageNode.lower knows how to turn into in-stage steps."""
    if node.sorted_by is not None or node.placement is not None:
        return False
    return isinstance(node, (
        logical.FilterNode,
        logical.ProjectionNode,
        logical.MapNode,
        logical.JoinNode,
        logical.AggNode,
    ))


def fuse_stages(sub: Dict[int, logical.Node], sink_id: int) -> None:
    """Whole-stage fusion (ROADMAP item 1, ops/stagefuse.py): rewrite each
    maximal single-consumer linear chain of fusible operators into ONE
    FusedStageNode, so the whole chain runs inside one exec dispatch with no
    store round-trip between members.  Chain rules:

    - extension follows the consumer's MAIN input (parents[0]) only, and only
      while the producer has exactly one consumer;
    - a non-broadcast hash join may only HEAD a chain (its probe-side hash
      edge partitions the stage's stream 0); interior joins must be broadcast
      — a hash build mid-chain would need the probe re-partitioned by a
      different key than the stage's input edge delivers;
    - an AggNode terminates the chain (its partial half fuses in-stage, the
      final half stays a separate key-partitioned actor);
    - blocking operators (sort, top-k, distinct, sinks) and stateful/ordered
      nodes never fuse;
    - 1-member "chains" are left untouched.

    Runs LAST: it consumes the shapes the earlier passes settle (broadcast
    choices, folded maps, reordered joins).  QK_STAGE_FUSE=0 disables it.
    """
    from quokka_tpu import config

    if not config.stage_fuse_enabled():
        return
    cons = _consumers(sub, sink_id)
    absorbed: Set[int] = set()
    for nid in _reachable(sub, sink_id):
        if nid in absorbed:
            continue
        node = sub.get(nid)
        if node is None or not _fusible_member(node):
            continue
        if isinstance(node, logical.AggNode):
            continue  # terminal-only: an agg heads nothing
        members = [node]
        ids = [nid]
        cur = nid
        while True:
            c = cons.get(cur, [])
            if len(c) != 1:
                break
            nxt = sub[c[0]]
            if nxt.parents[0] != cur:
                break  # we feed a build side, not the main input
            if not _fusible_member(nxt):
                break
            if isinstance(nxt, logical.JoinNode) and not nxt.broadcast:
                break
            members.append(nxt)
            ids.append(c[0])
            cur = c[0]
            if isinstance(nxt, logical.AggNode):
                break
        if len(members) < 2:
            continue
        chans = {m.channels for m in members if m.channels is not None}
        if len(chans) > 1:
            continue  # members pinned to conflicting widths
        tail = members[-1]
        tail_id = ids[-1]
        parents = [members[0].parents[0]] + [
            m.parents[1] for m in members if isinstance(m, logical.JoinNode)
        ]
        fused = logical.FusedStageNode(members, parents, list(tail.schema))
        fused.channels = chans.pop() if chans else None
        # the tail's id survives so consumers' parent links stay valid
        sub[tail_id] = fused
        for i in ids[:-1]:
            del sub[i]
        absorbed.update(ids)
        cons = _consumers(sub, sink_id)


def unfuse_stages(sub: Dict[int, logical.Node]) -> Dict[int, logical.Node]:
    """Inverse of fuse_stages, for executors that lower logical nodes
    themselves (the mesh SPMD path): expand every FusedStageNode back into
    its member chain.  Fusion never rewrote the members' own parent links —
    member[i].parents[0] still names member[i-1]'s pre-fusion id and the
    tail kept its id — so the original graph is recoverable exactly.
    Returns a new dict; the caller's (fused) plan is untouched."""
    out = dict(sub)
    for nid, node in sub.items():
        if not isinstance(node, logical.FusedStageNode):
            continue
        ids = [m.parents[0] for m in node.members[1:]] + [nid]
        for mid, m in zip(ids, node.members):
            out[mid] = m
    return out


def reorder_joins(sub: Dict[int, logical.Node], sink_id: int,
                  estimate=None, on_reorder=None, basis_of=None) -> None:
    """Greedy cardinality ordering of left-deep inner-join chains
    (df.py:1401-1513 merged multi-joins + 1570-1594 ordering): collect the
    chain's build subtrees, estimate each, and re-attach them smallest-first
    subject to key availability (snowflake joins whose keys come from an
    earlier dimension keep their dependency order).  Only applies when no
    column renames are involved and payload names are globally unique, so
    output schemas are order-independent.

    ``estimate(nid) -> Optional[float]`` overrides the catalog sampler
    (planner/decide.py feeds cost-model figures through here);
    ``on_reorder(chain_ids, before, after, basis)`` observes each applied
    reorder, with ``basis_of(nid)`` labelling the estimates' provenance."""
    cat = _get_catalog()
    if estimate is None:
        estimate = lambda nid: _estimate_subtree(sub, nid, cat)  # noqa: E731
    cons = _consumers(sub, sink_id)

    def chain_join(nid) -> bool:
        n = sub.get(nid)
        return (
            isinstance(n, logical.JoinNode)
            and n.how == "inner"
            and not n.broadcast
            and not (n.rename or {})
        )

    for nid in _reachable(sub, sink_id):
        if not chain_join(nid):
            continue
        # only start from the TOP of a chain
        c = cons.get(nid, [])
        if (
            len(c) == 1
            and chain_join(c[0])
            and sub[c[0]].parents[0] == nid
        ):
            continue
        chain: List[int] = []  # top-down join node ids
        cur = nid
        while chain_join(cur):
            chain.append(cur)
            pid = sub[cur].parents[0]
            if not chain_join(pid) or len(cons.get(pid, [])) != 1:
                break
            cur = pid
        if len(chain) < 2:
            continue
        base_id = sub[chain[-1]].parents[0]
        base_schema = list(sub[base_id].schema)
        levels = []  # bottom-up original order
        names = set(base_schema)
        ok = True
        for jid in reversed(chain):
            j = sub[jid]
            payload = [c for c in sub[j.parents[1]].schema if c not in set(j.right_on)]
            if any(p in names for p in payload):
                ok = False
                break
            names |= set(payload)
            est = estimate(j.parents[1])
            if est is None:
                ok = False
                break
            levels.append({
                "build": j.parents[1], "left_on": list(j.left_on),
                "right_on": list(j.right_on), "payload": payload, "est": est,
            })
        if not ok:
            continue
        # greedy: among joins whose keys are available, take the smallest build
        avail = set(base_schema)
        remaining = levels[:]
        order = []
        while remaining:
            cands = [lv for lv in remaining if set(lv["left_on"]) <= avail]
            if not cands:
                order = None
                break
            pick = min(cands, key=lambda lv: lv["est"])
            order.append(pick)
            remaining.remove(pick)
            avail |= set(pick["payload"])
        if order is None or order == levels:
            continue
        if on_reorder is not None:
            basis = "sampled"
            if basis_of is not None:
                ranks = {"hint": 0, "sampled": 1, "measured": 2}
                basis = min((basis_of(lv["build"]) for lv in levels),
                            key=lambda b: ranks.get(b, 0))
            on_reorder(
                chain, [lv["build"] for lv in levels],
                [lv["build"] for lv in order], basis)
        # reuse the chain's node ids positionally (bottom-up) so the top node
        # keeps its id and consumers stay untouched
        prev_id, prev_schema = base_id, base_schema
        for jid, lv in zip(reversed(chain), order):
            j = sub[jid]
            j.parents = [prev_id, lv["build"]]
            j.left_on = lv["left_on"]
            j.right_on = lv["right_on"]
            j.schema = prev_schema + lv["payload"]
            prev_id, prev_schema = jid, list(j.schema)


def plan_parallel_sorts(sub: Dict[int, logical.Node], sink_id: int,
                        exec_channels: int) -> None:
    """Give global sorts range boundaries from a source sample so they run
    partitioned across channels instead of on one."""
    if exec_channels < 2:
        return
    _get_catalog()
    for nid in _reachable(sub, sink_id):
        node = sub[nid]
        if not isinstance(node, logical.SortNode) or node.boundaries is not None:
            continue
        if len(node.by) != 1:
            continue
        col = node.by[0]
        sample = _sample_subtree(sub, node.parents[0], _CATALOG)
        if sample is None or sample.num_rows < 4 * exec_channels:
            continue
        if col not in sample.column_names:
            continue
        import numpy as np
        import pyarrow as pa

        arr = sample.column(col)
        t = arr.type
        if not (pa.types.is_integer(t) or pa.types.is_floating(t)
                or pa.types.is_date32(t)):
            continue  # string/timestamp boundaries: single-channel fallback
        vals = arr.combine_chunks().drop_null().cast(
            pa.int64() if pa.types.is_date32(t) else t
        ).to_numpy(zero_copy_only=False)
        if pa.types.is_floating(t):
            vals = vals[~np.isnan(vals)]
        if len(vals) < 4 * exec_channels:
            continue
        qs = np.quantile(vals, [i / exec_channels for i in range(1, exec_channels)])
        if pa.types.is_integer(t) or pa.types.is_date32(t):
            qs = np.unique(qs.astype(np.int64))
        else:
            qs = np.unique(qs)
        # spread sanity: degenerate/clustered samples (all quantiles at one
        # extreme) would route everything to one channel — fall back instead
        if (
            len(qs) == exec_channels - 1
            and vals.min() < qs[0]
            and qs[-1] < vals.max()
        ):
            node.boundaries = qs.tolist()
            node.channels = exec_channels


def _sample_subtree(sub, nid: int, cat):
    """Sample rows flowing out of a Filter/Projection/Map chain over a source
    (applies the chain's predicates to the sample)."""
    node = sub[nid]
    preds = []
    guard = 0
    while guard < 64:
        guard += 1
        if isinstance(node, logical.SourceNode):
            sample = cat._sample(node.reader)
            if sample is None or sample.num_rows == 0:
                return None
            all_preds = preds + (
                [node.predicate] if node.predicate is not None else []
            )
            if all_preds:
                from quokka_tpu.ops import bridge, kernels
                from quokka_tpu.ops.expr_compile import evaluate_predicate

                # project down before bridging: the full schema may contain
                # columns the bridge can't represent (structs/lists) that the
                # query never touches
                import numpy as np
                import pyarrow as pa

                needed = set()
                for p in all_preds:
                    needed |= p.required_columns()
                keep = [c for c in sample.column_names if c in needed]
                try:
                    b = bridge.arrow_to_device(sample.select(keep))
                    for p in all_preds:
                        b = kernels.apply_mask(b, evaluate_predicate(p, b))
                    mask = np.asarray(b.valid)[: sample.num_rows]
                    sample = sample.filter(pa.array(mask))
                except Exception:
                    # sampling is advisory; any failure means "no estimate"
                    return None
            return sample
        if isinstance(node, logical.FilterNode):
            preds.append(node.predicate)
            node = sub[node.parents[0]]
            continue
        if isinstance(node, (logical.ProjectionNode, logical.MapNode)):
            node = sub[node.parents[0]]
            continue
        return None
    return None


def _estimate_subtree(sub, nid: int, cat) -> Optional[float]:
    """Estimate rows flowing out of a Filter/Projection/Map chain over one
    source; None when the shape is more complex."""
    node = sub[nid]
    preds: List[Expr] = []
    guard = 0
    while guard < 64:
        guard += 1
        if isinstance(node, logical.SourceNode):
            pred = conjoin(preds + ([node.predicate] if node.predicate is not None else []))
            return cat.estimate_source(node.reader, pred)
        if isinstance(node, logical.FilterNode):
            preds.append(node.predicate)
            node = sub[node.parents[0]]
            continue
        if isinstance(node, (logical.ProjectionNode, logical.MapNode)):
            node = sub[node.parents[0]]
            continue
        return None
    return None
