"""Logical-plan optimizer.

Pass lineup mirrors the reference driver (pyquokka/df.py:887-907): ANN
pushdown, predicate pushdown, early projection, map folding, join merge with
cardinality ordering, cardinality propagation, stage determination (stage
assignment lives in context._assign_stages).  Passes land incrementally; each
is a pure rewrite of the node dict.
"""

from __future__ import annotations

from typing import Dict

from quokka_tpu import logical


def optimize(sub: Dict[int, logical.Node], sink_id: int) -> int:
    """Rewrite the plan in place; returns the (possibly new) sink id."""
    return sink_id
