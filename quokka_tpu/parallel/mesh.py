"""Device-mesh parallel plane: ICI-collective shuffles and distributed
relational steps.

Where the reference shuffles through per-machine Arrow Flight servers over the
network (pyquokka/flight.py + core.py:324-371), quokka-tpu adds a second, much
faster path for device-resident data inside a pod slice: hash-partition rows
on-device and exchange them with a single XLA all_to_all over ICI, inside one
jitted shard_map program.  The host data plane remains for cross-slice / DCN
movement; this module is the intra-slice fast path and the multi-chip execution
model (channels == mesh shards — the reference's channel data-parallelism
mapped onto jax.sharding).

Everything here is static-shape: each device owns N local (padded) rows; a
shuffle exchanges P buckets of capacity C = N (a bucket from one device can
never exceed its local rows), so the program compiles once per (N, P, schema).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quokka_tpu import config
from quokka_tpu.analysis import compat


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Version-portable shard_map.  jax >= 0.5 exposes ``jax.shard_map``
    (replication-check knob named ``check_vma``); older jax ships it as
    ``jax.experimental.shard_map.shard_map`` with the same knob named
    ``check_rep``.  Every mesh program goes through this shim so the mesh
    layer works on both — a bare ``jax.shard_map`` call raises
    AttributeError on 0.4.x and silently disables the whole multichip
    plane."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental import shard_map as _sm  # jax < 0.5

    return _sm.shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=check_vma)


# ---------------------------------------------------------------------------
# collective hash shuffle (the ICI fast path)
# ---------------------------------------------------------------------------


def _hash_u32(limbs: Sequence[jax.Array]) -> jax.Array:
    h = jnp.zeros(limbs[0].shape[0], dtype=jnp.uint32)
    for limb in limbs:
        u = limb.astype(jnp.int32).astype(jnp.uint32)
        h = h * jnp.uint32(0x9E3779B1) + u
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
    return h


def _local_bucketize(cols: Tuple[jax.Array, ...], valid, key_idx, n_parts):
    """Sort local rows into P contiguous buckets of capacity N (static)."""
    n = valid.shape[0]
    limbs = [cols[i] for i in key_idx]
    pid = (_hash_u32(limbs) % jnp.uint32(n_parts)).astype(jnp.int32)
    pid = jnp.where(valid, pid, n_parts)  # invalid rows sort last
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = lax.sort([pid, iota], num_keys=1)
    perm = sorted_ops[1]
    pid_sorted = sorted_ops[0]
    # position of each row within its bucket
    counts = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), pid_sorted, num_segments=n_parts + 1
    )
    starts = jnp.cumsum(counts) - counts
    pos_in_bucket = iota - starts[pid_sorted]
    # scatter rows into [P, N] frames; invalid rows carry pid == n_parts which
    # is out of bounds and dropped (mode="drop") rather than clipped into the
    # last real partition
    frame_valid = jnp.zeros((n_parts, n), dtype=bool)
    frame_valid = frame_valid.at[pid_sorted, pos_in_bucket].set(True, mode="drop")
    out_cols = []
    for c in cols:
        cs = c[perm]
        frame = jnp.zeros((n_parts, n), dtype=c.dtype)
        frame = frame.at[pid_sorted, pos_in_bucket].set(cs, mode="drop")
        out_cols.append(frame)
    return tuple(out_cols), frame_valid


def collective_hash_shuffle(
    cols: Tuple[jax.Array, ...],
    valid: jax.Array,
    key_idx: Tuple[int, ...],
    axis: str = "dp",
):
    """Inside shard_map: redistribute rows so equal-key rows land on the same
    device.  Input: per-device local columns [N]; output: [P*N] padded local
    columns after an all_to_all over the mesh axis."""
    n_parts = compat.axis_size(axis)
    frames, frame_valid = _local_bucketize(cols, valid, key_idx, n_parts)
    out_cols = []
    for f in frames:
        got = lax.all_to_all(f, axis, split_axis=0, concat_axis=0, tiled=False)
        out_cols.append(got.reshape(-1))
    got_valid = lax.all_to_all(frame_valid, axis, split_axis=0, concat_axis=0)
    return tuple(out_cols), got_valid.reshape(-1)


# ---------------------------------------------------------------------------
# distributed relational steps (jit-able whole programs over a Mesh)
# ---------------------------------------------------------------------------


def distributed_groupby_step(
    mesh: Mesh,
    key_cols: int,
    val_ops: Tuple[str, ...],
    axis: str = "dp",
):
    """Jitted distributed group-by-aggregate: local partial agg -> all_to_all
    shuffle of partials by key hash -> final agg per device.  Built from the
    SAME kernel the embedded engine uses (ops/kernels.sorted_groupby) — the
    full-plan version of this (with carried key values, AggPlan decomposition,
    string keys) lives in parallel/mesh_exec.mesh_groupby, which is what
    QuokkaContext(mesh=...) executes."""
    from quokka_tpu.ops import kernels

    recombine = tuple("sum" if op == "count" else op for op in val_ops)

    def _grouped(keys, vals, ops, valid):
        n = valid.shape[0]
        outs, _, rep, num = kernels.sorted_groupby(tuple(keys), tuple(vals), ops, valid)
        gkeys = tuple(k[rep] for k in keys)
        return gkeys, tuple(outs), jnp.arange(n) < num

    def step(*arrays):
        keys = arrays[:key_cols]
        vals = arrays[key_cols : key_cols + len(val_ops)]
        valid = arrays[-1]
        gkeys, gvals, gvalid = _grouped(keys, vals, val_ops, valid)
        cols = tuple(gkeys) + tuple(gvals)
        key_idx = tuple(range(key_cols))
        shuf, shuf_valid = collective_hash_shuffle(cols, gvalid, key_idx, axis)
        fkeys, fvals, fvalid = _grouped(
            shuf[:key_cols], shuf[key_cols:], recombine, shuf_valid
        )
        return fkeys + fvals + (fvalid,)

    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(sharded)


def distributed_join_groupby_step(mesh: Mesh, axis: str = "dp"):
    """Distributed shuffle-join + psum reduction built from the engine's rank
    join kernel (ops/join._pk_match): two dp-sharded tables are key-shuffled
    (all_to_all), PK-joined per device, and the joined product is psum-reduced
    to a replicated scalar.  Full relational joins over a mesh run through
    parallel/mesh_exec.mesh_join."""
    from quokka_tpu.ops import join as join_ops

    def step(l_key, l_val, l_valid, r_key, r_val, r_valid):
        (lk, lv), lvalid = collective_hash_shuffle((l_key, l_val), l_valid, (0,), axis)
        (rk, rv), rvalid = collective_hash_shuffle((r_key, r_val), r_valid, (0,), axis)
        p = lk.shape[0]
        limbs = (jnp.concatenate([lk, rk.astype(lk.dtype)]),)
        valid = jnp.concatenate([lvalid, rvalid])
        build_idx, matched = join_ops._pk_match(limbs, valid, p)
        rv_matched = rv[build_idx]
        prod = jnp.where(matched, lv * rv_matched, 0.0)
        total = lax.psum(jnp.sum(prod), axis)
        rows = lax.psum(jnp.sum(matched.astype(jnp.int32)), axis)
        return total, rows

    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
