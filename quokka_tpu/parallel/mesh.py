"""Device-mesh parallel plane: ICI-collective shuffles and distributed
relational steps.

Where the reference shuffles through per-machine Arrow Flight servers over the
network (pyquokka/flight.py + core.py:324-371), quokka-tpu adds a second, much
faster path for device-resident data inside a pod slice: hash-partition rows
on-device and exchange them with a single XLA all_to_all over ICI, inside one
jitted shard_map program.  The host data plane remains for cross-slice / DCN
movement; this module is the intra-slice fast path and the multi-chip execution
model (channels == mesh shards — the reference's channel data-parallelism
mapped onto jax.sharding).

Everything here is static-shape: each device owns N local (padded) rows; a
shuffle exchanges P buckets of capacity C = N (a bucket from one device can
never exceed its local rows), so the program compiles once per (N, P, schema).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quokka_tpu import config


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


# ---------------------------------------------------------------------------
# collective hash shuffle (the ICI fast path)
# ---------------------------------------------------------------------------


def _hash_u32(limbs: Sequence[jax.Array]) -> jax.Array:
    h = jnp.zeros(limbs[0].shape[0], dtype=jnp.uint32)
    for limb in limbs:
        u = limb.astype(jnp.int32).astype(jnp.uint32)
        h = h * jnp.uint32(0x9E3779B1) + u
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
    return h


def _local_bucketize(cols: Tuple[jax.Array, ...], valid, key_idx, n_parts):
    """Sort local rows into P contiguous buckets of capacity N (static)."""
    n = valid.shape[0]
    limbs = [cols[i] for i in key_idx]
    pid = (_hash_u32(limbs) % jnp.uint32(n_parts)).astype(jnp.int32)
    pid = jnp.where(valid, pid, n_parts)  # invalid rows sort last
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = lax.sort([pid, iota], num_keys=1)
    perm = sorted_ops[1]
    pid_sorted = sorted_ops[0]
    # position of each row within its bucket
    counts = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), pid_sorted, num_segments=n_parts + 1
    )
    starts = jnp.cumsum(counts) - counts
    pos_in_bucket = iota - starts[pid_sorted]
    # scatter rows into [P, N] frames; invalid rows carry pid == n_parts which
    # is out of bounds and dropped (mode="drop") rather than clipped into the
    # last real partition
    frame_valid = jnp.zeros((n_parts, n), dtype=bool)
    frame_valid = frame_valid.at[pid_sorted, pos_in_bucket].set(True, mode="drop")
    out_cols = []
    for c in cols:
        cs = c[perm]
        frame = jnp.zeros((n_parts, n), dtype=c.dtype)
        frame = frame.at[pid_sorted, pos_in_bucket].set(cs, mode="drop")
        out_cols.append(frame)
    return tuple(out_cols), frame_valid


def collective_hash_shuffle(
    cols: Tuple[jax.Array, ...],
    valid: jax.Array,
    key_idx: Tuple[int, ...],
    axis: str = "dp",
):
    """Inside shard_map: redistribute rows so equal-key rows land on the same
    device.  Input: per-device local columns [N]; output: [P*N] padded local
    columns after an all_to_all over the mesh axis."""
    n_parts = lax.axis_size(axis)
    frames, frame_valid = _local_bucketize(cols, valid, key_idx, n_parts)
    out_cols = []
    for f in frames:
        got = lax.all_to_all(f, axis, split_axis=0, concat_axis=0, tiled=False)
        out_cols.append(got.reshape(-1))
    got_valid = lax.all_to_all(frame_valid, axis, split_axis=0, concat_axis=0)
    return tuple(out_cols), got_valid.reshape(-1)


# ---------------------------------------------------------------------------
# distributed relational steps (jit-able whole programs over a Mesh)
# ---------------------------------------------------------------------------


def _local_groupby(keys: Tuple[jax.Array, ...], vals: Tuple[jax.Array, ...],
                   ops: Tuple[str, ...], valid: jax.Array):
    """Local sort+segment groupby: returns (group keys, agg values, gvalid)
    padded to the local length."""
    n = valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    inv = (~valid).astype(jnp.int32)
    sorted_ops = lax.sort([inv, *keys, iota], num_keys=1 + len(keys))
    perm = sorted_ops[-1]
    valid_s = sorted_ops[0] == 0
    changed = jnp.zeros(n, dtype=bool)
    for ks in sorted_ops[1:-1]:
        changed = changed | (ks != jnp.roll(ks, 1))
    starts = valid_s & (changed | (iota == 0))
    ranks = jnp.maximum(jnp.cumsum(starts.astype(jnp.int32)) - 1, 0)
    num = jnp.max(jnp.where(valid_s, ranks, -1)) + 1
    outs = []
    for v, op in zip(vals, ops):
        vs = v[perm]
        if op == "sum":
            outs.append(jax.ops.segment_sum(jnp.where(valid_s, vs, 0), ranks, num_segments=n))
        elif op == "count":
            outs.append(jax.ops.segment_sum(valid_s.astype(vs.dtype), ranks, num_segments=n))
        elif op == "min":
            big = jnp.array(jnp.inf, vs.dtype) if jnp.issubdtype(vs.dtype, jnp.floating) else jnp.array(jnp.iinfo(vs.dtype).max, vs.dtype)
            outs.append(jax.ops.segment_min(jnp.where(valid_s, vs, big), ranks, num_segments=n))
        elif op == "max":
            small = jnp.array(-jnp.inf, vs.dtype) if jnp.issubdtype(vs.dtype, jnp.floating) else jnp.array(jnp.iinfo(vs.dtype).min, vs.dtype)
            outs.append(jax.ops.segment_max(jnp.where(valid_s, vs, small), ranks, num_segments=n))
        else:
            raise ValueError(op)
    rep = jnp.full(n, n - 1, jnp.int32).at[ranks].min(jnp.where(valid_s, iota, n - 1))
    gkeys = tuple(ks[rep] for ks in sorted_ops[1:-1])
    gvalid = jnp.arange(n) < num
    return gkeys, tuple(outs), gvalid


def distributed_groupby_step(
    mesh: Mesh,
    key_cols: int,
    val_ops: Tuple[str, ...],
    axis: str = "dp",
):
    """Build a jitted distributed group-by-aggregate:
    local partial agg -> all_to_all shuffle of partials by key hash ->
    final agg per device.  Input arrays are sharded [total_rows] over `axis`;
    outputs are the per-device final groups (sharded).
    This is the TPU execution of the engine's PartialAgg -> HashPartition ->
    FinalAgg plan (logical.AggNode.lower)."""

    recombine = tuple("sum" if op == "count" else op for op in val_ops)

    def step(*arrays):
        keys = arrays[:key_cols]
        vals = arrays[key_cols : key_cols + len(val_ops)]
        valid = arrays[-1]
        gkeys, gvals, gvalid = _local_groupby(keys, vals, val_ops, valid)
        cols = tuple(gkeys) + tuple(gvals)
        key_idx = tuple(range(key_cols))
        shuf, shuf_valid = collective_hash_shuffle(cols, gvalid, key_idx, axis)
        skeys = shuf[:key_cols]
        svals = shuf[key_cols:]
        fkeys, fvals, fvalid = _local_groupby(skeys, svals, recombine, shuf_valid)
        return fkeys + fvals + (fvalid,)

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(sharded)


def distributed_join_groupby_step(mesh: Mesh, axis: str = "dp"):
    """A full distributed query step exercising both collective shuffle
    patterns: two dp-sharded tables are key-shuffled (all_to_all), hash-joined
    per device (rank-based), and the join output partially aggregated, then
    psum-reduced to a replicated scalar.  This is the multi-chip shape of
    TPC-H Q3-style plans."""

    def step(l_key, l_val, l_valid, r_key, r_val, r_valid):
        (lk, lv), lvalid = collective_hash_shuffle((l_key, l_val), l_valid, (0,), axis)
        (rk, rv), rvalid = collective_hash_shuffle((r_key, r_val), r_valid, (0,), axis)
        # rank-based PK join (build = right)
        p = lk.shape[0]
        keys = jnp.concatenate([lk, rk])
        valid = jnp.concatenate([lvalid, rvalid])
        n = keys.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        inv = (~valid).astype(jnp.int32)
        s_inv, s_key, s_iota = lax.sort([inv, keys, iota], num_keys=2)
        valid_s = s_inv == 0
        changed = (s_key != jnp.roll(s_key, 1)) | (iota == 0)
        ranks_sorted = jnp.maximum(jnp.cumsum((valid_s & changed).astype(jnp.int32)) - 1, 0)
        ranks = jnp.zeros(n, jnp.int32).at[s_iota].set(ranks_sorted)
        rp, rb = ranks[:p], ranks[p:]
        vb = valid[p:]
        b = n - p
        iota_b = jnp.arange(b, dtype=jnp.int32)
        first = jnp.full(n, b, jnp.int32).at[rb].min(jnp.where(vb, iota_b, b))
        cnt = jax.ops.segment_sum(vb.astype(jnp.int32), rb, num_segments=n)
        matched = lvalid & (cnt[rp] > 0)
        rv_matched = rv[jnp.clip(first[rp], 0, b - 1)]
        prod = jnp.where(matched, lv * rv_matched, 0.0)
        total = lax.psum(jnp.sum(prod), axis)
        rows = lax.psum(jnp.sum(matched.astype(jnp.int32)), axis)
        return total, rows

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
