"""Mesh execution: run a logical plan SPMD over a jax device mesh.

This is the multi-chip execution path VERDICT r1 asked for: **channels ==
mesh shards**.  Where the embedded engine runs each exec channel serially in
one Python loop (runtime/engine.py) and the reference spreads channels across
Ray workers (pyquokka/quokka_runtime.py:314-368), here a whole query executes
as sharded array programs over a `jax.sharding.Mesh`:

- sources ingest to ONE global DeviceBatch whose rows are sharded over the
  mesh axis (global string dictionaries, so codes are comparable across
  shards);
- elementwise nodes (filter / projection / map) run as ordinary jnp programs
  — XLA propagates the row sharding, no collectives;
- group-bys and joins run as ONE `shard_map` program per stage: local
  partial work with the SAME kernels the embedded engine uses
  (ops/kernels.sorted_groupby, ops/join._pk_match), an ICI `all_to_all`
  key shuffle between them (parallel/mesh.collective_hash_shuffle);
- small root-level post-ops (final agg having/order/limit, sort, top-k)
  finish on the materialized result through the real executors.

Plans containing nodes outside this set raise MeshUnsupported and the caller
falls back to the embedded engine.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np
import pyarrow as pa
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quokka_tpu import config, logical
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops import join as join_ops
from quokka_tpu.ops.batch import (
    DeviceBatch, NumCol, StrCol, VecCol, _int_sentinel, key_limbs, with_nulls,
)
from quokka_tpu.ops.expr_compile import evaluate_predicate, evaluate_to_column
from quokka_tpu.parallel.mesh import collective_hash_shuffle, shard_map


class MeshUnsupported(Exception):
    """Plan shape the mesh path doesn't cover — caller falls back."""


class _EmptyResult(Exception):
    """A root stateful operator legitimately produced zero rows: the collect
    is empty — NOT a fallback (re-running the plan on the engine would
    duplicate any executor side effects)."""


# ---------------------------------------------------------------------------
# sharding helpers
# ---------------------------------------------------------------------------


def _shard_batch(batch: DeviceBatch, mesh: Mesh, axis: str) -> DeviceBatch:
    """Place a batch's arrays row-sharded over the mesh axis.  Padded lengths
    are powers of two (config.bucket_size) so they divide the axis size."""
    n_dev = mesh.shape[axis]
    padded = batch.padded_len
    if padded % n_dev:
        raise MeshUnsupported(f"padded len {padded} not divisible by {n_dev}")
    row = NamedSharding(mesh, P(axis))
    row2 = NamedSharding(mesh, P(axis, None))

    def put(a, two_d=False):
        return jax.device_put(a, row2 if two_d else row)

    cols = {}
    for name, c in batch.columns.items():
        if isinstance(c, StrCol):
            cols[name] = StrCol(put(c.codes), c.dictionary)
        elif isinstance(c, VecCol):
            cols[name] = VecCol(put(c.data, two_d=True))
        else:
            cols[name] = NumCol(
                put(c.data), c.kind,
                hi=None if c.hi is None else put(c.hi), unit=c.unit,
            )
    return DeviceBatch(cols, put(batch.valid), batch.nrows, batch.sorted_by)


def _materialize(batch: DeviceBatch) -> DeviceBatch:
    """Gather a sharded batch onto the default device (host-mediated)."""
    table = bridge.device_to_arrow(batch)
    return bridge.arrow_to_device(table, sorted_by=batch.sorted_by)


# ---------------------------------------------------------------------------
# column <-> array flattening (for shard_map signatures)
# ---------------------------------------------------------------------------


def _col_value_arrays(c) -> List[jax.Array]:
    if isinstance(c, StrCol):
        return [c.codes]
    if isinstance(c, VecCol):
        raise MeshUnsupported("vector column as shuffle payload")
    return [c.data] if c.hi is None else [c.hi, c.data]


def _rebuild_col(template, arrays: List[jax.Array]):
    if isinstance(template, StrCol):
        return StrCol(arrays[0], template.dictionary)
    if template.hi is not None:
        return NumCol(arrays[1], template.kind, hi=arrays[0], unit=template.unit)
    return NumCol(arrays[0], template.kind, unit=template.unit)


def _flatten_cols(batch: DeviceBatch, names: Sequence[str]):
    arrays: List[jax.Array] = []
    slices: List[Tuple[str, int, int]] = []
    for n in names:
        a = _col_value_arrays(batch.columns[n])
        slices.append((n, len(arrays), len(arrays) + len(a)))
        arrays.extend(a)
    return arrays, slices


# ---------------------------------------------------------------------------
# mesh group-by (one shard_map: local partial -> all_to_all -> local final)
# ---------------------------------------------------------------------------


def mesh_groupby(
    mesh: Mesh,
    axis: str,
    batch: DeviceBatch,
    keys: List[str],
    partials: List[Tuple[str, str, Optional[str]]],
    recombine_ops: List[str],
) -> DeviceBatch:
    """partials: (out_name, op, input_column|None).  Returns a sharded batch
    of unique groups carrying key columns + partial outputs (already
    recombined across shards)."""
    from quokka_tpu.ops import strategy as kstrategy

    kstrategy.note_used("groupby", "sort")  # mesh programs embed the sort kernel
    limbs = key_limbs(batch, keys)  # hash limbs: consistent across dictionaries
    nlimb = len(limbs)
    carried, slices = _flatten_cols(batch, keys)
    ncarry = len(carried)
    vals = [
        batch.columns[c].data if c is not None
        else jnp.zeros(batch.padded_len, jnp.int32)
        for (_, _, c) in partials
    ]
    pops = tuple(op for (_, op, _) in partials)
    rops = tuple(recombine_ops)

    def step(*arrs):
        lb = arrs[:nlimb]
        ca = arrs[nlimb:nlimb + ncarry]
        va = arrs[nlimb + ncarry:-1]
        valid = arrs[-1]
        n = valid.shape[0]
        pouts, _, rep, num = kernels.sorted_groupby(tuple(lb), tuple(va), pops, valid)
        glimbs = tuple(l[rep] for l in lb)
        gcarry = tuple(c[rep] for c in ca)
        gvalid = jnp.arange(n) < num
        cols = glimbs + gcarry + tuple(pouts)
        shuf, svalid = collective_hash_shuffle(cols, gvalid, tuple(range(nlimb)), axis)
        slb = shuf[:nlimb]
        sca = shuf[nlimb:nlimb + ncarry]
        sva = shuf[nlimb + ncarry:]
        fouts, _, rep2, num2 = kernels.sorted_groupby(tuple(slb), tuple(sva), rops, svalid)
        fcarry = tuple(c[rep2] for c in sca)
        fvalid = jnp.arange(svalid.shape[0]) < num2
        return fcarry + tuple(fouts) + (fvalid,)

    fn = jax.jit(
        shard_map(step, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    outs = fn(*limbs, *carried, *vals, batch.valid)
    fcarry = outs[:ncarry]
    fvals = outs[ncarry:-1]
    fvalid = outs[-1]
    cols = {}
    for name, lo, hi in slices:
        cols[name] = _rebuild_col(batch.columns[name], list(fcarry[lo:hi]))
    for (pname, _, _), arr in zip(partials, fvals):
        cols[pname] = NumCol(
            arr, "f" if jnp.issubdtype(arr.dtype, jnp.floating) else "i"
        )
    return DeviceBatch(cols, fvalid, None, None)


# ---------------------------------------------------------------------------
# mesh join (one shard_map: shuffle both sides -> local rank join)
# ---------------------------------------------------------------------------


MM_CAPACITY_FACTOR = 4  # per-device output cap = factor * local probe rows


def mesh_join(
    mesh: Mesh,
    axis: str,
    probe: DeviceBatch,
    build: DeviceBatch,
    left_on: List[str],
    right_on: List[str],
    how: str,
    payload: List[str],
    unique: bool = True,
) -> DeviceBatch:
    """Join over the mesh: both sides key-shuffled with one all_to_all each,
    then the embedded engine's rank-join kernels per shard.  unique=True uses
    the probe-aligned PK kernel (_pk_match); unique=False runs the
    many-to-many kernel with a STATIC per-device output capacity — overflow
    is psum-counted and raises MeshUnsupported so the caller falls back to
    the embedded engine (shapes inside shard_map cannot be data-dependent)."""
    from quokka_tpu.ops import strategy as kstrategy

    kstrategy.note_used("join_build", "sort")  # mesh joins are rank-based
    pl = key_limbs(probe, left_on)
    bl = key_limbs(build, right_on)
    if len(pl) != len(bl):
        raise MeshUnsupported("join key column types differ")
    nlimb = len(pl)
    p_carry, p_slices = _flatten_cols(probe, probe.names)
    b_carry, b_slices = _flatten_cols(build, payload)
    npc, nbc = len(p_carry), len(b_carry)
    p_keyok = join_ops._nonnull_valid(probe, left_on)
    b_keyok = join_ops._nonnull_valid(build, right_on)

    def step(*arrs):
        i = 0
        plimbs = arrs[i:i + nlimb]; i += nlimb
        pcar = arrs[i:i + npc]; i += npc
        pvalid, pok = arrs[i], arrs[i + 1]; i += 2
        blimbs = arrs[i:i + nlimb]; i += nlimb
        bcar = arrs[i:i + nbc]; i += nbc
        bvalid, bok = arrs[i], arrs[i + 1]
        pcols = plimbs + pcar + (pok,)
        bcols = blimbs + bcar + (bok,)
        ps, pv = collective_hash_shuffle(pcols, pvalid, tuple(range(nlimb)), axis)
        bs, bv = collective_hash_shuffle(bcols, bvalid, tuple(range(nlimb)), axis)
        spl, spc, spok = ps[:nlimb], ps[nlimb:-1], ps[-1]
        sbl, sbc, sbok = bs[:nlimb], bs[nlimb:-1], bs[-1]
        p = pv.shape[0]
        limbs = tuple(
            jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(spl, sbl)
        )
        valid = jnp.concatenate([pv & spok.astype(bool), bv & sbok.astype(bool)])
        if unique or how in ("semi", "anti"):
            # semi/anti only need per-probe match existence: the PK kernel's
            # matched mask is correct for duplicate build keys too
            build_idx, matched = join_ops._pk_match(limbs, valid, p)
            payload_g = tuple(c[build_idx] for c in sbc)
            return spc + payload_g + (pv, matched, jnp.zeros(1, jnp.int32))
        # many-to-many: static output capacity per device; overflow reported
        mc, total, offsets, bps, rp = join_ops.mm_plan_for(
            limbs, valid, p, how, probe_valid=pv
        )
        cap = p * MM_CAPACITY_FACTOR
        overflow = jnp.maximum(total - cap, 0).astype(jnp.int32).reshape(1)
        probe_idx, build_idx, out_valid = join_ops._mm_expand(
            mc, offsets, bps, rp, jnp.minimum(total, cap), cap
        )
        out_pc = tuple(c[probe_idx] for c in spc)
        payload_g = tuple(c[build_idx] for c in sbc)
        if how == "left":
            matched = ~join_ops.mm_unmatched(limbs, valid, p, probe_idx, mc)
        else:
            matched = jnp.ones(cap, dtype=bool)
        return out_pc + payload_g + (out_valid, matched, overflow)

    fn = jax.jit(
        shard_map(step, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    outs = fn(
        *pl, *p_carry, probe.valid, p_keyok,
        *bl, *b_carry, build.valid, b_keyok,
    )
    spc = outs[:npc]
    pay = outs[npc:npc + nbc]
    pvalid, matched, overflow = outs[-3], outs[-2], outs[-1]
    mm = not (unique or how in ("semi", "anti"))
    if mm and int(jnp.max(overflow)) > 0:
        raise MeshUnsupported(
            "mm join overflowed the static per-device capacity "
            f"({MM_CAPACITY_FACTOR}x local probe rows) — engine fallback"
        )
    cols = {}
    for name, lo, hi in p_slices:
        cols[name] = _rebuild_col(probe.columns[name], list(spc[lo:hi]))
    out = DeviceBatch(cols, pvalid, None, None)
    if how == "semi":
        return DeviceBatch(cols, pvalid & matched, None, None)
    if how == "anti":
        return DeviceBatch(cols, pvalid & ~matched, None, None)
    for name, lo, hi in b_slices:
        col = _rebuild_col(build.columns[name], list(pay[lo:hi]))
        if how == "left":
            col = with_nulls(col, ~matched)
        out = out.with_column(name, col)
    if how == "inner":
        if mm:
            return DeviceBatch(out.columns, pvalid, None, None)
        return DeviceBatch(out.columns, pvalid & matched, None, None)
    if how == "left":
        return DeviceBatch(out.columns, pvalid, None, None)
    raise MeshUnsupported(f"join how={how}")


# ---------------------------------------------------------------------------
# mesh asof join (shuffle both sides by `by` keys -> per-shard sort+scan)
# ---------------------------------------------------------------------------


def _side_time_limbs(col, other, direction: str) -> List[jax.Array]:
    """Per-side time arrays for the asof kernel, widened consistently with
    the OTHER side (mixed wide/narrow int pairs widen both — same rule as
    ops/asof.asof_join)."""
    from quokka_tpu.ops import timewide

    if col.hi is not None or other.hi is not None:
        limbs = timewide.widen_limbs(col)
        if direction == "forward":
            limbs = timewide.not_limbs(limbs)
        return list(limbs)
    d = col.data
    return [-d] if direction == "forward" else [d]


def mesh_asof(
    mesh: Mesh,
    axis: str,
    trades: DeviceBatch,
    quotes: DeviceBatch,
    left_on: str,
    right_on: str,
    left_by: List[str],
    right_by: List[str],
    payload: List[str],
    direction: str,
) -> DeviceBatch:
    """As-of join over the mesh: both sides key-shuffled by the `by` columns
    with one all_to_all each (equal-key groups land whole on one shard), then
    the embedded engine's data-parallel sort+scan asof kernel
    (ops/asof._asof_match) per shard.  Unmatched trades are dropped — the
    same default as the streaming SortedAsofExecutor (keep_unmatched=False,
    executors/ts_execs.py:210).

    The reference reaches the same layout by hash-partitioning channels on
    the symbol key and walking frontiers per channel
    (pyquokka/executors/ts_executors.py:324-383); here the per-shard match is
    one sort + one log-depth associative scan — no sequential walk."""
    from quokka_tpu.ops.asof import _asof_match
    from quokka_tpu.ops import strategy as kstrategy

    kstrategy.note_used("asof", "sort")  # per-shard sort+scan kernel
    if not left_by:
        raise MeshUnsupported("by-less asof join on mesh (no shuffle key)")
    tl = key_limbs(trades, left_by)
    ql = key_limbs(quotes, right_by)
    if len(tl) != len(ql):
        raise MeshUnsupported("asof by-key column types differ")
    nlimb = len(tl)
    tc, qc = trades.columns[left_on], quotes.columns[right_on]
    t_times = _side_time_limbs(tc, qc, direction)
    q_times = _side_time_limbs(qc, tc, direction)
    ntime = len(t_times)
    t_carry, t_slices = _flatten_cols(trades, trades.names)
    q_carry, q_slices = _flatten_cols(quotes, payload)
    ntc, nqc = len(t_carry), len(q_carry)
    # carried-array positions of the trade time column: the per-shard output
    # re-sorts on these raw (un-negated) limbs so each shard stays ascending
    # in time — the OrderedStream contract the streaming executor keeps per
    # channel (shard == channel)
    t_time_lo, t_time_hi = next(
        (lo, hi) for (name, lo, hi) in t_slices if name == left_on
    )

    def step(*arrs):
        i = 0
        tlimbs = arrs[i:i + nlimb]; i += nlimb
        tt = arrs[i:i + ntime]; i += ntime
        tcar = arrs[i:i + ntc]; i += ntc
        tvalid = arrs[i]; i += 1
        qlimbs = arrs[i:i + nlimb]; i += nlimb
        qt = arrs[i:i + ntime]; i += ntime
        qcar = arrs[i:i + nqc]; i += nqc
        qvalid = arrs[i]
        ts, tv = collective_hash_shuffle(
            tlimbs + tt + tcar, tvalid, tuple(range(nlimb)), axis
        )
        qs, qv = collective_hash_shuffle(
            qlimbs + qt + qcar, qvalid, tuple(range(nlimb)), axis
        )
        stl, stt, stc = ts[:nlimb], ts[nlimb:nlimb + ntime], ts[nlimb + ntime:]
        sql, sqt, sqc = qs[:nlimb], qs[nlimb:nlimb + ntime], qs[nlimb + ntime:]
        p = tv.shape[0]
        limbs = tuple(
            jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(stl, sql)
        )
        times = tuple(
            jnp.concatenate([a, b.astype(a.dtype)]) for a, b in zip(stt, sqt)
        )
        is_trade = jnp.concatenate(
            [jnp.ones(p, dtype=bool), jnp.zeros(qv.shape[0], dtype=bool)]
        )
        valid = jnp.concatenate([tv, qv])
        match_orig, matched = _asof_match(
            limbs, times, is_trade, valid, p,
            forward_ties=(direction == "forward"),
        )
        quote_idx = jnp.clip(match_orig - p, 0, qv.shape[0] - 1)
        pay = tuple(c[quote_idx] for c in sqc)
        # drop unmatched (SortedAsofExecutor's keep_unmatched=False default)
        # and restore per-shard time order, invalid rows last
        ovalid = tv & matched
        out_cols = stc + pay
        iota = jnp.arange(p, dtype=jnp.int32)
        inv = (~ovalid).astype(jnp.int32)
        tkeys = list(stc[t_time_lo:t_time_hi])
        sorted_ = lax.sort([inv, *tkeys, iota], num_keys=1 + len(tkeys))
        perm = sorted_[-1]
        return tuple(c[perm] for c in out_cols) + (sorted_[0] == 0,)

    fn = jax.jit(
        shard_map(step, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    outs = fn(*tl, *t_times, *t_carry, trades.valid,
              *ql, *q_times, *q_carry, quotes.valid)
    stc = outs[:ntc]
    pay = outs[ntc:ntc + nqc]
    ovalid = outs[-1]
    cols = {}
    for name, lo, hi in t_slices:
        cols[name] = _rebuild_col(trades.columns[name], list(stc[lo:hi]))
    out = DeviceBatch(cols, ovalid, None, None)
    for name, lo, hi in q_slices:
        col = _rebuild_col(quotes.columns[name], list(pay[lo:hi]))
        out = out.with_column(name, col)
    return out


# ---------------------------------------------------------------------------
# mesh window aggregation (window-id group-by in one shard_map)
# ---------------------------------------------------------------------------


def mesh_window_agg(
    mesh: Mesh,
    axis: str,
    batch: DeviceBatch,
    by: List[str],
    time_data: jax.Array,
    size: int,
    hop: int,
    partials: List[Tuple[str, str, Optional[str]]],
    recombine_ops: List[str],
) -> DeviceBatch:
    """Tumbling/hopping window aggregation over the mesh.  In a (bounded)
    batch execution a time window is just a computed group key: each row is
    replicated size//hop times onto its covering window ids INSIDE the
    shard_map (static factor), locally partial-aggregated, key-shuffled by
    (by..., window id) over ICI, and final-aggregated per shard — the same
    partial->shuffle->final discipline as mesh_groupby.  The streaming
    engine's HoppingWindowExecutor (executors/ts_execs.py:372-430) emits
    identical windows incrementally via watermarks; triggers only change
    WHEN windows emit, not their content, so the batch result matches both.
    Returns groups carrying by-columns + "__wid" + partial outputs."""
    k = max(1, size // hop)
    limbs = key_limbs(batch, by) if by else []
    nlimb = len(limbs)
    carried, slices = _flatten_cols(batch, by)
    ncarry = len(carried)
    vals = [
        batch.columns[c].data if c is not None
        else jnp.zeros(batch.padded_len, jnp.int32)
        for (_, _, c) in partials
    ]
    pops = tuple(op for (_, op, _) in partials)
    rops = tuple(recombine_ops)

    def step(*arrs):
        lb = arrs[:nlimb]
        t = arrs[nlimb]
        ca = arrs[nlimb + 1:nlimb + 1 + ncarry]
        va = arrs[nlimb + 1 + ncarry:-1]
        valid = arrs[-1]
        # replicate onto the k covering windows (same mask expression as
        # HoppingWindowExecutor._assign_windows)
        wids, oks = [], []
        for j in range(k):
            wid = t // hop - j
            ok = valid & (wid >= 0) & (t < (wid * hop + size)) & (t >= wid * hop)
            wids.append(wid.astype(jnp.int32))
            oks.append(ok)
        wid = jnp.concatenate(wids)
        rvalid = jnp.concatenate(oks)
        rep = lambda xs: tuple(jnp.concatenate([x] * k) for x in xs)  # noqa: E731
        rlb = rep(lb) + (wid,)
        rca = rep(ca)
        rva = rep(va)
        n = rvalid.shape[0]
        pouts, _, grep, num = kernels.sorted_groupby(rlb, rva, pops, rvalid)
        glimbs = tuple(l[grep] for l in rlb)
        gcarry = tuple(c[grep] for c in rca)
        gvalid = jnp.arange(n) < num
        cols = glimbs + gcarry + tuple(pouts)
        shuf, svalid = collective_hash_shuffle(
            cols, gvalid, tuple(range(nlimb + 1)), axis
        )
        slb = shuf[:nlimb + 1]
        sca = shuf[nlimb + 1:nlimb + 1 + ncarry]
        sva = shuf[nlimb + 1 + ncarry:]
        fouts, _, rep2, num2 = kernels.sorted_groupby(slb, sva, rops, svalid)
        fcarry = tuple(c[rep2] for c in sca)
        fwid = slb[nlimb][rep2]
        fvalid = jnp.arange(svalid.shape[0]) < num2
        return fcarry + (fwid,) + tuple(fouts) + (fvalid,)

    fn = jax.jit(
        shard_map(step, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    outs = fn(*limbs, time_data, *carried, *vals, batch.valid)
    fcarry = outs[:ncarry]
    fwid = outs[ncarry]
    fvals = outs[ncarry + 1:-1]
    fvalid = outs[-1]
    cols = {}
    for name, lo, hi in slices:
        cols[name] = _rebuild_col(batch.columns[name], list(fcarry[lo:hi]))
    cols["__wid"] = NumCol(fwid, "i")
    for (pname, _, _), arr in zip(partials, fvals):
        cols[pname] = NumCol(
            arr, "f" if jnp.issubdtype(arr.dtype, jnp.floating) else "i"
        )
    return DeviceBatch(cols, fvalid, None, None)


def _shuffle_sort_segments(limbs, tlimbs, carried, valid, axis: str):
    """Shared preamble of every per-key ordered mesh kernel (session /
    sliding / shift): key-hash shuffle -> per-shard stable sort by
    (validity, key limbs, time limbs) -> segment-boundary flags.

    Boundaries INCLUDE the valid->padding transition (the sort's validity
    operand participates in the change detection): the all_to_all zero-fills
    padding slots, so a trailing segment whose key limbs are genuinely
    all-zero would otherwise absorb the padding rows and positional window
    bounds (bisection past the segment end) would silently read them.

    Returns (perm, valid_s, klimbs_s, tlimbs_s, shuffled_carried, seg_flag)
    — carried arrays are SHUFFLED but not yet permuted (gather by `perm` as
    needed)."""
    nlimb = len(limbs)
    nt = len(tlimbs)
    shuf, svalid = collective_hash_shuffle(
        tuple(limbs) + tuple(tlimbs) + tuple(carried), valid,
        tuple(range(nlimb)), axis,
    )
    slb = shuf[:nlimb]
    stl = shuf[nlimb:nlimb + nt]
    sca = shuf[nlimb + nt:]
    p = svalid.shape[0]
    iota = jnp.arange(p, dtype=jnp.int32)
    inv = (~svalid).astype(jnp.int32)
    sorted_ = lax.sort([inv, *slb, *stl, iota], num_keys=1 + nlimb + nt)
    perm = sorted_[-1]
    valid_s = sorted_[0] == 0
    klimbs_s = tuple(sorted_[1:1 + nlimb])
    tlimbs_s = tuple(sorted_[1 + nlimb:1 + nlimb + nt])
    changed = jnp.zeros(p, dtype=bool)
    for l in (sorted_[0],) + klimbs_s:
        changed = changed | (l != jnp.roll(l, 1))
    seg_flag = changed | (iota == 0)
    return perm, valid_s, klimbs_s, tlimbs_s, sca, seg_flag


def _rebase_time(b: DeviceBatch, col, headroom: int, align: int = 1):
    """(narrow_col, tbase): exact int32 rebase when the time column is wide
    or holds int64 absolute values outside int32 window arithmetic — the
    _TimeRebase discipline shared by every mesh window/shift path.  Two
    device reductions + two scalar transfers; never a full-column gather."""
    from quokka_tpu.ops import timewide

    tbase = 0
    need = col.hi is not None
    mn = 0
    if (need or col.data.dtype == jnp.int64) and b.count_valid():
        mn = timewide.host_min_i64(col, b.valid)
        if not need:
            mx = timewide.host_max_i64(col, b.valid)
            need = mn <= -(2**31) or mx >= 2**31 - 1 - headroom
    if need:
        align = max(1, int(align))
        tbase = ((mn - 2**29) // align) * align
        col = timewide.rebase_narrow(col, b.valid, tbase, headroom=headroom)
    return col, tbase


# ---------------------------------------------------------------------------
# mesh session windows (shuffle by key -> per-shard sessionize + groupby)
# ---------------------------------------------------------------------------


def mesh_session_window(
    mesh: Mesh,
    axis: str,
    batch: DeviceBatch,
    by: List[str],
    time_data: jax.Array,
    timeout: int,
    partials: List[Tuple[str, str, Optional[str]]],
) -> DeviceBatch:
    """Gap-based session windows over the mesh: rows key-shuffle with one
    all_to_all, each shard sorts its complete key groups by time, flags a
    new session where the gap exceeds the timeout (same boundary rule as
    SessionWindowExecutor._sessionize, executors/ts_execs.py:505-530), and
    aggregates per (key, session id) locally — sessions are whole per shard,
    so no second shuffle or recombine pass is needed.  Returns groups
    carrying by-columns + "__first_t"/"__last_t" + partial outputs."""
    limbs = key_limbs(batch, by) if by else []
    nlimb = len(limbs)
    carried, slices = _flatten_cols(batch, by)
    ncarry = len(carried)
    vals = [
        batch.columns[c].data if c is not None
        else jnp.zeros(batch.padded_len, jnp.int32)
        for (_, _, c) in partials
    ]
    pops = tuple(op for (_, op, _) in partials) + ("min", "max")

    def step(*arrs):
        lb = arrs[:nlimb]
        t = arrs[nlimb]
        ca = arrs[nlimb + 1:nlimb + 1 + ncarry]
        va = arrs[nlimb + 1 + ncarry:-1]
        valid = arrs[-1]
        perm, valid_s, klimbs_s, (t_s,), shuffled, seg_flag = (
            _shuffle_sort_segments(lb, (t,), ca + tuple(va), valid, axis)
        )
        sca = shuffled[:ncarry]
        sva = shuffled[ncarry:]
        p = valid_s.shape[0]
        gap = t_s - jnp.roll(t_s, 1)
        new_sess = seg_flag | (gap > timeout)
        sess_id = jnp.cumsum(new_sess.astype(jnp.int32)) - 1
        va_s = tuple(a[perm] for a in sva)
        ca_s = tuple(c[perm] for c in sca)
        glimbs = klimbs_s + (sess_id,)
        outs, _, rep, num = kernels.sorted_groupby(
            glimbs, va_s + (t_s, t_s), pops, valid_s
        )
        gcarry = tuple(c[rep] for c in ca_s)
        gvalid = jnp.arange(p) < num
        return gcarry + tuple(outs) + (gvalid,)

    fn = jax.jit(
        shard_map(step, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    outs = fn(*limbs, time_data, *carried, *vals, batch.valid)
    gcarry = outs[:ncarry]
    pouts = outs[ncarry:-1]
    gvalid = outs[-1]
    cols = {}
    for name, lo, hi in slices:
        cols[name] = _rebuild_col(batch.columns[name], list(gcarry[lo:hi]))
    for (pname, _, _), arr in zip(partials, pouts[:-2]):
        cols[pname] = NumCol(
            arr, "f" if jnp.issubdtype(arr.dtype, jnp.floating) else "i"
        )
    cols["__first_t"] = NumCol(pouts[-2], "i")
    cols["__last_t"] = NumCol(pouts[-1], "i")
    return DeviceBatch(cols, gvalid, None, None)


# ---------------------------------------------------------------------------
# mesh sliding windows (shuffle by key -> per-shard rolling kernels)
# ---------------------------------------------------------------------------


def mesh_sliding_window(
    mesh: Mesh,
    axis: str,
    batch: DeviceBatch,
    by: List[str],
    time_data: jax.Array,
    size: int,
    partials: List[Tuple[str, str, Optional[str]]],
) -> Tuple[DeviceBatch, List[str]]:
    """Per-event trailing-window aggregates over the mesh: key-shuffle, then
    each shard runs the SAME rolling kernels as SlidingWindowExecutor
    (executors/ts_execs.py:638-686 — segmented bisection for window bounds,
    prefix sums for sum/count, sparse-table range queries for min/max) over
    its complete key groups.  Returns (per-event batch in per-shard
    key-major order, partial output names)."""
    from quokka_tpu.executors.ts_execs import (
        _bisect_left_segmented,
        _bisect_right_segmented,
        _max_fill,
        _min_fill,
        _range_minmax,
        _rows_from_segment_end,
    )
    from quokka_tpu.ops.asof import _seg_fill_forward

    if not by:
        raise MeshUnsupported("by-less sliding window on mesh")
    for _, op, _ in partials:
        if op not in ("sum", "count", "min", "max"):
            raise MeshUnsupported(f"sliding window op {op!r} on mesh")
    limbs = key_limbs(batch, by)
    nlimb = len(limbs)
    carried, slices = _flatten_cols(batch, batch.names)
    ncarry = len(carried)
    # value columns (incl. plan-pre temps) are already inside `carried`:
    # index them there instead of shuffling the same data twice.  count has
    # no input column (index -1, derived from validity inside the step).
    val_idx = []
    for (_, op, tmp) in partials:
        if tmp is None:
            val_idx.append(-1)
        else:
            col = batch.columns[tmp]
            if isinstance(col, (StrCol, VecCol)) or col.hi is not None:
                # wide ints span two limbs — the rolling kernels want one
                # array; fall back to the streaming executor instead of
                # crashing the query
                raise MeshUnsupported(
                    f"sliding window over non-narrow column {tmp!r} on mesh"
                )
            lo, hi = next((lo, hi) for (n2, lo, hi) in slices if n2 == tmp)
            val_idx.append(lo)
    pops = tuple(op for (_, op, _) in partials)
    count_dtype = jnp.float64 if config.x64_enabled() else jnp.float32

    def step(*arrs):
        i = 0
        lb = arrs[i:i + nlimb]; i += nlimb
        t_in = arrs[i]; i += 1
        ca = arrs[i:i + ncarry]; i += ncarry
        valid = arrs[-1]
        perm, valid_s, klimbs_s, (t_s,), sca, seg_flag = (
            _shuffle_sort_segments(lb, (t_in,), ca, valid, axis)
        )
        sva = tuple(sca[j] if j >= 0 else valid_s for j in val_idx)
        p = valid_s.shape[0]
        iota = jnp.arange(p, dtype=jnp.int32)
        seg_start = _seg_fill_forward(jnp.where(seg_flag, iota, -1), seg_flag)
        lo_t = t_s - size
        left = _bisect_left_segmented(t_s, lo_t, seg_start, iota)
        seg_end = iota + _rows_from_segment_end(iota, seg_flag, p)
        right = _bisect_right_segmented(t_s, t_s, iota, seg_end)
        outs = []
        for (pname, op, _), varr in zip(partials, sva):
            x_s = varr[perm]
            if op in ("min", "max"):
                fill = _max_fill(x_s.dtype) if op == "min" else _min_fill(x_s.dtype)
                x = jnp.where(valid_s, x_s, fill)
                outs.append(_range_minmax(x, left, right, op))
                continue
            if op == "count":
                x = valid_s.astype(count_dtype)
            else:
                x = jnp.where(valid_s, x_s, 0)
            cs = jnp.cumsum(x)
            before = jnp.where(left > 0, cs[jnp.maximum(left - 1, 0)], 0)
            outs.append(cs[right] - before)
        out_ca = tuple(c[perm] for c in sca)
        return out_ca + tuple(outs) + (valid_s,)

    fn = jax.jit(
        shard_map(step, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    outs = fn(*limbs, time_data, *carried, batch.valid)
    oca = outs[:ncarry]
    pouts = outs[ncarry:-1]
    ovalid = outs[-1]
    cols = {}
    for name, lo, hi in slices:
        cols[name] = _rebuild_col(batch.columns[name], list(oca[lo:hi]))
    out = DeviceBatch(cols, ovalid, None, None)
    pnames = []
    for (pname, _, _), arr in zip(partials, pouts):
        out = out.with_column(pname, NumCol(arr, "f"))
        pnames.append(pname)
    return out, pnames


# ---------------------------------------------------------------------------
# mesh shift (shuffle by key -> per-shard sort + segment lag)
# ---------------------------------------------------------------------------


def mesh_shift(
    mesh: Mesh,
    axis: str,
    batch: DeviceBatch,
    by: List[str],
    time_col: str,
    columns: List[str],
    n_lag: int,
) -> DeviceBatch:
    """Per-key lag over the mesh: rows key-shuffle with one all_to_all, then
    each shard sorts its (complete) key groups by (key, time) and takes the
    value n rows earlier within the key segment — the same segment
    formulation as the streaming ShiftExecutor
    (executors/ts_execs.py:716-757), without the cross-batch tail carry
    (each shard sees its keys whole).  Rows with no history get NULL
    (NaN for floats, the int sentinel otherwise); per-shard output is
    KEY-major (sorted by key limbs, then time), not globally time-ordered."""
    from quokka_tpu.ops import timewide
    from quokka_tpu.ops.asof import _seg_fill_forward

    if not by:
        raise MeshUnsupported("by-less shift on mesh (no shuffle key)")
    limbs = key_limbs(batch, by)
    nlimb = len(limbs)
    tc = batch.columns[time_col]
    if jnp.issubdtype(tc.data.dtype, jnp.floating):
        tlimbs = [tc.data]
    else:
        tlimbs = list(timewide.widen_limbs(tc))
    ntime = len(tlimbs)
    carried, slices = _flatten_cols(batch, batch.names)
    ncarry = len(carried)
    # shift sources are single narrow arrays already inside `carried` (the
    # rejection rules below guarantee one array per column): index them there
    # instead of shuffling the same data twice
    shift_idx = []
    shift_float = []
    for c in columns:
        col = batch.columns[c]
        if isinstance(col, (StrCol, VecCol)):
            raise MeshUnsupported(f"shift of non-numeric column {c!r} on mesh")
        if col.hi is not None or col.kind == "b":
            raise MeshUnsupported(
                f"shift of wide-int/bool column {c!r} on mesh"
            )
        lo, hi = next((lo, hi) for (n2, lo, hi) in slices if n2 == c)
        assert hi == lo + 1
        shift_idx.append(lo)
        shift_float.append(jnp.issubdtype(col.data.dtype, jnp.floating))

    def step(*arrs):
        i = 0
        lb = arrs[i:i + nlimb]; i += nlimb
        tl = arrs[i:i + ntime]; i += ntime
        ca = arrs[i:i + ncarry]; i += ncarry
        valid = arrs[i]
        perm, valid_s, _klimbs_s, _tl_s, sca, seg_flag = (
            _shuffle_sort_segments(lb, tl, ca, valid, axis)
        )
        ssv = tuple(sca[j] for j in shift_idx)
        p = valid_s.shape[0]
        iota = jnp.arange(p, dtype=jnp.int32)
        seg_start = _seg_fill_forward(
            jnp.where(seg_flag, iota, -1), seg_flag
        )
        src = iota - n_lag
        ok = src >= seg_start
        src = jnp.clip(src, 0, p - 1)
        out_ca = tuple(c[perm] for c in sca)
        shifted = []
        for arr, is_f in zip(ssv, shift_float):
            t = arr[perm][src]
            if is_f:
                t = jnp.where(ok, t, jnp.nan)
            else:
                # no-history rows get the int null sentinel (with_nulls
                # semantics — parity with the streaming ShiftExecutor)
                t = jnp.where(ok, t, _int_sentinel(t.dtype))
            shifted.append(t)
        return out_ca + tuple(shifted) + (valid_s,)

    fn = jax.jit(
        shard_map(step, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                      check_vma=False)
    )
    outs = fn(*limbs, *tlimbs, *carried, batch.valid)
    oca = outs[:ncarry]
    osh = outs[ncarry:-1]
    ovalid = outs[-1]
    cols = {}
    for name, lo, hi in slices:
        cols[name] = _rebuild_col(batch.columns[name], list(oca[lo:hi]))
    out = DeviceBatch(cols, ovalid, None, None)
    for c, arr, is_f in zip(columns, osh, shift_float):
        out = out.with_column(
            f"{c}_shifted_{n_lag}",
            NumCol(arr, batch.columns[c].kind, unit=batch.columns[c].unit),
        )
    return out


# ---------------------------------------------------------------------------
# plan walker
# ---------------------------------------------------------------------------


class MeshExecutor:
    def __init__(self, mesh: Mesh, axis: str = "dp"):
        self.mesh = mesh
        self.axis = axis

    SUPPORTED = (
        logical.SourceNode, logical.FilterNode, logical.ProjectionNode,
        logical.MapNode, logical.DistinctNode, logical.AggNode,
        logical.JoinNode, logical.SortNode, logical.TopKNode, logical.SinkNode,
        logical.AsofJoinNode, logical.WindowAggNode, logical.ShiftNode,
        logical.StatefulNode,
    )
    MAX_WINDOW_REPLICATION = 16

    def run_to_arrow(self, sub: Dict[int, logical.Node], sink_id: int) -> pa.Table:
        # pre-walk node TYPES so unsupported plans fall back before any work
        # runs (data-dependent bailouts like a non-unique join build side can
        # still abort mid-run and re-execute on the engine — unavoidable)
        from quokka_tpu import windows as W
        from quokka_tpu.optimizer import unfuse_stages

        # whole-stage fusion is an ENGINE-actor regrouping; the mesh lowers
        # logical nodes itself, so expand fused chains back to their members
        # (a copy — an engine fallback still runs the fused plan)
        sub = unfuse_stages(sub)
        for node in sub.values():
            if not isinstance(node, self.SUPPORTED):
                raise MeshUnsupported(f"node {type(node).__name__} on mesh")
            if isinstance(node, logical.AsofJoinNode) and not node.left_by:
                raise MeshUnsupported("by-less asof join on mesh")
            if isinstance(node, logical.ShiftNode) and not node.by:
                raise MeshUnsupported("by-less shift on mesh")
            if type(node) is logical.StatefulNode and len(node.parents) != 1:
                # generic stateful operators (CEP, user stateful_transform)
                # run as a single-device tail over the SPMD upstream — only
                # the single-input shape maps onto that
                raise MeshUnsupported(
                    "multi-input stateful operator on mesh"
                )
            if isinstance(node, logical.WindowAggNode):
                if isinstance(node.window, W.SessionWindow):
                    if not node.by:
                        raise MeshUnsupported(
                            "by-less session window on mesh (global timeline)"
                        )
                elif isinstance(node.window, W.SlidingWindow):
                    if not node.by:
                        raise MeshUnsupported(
                            "by-less sliding window on mesh (global timeline)"
                        )
                    if any(
                        op not in ("sum", "count", "min", "max")
                        for _, op, _ in node.plan.partials
                    ):
                        raise MeshUnsupported("sliding window op on mesh")
                elif not isinstance(
                    node.window, (W.TumblingWindow, W.HoppingWindow)
                ):
                    raise MeshUnsupported(
                        f"{type(node.window).__name__} on mesh"
                    )
                else:
                    hop = (
                        node.window.size
                        if isinstance(node.window, W.TumblingWindow)
                        else node.window.hop
                    )
                    # the replication factor is a STATIC in-program blowup of
                    # the whole sharded dataset (the streaming executor pays
                    # it only per bounded batch) — cap it and let the engine
                    # take fine-hopped windows
                    if node.window.size // max(1, hop) > self.MAX_WINDOW_REPLICATION:
                        raise MeshUnsupported(
                            f"hopping replication factor "
                            f"{node.window.size // hop} "
                            f"> {self.MAX_WINDOW_REPLICATION} on mesh"
                        )
            if isinstance(node, logical.JoinNode) and node.how not in (
                "inner", "left", "semi", "anti"
            ):
                raise MeshUnsupported(f"join how={node.how} on mesh")
        node = sub[sink_id]
        if isinstance(node, logical.SinkNode):
            sink_id = node.parents[0]
        self._root_nid = sink_id
        try:
            out = self._exec(sub, sink_id)
        except _EmptyResult:
            return None  # legitimately empty result set
        return bridge.device_to_arrow(out)  # gathers shards host-side

    def _compact_reshard(self, batch: DeviceBatch) -> DeviceBatch:
        """Shuffles pad per-device rows by the mesh size (P buckets of
        capacity N concatenate to P*N).  Chained stages would grow P^stages —
        compact back to the true row count and re-shard when inflated."""
        n = batch.count_valid()
        target = config.bucket_size(max(n, 1))
        if batch.padded_len <= 2 * target:
            return batch
        return _shard_batch(kernels.compact(batch), self.mesh, self.axis)

    def _exec(self, sub, nid) -> DeviceBatch:
        node = sub[nid]
        if isinstance(node, logical.SourceNode):
            return self._source(node)
        if isinstance(node, logical.FilterNode):
            b = self._exec(sub, node.parents[0])
            return kernels.apply_mask(b, evaluate_predicate(node.predicate, b))
        if isinstance(node, logical.ProjectionNode):
            b = self._exec(sub, node.parents[0])
            return b.select([c for c in node.schema if c in b.columns])
        if isinstance(node, logical.MapNode):
            b = self._exec(sub, node.parents[0])
            if node.exprs is not None:
                for name, e in node.exprs.items():
                    b = b.with_column(name, evaluate_to_column(e, b))
                return b.select([c for c in node.schema if c in b.columns])
            return node.fn(b)
        if isinstance(node, logical.DistinctNode):
            b = self._exec(sub, node.parents[0])
            g = mesh_groupby(self.mesh, self.axis, b, list(node.keys), [], [])
            return self._compact_reshard(g.select(list(node.keys)))
        if isinstance(node, logical.AggNode):
            return self._agg(sub, node)
        if isinstance(node, logical.AsofJoinNode):
            return self._asof(sub, node)
        if isinstance(node, logical.ShiftNode):
            b = self._exec(sub, node.parents[0])
            out = mesh_shift(
                self.mesh, self.axis, b, list(node.by), node.time_col,
                list(node.columns), node.n,
            )
            out = out.select([c for c in node.schema if c in out.columns])
            return self._compact_reshard(out)
        if isinstance(node, logical.WindowAggNode):
            return self._window(sub, node)
        if isinstance(node, logical.JoinNode):
            return self._join(sub, node)
        if isinstance(node, (logical.SortNode, logical.TopKNode)):
            # root-level order/limit: small after aggregation — finish on the
            # materialized (single-device) result with the embedded kernels
            b = _materialize(self._exec(sub, node.parents[0]))
            if isinstance(node, logical.TopKNode):
                return kernels.top_k(b, node.by, node.k, node.descending)
            return kernels.sort_batch(b, node.by, node.descending)
        if type(node) is logical.StatefulNode:
            # generic stateful operator (CEP pattern recognition, user
            # stateful_transform): the upstream plan stays SPMD; the
            # operator itself runs once over the materialized result — the
            # same single-device-tail discipline as root sort/top-k, and
            # semantically identical to exec_channels=1 on the engine
            b = _materialize(self._exec(sub, node.parents[0]))
            parent_sorted = getattr(sub[node.parents[0]], "sorted_by", None)
            if parent_sorted:
                # shuffling upstream ops leave shard-major order; restore
                # the time-order contract sorted stateful executors get
                # from the engine's ordered delivery
                b = kernels.sort_batch(b, list(parent_sorted),
                                       [False] * len(parent_sorted))
            executor = node.executor_factory()
            parts = []
            # full engine executor-driving contract: execute, then the
            # source-exhausted hook, then done — each may emit
            out = executor.execute([b], 0, 0)
            if out is not None:
                parts.append(out)
            sd = (
                executor.source_done(0, 0)
                if hasattr(executor, "source_done") else None
            )
            if sd is not None:
                parts.append(sd)
            fin = executor.done(0)
            if fin is not None:
                if isinstance(fin, DeviceBatch):
                    parts.append(fin)
                else:
                    parts.extend(x for x in fin if x is not None)
            if not parts:
                if nid == self._root_nid:
                    # a legitimately empty result (e.g. no CEP matches):
                    # surface as the empty collect, not an engine re-run
                    raise _EmptyResult()
                # mid-plan empties would need typed empty batches; fall
                # back (rare: an empty stateful feeding further operators)
                raise MeshUnsupported("empty mid-plan stateful output")
            out = parts[0] if len(parts) == 1 else bridge.concat_batches(parts)
            return out.select([c for c in node.schema if c in out.columns])
        raise MeshUnsupported(f"node {type(node).__name__} on mesh")

    def _source(self, node: logical.SourceNode) -> DeviceBatch:
        reader = node.reader
        state = reader.get_own_state(1)
        tables = [reader.execute(0, lineage) for lineage in state.get(0, [])]
        tables = [t for t in tables if t is not None]
        if not tables:
            raise MeshUnsupported("source produced no batches")
        table = pa.concat_tables(tables, promote_options="default")
        if node.predicate is not None:
            cols_needed = set(node.schema) | set(node.predicate.required_columns())
            keep = [c for c in table.column_names if c in cols_needed]
            table = table.select(keep)
        else:
            keep = [c for c in table.column_names if c in set(node.schema)]
            table = table.select(keep)
        batch = bridge.arrow_to_device(table, sorted_by=node.sorted_by)
        batch = _shard_batch(batch, self.mesh, self.axis)
        if node.predicate is not None:
            batch = kernels.apply_mask(batch, evaluate_predicate(node.predicate, batch))
            batch = batch.select([c for c in node.schema if c in batch.columns])
        return batch

    def _agg(self, sub, node: logical.AggNode) -> DeviceBatch:
        from quokka_tpu.executors.sql_execs import FinalAggExecutor

        b = self._exec(sub, node.parents[0])
        plan = node.plan
        for name, e in plan.pre:
            b = b.with_column(name, evaluate_to_column(e, b))
        partials = [(p, op, tmp) for (p, op, tmp) in plan.partials]
        recombine = [op for (_, op) in plan.recombine]
        if not node.keys:
            # keyless (whole-table) aggregate: plain jnp reductions over the
            # sharded arrays — XLA inserts the cross-shard collectives
            cols = {}
            for pname, op, tmp in partials:
                arr = (
                    b.columns[tmp].data if tmp is not None
                    else jnp.zeros(b.padded_len, jnp.int32)
                )
                red = kernels.reduce_array(arr, b.valid, op)
                cols[pname] = NumCol(
                    jnp.asarray(red).reshape(1),
                    "f" if jnp.issubdtype(red.dtype, jnp.floating) else "i",
                )
            g = DeviceBatch(cols, jnp.ones(1, dtype=bool), 1, None)
        else:
            g = mesh_groupby(
                self.mesh, self.axis, b, list(node.keys), partials, recombine
            )
        # finals / having / order / limit via the real executor on the (small)
        # materialized group set — recombining unique groups is the identity
        host = _materialize(g)
        fin = FinalAggExecutor(list(node.keys), plan, node.having,
                               node.order_by, node.limit)
        out = fin.execute([host], 0, 0)
        done = fin.done(0)
        parts = [x for x in (out, done) if x is not None]
        if not parts:
            raise MeshUnsupported("aggregation produced no output")
        return parts[0] if len(parts) == 1 else bridge.concat_batches(parts)

    def _asof(self, sub, node: logical.AsofJoinNode) -> DeviceBatch:
        trades = self._exec(sub, node.parents[0])
        quotes = self._exec(sub, node.parents[1])
        # payload naming mirrors OrderedStream.join_asof: quote columns other
        # than the by-keys and the time key, suffixed on collision
        rpayload = [
            c for c in quotes.names
            if c not in set(node.right_by) and c != node.right_on
        ]
        rename = {
            c: c + node.suffix for c in rpayload if c in set(trades.names)
        }
        if rename:
            quotes = quotes.rename(rename)
            rpayload = [rename.get(c, c) for c in rpayload]
        out = mesh_asof(
            self.mesh, self.axis, trades, quotes, node.left_on, node.right_on,
            list(node.left_by), list(node.right_by), rpayload, node.direction,
        )
        out = out.select([c for c in node.schema if c in out.columns])
        return self._compact_reshard(out)

    def _window(self, sub, node: logical.WindowAggNode) -> DeviceBatch:
        from quokka_tpu import windows as W
        from quokka_tpu.ops import timewide

        b = self._exec(sub, node.parents[0])
        plan = node.plan
        for name, e in plan.pre:
            b = b.with_column(name, evaluate_to_column(e, b))
        win = node.window
        if isinstance(win, W.SessionWindow):
            return self._session_window(node, b)
        if isinstance(win, W.SlidingWindow):
            return self._sliding_window(node, b)
        size = win.size
        hop = size if isinstance(win, W.TumblingWindow) else win.hop
        col = b.columns[node.time_col]
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            raise MeshUnsupported("float time column in mesh window")
        t_kind, t_unit = col.kind, col.unit
        # base aligned to the hop so absolute window boundaries stay
        # epoch-aligned
        col, tbase = _rebase_time(b, col, headroom=size + hop, align=hop)
        partials = [(p, op, tmp) for (p, op, tmp) in plan.partials]
        recombine = [op for (_, op) in plan.recombine]
        g = mesh_window_agg(
            self.mesh, self.axis, b, list(node.by), col.data, size, hop,
            partials, recombine,
        )
        # window bounds + finals on the (small) materialized group set
        host = _materialize(g)
        start = host.columns["__wid"].data * hop
        host = host.with_column(
            "window_start", timewide.add_base(start, tbase, t_kind, t_unit)
        )
        host = host.with_column(
            "window_end", timewide.add_base(start + size, tbase, t_kind, t_unit)
        )
        for name, e in plan.finals:
            host = host.with_column(name, evaluate_to_column(e, host))
        seen, out_cols = set(), []
        for c in node.by + ["window_start", "window_end"] + [
            n for n, _ in plan.finals
        ]:
            if c not in seen:
                seen.add(c)
                out_cols.append(c)
        # honor the node's declared sorted_output (windows emit ordered by
        # their start — same contract as the streaming executors)
        return kernels.sort_batch(host.select(out_cols), ["window_start"], [False])

    def _session_window(self, node: logical.WindowAggNode, b: DeviceBatch) -> DeviceBatch:
        from quokka_tpu.ops import timewide

        plan = node.plan
        timeout = node.window.timeout
        col = b.columns[node.time_col]
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            raise MeshUnsupported("float time column in mesh session window")
        t_kind, t_unit = col.kind, col.unit
        col, tbase = _rebase_time(b, col, headroom=int(timeout) + 1)
        partials = [(p, op, tmp) for (p, op, tmp) in plan.partials]
        g = mesh_session_window(
            self.mesh, self.axis, b, list(node.by), col.data, int(timeout),
            partials,
        )
        host = _materialize(g)
        host = host.rename(
            {"__first_t": "session_start", "__last_t": "session_end"}
        )
        for c in ("session_start", "session_end"):
            host = host.with_column(
                c, timewide.add_base(host.columns[c].data, tbase, t_kind, t_unit)
            )
        for name, e in plan.finals:
            host = host.with_column(name, evaluate_to_column(e, host))
        seen, out_cols = set(), []
        for c in node.by + ["session_start", "session_end"] + [
            n for n, _ in plan.finals
        ]:
            if c not in seen:
                seen.add(c)
                out_cols.append(c)
        return kernels.sort_batch(
            host.select(out_cols), ["session_start"], [False]
        )

    def _sliding_window(self, node: logical.WindowAggNode, b: DeviceBatch) -> DeviceBatch:
        from quokka_tpu.ops import timewide

        plan = node.plan
        size = int(node.window.size_before)
        col = b.columns[node.time_col]
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            raise MeshUnsupported("float time column in mesh sliding window")
        col, _tbase = _rebase_time(b, col, headroom=size + 1)
        # the ORIGINAL (absolute) time column rides in the carried set; only
        # the kernel's window arithmetic uses the rebased copy
        partials = [(p, op, tmp) for (p, op, tmp) in plan.partials]
        out, _pnames = mesh_sliding_window(
            self.mesh, self.axis, b, list(node.by), col.data, size, partials,
        )
        for name, e in plan.finals:
            out = out.with_column(name, evaluate_to_column(e, out))
        return out.select([c for c in node.schema if c in out.columns])

    def _join(self, sub, node: logical.JoinNode) -> DeviceBatch:
        probe = self._exec(sub, node.parents[0])
        build = self._exec(sub, node.parents[1])
        unique = join_ops.build_keys_unique(build, node.right_on)
        payload = [c for c in build.names if c not in set(node.right_on)]
        rename = node.rename or {
            c: c + node.suffix for c in payload if c in probe.columns
        }
        rename = {c: n for c, n in rename.items() if c in payload}
        if rename:
            build = build.rename(rename)
            payload = [rename.get(c, c) for c in payload]
        out = mesh_join(
            self.mesh, self.axis, probe, build,
            list(node.left_on), list(node.right_on), node.how, payload,
            unique=unique,
        )
        if node.how not in ("semi", "anti"):
            out = out.select([c for c in node.schema if c in out.columns])
        return self._compact_reshard(out)
