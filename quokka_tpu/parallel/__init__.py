from quokka_tpu.parallel.mesh import (
    collective_hash_shuffle,
    distributed_groupby_step,
    distributed_join_groupby_step,
    make_mesh,
)
