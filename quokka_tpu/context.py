"""QuokkaContext: the session object.

Reference role (pyquokka/df.py:14-134): owns the logical-plan node registry,
the read_* entry points, the optimizer driver, and lowering into the runtime.
In the embedded single-host deployment it builds a TaskGraph per executed sink;
cluster deployments swap the TaskGraph's store/cache for served ones.
"""

from __future__ import annotations

import copy
import logging
from typing import Dict, List, Optional

import pyarrow as pa

from quokka_tpu import config, logical
from quokka_tpu.datastream import DataStream, OrderedStream
from quokka_tpu.dataset.readers import (
    InputArrowDataset,
    InputCSVDataset,
    InputJSONDataset,
    InputParquetDataset,
)
from quokka_tpu.runtime.engine import TaskGraph, new_query_id

_log = logging.getLogger("quokka_tpu.mesh")


def _contains_agg(e) -> bool:
    from quokka_tpu.expression import Agg

    if isinstance(e, Agg):
        return True
    return any(_contains_agg(c) for c in e.children())


class QuokkaContext:
    def __init__(
        self,
        cluster=None,
        io_channels: int = 2,
        exec_channels: int = 2,
        exec_config: Optional[dict] = None,
        optimize: bool = True,
        mesh=None,
    ):
        self.cluster = cluster  # reserved for multi-host deployments
        # jax.sharding.Mesh: run supported plans SPMD with channels == shards
        # (parallel/mesh_exec.py); unsupported plans fall back to the engine
        self.mesh = mesh
        # reason string for the most recent mesh->engine fallback (None when
        # the last collect ran on the mesh); also logged as a warning
        self.last_mesh_fallback = None
        self.io_channels = io_channels
        self.exec_channels = exec_channels
        self.exec_config = dict(config.DEFAULT_EXEC_CONFIG)
        if exec_config:
            self.exec_config.update(exec_config)
        self.optimize_plans = optimize
        self.nodes: Dict[int, logical.Node] = {}
        self._next_node = 0
        self.latest_graph = None  # last executed TaskGraph (introspection)

    @property
    def cluster_workers(self) -> int:
        """Worker-process count placement strategies resolve against (1 for
        the embedded engine); externally-launched daemons (TPUPodCluster
        hosts) count as workers."""
        n = getattr(self.cluster, "n_workers", 0) if self.cluster else 0
        n += getattr(self.cluster, "external_workers", 0) if self.cluster else 0
        return max(1, n)

    @property
    def worker_tags(self):
        return getattr(self.cluster, "worker_tags", None) if self.cluster else None

    def set_config(self, key, value):
        self.exec_config[key] = value

    # -- plan registry --------------------------------------------------------
    def add_node(self, node: logical.Node) -> int:
        nid = self._next_node
        self.nodes[nid] = node
        self._next_node += 1
        return nid

    def new_stream(self, node: logical.Node, ordered: bool = False) -> DataStream:
        nid = self.add_node(node)
        return OrderedStream(self, nid) if ordered else DataStream(self, nid)

    # -- readers ---------------------------------------------------------------
    def read_parquet(self, path, columns=None) -> DataStream:
        if "://" in str(path):
            # object-store URL (s3://, gs://, file://, ...): fsspec byte-range
            # reader with the same row-group partitioning + stats pruning
            from quokka_tpu.dataset.cloud import InputObjectParquetDataset

            reader = InputObjectParquetDataset(path, columns=columns)
        else:
            reader = InputParquetDataset(path, columns=columns)
        schema = [f for f in reader.schema.names]
        if columns:
            schema = list(columns)
        return self.new_stream(logical.SourceNode(reader, schema))

    def read_iceberg(self, table_dir, snapshot_id=None, columns=None) -> DataStream:
        """Scan an Iceberg table directory (current snapshot, or any retained
        snapshot via snapshot_id for time travel).  The metadata walk
        (version json -> manifest-list avro -> manifest avro -> data files)
        runs in-repo (dataset/iceberg.py, dataset/avro.py — reference
        df.py:802 does this through pyiceberg); the resulting parquet list
        scans through the standard reader with row-group channels, stats
        pruning and the scan cache."""
        from quokka_tpu.dataset.iceberg import IcebergTable

        files = IcebergTable(str(table_dir)).data_files(snapshot_id)
        if not files:
            raise ValueError(f"iceberg snapshot of {table_dir} has no data files")
        reader = InputParquetDataset(files, columns=columns)
        schema = [f for f in reader.schema.names]
        if columns:
            schema = list(columns)
        return self.new_stream(logical.SourceNode(reader, schema))

    def read_csv(self, path, schema: Optional[List[str]] = None,
                 has_header: bool = True, sep: str = ",") -> DataStream:
        if "://" in str(path):
            from quokka_tpu.dataset.cloud import InputObjectCSVDataset

            obj = InputObjectCSVDataset(path, names=schema,
                                        has_header=has_header, sep=sep)
            return self.new_stream(logical.SourceNode(obj, list(obj.schema)))
        reader = InputCSVDataset(path, schema=schema, has_header=has_header, sep=sep)
        return self.new_stream(logical.SourceNode(reader, list(reader.schema.names)))

    def read_rest(self, requests_list, record_path=None, schema=None,
                  method: str = "get", headers=None) -> DataStream:
        """Paged REST endpoint: each (url, params) request is one lineage unit
        (reference crypto_dataset.py, GET and POST variants — method="post"
        sends params as the JSON body)."""
        from quokka_tpu.dataset.cloud import InputRestDataset

        reader = InputRestDataset(requests_list, record_path=record_path,
                                  schema=schema, method=method, headers=headers)
        return self.new_stream(logical.SourceNode(reader, list(reader.schema)))

    def read_files(self, path: str, files_per_batch: int = 1) -> DataStream:
        """Whole files as (filename, object) rows — unstructured blobs
        (reference InputDiskFilesDataset / InputS3FilesDataset,
        pyquokka/dataset/unordered_readers.py:206-272).  `path` may be a local
        directory, a glob, or an fsspec URL (s3://bucket/prefix)."""
        from quokka_tpu.dataset.cloud import InputFilesDataset

        reader = InputFilesDataset(path, files_per_batch=files_per_batch)
        return self.new_stream(logical.SourceNode(reader, list(reader.schema)))

    def read_lance(self, path: str, columns=None) -> DataStream:
        """Lance-format dataset (reference InputLanceDataset,
        pyquokka/dataset/unordered_readers.py:101-205).  Requires the `lance`
        library, which is not baked into every image: when it is present the
        dataset reads fragment-by-fragment (one lineage unit per fragment);
        when absent this raises with the supported substitute — Parquet plus
        the IVF ANN sidecar (ctx.read_parquet + build_ivf_index +
        nearest_neighbors, dataset/vector.py), which covers the reference's
        Lance use case (vector top-k with index pushdown, apps/vectors)."""
        try:
            import lance  # noqa: F401
        except ImportError:
            raise ImportError(
                "the 'lance' library is not installed in this image.  For the "
                "vector-search role Lance plays in the reference, use Parquet "
                "with the IVF sidecar instead: ctx.read_parquet(...) + "
                "quokka_tpu.dataset.vector.build_ivf_index(...) + "
                ".nearest_neighbors(...) — same pushdown, TPU-native top-k."
            ) from None
        from quokka_tpu.dataset.cloud import InputLanceDataset

        reader = InputLanceDataset(path, columns=columns)
        return self.new_stream(logical.SourceNode(reader, list(reader.schema)))

    def read_json(self, path) -> DataStream:
        reader = InputJSONDataset(path)
        return self.new_stream(logical.SourceNode(reader, list(reader.schema.names)))

    def read_sorted_parquet(self, path, sorted_by: str, columns=None,
                            mode: str = "stride") -> "OrderedStream":
        """Time-ordered Parquet scan: row groups ordered by min/max stats on
        `sorted_by`, non-overlap asserted (reference df.py:790)."""
        from quokka_tpu.dataset.ordered import InputSortedParquetDataset

        reader = InputSortedParquetDataset(path, sorted_by, columns=columns, mode=mode)
        schema = list(columns) if columns else [f for f in reader.schema.names]
        return self.new_stream(
            logical.SourceNode(reader, schema, sorted_by=[sorted_by]), ordered=True
        )

    def read_sorted_csv(self, path, sorted_by: str, schema=None, has_header=True,
                        sep: str = ",") -> "OrderedStream":
        """Ordered CSV scan: byte ranges are in file order; the caller asserts
        the file is sorted by `sorted_by` (reference read_sorted_csv)."""
        reader = InputCSVDataset(path, schema=schema, has_header=has_header, sep=sep)
        return self.new_stream(
            logical.SourceNode(reader, list(reader.schema.names), sorted_by=[sorted_by]),
            ordered=True,
        )

    def from_arrow_sorted(self, table: pa.Table, sorted_by: str) -> "OrderedStream":
        reader = InputArrowDataset(table)
        return self.new_stream(
            logical.SourceNode(reader, list(table.column_names), sorted_by=[sorted_by]),
            ordered=True,
        )

    def from_arrow(self, table: pa.Table) -> DataStream:
        reader = InputArrowDataset(table)
        return self.new_stream(logical.SourceNode(reader, list(table.column_names)))

    def from_pandas(self, df) -> DataStream:
        return self.from_arrow(pa.Table.from_pandas(df, preserve_index=False))

    from_polars = from_pandas  # API-compat alias (no polars in this stack)

    def read_dataset(self, reader, schema=None, sorted_by=None) -> DataStream:
        schema = schema or list(reader.schema.names)
        return self.new_stream(
            logical.SourceNode(reader, schema, sorted_by=sorted_by),
            ordered=sorted_by is not None,
        )

    # -- SQL frontend (reference: pyquokka/sql.py experimental tier) -----------
    def register(self, name: str, stream) -> None:
        """Register a DataStream as a SQL-visible table."""
        if not hasattr(self, "_tables"):
            self._tables = {}
        self._tables[name] = stream

    def sql(self, query: str):
        """SELECT ... FROM registered tables -> DataStream.  Supports joins
        with equi-conditions, WHERE, GROUP BY aggregates, HAVING, ORDER BY,
        LIMIT, DISTINCT."""
        from quokka_tpu import sqlparse
        from quokka_tpu.expression import Agg, Alias, BinOp, ColRef

        st = sqlparse.parse_select(query)
        tables = getattr(self, "_tables", {})
        if st.table not in tables:
            raise ValueError(f"unknown table {st.table}; register() it first")
        stream = tables[st.table]
        for how, tname, cond in st.joins:
            if tname not in tables:
                raise ValueError(f"unknown table {tname}")
            right = tables[tname]
            if not (isinstance(cond, BinOp) and cond.op == "="):
                raise NotImplementedError("JOIN ON supports equi-conditions")
            lcol, rcol = cond.left, cond.right
            if not (isinstance(lcol, ColRef) and isinstance(rcol, ColRef)):
                raise NotImplementedError("JOIN ON supports column = column")
            # route each side to the schema that owns it
            if lcol.name in right.schema and rcol.name in stream.schema:
                lcol, rcol = rcol, lcol
            stream = stream.join(right, left_on=lcol.name, right_on=rcol.name, how=how)
        if st.where is not None:
            stream = stream.filter(st.where)
        has_agg = any(_contains_agg(e) for e in st.select)
        if st.group_by or has_agg:
            from quokka_tpu.datastream import GroupedDataStream

            named = []
            keys = list(st.group_by)
            desired = []  # output columns in SELECT order (with key aliases)
            key_alias = {}
            for i, e in enumerate(st.select):
                inner = e.expr if isinstance(e, Alias) else e
                name = e.name if isinstance(e, Alias) else (
                    inner.name if isinstance(inner, ColRef) else f"col{i}"
                )
                desired.append(name)
                if isinstance(inner, ColRef) and inner.name in keys:
                    if name != inner.name:
                        key_alias[inner.name] = name
                    continue  # group key passes through
                named.append(Alias(inner, name))
            # ORDER BY may use the alias; resolve back to the key name
            alias_inv = {v: k for k, v in key_alias.items()}
            order_by = [(alias_inv.get(n, n), d) for n, d in st.order_by] or None
            out = GroupedDataStream(stream, keys, None)._agg_exprs(
                named, having=st.having, order_by=order_by, limit=st.limit
            )
            if key_alias:
                out = out.rename(key_alias)
            if list(out.schema) != desired:
                out = out.select(desired)
            return out
        # projection-only select
        names, exprs = [], {}
        for i, e in enumerate(st.select):
            inner = e.expr if isinstance(e, Alias) else e
            name = e.name if isinstance(e, Alias) else (
                inner.name if isinstance(inner, ColRef) else f"col{i}"
            )
            names.append(name)
            if not (isinstance(inner, ColRef) and inner.name == name):
                exprs[name] = inner
        out = stream.with_columns(exprs) if exprs else stream
        out = out.select(names)
        if st.distinct:
            out = out.distinct()
        if st.order_by:
            if st.limit is not None:
                out = out.top_k([n for n, _ in st.order_by], st.limit,
                                [d for _, d in st.order_by])
            else:
                out = out.sort([n for n, _ in st.order_by],
                               [d for _, d in st.order_by])
        elif st.limit is not None:
            out = out.head(st.limit)
        return out

    # -- execution -------------------------------------------------------------
    def _prepare_plan(self, node_id: int):
        """Copy the reachable subgraph (so optimizer rewrites don't mutate
        the user's plan, df.py:956-979), wrap it in a sink, optimize.
        Returns (sub, sink_id)."""
        sub, mapping = self._copy_subgraph(node_id)
        sink_id = mapping[node_id]
        if not isinstance(sub[sink_id], logical.SinkNode):
            sink = logical.SinkNode([sink_id], sub[sink_id].schema)
            sub_sink_id = max(sub) + 1
            sub[sub_sink_id] = sink
            sink_id = sub_sink_id
        if self.optimize_plans:
            from quokka_tpu.optimizer import optimize
            from quokka_tpu.planner import decide

            # collect the cost-based passes' decision log for this plan
            # (harvested in _lower_plan, surfaced by explain())
            decide.begin_decisions()
            sink_id = optimize(sub, sink_id, exec_channels=self.exec_channels)
        return sub, sink_id

    def _lower_plan(self, sub, sink_id: int, graph: TaskGraph) -> int:
        """Assign stages and lower the prepared plan into ``graph``;
        returns the sink's actor id."""
        self._assign_stages(sub, sink_id)
        actor_of: Dict[int, int] = {}
        for nid in self._toposort(sub, sink_id):
            sub[nid].lower(self, graph, actor_of, nid)
        for nid, aid in actor_of.items():
            pl = getattr(sub.get(nid), "placement", None)
            if pl is not None:
                graph.actors[aid].placement = pl
        self.latest_graph = graph
        # planner decision log (begun in _prepare_plan): rides the graph so
        # opstats.register_plan stores it and explain() renders it
        from quokka_tpu.planner import decide

        graph.planner_decisions = decide.take_decisions()
        # compile plane: fingerprint the lowered plan and start loading its
        # persisted executables in the background — warmup overlaps the
        # scan/admission work between here and the first dispatch
        from quokka_tpu.runtime import compileplane

        graph.plan_fp = compileplane.plan_fingerprint(graph)
        # kept on the graph so a caller that wants a SYNCHRONOUS warm
        # (QueryService.prewarm) joins this thread instead of racing a
        # duplicate replay over the same executables
        graph.prewarm_thread = compileplane.prewarm_plan(graph.plan_fp)
        return actor_of[sink_id]

    def lower_into(self, node_id: int, graph: TaskGraph) -> int:
        """Lower ``node_id``'s plan into a caller-provided TaskGraph (the
        query service's entry point: the graph carries the service's shared
        store/cache and the query's namespace).  Returns the sink actor id;
        the caller owns execution and teardown."""
        sub, sink_id = self._prepare_plan(node_id)
        return self._lower_plan(sub, sink_id, graph)

    def execute_node(self, node_id: int):
        sub, sink_id = self._prepare_plan(node_id)
        if self.mesh is not None:
            from quokka_tpu.parallel.mesh_exec import MeshExecutor, MeshUnsupported
            from quokka_tpu.runtime.dataset import ResultDataset

            try:
                table = MeshExecutor(self.mesh).run_to_arrow(sub, sink_id)
                ds = ResultDataset()
                if table is not None:  # None = legitimately empty result
                    ds.append(0, table)
                self.last_mesh_fallback = None
                return ds
            except MeshUnsupported as e:
                # plan shape not covered: embedded engine below — LOUDLY
                # (the mesh is an explicit user request; a silent single-
                # device downgrade would misrepresent what ran)
                self.last_mesh_fallback = str(e)
                _log.warning(
                    "mesh execution fell back to the embedded engine: %s", e
                )
        n_workers = getattr(self.cluster, "n_workers", 0) if self.cluster else 0
        ext = getattr(self.cluster, "external_workers", 0) if self.cluster else 0
        # one-shot embedded runs get a fresh namespace so teardown is an
        # explicit drop_namespace (same GC discipline the query service
        # uses); distributed sessions keep the un-namespaced store its
        # workers expect (one query per served store)
        graph = TaskGraph(
            self.exec_config,
            query_id=None if (n_workers or ext) else new_query_id(),
        )
        sink_actor = self._lower_plan(sub, sink_id, graph)
        if n_workers or ext:
            from quokka_tpu.runtime.distributed import run_distributed

            try:
                run_distributed(
                    graph,
                    n_workers=n_workers,
                    kill_after_inputs=self.exec_config.get("inject_kill_worker"),
                    heartbeat_timeout=self.exec_config.get("heartbeat_timeout"),
                    worker_tags=self.worker_tags,
                    external_workers=ext,
                    # external daemons (TPUPodCluster hosts) reach the store
                    # across the network: serve on the cluster's declared bind
                    # interface (default = the coordinator's own address, NOT
                    # 0.0.0.0); local-only runs stay on loopback
                    bind=(getattr(self.cluster, "bind", None)
                          or getattr(self.cluster, "coordinator", "127.0.0.1"))
                    if ext else "127.0.0.1",
                    store_port=getattr(self.cluster, "store_port", 0),
                )
            finally:
                graph.cleanup()
        else:
            graph.run()
        return graph.result(sink_actor)

    def _copy_subgraph(self, node_id: int):
        mapping: Dict[int, int] = {}
        sub: Dict[int, logical.Node] = {}

        def rec(nid: int) -> int:
            if nid in mapping:
                return mapping[nid]
            node = self.nodes[nid]
            cp = copy.copy(node)
            cp.parents = [rec(p) for p in node.parents]
            cp.schema = list(node.schema)
            mapping[nid] = nid
            sub[nid] = cp
            return nid

        rec(node_id)
        return sub, mapping

    def _toposort(self, sub: Dict[int, logical.Node], sink_id: int) -> List[int]:
        out: List[int] = []
        seen = set()

        def rec(nid):
            if nid in seen:
                return
            seen.add(nid)
            for p in sub[nid].parents:
                rec(p)
            out.append(nid)

        rec(sink_id)
        return out

    def _assign_stages(self, sub: Dict[int, logical.Node], sink_id: int) -> None:
        """Build-before-probe stage assignment (df.py:1530-1621): walking from
        the sink, a build parent's subtree gets stage-1; normalize to 0-based
        ascending so the coordinator runs stages in increasing order."""
        stage: Dict[int, int] = {}

        def rec(nid: int, s: int):
            # only re-walk a subtree when this visit improves (lowers) the
            # stage — otherwise shared diamonds cost 2^k walks
            if nid in stage and s >= stage[nid]:
                return
            stage[nid] = s
            node = sub[nid]
            for i, p in enumerate(node.parents):
                rec(p, s - 1 if i in node.build_parents else s)

        rec(sink_id, 0)
        lo = min(stage.values())
        for nid, s in stage.items():
            sub[nid].stage = s - lo

    # -- introspection ---------------------------------------------------------
    def explain(self, node_id: int) -> str:
        # same prepare as execute_node: sink wrap (optimizer rewrites assume
        # the root has a consumer) + optimize
        sub, sink_id = self._prepare_plan(node_id)
        self._assign_stages(sub, sink_id)
        lines = []
        for nid in self._toposort(sub, sink_id):
            n = sub[nid]
            indent = "  " * (max(n.stage, 0))
            lines.append(
                f"{indent}[{nid}] {n.describe()} stage={n.stage} "
                f"schema={n.schema} parents={n.parents}"
            )
        return "\n".join(lines)
