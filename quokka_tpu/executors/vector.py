"""Vector search executors: streaming brute-force top-k cosine similarity.

Reference parity: DFProbeDataStreamNNExecutor1/2 (pyquokka/executors/
vector_executors.py:3-114): per-partition brute-force top-k via BLAS matmul,
then a global reduce of the per-partition top-ks.  On TPU the Q x D @ D x N
similarity matrix is exactly what the MXU is for; the running per-query top-k
merges with jax.lax.top_k each batch, so state stays at [Q, k]."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from quokka_tpu.executors.base import Executor
from quokka_tpu.ops import bridge
from quokka_tpu.ops.batch import DeviceBatch, NumCol, VecCol


class NearestNeighborExecutor(Executor):
    """Probe every batch's vectors against a fixed query matrix; keep the
    running top-k (by cosine similarity) per query.  Emits at done:
    (query_idx, score, <payload columns of the matched rows>)."""

    def __init__(self, queries: np.ndarray, vec_col: str, k: int,
                 payload: Optional[List[str]] = None):
        q = np.asarray(queries, dtype=np.float32)
        q = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        self.queries = jnp.asarray(q)  # [Q, D] normalized
        self.vec_col = vec_col
        self.k = k
        self.payload = payload
        # running state: scores [Q, k] and matched host rows per (query, slot)
        self.scores: Optional[jnp.ndarray] = None
        self.rows: Optional[list] = None  # parallel [Q][k] arrow row indices
        self.row_tables: List[pa.Table] = []

    def execute(self, batches, stream_id, channel):
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            self._probe(b)

    @staticmethod
    @jax.jit
    def _sims(queries, vecs, valid):
        v = vecs / jnp.maximum(
            jnp.linalg.norm(vecs, axis=1, keepdims=True), 1e-12
        )
        sims = queries @ v.T  # [Q, N] on the MXU
        return jnp.where(valid[None, :], sims, -jnp.inf)

    def _probe(self, b: DeviceBatch):
        vec = b.columns[self.vec_col]
        assert isinstance(vec, VecCol), f"{self.vec_col} is not a vector column"
        sims = self._sims(self.queries, vec.data.astype(jnp.float32), b.valid)
        k = min(self.k, sims.shape[1])
        top_s, top_i = jax.lax.top_k(sims, k)  # [Q, k] per batch
        # stash matched rows host-side, merge scores with running state
        table_idx = len(self.row_tables)
        payload_cols = self.payload or [c for c in b.names if c != self.vec_col]
        self.row_tables.append(
            bridge.device_to_arrow(b.select(payload_cols))
        )
        # map padded row index -> compacted arrow row index
        valid_np = np.asarray(b.valid)
        remap = np.cumsum(valid_np) - 1
        top_i_np = remap[np.asarray(top_i)]
        handles = np.stack(
            [np.full_like(top_i_np, table_idx), top_i_np], axis=-1
        )  # [Q, k, 2]
        top_s_np = np.asarray(top_s)
        if self.scores is None:
            self.scores = top_s_np
            self.rows = handles
        else:
            merged_s = np.concatenate([self.scores, top_s_np], axis=1)
            merged_r = np.concatenate([self.rows, handles], axis=1)
            order = np.argsort(-merged_s, axis=1)[:, : self.k]
            self.scores = np.take_along_axis(merged_s, order, axis=1)
            self.rows = np.take_along_axis(
                merged_r, order[..., None], axis=1
            )

    def done(self, channel):
        if self.scores is None:
            return None
        qn, kn = self.scores.shape
        qi_g, sl_g = np.meshgrid(np.arange(qn), np.arange(kn), indexing="ij")
        alive = self.scores != -np.inf
        qi_f = qi_g[alive]
        scores_f = self.scores[alive]
        ti_f = self.rows[..., 0][alive]
        ri_f = self.rows[..., 1][alive]
        if len(qi_f) == 0:
            return None
        # gather payload rows with ONE take per source table, then one
        # permutation take to restore (query, slot) order
        order = np.argsort(ti_f, kind="stable")
        parts = []
        for ti in np.unique(ti_f):
            sel = order[ti_f[order] == ti]
            parts.append(self.row_tables[int(ti)].take(pa.array(ri_f[sel])))
        payload_sorted = pa.concat_tables(parts, promote_options="permissive")
        inverse = np.empty(len(order), dtype=np.int64)
        inverse[order] = np.arange(len(order))
        payload = payload_sorted.take(pa.array(inverse))
        out = pa.table(
            {
                "query_idx": pa.array(qi_f.astype(np.int64)),
                "score": pa.array(scores_f.astype(np.float64)),
                **{c: payload.column(c) for c in payload.column_names},
            }
        )
        self.scores = None
        self.rows = None
        self.row_tables = []
        return bridge.arrow_to_device(out)


class GlobalTopKReduceExecutor(Executor):
    """Second stage: merge per-partition (query_idx, score, payload) top-ks
    into the global top-k per query (vector_executors.py:53)."""

    def __init__(self, k: int):
        self.k = k
        self.parts: List[DeviceBatch] = []

    def execute(self, batches, stream_id, channel):
        self.parts.extend(b for b in batches if b is not None)

    def done(self, channel):
        if not self.parts:
            return None
        import pandas as pd

        df = pd.concat([bridge.to_pandas(b) for b in self.parts], ignore_index=True)
        self.parts = []
        out = (
            df.sort_values(["query_idx", "score"], ascending=[True, False])
            .groupby("query_idx")
            .head(self.k)
            .reset_index(drop=True)
        )
        return bridge.arrow_to_device(pa.Table.from_pandas(out, preserve_index=False))
