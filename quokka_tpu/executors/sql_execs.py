"""Core relational executors on device kernels.

Functional parity targets (reference: pyquokka/executors/sql_executors.py):
UDFExecutor:3, CountExecutor:69, StorageExecutor:24, BuildProbeJoinExecutor:325,
DistinctExecutor:517, SQLAggExecutor:556 (split here into PartialAgg/FinalAgg so
aggregation is decomposed partial->shuffle->final instead of concat-then-DuckDB),
ConcatThenSQLExecutor:45 (TopK/Sort below).
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from quokka_tpu import config
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops import join as join_ops
from quokka_tpu.ops.batch import DeviceBatch, NumCol
from quokka_tpu.ops.expr_compile import AggPlan, evaluate_predicate, evaluate_to_column
from quokka_tpu.executors.base import Executor


def _coalesce(live: List[DeviceBatch],
              cap_rows: int = 1 << 22) -> List[DeviceBatch]:
    """Concat a dispatch's ready batches into few compacted batches so the
    per-batch kernel chains (group-by sort, join probe) run once over a
    bucketed whole instead of once per per-partition slice.  Bounded by
    accumulated PADDED rows so one group can never overflow MAX_BUCKET (or
    spike device memory) regardless of how many batches the planner
    delivered."""
    if len(live) <= 1:
        return live
    groups: List[List[DeviceBatch]] = []
    cur: List[DeviceBatch] = []
    acc = 0
    for b in live:
        if cur and acc + b.padded_len > cap_rows:
            groups.append(cur)
            cur, acc = [], 0
        cur.append(b)
        acc += b.padded_len
    groups.append(cur)
    return [bridge.concat_batches(g) if len(g) > 1 else g[0] for g in groups]


class UDFExecutor(Executor):
    """Stateless per-batch transform (DataStream.transform)."""

    # carries no cross-batch state: a fused stage containing one of these
    # checkpoints without snapshotting it (ops/stagefuse.py) — tape replay
    # already relies on transform purity engine-wide
    STATELESS = True

    def __init__(self, fn: Callable[[DeviceBatch], DeviceBatch]):
        self.fn = fn

    def execute(self, batches, stream_id, channel):
        out = [self.fn(b) for b in batches if b is not None]
        out = [b for b in out if b is not None]
        if not out:
            return None
        return bridge.concat_batches(out) if len(out) > 1 else out[0]


class CountExecutor(Executor):
    def __init__(self):
        self.count = 0

    def execute(self, batches, stream_id, channel):
        self.count += sum(b.count_valid() for b in batches)

    def done(self, channel):
        import pyarrow as pa

        return bridge.arrow_to_device(pa.table({"count": [self.count]}))




# ---------------------------------------------------------------------------
# spill-directory registry: executors that never reach done() (failed query,
# killed worker) must not leak dirs under config.SPILL_DIR forever
_SPILL_DIRS: set = set()

# process-wide count of operators that crossed a spill threshold (one per
# spilling operator instance) — tests assert production-threshold runs
# actually exercised the disk tier
SPILL_EVENTS = 0


def _new_spill_dir(prefix: str) -> str:
    global SPILL_EVENTS
    SPILL_EVENTS += 1
    import atexit
    import os
    import tempfile

    os.makedirs(config.SPILL_DIR, exist_ok=True)
    if not _SPILL_DIRS:
        atexit.register(_purge_spill_dirs)
    d = tempfile.mkdtemp(prefix=prefix, dir=config.SPILL_DIR)
    _SPILL_DIRS.add(d)
    return d


def _drop_spill_dir(d: str) -> None:
    import shutil

    shutil.rmtree(d, ignore_errors=True)
    _SPILL_DIRS.discard(d)


def _purge_spill_dirs() -> None:
    for d in list(_SPILL_DIRS):
        _drop_spill_dir(d)


class StorageExecutor(Executor):
    """Pass batches through unchanged (terminal collect node)."""

    def execute(self, batches, stream_id, channel):
        live = [b for b in batches if b is not None and b.count_valid() > 0]
        if not live:
            return None
        return bridge.concat_batches(live) if len(live) > 1 else live[0]


class SelectingStorageExecutor(StorageExecutor):
    """Terminal collect that also projects to the plan schema (picklable —
    the sink factory crosses process boundaries in the multi-worker runtime)."""

    def __init__(self, schema: Sequence[str]):
        self.schema = list(schema)

    def execute(self, batches, stream_id, channel):
        out = StorageExecutor.execute(self, batches, stream_id, channel)
        if out is None:
            return None
        return out.select([c for c in self.schema if c in out.columns])


class PartialAggExecutor(Executor):
    SUPPORTS_CHECKPOINT = True
    """Per-channel partial group-by: maintains one running partial-aggregate
    batch; emits it at done.  Sits upstream of the hash shuffle."""

    # merge cadence: per-batch partials are buffered (uncompacted, with an
    # async live-count already in flight) and folded into the running state
    # every K batches — by merge time the counts have landed on the host, so
    # compaction costs no blocking device round trip
    MERGE_EVERY = 8

    # adaptive bailout: when the FIRST batch's group count is close to its
    # row count (near-unique keys — e.g. TPC-H Q3's order-level group-by),
    # per-batch partial sorts reduce almost nothing while costing the
    # engine's dominant kernel; switch to PASSTHROUGH: map rows to partial
    # FORM (pre-exprs + count columns, purely elementwise) and emit them
    # immediately for the final agg to reduce.  DuckDB's partial-agg
    # abandonment, TPU-style.  The decision depends only on batch 1's
    # content, so tape replay reproduces it deterministically.
    PASSTHROUGH_RATIO = 0.7

    def __init__(self, keys: Sequence[str], plan: AggPlan):
        self.keys = list(keys)
        self.plan = plan
        self.state: Optional[DeviceBatch] = None
        self._buffer: List[DeviceBatch] = []
        self._passthrough: Optional[bool] = None  # undecided until batch 1
        from quokka_tpu.ops.fuse import FusedPartialAgg

        self._fused = FusedPartialAgg(self.keys, plan)

    def _partial(self, batch: DeviceBatch) -> DeviceBatch:
        from quokka_tpu.ops.expr_compile import CompileError

        try:
            g = self._fused(batch)
        except CompileError:
            b = batch
            for name, e in self.plan.pre:
                b = b.with_column(name, evaluate_to_column(e, b))
            aggs = [
                (p, op, None if tmp is None else b.columns[tmp].data)
                for (p, op, tmp) in self.plan.partials
            ]
            g = kernels.groupby_aggregate(b, self.keys, aggs)
        return g.select(self.keys + [p for p, _, _ in self.plan.partials])

    def _recombine(self, parts: List[DeviceBatch]) -> DeviceBatch:
        parts = [kernels.compact(p) for p in parts]
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        aggs = [(p, op, merged.columns[p].data) for (p, op) in self.plan.recombine]
        g = kernels.groupby_aggregate(merged, self.keys, aggs)
        return g.select(self.keys + [p for p, _ in self.plan.recombine])

    # NOTE: _recombine's per-part compact blocks only on counts that have not
    # yet landed (async copies start at partial creation; merges run batches
    # later, so in steady state the reads are from host memory)

    def _merge(self) -> None:
        if not self._buffer:
            return  # state alone is already folded
        parts, self._buffer = self._buffer, []
        if self.state is not None:
            parts.append(self.state)
        self.state = self._recombine(parts)

    def _partial_form(self, batch: DeviceBatch) -> DeviceBatch:
        """Raw rows -> partial-FORM rows (count columns = 1 per valid row,
        value columns = the pre-expression inputs) with NO grouping: the
        recombine ops downstream aggregate them exactly like grouped
        partials."""
        b = batch
        for name, e in self.plan.pre:
            b = b.with_column(name, evaluate_to_column(e, b))
        cols = {k: b.columns[k] for k in self.keys}
        for pname, op, tmp in self.plan.partials:
            if op == "count":
                cols[pname] = NumCol(b.valid.astype(jnp.int32), "i")
            else:
                cols[pname] = b.columns[tmp]
        return DeviceBatch(cols, b.valid, b.nrows, None, b.nrows_dev)

    def execute(self, batches, stream_id, channel):
        outs = []
        live = [b for b in batches if b is not None]
        if not self._passthrough:
            # one group-by over the dispatch's bucketed whole instead of a
            # sort per per-partition batch; deterministic under tape replay
            # (the same recorded batch set coalesces identically)
            live = _coalesce(live)
        for b in live:
            if self._passthrough:
                outs.append(self._partial_form(b))
                continue
            g = self._partial(b)
            if self._passthrough is None:
                rows = b.count_valid()
                # tiny batches can't decide (a selective first chunk must
                # not pin the mode for a stream of millions of rows): stay
                # undecided until a big-enough batch arrives — still
                # deterministic under tape replay (content-driven)
                if rows > 4096:
                    groups = g.count_valid()
                    self._passthrough = (
                        groups >= self.PASSTHROUGH_RATIO * rows
                    )
            self._buffer.append(g)
        if len(self._buffer) >= self.MERGE_EVERY:
            self._merge()
        if not outs:
            return None
        return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]

    def done(self, channel):
        self._merge()
        out, self.state = self.state, None
        # state after a merge is already bucket-sized; only compact when the
        # trailing merge left a large padded region (avoids a blocking count)
        return None if out is None else kernels.compact_if_large(out)

    def checkpoint(self):
        self._merge()  # state-folding is semantics-preserving
        table = None if self.state is None else bridge.device_to_arrow(self.state)
        return {"passthrough": self._passthrough, "state": table}

    def restore(self, state):
        self._buffer = []
        if isinstance(state, dict):
            self._passthrough = state.get("passthrough")
            state = state.get("state")
        else:
            self._passthrough = None  # legacy checkpoint blob: re-decide
        self.state = None if state is None else bridge.arrow_to_device(state)


class FinalAggExecutor(Executor):
    """Downstream of the key shuffle: recombines partials for its key range,
    then applies final expressions, HAVING, ORDER BY and LIMIT at done."""

    def __init__(
        self,
        keys: Sequence[str],
        plan: AggPlan,
        having=None,
        order_by: Optional[List[Tuple[str, bool]]] = None,
        limit: Optional[int] = None,
    ):
        self.keys = list(keys)
        self.plan = plan
        self.having = having
        self.order_by = order_by
        self.limit = limit
        self.state: Optional[DeviceBatch] = None
        self._buffer: List[DeviceBatch] = []

    MERGE_EVERY = 32  # incoming partials are small (post-shuffle compacted)
    # a passthrough upstream (PartialAggExecutor bailout) ships FULL-SIZE row
    # batches instead of compacted partials: also fold on accumulated padded
    # rows so the buffer can't hold 32 raw batches on device at once
    MERGE_ROWS = 1 << 21

    def _merge(self) -> None:
        if not self._buffer:
            return  # state alone is already folded
        parts, self._buffer = self._buffer, []
        if self.state is not None:
            parts.append(self.state)
        parts = [kernels.compact(p) for p in parts]
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        aggs = [(p, op, merged.columns[p].data) for (p, op) in self.plan.recombine]
        g = kernels.groupby_aggregate(merged, self.keys, aggs)
        self.state = g.select(self.keys + [p for p, _ in self.plan.recombine])

    def execute(self, batches, stream_id, channel):
        self._buffer.extend(b for b in batches if b is not None)
        if (
            len(self._buffer) >= self.MERGE_EVERY
            or sum(p.padded_len for p in self._buffer) >= self.MERGE_ROWS
        ):
            self._merge()
        return None

    def done(self, channel):
        self._merge()
        if self.state is not None:
            self.state = kernels.compact_if_large(self.state)
        if self.state is None:
            if self.keys:
                return None
            # SQL semantics: a global aggregate over zero rows yields one row
            # (count = 0, sum = 0, min/max = null)
            import numpy as np
            import pyarrow as pa

            cols = {}
            for pname, op, _tmp in self.plan.partials:
                if op == "count":
                    cols[pname] = np.array([0], dtype=np.int64)
                elif op == "sum":
                    cols[pname] = np.array([0.0])
                else:
                    cols[pname] = np.array([np.nan])
            self.state = bridge.arrow_to_device(pa.table(cols))
        g = self.state
        for name, e in self.plan.finals:
            g = g.with_column(name, evaluate_to_column(e, g))
        # HAVING runs before the projection: it may reference partial columns
        # (aggregates rewritten by plan.rewrite) that the output drops
        if self.having is not None:
            g = kernels.compact(kernels.apply_mask(g, evaluate_predicate(self.having, g)))
        out_cols = self.keys + [n for n, _ in self.plan.finals]
        # dedupe (a key may also be an output)
        seen, cols = set(), []
        for c in out_cols:
            if c not in seen:
                seen.add(c)
                cols.append(c)
        g = g.select(cols)
        if self.order_by:
            names = [n for n, _ in self.order_by]
            desc = [d for _, d in self.order_by]
            if self.limit is not None:
                g = kernels.top_k(g, names, self.limit, desc)
            else:
                g = kernels.sort_batch(g, names, desc)
        elif self.limit is not None:
            g = kernels.head(g, self.limit)
        self.state = None
        return g


class BuildProbeJoinExecutor(Executor):
    SUPPORTS_CHECKPOINT = True

    """Streamed hash join: stream 1 is the build side (buffered until its
    stage completes), stream 0 probes.  Stage scheduling guarantees build
    completes before the first probe batch arrives (the reference asserts the
    same invariant, sql_executors.py:357)."""

    def __init__(
        self,
        left_on: Sequence[str],
        right_on: Sequence[str],
        how: str = "inner",
        suffix: str = "_2",
        rename: Optional[Dict[str, str]] = None,
        out_schema: Optional[List[str]] = None,
    ):
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.how = how
        self.suffix = suffix
        # plan-time output schema: lets a left join emit all-null payload even
        # when this channel never saw a single build batch (schema unknown)
        self.out_schema = list(out_schema) if out_schema else None
        # plan-time rename of clashing build columns; None -> detect at
        # runtime from the first probe batch (raw TaskGraph usage)
        self.planned_rename = rename
        self.build_parts: List[DeviceBatch] = []
        self.build: Optional[DeviceBatch] = None
        self.build_done = False
        self.probe_buffer: List[DeviceBatch] = []
        self.build_unique: Optional[bool] = None
        self.payload: Optional[List[str]] = None
        self.rename: Dict[str, str] = {}
        # grace-join spill tier (DiskBuildProbeJoinExecutor,
        # sql_executors.py:456-515): past SPILL_JOIN_BUILD_ROWS accumulated
        # build rows, both sides hash-partition to disk and done() joins
        # partition-by-partition in bounded memory
        self.spill_rows = config.SPILL_JOIN_BUILD_ROWS
        self.fanout = config.SPILL_JOIN_FANOUT
        self._disk = False
        self._build_rows = 0
        self._spill_dir: Optional[str] = None
        self._writers: Dict[Tuple[str, int], object] = {}
        self._files: Dict[Tuple[str, int], str] = {}
        self._build_arrow_schema = None

    def _finalize_build(self, probe_cols: List[str]):
        if not self.build_parts:
            self.build = None
            return
        b = (
            bridge.concat_batches(self.build_parts)
            if len(self.build_parts) > 1
            else self.build_parts[0]
        )
        self.build_parts = []
        # payload = build columns minus its join keys; rename clashes
        payload = [c for c in b.names if c not in self.right_on]
        if self.planned_rename is not None:
            self.rename = {c: n for c, n in self.planned_rename.items() if c in payload}
        else:
            self.rename = {c: c + self.suffix for c in payload if c in probe_cols}
        if self.rename:
            b = b.rename(self.rename)
            payload = [self.rename.get(c, c) for c in payload]
        self.payload = payload
        self.build = b
        # build-side hash state is the largest single device residency a
        # join pins; ledger it (query attribution happens at graph level —
        # executors do not know their query id) and retire in done()
        from quokka_tpu.obs import memplane
        from quokka_tpu.runtime.cache import _batch_nbytes

        memplane.LEDGER.track(("join_build", id(self)), memplane.SITE_BUILD,
                              _batch_nbytes(b))
        self.build_unique = join_ops.build_keys_unique(b, self.right_on)
        # the strategy that will serve every probe batch of this build is
        # decided here — stamp it into the flight timeline so critpath /
        # bench_obs can attribute the probe pipeline to the kernel family
        # that actually ran (ops/strategy.py matrix)
        from quokka_tpu.obs import RECORDER
        from quokka_tpu.ops import strategy as kstrategy

        RECORDER.record(
            "strategy", "join_build",
            choice=kstrategy.choice("join_build") if self.build_unique
            else "sort", unique=bool(self.build_unique),
        )
        # EXPLAIN ANALYZE: the finalized build size on the operator's
        # record (padded length — host-known, never a device sync)
        from quokka_tpu.obs import opstats

        opstats.note(join_build_rows=b.padded_len)

    def execute(self, batches, stream_id, channel):
        live = [b for b in batches if b is not None]
        if not live:
            return None
        if stream_id == 1:
            assert self.build is None, "build batch arrived after probing began"
            if self._disk:
                for b in live:
                    self._spill(b, "build", self.right_on)
                return None
            self.build_parts.extend(live)
            # padded length is a free upper bound on live rows: the real
            # counts (a blocking device read per batch when the producer
            # filtered device-side) are only paid once the bound crosses
            # the spill threshold
            self._build_rows += sum(b.padded_len for b in live)
            if self._build_rows > self.spill_rows:
                rows = sum(b.count_valid() for b in self.build_parts)
                if rows > self.spill_rows:
                    self._enter_disk_mode()
                else:
                    self._build_rows = rows
            return None
        if self._disk:
            for b in live:
                self._spill(b, "probe", self.left_on)
            return None
        # probe: if the build stream hasn't been declared exhausted yet
        # (stage-tie cases like self-joins), buffer and flush on source_done
        if not self.build_done:
            self.probe_buffer.extend(live)
            return None
        return self._probe(live)

    # -- grace-join spill tier -------------------------------------------------
    def _enter_disk_mode(self):
        self._disk = True
        # interval checkpoints can't capture on-disk partition state cheaply;
        # recovery falls back to full lineage-tape replay (deterministic)
        self.SUPPORTS_CHECKPOINT = False
        parts, self.build_parts = self.build_parts, []
        self._build_rows = 0
        for b in parts:
            self._spill(b, "build", self.right_on)
        # stage-tie probes buffered before build completion spill too
        buffered, self.probe_buffer = self.probe_buffer, []
        for b in buffered:
            self._spill(b, "probe", self.left_on)

    def _spill(self, batch: DeviceBatch, side: str, keys) -> None:
        import os
        import tempfile

        import pyarrow as pa

        if self._spill_dir is None:
            self._spill_dir = _new_spill_dir("join-")
        pids = kernels.partition_ids(batch, list(keys), self.fanout)
        # compacted split: each partition converts to Arrow right here, so
        # masked views would pay fanout-times the d2h bytes
        for p, part in enumerate(
                kernels.split_by_partition(batch, pids, self.fanout,
                                           compact=True)):
            if part.count_valid() == 0:
                continue
            table = bridge.device_to_arrow(part)
            if side == "build" and self._build_arrow_schema is None:
                # remember the build schema: probe-only partitions still need
                # a schema'd (empty) build for typed left-join null payloads
                self._build_arrow_schema = table.schema
            key = (side, p)
            w = self._writers.get(key)
            if w is None:
                path = os.path.join(self._spill_dir, f"{side}-{p}.arrow")
                self._files[key] = path
                sink = pa.OSFile(path, "wb")
                w = pa.ipc.new_file(sink, table.schema)
                self._writers[key] = (w, sink)
            self._writers[key][0].write_table(table)

    def _disk_join(self):
        import pyarrow as pa

        for w, sink in self._writers.values():
            w.close()
            sink.close()
        self._writers = {}
        try:
            for p in range(self.fanout):
                probe_path = self._files.get(("probe", p))
                if probe_path is None:
                    continue  # no probe rows in this partition -> no output
                build_path = self._files.get(("build", p))
                inner = BuildProbeJoinExecutor(
                    self.left_on, self.right_on, self.how, self.suffix,
                    rename=self.planned_rename, out_schema=self.out_schema,
                )
                inner.build_done = True
                if build_path is not None:
                    with pa.ipc.open_file(build_path) as r:
                        inner.build_parts = [
                            bridge.arrow_to_device(
                                pa.Table.from_batches([r.get_batch(i)])
                            )
                            for i in range(r.num_record_batches)
                        ]
                elif self._build_arrow_schema is not None:
                    # probe-only partition: a schema'd empty build keeps
                    # left-join null payloads correctly typed
                    inner.build_parts = [
                        bridge.arrow_to_device(self._build_arrow_schema.empty_table())
                    ]
                with pa.ipc.open_file(probe_path) as r:
                    for i in range(r.num_record_batches):
                        chunk = bridge.arrow_to_device(
                            pa.Table.from_batches([r.get_batch(i)])
                        )
                        o = inner._probe([chunk])
                        if o is not None and o.count_valid() > 0:
                            yield o
                # each partition's build state dies with its inner executor
                # — retire its ledger entry so a high-fanout grace join does
                # not read as fanout simultaneous build residencies
                from quokka_tpu.obs import memplane

                memplane.LEDGER.retire(("join_build", id(inner)))
        finally:
            if self._spill_dir is not None:
                _drop_spill_dir(self._spill_dir)

    def source_done(self, stream_id, channel):
        if stream_id != 1 or self.build_done:
            return None
        self.build_done = True
        buffered, self.probe_buffer = self.probe_buffer, []
        if self._disk:
            for b in buffered:
                self._spill(b, "probe", self.left_on)
            return None
        if buffered:
            return self._probe(buffered)
        return None

    def done(self, channel):
        from quokka_tpu.obs import memplane

        memplane.LEDGER.retire(("join_build", id(self)))
        if self._disk:
            return self._disk_join()
        return None

    def _probe(self, live):
        if self.build is None and self.build_parts:
            self._finalize_build(live[0].names)
        from quokka_tpu.obs import opstats

        opstats.note(join_probe_rows=sum(
            b.nrows if b.nrows is not None else b.padded_len for b in live))
        # vectorized probe pipeline: the dispatch's whole ready set flows
        # through ONE bucketed join call instead of one kernel chain per
        # per-partition batch (their async live counts have landed by now,
        # so the concat compacts without blocking round trips)
        live = _coalesce(live)
        if self.build is None:
            # No build batch ever arrived on this channel.  Engine.push always
            # delivers every hash partition (even zero-valid ones), so this
            # only happens when the build SOURCE emitted zero batches — i.e.
            # consistently on every channel.  Payload kinds are unknowable
            # then; all-null float columns stand in (documented limitation:
            # a string payload column degrades to float nulls in this case).
            if self.how in ("inner", "semi"):
                return None
            if self.how == "anti":
                out = live
                return bridge.concat_batches(out) if len(out) > 1 else out[0]
            if self.out_schema is None:
                raise RuntimeError(
                    "left join: build side produced no batches and no plan "
                    "schema was provided (pass out_schema=)"
                )
                outs = []
            for probe in live:
                payload = [c for c in self.out_schema if c not in probe.columns]
                b = probe
                for c in payload:
                    b = b.with_column(
                        c,
                        NumCol(jnp.full(b.padded_len, jnp.nan, config.float_dtype()), "f"),
                    )
                outs.append(b)
            return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]
        if self.build.count_valid() == 0 and self.how in ("inner", "semi"):
            return None
        # empty-but-schema'd build: anti/left fall through — the general join
        # kernel handles a zero-valid build (every probe row unmatched)
        outs = []
        for probe in live:
            if self.build_unique and self.how in ("inner", "semi", "anti"):
                out = join_ops.hash_join_pk(
                    probe, self.build, self.left_on, self.right_on, self.how, self.payload
                )
            else:
                out = join_ops.hash_join_general(
                    probe, self.build, self.left_on, self.right_on, self.how, self.payload
                )
            if out is not None:
                outs.append(out)
        if not outs:
            return None
        return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]

    def checkpoint(self):
        build = self.build
        if build is None and self.build_parts:
            build = bridge.concat_batches(self.build_parts)
        return {
            "build": None if build is None else bridge.device_to_arrow(build),
            # without these, a restore past the build's source_done event
            # would buffer every probe batch forever (build_done False) and
            # silently emit nothing
            "build_done": self.build_done,
            "finalized": self.build is not None,
            "rename": self.rename,
            "payload": self.payload,
            "probe_buffer": [bridge.device_to_arrow(b) for b in self.probe_buffer],
        }

    def restore(self, state):
        if state is None:
            return
        if not isinstance(state, dict):  # legacy: bare build table
            self.build_parts = [bridge.arrow_to_device(state)]
            return
        if state["build"] is not None:
            b = bridge.arrow_to_device(state["build"])
            if state["finalized"]:
                self.build = b
                self.rename = state["rename"]
                self.payload = state["payload"]
                self.build_unique = join_ops.build_keys_unique(b, self.right_on)
            else:
                self.build_parts = [b]
        self.build_done = state["build_done"]
        self.probe_buffer = [
            bridge.arrow_to_device(t) for t in state["probe_buffer"]
        ]


class BroadcastJoinExecutor(BuildProbeJoinExecutor):
    """Small side broadcast to every channel (reference sql_executors.py:275):
    identical device logic; only the partitioner differs (Broadcast)."""


class DistinctExecutor(Executor):
    """Streaming distinct: emit rows not seen before (anti-join against the
    accumulated key state, reference sql_executors.py:517)."""

    def __init__(self, keys: Sequence[str]):
        self.keys = list(keys)
        self.seen: Optional[DeviceBatch] = None

    def execute(self, batches, stream_id, channel):
        outs = []
        for b in batches:
            if b is None:
                continue
            b = kernels.distinct(b, self.keys)
            b = kernels.compact(b)
            if self.seen is not None:
                b = kernels.compact(
                    join_ops.hash_join_general(b, self.seen, self.keys, self.keys, "anti")
                )
            if b.count_valid() == 0:
                continue
            self.seen = (
                b if self.seen is None else bridge.concat_batches([self.seen, b])
            )
            outs.append(b)
        if not outs:
            return None
        return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]


class TopKExecutor(Executor):
    """Running top-k by sort keys (reference expresses this via
    ConcatThenSQLExecutor; here the running state is never larger than k)."""

    def __init__(self, by: List[str], k: int, descending: List[bool]):
        self.by = by
        self.k = k
        self.descending = descending
        self.state: Optional[DeviceBatch] = None

    def execute(self, batches, stream_id, channel):
        parts = [b for b in batches if b is not None]
        if self.state is not None:
            parts.append(self.state)
        if not parts:
            return None
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        self.state = kernels.top_k(merged, self.by, self.k, self.descending)
        return None

    def done(self, channel):
        out, self.state = self.state, None
        return out


class SortExecutor(Executor):
    """Blocking sort with an external-merge spill tier.

    Small inputs: accumulate and sort once at done (the original path).
    Past config.SPILL_SORT_ROWS accumulated rows, each bucket is sorted on
    device and written to disk as a sorted RUN (Arrow IPC, chunked); done()
    k-way-merges the runs in bounded memory and emits a LIST of batches.
    Reference: SuperFastSortExecutor, sql_executors.py:88-188 — same
    sorted-run + merge design, with the device doing every sort.

    Merge invariant: after device-sorting the in-memory buffers, every row at
    or before the FIRST buffer-tail row (the min over live runs of each run's
    last buffered row) is globally final — later chunks of every run sort
    after their run's tail.  Rows are tagged (__run, __pos) so that boundary
    is found by identity, not by re-comparing keys on the host."""

    def __init__(self, by: List[str], descending: List[bool],
                 spill_rows: Optional[int] = None,
                 chunk_rows: Optional[int] = None):
        self.by = by
        self.descending = descending
        self.parts: List[DeviceBatch] = []
        self.rows = 0
        self.spill_rows = spill_rows or config.SPILL_SORT_ROWS
        self.chunk_rows = chunk_rows or config.SPILL_MERGE_CHUNK_ROWS
        self.runs: List[str] = []
        self._dir: Optional[str] = None

    def execute(self, batches, stream_id, channel):
        for b in batches:
            if b is None:
                continue
            self.parts.append(b)
            self.rows += b.count_valid()
        if self.rows >= self.spill_rows:
            self._spill_run()

    def _spill_run(self):
        import os
        import tempfile

        import pyarrow as pa

        if not self.parts:
            return
        if self._dir is None:
            self._dir = _new_spill_dir("sort-")
        merged = bridge.concat_batches(self.parts) if len(self.parts) > 1 else self.parts[0]
        s = kernels.sort_batch(merged, self.by, self.descending)
        table = bridge.device_to_arrow(s)
        path = os.path.join(self._dir, f"run-{len(self.runs)}.arrow")
        with pa.OSFile(path, "wb") as f:
            with pa.ipc.new_file(f, table.schema) as w:
                w.write_table(table, max_chunksize=self.chunk_rows)
        self.runs.append(path)
        self.parts = []
        self.rows = 0

    def done(self, channel):
        if not self.runs:
            if not self.parts:
                return None
            merged = bridge.concat_batches(self.parts) if len(self.parts) > 1 else self.parts[0]
            self.parts = []
            return kernels.sort_batch(merged, self.by, self.descending)
        self._spill_run()
        return self._merge_and_cleanup()

    def _merge_and_cleanup(self):
        try:
            yield from self._merge_runs()
        finally:
            _drop_spill_dir(self._dir)

    def _merge_runs(self):
        import numpy as np
        import pyarrow as pa

        readers = [pa.ipc.open_file(p) for p in self.runs]
        n_chunks = [r.num_record_batches for r in readers]
        next_chunk = [0] * len(readers)
        next_pos = [0] * len(readers)
        buffers: List[Optional[DeviceBatch]] = [None] * len(readers)
        # bounds[i]: (run, pos) tag of run i's last READ row.  While set, no
        # row sorting after it may be emitted (unread rows of run i all sort
        # after it).  None <=> the run is fully read AND its tail was emitted.
        bounds: List[Optional[Tuple[int, int]]] = [None] * len(readers)
        carry: Optional[DeviceBatch] = None

        def load(i) -> None:
            if next_chunk[i] >= n_chunks[i]:
                bounds[i] = None  # exhausted
                return
            rb = readers[i].get_batch(next_chunk[i])
            next_chunk[i] += 1
            t = pa.Table.from_batches([rb])
            b = bridge.arrow_to_device(t)
            n = b.padded_len
            b = b.with_column("__run", NumCol(jnp.full(n, i, dtype=jnp.int32), "i"))
            b = b.with_column(
                "__pos",
                NumCol(jnp.arange(next_pos[i], next_pos[i] + n, dtype=jnp.int32), "i"),
            )
            next_pos[i] += t.num_rows
            bounds[i] = (i, next_pos[i] - 1)
            buffers[i] = b

        for i in range(len(readers)):
            load(i)
        while True:
            parts = [b for b in buffers if b is not None]
            if carry is not None and carry.count_valid() > 0:
                parts.append(carry)
            if not parts:
                break
            merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
            s = kernels.sort_batch(merged, self.by, self.descending)
            nvalid = s.count_valid()
            run_arr = np.asarray(s.columns["__run"].data)[:nvalid]
            pos_arr = np.asarray(s.columns["__pos"].data)[:nvalid]
            pending = [b for b in bounds if b is not None]
            if pending:
                cut = min(
                    int(np.nonzero((run_arr == r) & (pos_arr == p))[0][0])
                    for (r, p) in pending
                ) + 1
            else:
                cut = nvalid
            yield kernels.head(s, cut).drop(["__run", "__pos"])
            rest_mask = s.valid & (jnp.arange(s.padded_len) >= cut)
            rest = kernels.compact(kernels.apply_mask(s, rest_mask))
            carry = rest if rest.count_valid() > 0 else None
            # all buffered rows now live in carry (or were emitted); reload
            # any run whose tail row was emitted — only then can its next
            # chunk contribute to the frontier
            emitted_runs = {int(r) for r in run_arr[:cut]}
            for i in range(len(readers)):
                buffers[i] = None
                if bounds[i] is not None and bounds[i][0] in emitted_runs:
                    r, p = bounds[i]
                    if (run_arr[:cut] == r).any() and (
                        pos_arr[:cut][run_arr[:cut] == r].max() >= p
                    ):
                        load(i)


class CogroupExecutor(Executor):
    """Cogroup two key-partitioned streams (reference datastream.py:2073):
    buffer both sides, then per distinct key call fn(key, left_df, right_df)
    with host DataFrames (either may be empty) and emit the concatenated
    results.  Keys are colocated per channel by the hash-partitioned edges."""

    def __init__(self, left_on: str, right_on: str, fn: Callable,
                 out_schema: Sequence[str],
                 left_schema: Optional[Sequence[str]] = None,
                 right_schema: Optional[Sequence[str]] = None):
        self.left_on = left_on
        self.right_on = right_on
        self.fn = fn
        self.out_schema = list(out_schema)
        # plan-time schemas: a channel that received zero rows on one side
        # must still hand fn an empty frame WITH that side's columns
        self.left_schema = list(left_schema) if left_schema else None
        self.right_schema = list(right_schema) if right_schema else None
        self.left_parts: List[DeviceBatch] = []
        self.right_parts: List[DeviceBatch] = []

    def execute(self, batches, stream_id, channel):
        live = [b for b in batches if b is not None and b.count_valid() > 0]
        (self.left_parts if stream_id == 0 else self.right_parts).extend(live)
        return None

    def done(self, channel):
        import pandas as pd
        import pyarrow as pa

        def to_df(parts):
            if not parts:
                return None
            return pd.concat(
                [bridge.to_pandas(b) for b in parts], ignore_index=True
            )

        ldf, rdf = to_df(self.left_parts), to_df(self.right_parts)
        self.left_parts, self.right_parts = [], []
        if ldf is None and rdf is None:
            return None
        keys = set()
        if ldf is not None:
            keys |= set(ldf[self.left_on].dropna().unique().tolist())
        if rdf is not None:
            keys |= set(rdf[self.right_on].dropna().unique().tolist())
        outs = []
        empty_l = (ldf.iloc[0:0] if ldf is not None
                   else pd.DataFrame(columns=self.left_schema or []))
        empty_r = (rdf.iloc[0:0] if rdf is not None
                   else pd.DataFrame(columns=self.right_schema or []))
        for k in sorted(keys):
            lg = ldf[ldf[self.left_on] == k] if ldf is not None else empty_l
            rg = rdf[rdf[self.right_on] == k] if rdf is not None else empty_r
            out = self.fn(k, lg, rg)
            if out is not None and len(out):
                outs.append(out)
        if not outs:
            return None
        res = pd.concat(outs, ignore_index=True)[self.out_schema]
        return bridge.arrow_to_device(pa.Table.from_pandas(res, preserve_index=False))
