"""Core relational executors on device kernels.

Functional parity targets (reference: pyquokka/executors/sql_executors.py):
UDFExecutor:3, CountExecutor:69, StorageExecutor:24, BuildProbeJoinExecutor:325,
DistinctExecutor:517, SQLAggExecutor:556 (split here into PartialAgg/FinalAgg so
aggregation is decomposed partial->shuffle->final instead of concat-then-DuckDB),
ConcatThenSQLExecutor:45 (TopK/Sort below).
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from quokka_tpu import config
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops import join as join_ops
from quokka_tpu.ops.batch import DeviceBatch
from quokka_tpu.ops.expr_compile import AggPlan, evaluate_predicate, evaluate_to_column
from quokka_tpu.executors.base import Executor


class UDFExecutor(Executor):
    """Stateless per-batch transform (DataStream.transform)."""

    def __init__(self, fn: Callable[[DeviceBatch], DeviceBatch]):
        self.fn = fn

    def execute(self, batches, stream_id, channel):
        out = [self.fn(b) for b in batches if b is not None]
        out = [b for b in out if b is not None]
        if not out:
            return None
        return bridge.concat_batches(out) if len(out) > 1 else out[0]


class CountExecutor(Executor):
    def __init__(self):
        self.count = 0

    def execute(self, batches, stream_id, channel):
        self.count += sum(b.count_valid() for b in batches)

    def done(self, channel):
        import pyarrow as pa

        return bridge.arrow_to_device(pa.table({"count": [self.count]}))


class StorageExecutor(Executor):
    """Pass batches through unchanged (terminal collect node)."""

    def execute(self, batches, stream_id, channel):
        live = [b for b in batches if b is not None and b.count_valid() > 0]
        if not live:
            return None
        return bridge.concat_batches(live) if len(live) > 1 else live[0]


class SelectingStorageExecutor(StorageExecutor):
    """Terminal collect that also projects to the plan schema (picklable —
    the sink factory crosses process boundaries in the multi-worker runtime)."""

    def __init__(self, schema: Sequence[str]):
        self.schema = list(schema)

    def execute(self, batches, stream_id, channel):
        out = StorageExecutor.execute(self, batches, stream_id, channel)
        if out is None:
            return None
        return out.select([c for c in self.schema if c in out.columns])


class PartialAggExecutor(Executor):
    SUPPORTS_CHECKPOINT = True
    """Per-channel partial group-by: maintains one running partial-aggregate
    batch; emits it at done.  Sits upstream of the hash shuffle."""

    def __init__(self, keys: Sequence[str], plan: AggPlan):
        self.keys = list(keys)
        self.plan = plan
        self.state: Optional[DeviceBatch] = None
        from quokka_tpu.ops.fuse import FusedPartialAgg

        self._fused = FusedPartialAgg(self.keys, plan)

    def _partial(self, batch: DeviceBatch) -> DeviceBatch:
        from quokka_tpu.ops.expr_compile import CompileError

        try:
            g = self._fused(batch)
        except CompileError:
            b = batch
            for name, e in self.plan.pre:
                b = b.with_column(name, evaluate_to_column(e, b))
            aggs = [
                (p, op, None if tmp is None else b.columns[tmp].data)
                for (p, op, tmp) in self.plan.partials
            ]
            g = kernels.groupby_aggregate(b, self.keys, aggs)
        return kernels.compact(g.select(self.keys + [p for p, _, _ in self.plan.partials]))

    def _recombine(self, parts: List[DeviceBatch]) -> DeviceBatch:
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        aggs = [(p, op, merged.columns[p].data) for (p, op) in self.plan.recombine]
        g = kernels.groupby_aggregate(merged, self.keys, aggs)
        return kernels.compact(g.select(self.keys + [p for p, _ in self.plan.recombine]))

    def execute(self, batches, stream_id, channel):
        parts = [self._partial(b) for b in batches if b is not None]
        if self.state is not None:
            parts.append(self.state)
        if parts:
            self.state = self._recombine(parts)
        return None

    def done(self, channel):
        out, self.state = self.state, None
        return out

    def checkpoint(self):
        return None if self.state is None else bridge.device_to_arrow(self.state)

    def restore(self, state):
        self.state = None if state is None else bridge.arrow_to_device(state)


class FinalAggExecutor(Executor):
    """Downstream of the key shuffle: recombines partials for its key range,
    then applies final expressions, HAVING, ORDER BY and LIMIT at done."""

    def __init__(
        self,
        keys: Sequence[str],
        plan: AggPlan,
        having=None,
        order_by: Optional[List[Tuple[str, bool]]] = None,
        limit: Optional[int] = None,
    ):
        self.keys = list(keys)
        self.plan = plan
        self.having = having
        self.order_by = order_by
        self.limit = limit
        self.state: Optional[DeviceBatch] = None

    def execute(self, batches, stream_id, channel):
        parts = [b for b in batches if b is not None and b.count_valid() > 0]
        if self.state is not None:
            parts.append(self.state)
        if not parts:
            return None
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        aggs = [(p, op, merged.columns[p].data) for (p, op) in self.plan.recombine]
        g = kernels.groupby_aggregate(merged, self.keys, aggs)
        self.state = kernels.compact(g.select(self.keys + [p for p, _ in self.plan.recombine]))
        return None

    def done(self, channel):
        if self.state is None:
            if self.keys:
                return None
            # SQL semantics: a global aggregate over zero rows yields one row
            # (count = 0, sum = 0, min/max = null)
            import numpy as np
            import pyarrow as pa

            cols = {}
            for pname, op, _tmp in self.plan.partials:
                if op == "count":
                    cols[pname] = np.array([0], dtype=np.int64)
                elif op == "sum":
                    cols[pname] = np.array([0.0])
                else:
                    cols[pname] = np.array([np.nan])
            self.state = bridge.arrow_to_device(pa.table(cols))
        g = self.state
        for name, e in self.plan.finals:
            g = g.with_column(name, evaluate_to_column(e, g))
        # HAVING runs before the projection: it may reference partial columns
        # (aggregates rewritten by plan.rewrite) that the output drops
        if self.having is not None:
            g = kernels.compact(kernels.apply_mask(g, evaluate_predicate(self.having, g)))
        out_cols = self.keys + [n for n, _ in self.plan.finals]
        # dedupe (a key may also be an output)
        seen, cols = set(), []
        for c in out_cols:
            if c not in seen:
                seen.add(c)
                cols.append(c)
        g = g.select(cols)
        if self.order_by:
            names = [n for n, _ in self.order_by]
            desc = [d for _, d in self.order_by]
            if self.limit is not None:
                g = kernels.top_k(g, names, self.limit, desc)
            else:
                g = kernels.sort_batch(g, names, desc)
        elif self.limit is not None:
            g = kernels.head(g, self.limit)
        self.state = None
        return g


class BuildProbeJoinExecutor(Executor):
    SUPPORTS_CHECKPOINT = True

    """Streamed hash join: stream 1 is the build side (buffered until its
    stage completes), stream 0 probes.  Stage scheduling guarantees build
    completes before the first probe batch arrives (the reference asserts the
    same invariant, sql_executors.py:357)."""

    def __init__(
        self,
        left_on: Sequence[str],
        right_on: Sequence[str],
        how: str = "inner",
        suffix: str = "_2",
        rename: Optional[Dict[str, str]] = None,
        out_schema: Optional[List[str]] = None,
    ):
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.how = how
        self.suffix = suffix
        # plan-time output schema: lets a left join emit all-null payload even
        # when this channel never saw a single build batch (schema unknown)
        self.out_schema = list(out_schema) if out_schema else None
        # plan-time rename of clashing build columns; None -> detect at
        # runtime from the first probe batch (raw TaskGraph usage)
        self.planned_rename = rename
        self.build_parts: List[DeviceBatch] = []
        self.build: Optional[DeviceBatch] = None
        self.build_done = False
        self.probe_buffer: List[DeviceBatch] = []
        self.build_unique: Optional[bool] = None
        self.payload: Optional[List[str]] = None
        self.rename: Dict[str, str] = {}

    def _finalize_build(self, probe_cols: List[str]):
        if not self.build_parts:
            self.build = None
            return
        b = (
            bridge.concat_batches(self.build_parts)
            if len(self.build_parts) > 1
            else self.build_parts[0]
        )
        self.build_parts = []
        # payload = build columns minus its join keys; rename clashes
        payload = [c for c in b.names if c not in self.right_on]
        if self.planned_rename is not None:
            self.rename = {c: n for c, n in self.planned_rename.items() if c in payload}
        else:
            self.rename = {c: c + self.suffix for c in payload if c in probe_cols}
        if self.rename:
            b = b.rename(self.rename)
            payload = [self.rename.get(c, c) for c in payload]
        self.payload = payload
        self.build = b
        self.build_unique = join_ops.build_keys_unique(b, self.right_on)

    def execute(self, batches, stream_id, channel):
        live = [b for b in batches if b is not None]
        if not live:
            return None
        if stream_id == 1:
            assert self.build is None, "build batch arrived after probing began"
            self.build_parts.extend(live)
            return None
        # probe: if the build stream hasn't been declared exhausted yet
        # (stage-tie cases like self-joins), buffer and flush on source_done
        if not self.build_done:
            self.probe_buffer.extend(live)
            return None
        return self._probe(live)

    def source_done(self, stream_id, channel):
        if stream_id != 1 or self.build_done:
            return None
        self.build_done = True
        buffered, self.probe_buffer = self.probe_buffer, []
        if buffered:
            return self._probe(buffered)
        return None

    def _probe(self, live):
        if self.build is None and self.build_parts:
            self._finalize_build(live[0].names)
        if self.build is None:
            # No build batch ever arrived on this channel.  Engine.push always
            # delivers every hash partition (even zero-valid ones), so this
            # only happens when the build SOURCE emitted zero batches — i.e.
            # consistently on every channel.  Payload kinds are unknowable
            # then; all-null float columns stand in (documented limitation:
            # a string payload column degrades to float nulls in this case).
            if self.how in ("inner", "semi"):
                return None
            if self.how == "anti":
                out = live
                return bridge.concat_batches(out) if len(out) > 1 else out[0]
            if self.out_schema is None:
                raise RuntimeError(
                    "left join: build side produced no batches and no plan "
                    "schema was provided (pass out_schema=)"
                )
            import jax.numpy as jnp

            from quokka_tpu.ops.batch import NumCol

            outs = []
            for probe in live:
                payload = [c for c in self.out_schema if c not in probe.columns]
                b = probe
                for c in payload:
                    b = b.with_column(
                        c,
                        NumCol(jnp.full(b.padded_len, jnp.nan, config.float_dtype()), "f"),
                    )
                outs.append(b)
            return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]
        if self.build.count_valid() == 0 and self.how in ("inner", "semi"):
            return None
        # empty-but-schema'd build: anti/left fall through — the general join
        # kernel handles a zero-valid build (every probe row unmatched)
        outs = []
        for probe in live:
            if self.build_unique and self.how in ("inner", "semi", "anti"):
                out = join_ops.hash_join_pk(
                    probe, self.build, self.left_on, self.right_on, self.how, self.payload
                )
            else:
                out = join_ops.hash_join_general(
                    probe, self.build, self.left_on, self.right_on, self.how, self.payload
                )
            if out is not None:
                outs.append(out)
        if not outs:
            return None
        return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]

    def checkpoint(self):
        build = self.build
        if build is None and self.build_parts:
            build = bridge.concat_batches(self.build_parts)
        return {
            "build": None if build is None else bridge.device_to_arrow(build),
            # without these, a restore past the build's source_done event
            # would buffer every probe batch forever (build_done False) and
            # silently emit nothing
            "build_done": self.build_done,
            "finalized": self.build is not None,
            "rename": self.rename,
            "payload": self.payload,
            "probe_buffer": [bridge.device_to_arrow(b) for b in self.probe_buffer],
        }

    def restore(self, state):
        if state is None:
            return
        if not isinstance(state, dict):  # legacy: bare build table
            self.build_parts = [bridge.arrow_to_device(state)]
            return
        if state["build"] is not None:
            b = bridge.arrow_to_device(state["build"])
            if state["finalized"]:
                self.build = b
                self.rename = state["rename"]
                self.payload = state["payload"]
                self.build_unique = join_ops.build_keys_unique(b, self.right_on)
            else:
                self.build_parts = [b]
        self.build_done = state["build_done"]
        self.probe_buffer = [
            bridge.arrow_to_device(t) for t in state["probe_buffer"]
        ]


class BroadcastJoinExecutor(BuildProbeJoinExecutor):
    """Small side broadcast to every channel (reference sql_executors.py:275):
    identical device logic; only the partitioner differs (Broadcast)."""


class DistinctExecutor(Executor):
    """Streaming distinct: emit rows not seen before (anti-join against the
    accumulated key state, reference sql_executors.py:517)."""

    def __init__(self, keys: Sequence[str]):
        self.keys = list(keys)
        self.seen: Optional[DeviceBatch] = None

    def execute(self, batches, stream_id, channel):
        outs = []
        for b in batches:
            if b is None:
                continue
            b = kernels.distinct(b, self.keys)
            b = kernels.compact(b)
            if self.seen is not None:
                b = kernels.compact(
                    join_ops.hash_join_general(b, self.seen, self.keys, self.keys, "anti")
                )
            if b.count_valid() == 0:
                continue
            self.seen = (
                b if self.seen is None else bridge.concat_batches([self.seen, b])
            )
            outs.append(b)
        if not outs:
            return None
        return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]


class TopKExecutor(Executor):
    """Running top-k by sort keys (reference expresses this via
    ConcatThenSQLExecutor; here the running state is never larger than k)."""

    def __init__(self, by: List[str], k: int, descending: List[bool]):
        self.by = by
        self.k = k
        self.descending = descending
        self.state: Optional[DeviceBatch] = None

    def execute(self, batches, stream_id, channel):
        parts = [b for b in batches if b is not None]
        if self.state is not None:
            parts.append(self.state)
        if not parts:
            return None
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        self.state = kernels.top_k(merged, self.by, self.k, self.descending)
        return None

    def done(self, channel):
        out, self.state = self.state, None
        return out


class SortExecutor(Executor):
    """Blocking sort: accumulate, sort once at done.  (External merge-sort
    with spill, as in SuperFastSortExecutor, is a later tier.)"""

    def __init__(self, by: List[str], descending: List[bool]):
        self.by = by
        self.descending = descending
        self.parts: List[DeviceBatch] = []

    def execute(self, batches, stream_id, channel):
        self.parts.extend(b for b in batches if b is not None)

    def done(self, channel):
        if not self.parts:
            return None
        merged = bridge.concat_batches(self.parts) if len(self.parts) > 1 else self.parts[0]
        self.parts = []
        return kernels.sort_batch(merged, self.by, self.descending)
