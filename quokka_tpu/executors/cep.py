"""Complex-event-processing (pattern recognition) executor.

Reference parity: CEPExecutor / nfa_cep (pyquokka/executors/cep_executors.py:
13-272): given an ordered event pattern [(name, condition), ...] and a time
bound, find row sequences e1 < e2 < ... < ek within `within` time units where
each condition holds; conditions may reference prior events' bound values as
``name.column``.

TPU-hybrid design (SURVEY.md hard-part #6): per-event row predicates that
depend only on the current row are evaluated as vectorized device masks (one
fused pass over the batch); the genuinely sequential NFA walk then runs on the
host but only over the sparse candidate rows that passed some mask.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from quokka_tpu import sqlparse
from quokka_tpu.executors.base import Executor
from quokka_tpu.expression import Expr
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops.batch import DeviceBatch
from quokka_tpu.ops.expr_compile import CompileError, evaluate_predicate

_BINDING_RE = re.compile(r"\b([A-Za-z_][A-Za-z_0-9]*)\.([A-Za-z_][A-Za-z_0-9]*)\b")


class CEPExecutor(Executor):
    """Match an event pattern on a time-ordered stream.

    events: [(name, condition_sql)]; conditions may use `prior.col` bindings.
    Emits one row per match: {<name>_<time_col> for each event} + key columns.
    Matching semantics: each event binds the FIRST row satisfying its
    condition after the previous event (skip-till-next-match), all within
    `within` of the first event.
    """

    def __init__(self, time_col: str, events: Sequence[Tuple[str, str]],
                 within, by: Optional[Sequence[str]] = None):
        self.time_col = time_col
        self.within = within
        self.by = list(by or [])
        self.names = [n for n, _ in events]
        self.conds = [c for _, c in events]
        # split each condition into a self-only device prefilter and a
        # binding-dependent host residual
        self.device_pred: List[Optional[Expr]] = []
        self.host_cond: List[Optional[str]] = []
        for cond in self.conds:
            if _BINDING_RE.search(cond):
                self.device_pred.append(None)
                self.host_cond.append(cond)
            else:
                self.device_pred.append(sqlparse.parse_expression(cond))
                self.host_cond.append(None)
        self.buffer: List = []  # host rows pending (may match future events)
        self.schema_cols: Optional[List[str]] = None

    def execute(self, batches, stream_id, channel):
        import pandas as pd

        import jax.numpy as jnp

        rows = []
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            # device prefilter: keep only rows that can participate in ANY
            # event (sparse candidates for the host NFA)
            any_mask = jnp.zeros(b.padded_len, dtype=bool)
            for pred in self.device_pred:
                if pred is None:
                    any_mask = b.valid
                    break
                any_mask = any_mask | evaluate_predicate(pred, b)
            df = bridge.to_pandas(kernels.compact(kernels.apply_mask(b, any_mask)))
            rows.append(df)
        if not rows:
            return None
        if self.buffer:
            rows = self.buffer + rows
        df = pd.concat(rows, ignore_index=True) if len(rows) > 1 else rows[0]
        self.schema_cols = list(df.columns)
        # matches starting after (watermark - within) may still grow with
        # future rows: emit only fully-determined matches, carry the tail
        watermark = df[self.time_col].max()
        cutoff = watermark - self.within
        matches = self._scan(df, start_cutoff=cutoff)
        self.buffer = [df[df[self.time_col] > cutoff]]
        if matches is None or len(matches) == 0:
            return None
        import pyarrow as pa

        return bridge.arrow_to_device(pa.Table.from_pandas(matches, preserve_index=False))

    def done(self, channel):
        import pandas as pd

        if not self.buffer:
            return None
        df = pd.concat(self.buffer, ignore_index=True)
        self.buffer = []
        if len(df) == 0:
            return None
        self.schema_cols = list(df.columns)
        matches = self._scan(df)
        if matches is None or len(matches) == 0:
            return None
        import pyarrow as pa

        return bridge.arrow_to_device(pa.Table.from_pandas(matches, preserve_index=False))

    def _eval_cond(self, cond: str, row, bound: Dict[str, Dict]) -> bool:
        expr = cond
        env = {}
        for name, b in bound.items():
            env[name] = b

        def repl(m):
            return f"__b['{m.group(1)}']['{m.group(2)}']"

        py = _BINDING_RE.sub(repl, expr)
        py = re.sub(r"\band\b", " and ", py)
        py = re.sub(r"\bor\b", " or ", py)
        py = re.sub(r"(?<![<>!=])=(?!=)", "==", py)
        try:
            cols = {c: row[c] for c in self.schema_cols or []}
            return bool(eval(py, {"__b": env, "__builtins__": {}}, cols))
        except Exception:
            return False

    def _scan(self, df, start_cutoff=None):
        import pandas as pd

        out = []
        groups = df.groupby(self.by) if self.by else [((), df)]
        for gkey, g in groups:
            g = g.sort_values(self.time_col)
            recs = g.to_dict("records")
            n = len(recs)
            k = len(self.names)
            for i, start in enumerate(recs):
                if start_cutoff is not None and start[self.time_col] > start_cutoff:
                    continue  # not yet determined; retried from the carry
                if not self._row_matches(0, start, {}):
                    continue
                bound = {self.names[0]: start}
                t0 = start[self.time_col]
                j = i + 1
                level = 1
                while level < k and j < n:
                    row = recs[j]
                    if row[self.time_col] - t0 > self.within:
                        break
                    if self._row_matches(level, row, bound):
                        bound[self.names[level]] = row
                        level += 1
                    j += 1
                if level == k:
                    rec = {}
                    if self.by:
                        keyvals = gkey if isinstance(gkey, tuple) else (gkey,)
                        for c, v in zip(self.by, keyvals):
                            rec[c] = v
                    for name in self.names:
                        rec[f"{name}_{self.time_col}"] = bound[name][self.time_col]
                    out.append(rec)
        if not out:
            return None
        return pd.DataFrame(out)

    def _row_matches(self, level: int, row, bound) -> bool:
        cond = self.conds[level]
        if self.host_cond[level] is None:
            # pure self-condition: re-evaluate cheaply on host
            return self._eval_cond(cond, row, {})
        return self._eval_cond(cond, row, bound)
