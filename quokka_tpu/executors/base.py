"""Executor protocol.

Same plugin boundary as the reference (pyquokka/executors/base_executor.py:26-32):
an executor is a per-channel stateful object the runtime drives with
``execute(batches, stream_id, channel)`` for every input batch-set and
``done(channel)`` once all inputs are exhausted; optional checkpoint/restore
make it fault-tolerant.  Batches here are DeviceBatches (on-chip), and
executors express their compute as jitted kernel calls.
"""

from __future__ import annotations

from typing import List, Optional

from quokka_tpu.ops.batch import DeviceBatch


class Executor:
    # executors that implement checkpoint()/restore() set this True; the
    # runtime must NOT record a recovery point for executors without real
    # snapshot support (a fresh instance + full tape replay is the only safe
    # recovery for them)
    SUPPORTS_CHECKPOINT = False

    def execute(
        self, batches: List[DeviceBatch], stream_id: int, channel: int
    ) -> Optional[DeviceBatch]:
        raise NotImplementedError

    def done(self, channel: int) -> Optional[DeviceBatch]:
        return None

    def source_done(self, stream_id: int, channel: int) -> Optional[DeviceBatch]:
        """Called by the runtime when one input stream is exhausted (other
        streams may still flow).  Lets multi-stream executors (joins) finalize
        a side; may return an output batch."""
        return None

    # -- fault tolerance hooks (optional) ------------------------------------
    def checkpoint(self):
        """Return a picklable snapshot of executor state, or None."""
        return None

    def restore(self, state) -> None:
        pass
