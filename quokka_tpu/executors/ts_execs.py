"""Time-series executors: asof join, tumbling/hopping/sliding/session windows.

Reference parity: pyquokka/executors/ts_executors.py — SortedAsofExecutor:324,
HoppingWindowExecutor:12, SlidingWindowExecutor:147, SessionWindowExecutor:197.
The sequential frontier walks become batched device kernels (merged sort +
segmented scans, ops/asof.py); executors keep only watermark state and the
buffered tail that future batches can still affect.

All executors assume their channel receives a per-key time-ordered stream —
guaranteed by sorted sources (SAT interleaved delivery, runtime/cache.py) and
hash-by-key partitioning.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from quokka_tpu.executors.base import Executor
from quokka_tpu.ops import asof as asof_ops
from quokka_tpu.ops import bridge, kernels, timewide
from quokka_tpu.ops.batch import DeviceBatch, NumCol
from quokka_tpu.ops.expr_compile import AggPlan, evaluate_to_column
from quokka_tpu.windows import (
    HoppingWindow,
    OnCompletionTrigger,
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
    Trigger,
    Window,
)


def _time_max(batch: DeviceBatch, col: str):
    """Watermark: float for float times, exact host int for (wide) int times."""
    c = batch.columns[col]
    if c.hi is not None:
        return timewide.host_max_i64(c, batch.valid)
    return float(kernels.reduce_array(c.data, batch.valid, "max"))


def _time_min(batch: DeviceBatch, col: str, valid=None):
    """Min over `valid` (default batch.valid): float or exact host int."""
    c = batch.columns[col]
    v = batch.valid if valid is None else valid
    if c.hi is not None:
        return timewide.host_min_i64(c, v)
    return float(kernels.reduce_array(c.data, v, "min"))


def _cmp_time(col, v, op: str):
    """col <op> v where v is a host watermark (int, float, or +/-inf) and col
    may be a two-limb wide column."""
    if isinstance(v, float) and not np.isfinite(v):
        full = jnp.ones(col.padded_len, dtype=bool)
        hit = (v > 0) if op in ("<", "<=") else (v < 0)
        return full if hit else ~full
    if col.hi is None:
        d = col.data
        return {"<": d < v, "<=": d <= v, ">": d > v, ">=": d >= v,
                "=": d == v, "!=": d != v}[op]
    return timewide.cmp_scalar(col, int(v), op)


class _TimeRebase:
    """Exact int32 rebase for wide (two-limb int64) time columns.

    Streaming executors do single-array time arithmetic (watermarks, ``t //
    hop``, ``t - size``).  Wide columns are rebased once per executor onto an
    int32 window relative to a host base taken from the first batch (minus
    2**29 slack for late/out-of-order starts, floor-aligned to the window hop
    so absolute window boundaries stay epoch-aligned).  The rebase is exact or
    it raises — never a silent low-limb truncation (see ops/timewide.py).
    Emitted absolute times are reconstructed with ``add_base``.
    """

    _tbase: Optional[int] = None
    _t_kind: Optional[str] = None
    _t_unit: Optional[str] = None

    def _rebase_batch(self, batch: DeviceBatch, col_name: str, align: int = 1,
                      headroom: int = 0) -> DeviceBatch:
        col = batch.columns[col_name]
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            return batch
        if self._tbase is None:
            # The base is fixed by the FIRST batch — including a narrow-int32
            # one (base 0, passthrough).  A later wide batch then rebases
            # against base 0 and raises cleanly instead of silently mixing
            # absolute and rebased window coordinates in one executor state.
            # Narrow int64 (x64 mode) keeps absolute coordinates while they
            # fit int32 window arithmetic (parity with the non-x64 narrow
            # path) and rebases like wide when they don't (ns epochs — the
            # downstream ``wid.astype(int32)`` would overflow).
            if col.hi is None and col.data.dtype != jnp.int64:
                self._tbase = 0
            else:
                if batch.count_valid():
                    mn = timewide.host_min_i64(col, batch.valid)
                    mx = timewide.host_max_i64(col, batch.valid)
                else:
                    mn = mx = 0
                if (
                    col.hi is None
                    and mn > -(2**31)
                    and mx < 2**31 - 1 - headroom
                ):
                    self._tbase = 0
                else:
                    align = max(1, int(align))
                    self._tbase = ((mn - 2**29) // align) * align
            self._t_kind = col.kind
            self._t_unit = col.unit
        if self._tbase == 0 and col.hi is None:
            if col.data.dtype == jnp.int64 and batch.count_valid():
                # absolute-coordinate mode was fixed by the first batch:
                # verify every later batch still fits int32 instead of
                # silently overflowing downstream casts
                mx = timewide.host_max_i64(col, batch.valid)
                mn = timewide.host_min_i64(col, batch.valid)
                if mn <= -(2**31) or mx >= 2**31 - 1 - headroom:
                    raise ValueError(
                        "time column left the int32 window range fixed by "
                        "the stream's first batch; cast to a coarser unit "
                        "(e.g. ms/s)"
                    )
            return batch  # narrow stream: absolute int32 coordinates as-is
        rel = timewide.rebase_narrow(col, batch.valid, self._tbase, headroom)
        return batch.with_column(col_name, rel)

    def _restore_time(self, data, kind: str = "i") -> NumCol:
        if self._tbase is None:
            return NumCol(data, kind)
        return timewide.add_base(data, self._tbase, self._t_kind or kind, self._t_unit)


class SortedAsofExecutor(Executor):
    SUPPORTS_CHECKPOINT = True

    """Streaming backward asof join.  Stream 0 = left/trades, stream 1 =
    right/quotes.  Trades are emitted once the quote watermark passes their
    timestamp; the quote buffer is pruned to the last quote per key below the
    frontier plus everything above it."""

    # large streams flush in chunks of at least this many ready trades (the
    # joint sort per flush covers the whole quote buffer)
    MIN_FLUSH_ROWS = 1 << 19

    # prune the quote buffer only past this many padded rows: pruning costs
    # a full-buffer sort, so below the valve it is pure overhead — keeping
    # already-matched quotes around is semantically harmless for backward
    # asof (they simply lose to later quotes)
    PRUNE_ROWS = 1 << 23

    # asof_probe="coalesced" (ops/strategy.py): on big streams, hold ready
    # trades until at least this many accumulate so each flush's joint sort
    # amortizes over one large probe instead of per-dispatch slivers.  Safe
    # to hold: quotes arrive at/after the watermark that made these trades
    # ready, so a later flush computes the identical matches.
    COALESCE_ROWS = 1 << 15

    def __init__(self, left_on: str, right_on: str, left_by, right_by,
                 suffix: str = "_2", keep_unmatched: bool = False,
                 direction: str = "backward"):
        if direction not in ("backward", "forward"):
            raise ValueError(direction)
        self.direction = direction
        self.left_on = left_on
        self.right_on = right_on
        self.left_by = list(left_by or [])
        self.right_by = list(right_by or [])
        self.suffix = suffix
        self.keep_unmatched = keep_unmatched
        self.trades: Optional[DeviceBatch] = None
        self.quotes: Optional[DeviceBatch] = None
        # incoming batches buffer in LISTS; the quote buffer concats only
        # when a flush actually runs a join (the flush-throttle gates pass
        # on watermarks + running VALID counts first) — eager per-append
        # concats of a growing buffer were the executor's top cost at scale
        self._t_parts: List[DeviceBatch] = []
        self._q_parts: List[DeviceBatch] = []
        # running valid-row counts: gate decisions key on CONTENT (counts),
        # never on padded lengths — padding is not preserved across
        # checkpoint/restore, and a padded-length gate would flip emission
        # decisions during tape replay (the engine asserts re_emitted ==
        # emitted)
        self._t_rows = 0
        self._q_rows = 0
        self.q_watermark: Optional[float] = None
        self.t_watermark: Optional[float] = None
        self.q_done = False
        self.payload: Optional[List[str]] = None
        self.rename: Dict[str, str] = {}
        # renamed view of the current quote buffer, cached by buffer
        # identity: DeviceBatch.rename builds a NEW object, which would
        # discard the searchsorted strategy's cached quote sort
        # (ops/asof._ss_quote_sorted) on every flush even when no quotes
        # arrived — derived state, deliberately not checkpointed
        self._renamed_src: Optional[DeviceBatch] = None
        self._renamed: Optional[DeviceBatch] = None

    def _materialize_trades(self) -> None:
        if self._t_parts:
            parts = ([self.trades] if self.trades is not None else []) + self._t_parts
            self._t_parts = []
            self.trades = (
                bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
            )

    def _materialize_quotes(self) -> None:
        if self._q_parts:
            parts = ([self.quotes] if self.quotes is not None else []) + self._q_parts
            self._q_parts = []
            self.quotes = (
                bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
            )

    def execute(self, batches, stream_id, channel):
        from quokka_tpu.obs import opstats
        from quokka_tpu.ops import strategy as kstrategy

        live = [b for b in batches if b is not None and b.count_valid() > 0]
        if stream_id == 0:
            mode = kstrategy.choice("asof_probe")
            kstrategy.note_used("asof_probe", mode)
            if mode == "coalesced" and len(live) > 1:
                # the join probe's bucketed concat path: a dispatch's small
                # per-partition slices merge cap-aware before buffering
                from quokka_tpu.executors.sql_execs import _coalesce

                live = _coalesce(live)
        if stream_id == 1:
            for b in live:
                self._q_parts.append(b)
                self._q_rows += b.count_valid()
                wm = _time_max(b, self.right_on)
                if self.q_watermark is None or wm > self.q_watermark:
                    self.q_watermark = wm
            # quote side is the asof's build analog (counts already host-
            # resolved by the live filter above — no extra sync)
            opstats.note(join_build_rows=sum(b.nrows for b in live))
            return self._flush()
        for b in live:
            self._t_parts.append(b)
            self._t_rows += b.count_valid()
            wm = _time_max(b, self.left_on)
            if self.t_watermark is None or wm > self.t_watermark:
                self.t_watermark = wm
        opstats.note(join_probe_rows=sum(
            b.nrows if b.nrows is not None else b.padded_len for b in live))
        return self._flush()

    def source_done(self, stream_id, channel):
        if stream_id == 1:
            self.q_done = True
            return self._flush()
        return None

    def done(self, channel):
        self.q_done = True
        return self._flush(final=True)

    def _setup_payload(self, probe_names):
        if self.payload is None:
            payload = [c for c in self.quotes.names
                       if c not in set(self.right_by) and c != self.right_on]
            self.rename = {c: c + self.suffix for c in payload if c in probe_names}
            self.payload = [self.rename.get(c, c) for c in payload]

    def _renamed_quotes(self) -> DeviceBatch:
        """The (possibly renamed) quote buffer to join against, one rename
        per buffer object: repeated flushes of an unchanged buffer reuse
        the same DeviceBatch, keeping its cached quote-side sort warm."""
        if not self.rename:
            return self.quotes
        if self._renamed_src is not self.quotes:
            self._renamed_src = self.quotes
            self._renamed = self.quotes.rename(self.rename)
        return self._renamed

    def _flush(self, final: bool = False):
        self._materialize_trades()
        if self.trades is None or self.trades.count_valid() == 0:
            return None
        if self.quotes is None and not self._q_parts:
            if self.q_done:
                out, self.trades = self.trades, None
                return out if self.keep_unmatched else None
            return None
        if self.direction == "forward":
            self._materialize_quotes()
            return self._flush_forward()
        if self.q_done:
            safe = float("inf")
        elif self.q_watermark is None:
            return None
        else:
            safe = self.q_watermark
        tcol = self.trades.columns[self.left_on]
        # strictly below the quote watermark: a future quote batch can still
        # contain quotes at exactly `safe` (ties must win per backward-asof)
        op = "<=" if safe == float("inf") else "<"
        ready_mask = self.trades.valid & _cmp_time(tcol, safe, op)
        nready = int(jnp.sum(ready_mask.astype(jnp.int32)))
        if nready == 0:
            return None
        # each flush pays one joint sort of (ready + ENTIRE quote buffer) —
        # at scale, emitting per event makes that quadratic-ish.  Large
        # streams accumulate ready trades into big flushes; small streams
        # (below the threshold) keep per-event emission.  Gates key on
        # running VALID counts (content-deterministic across replay); the
        # quote buffer has not been concatenated yet when they bail
        big = self._t_rows + self._q_rows > 4 * self.MIN_FLUSH_ROWS
        if big and not self.q_done and nready < self.MIN_FLUSH_ROWS:
            return None
        # asof_probe="coalesced": mid-size streams also hold sliver flushes
        # until a worthwhile probe accumulates (each flush pays a joint sort
        # over the whole quote buffer).  Content-identical output — quotes
        # arriving after the hold are at/above the watermark that made these
        # trades ready, so they can't change a held trade's match.  The gate
        # keys on VALID counts only (deterministic under tape replay).
        if (
            not self.q_done
            and nready < self.COALESCE_ROWS
            and self._t_rows + self._q_rows > 2 * self.COALESCE_ROWS
        ):
            from quokka_tpu.ops import strategy as kstrategy

            if kstrategy.choice("asof_probe") == "coalesced":
                return None
        self._materialize_quotes()
        ready = kernels.compact(kernels.apply_mask(self.trades, ready_mask))
        if ready.count_valid() == 0:
            return None
        rest = kernels.compact(kernels.apply_mask(self.trades, self.trades.valid & ~ready_mask))
        self.trades = rest if rest.count_valid() > 0 else None
        self._t_rows = 0 if self.trades is None else self.trades.count_valid()
        self._setup_payload(ready.names)
        quotes = self._renamed_quotes()
        out = asof_ops.asof_join(
            ready, quotes, self.left_on, self.right_on,
            self.left_by, self.right_by, self.payload,
        )
        matched = out.columns.pop("__asof_matched__")
        if not self.keep_unmatched:
            out = kernels.apply_mask(out, matched.data)
        # prune only below what BOTH streams have passed: future trades can
        # still arrive below the quote watermark when quotes run ahead —
        # and only past the memory valve (pruning costs a full-buffer sort;
        # the count-based gate keys on content, so replay reproduces it)
        if self.quotes is not None and self._q_rows >= self.PRUNE_ROWS:
            prune_to = safe
            if self.t_watermark is not None:
                prune_to = min(prune_to, self.t_watermark)
            self._prune_quotes(prune_to)
            self._q_rows = 0 if self.quotes is None else self.quotes.count_valid()
        return out

    def _flush_forward(self):
        """Forward asof: a trade's match is the FIRST quote of its key at/after
        its time.  A global quote watermark can't tell us a per-key match has
        arrived, so instead: join the whole buffer, and a matched trade is
        final (future quotes arrive later in time and can't beat the match).
        To keep the output time-ordered, matched trades are held back until no
        earlier trade remains unmatched."""
        self._setup_payload(self.trades.names)
        quotes = self._renamed_quotes()
        out = asof_ops.asof_join(
            self.trades, quotes, self.left_on, self.right_on,
            self.left_by, self.right_by, self.payload, direction="forward",
        )
        matched = out.columns.pop("__asof_matched__").data
        if self.q_done:
            result = out if self.keep_unmatched else kernels.compact(
                kernels.apply_mask(out, matched)
            )
            self.trades = None
            self.quotes = None
            self._t_rows = 0
            self._q_rows = 0
            return result if result.count_valid() > 0 else None
        tcol = self.trades.columns[self.left_on]
        unmatched = self.trades.valid & ~matched
        emit = self.trades.valid & matched
        if bool(jnp.any(unmatched)):
            cutoff = _time_min(self.trades, self.left_on, unmatched)
            emit = emit & _cmp_time(tcol, cutoff, "<")
        result = kernels.compact(kernels.apply_mask(out, emit))
        rest = kernels.compact(
            kernels.apply_mask(self.trades, self.trades.valid & ~emit)
        )
        self.trades = rest if rest.count_valid() > 0 else None
        self._t_rows = 0 if self.trades is None else self.trades.count_valid()
        # prune quotes below every retained and every possible future trade —
        # forward matches need quote time >= trade time, so those can't match
        bound = self.t_watermark
        if self.trades is not None:
            tmin = _time_min(self.trades, self.left_on)
            bound = tmin if bound is None else min(bound, tmin)
        if bound is not None and self.quotes is not None:
            q = self.quotes
            keep = q.valid & _cmp_time(q.columns[self.right_on], bound, ">=")
            pruned = kernels.compact(kernels.apply_mask(q, keep))
            self.quotes = pruned if pruned.count_valid() > 0 else None
            self._q_rows = 0 if self.quotes is None else self.quotes.count_valid()
        return result if result.count_valid() > 0 else None

    def _prune_quotes(self, safe):
        """Drop quotes no future trade can match: everything at/below the
        frontier except the latest quote per key.  Sort-based so it is exact
        for wide (two-limb) time columns — sort_batch keys are limb-aware."""
        if self.quotes is None or safe == float("inf"):
            if self.q_done:
                self.quotes = None
            return
        q = self.quotes
        qt = q.columns[self.right_on]
        above = q.valid & _cmp_time(qt, safe, ">")
        below = q.valid & ~above
        if self.right_by:
            s = kernels.sort_batch(q, self.right_by + [self.right_on])
            st = s.columns[self.right_on]
            s_below = s.valid & _cmp_time(st, safe, "<=")
            from quokka_tpu.ops.batch import key_limbs

            n = s.padded_len
            limbs = key_limbs(s, self.right_by)
            next_key_same = jnp.ones(n, dtype=bool)
            for l in limbs:
                next_key_same = next_key_same & (l == jnp.roll(l, -1))
            next_key_same = next_key_same.at[n - 1].set(False)
            next_below = jnp.roll(s_below, -1).at[n - 1].set(False) & jnp.roll(
                s.valid, -1
            ).at[n - 1].set(False)
            # last below-frontier quote in its key run: successor is out of
            # key, invalid, or above the frontier
            is_last_below = s_below & ~(next_key_same & next_below)
            keep_s = (s.valid & _cmp_time(st, safe, ">")) | is_last_below
            pruned = kernels.compact(kernels.apply_mask(s, keep_s))
        else:
            if bool(jnp.any(below)):
                maxt = _time_max(
                    DeviceBatch(
                        {self.right_on: qt}, below, None, None
                    ),
                    self.right_on,
                )
                keep = above | (below & _cmp_time(qt, maxt, "="))
            else:
                keep = above
            pruned = kernels.compact(kernels.apply_mask(q, keep))
        self.quotes = pruned if pruned.count_valid() > 0 else None

    def checkpoint(self):
        self._materialize_trades()  # fold pending parts into the buffers
        self._materialize_quotes()
        return {
            "trades": None if self.trades is None else bridge.device_to_arrow(self.trades),
            "quotes": None if self.quotes is None else bridge.device_to_arrow(self.quotes),
            "q_watermark": self.q_watermark,
            "t_watermark": self.t_watermark,
            "q_done": self.q_done,
        }

    def restore(self, state):
        self._t_parts = []
        self._q_parts = []
        if state is None:
            return
        self.trades = None if state["trades"] is None else bridge.arrow_to_device(state["trades"])
        self.quotes = None if state["quotes"] is None else bridge.arrow_to_device(state["quotes"])
        self._t_rows = 0 if self.trades is None else self.trades.count_valid()
        self._q_rows = 0 if self.quotes is None else self.quotes.count_valid()
        self.q_watermark = state["q_watermark"]
        self.t_watermark = state.get("t_watermark")
        self.q_done = state["q_done"]


class _PartialWindowAgg:
    """Shared helper: turn a raw batch into partial-agg rows over
    (keys + window id), and recombine partial batches."""

    def __init__(self, keys: Sequence[str], plan: AggPlan, wid_col: str = "__wid"):
        self.keys = list(keys)
        self.plan = plan
        self.wid_col = wid_col

    def partial(self, batch: DeviceBatch) -> DeviceBatch:
        b = batch
        for name, e in self.plan.pre:
            b = b.with_column(name, evaluate_to_column(e, b))
        aggs = [
            (p, op, None if tmp is None else b.columns[tmp].data)
            for (p, op, tmp) in self.plan.partials
        ]
        g = kernels.groupby_aggregate(b, self.keys + [self.wid_col], aggs)
        return kernels.compact(
            g.select(self.keys + [self.wid_col] + [p for p, _, _ in self.plan.partials])
        )

    def recombine(self, parts: List[DeviceBatch]) -> DeviceBatch:
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        aggs = [(p, op, merged.columns[p].data) for (p, op) in self.plan.recombine]
        g = kernels.groupby_aggregate(merged, self.keys + [self.wid_col], aggs)
        return kernels.compact(
            g.select(self.keys + [self.wid_col] + [p for p, _ in self.plan.recombine])
        )

    def finalize(self, g: DeviceBatch, extra: Sequence[str] = ()) -> DeviceBatch:
        for name, e in self.plan.finals:
            g = g.with_column(name, evaluate_to_column(e, g))
        cols = self.keys + list(extra) + [n for n, _ in self.plan.finals]
        seen, out = set(), []
        for c in cols:
            if c not in seen:
                seen.add(c)
                out.append(c)
        return g.select(out)


class HoppingWindowExecutor(_TimeRebase, Executor):
    """Hopping (and tumbling: hop == size) window aggregation.  Rows are
    replicated size//hop times onto their covering windows (static factor),
    partially aggregated, and windows are emitted once the watermark passes
    their end (OnEventTrigger) or all at done (OnCompletionTrigger)."""

    def __init__(self, time_col: str, keys: Sequence[str], window: Window,
                 plan: AggPlan, trigger: Optional[Trigger] = None):
        if isinstance(window, TumblingWindow):
            self.size, self.hop = window.size, window.size
        elif isinstance(window, HoppingWindow):
            self.size, self.hop = window.size, window.hop
        else:
            raise TypeError(f"expected Tumbling/HoppingWindow, got {type(window)}")
        self.time_col = time_col
        self.keys = list(keys)
        self.plan = plan
        self.emit_incremental = not isinstance(trigger, OnCompletionTrigger)
        self.helper = _PartialWindowAgg(self.keys, plan)
        self.state: Optional[DeviceBatch] = None

    def _assign_windows(self, batch: DeviceBatch) -> DeviceBatch:
        k = self.size // self.hop
        t = batch.columns[self.time_col].data
        reps = []
        for j in range(k):
            wid = t // self.hop - j
            ok = (wid >= 0) & (t < (wid * self.hop + self.size)) & (t >= wid * self.hop)
            b = batch.with_column("__wid", NumCol(wid.astype(jnp.int32), "i"))
            reps.append(kernels.apply_mask(b, ok))
        return bridge.concat_batches(reps) if len(reps) > 1 else reps[0]

    def execute(self, batches, stream_id, channel):
        parts = []
        watermark = None
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            b = self._rebase_batch(
                b, self.time_col, align=self.hop, headroom=self.size + self.hop
            )
            watermark = _time_max(b, self.time_col)
            parts.append(self.helper.partial(self._assign_windows(b)))
        if self.state is not None:
            parts.append(self.state)
        if not parts:
            return None
        self.state = self.helper.recombine(parts)
        if not self.emit_incremental or watermark is None:
            return None
        # windows fully below the watermark cannot receive future rows
        wid = self.state.columns["__wid"].data
        closed = self.state.valid & ((wid * self.hop + self.size) <= watermark)
        ready = kernels.compact(kernels.apply_mask(self.state, closed))
        if ready.count_valid() == 0:
            return None
        rest = kernels.compact(kernels.apply_mask(self.state, self.state.valid & ~closed))
        self.state = rest if rest.count_valid() > 0 else None
        return self._emit(ready)

    def _emit(self, g: DeviceBatch) -> DeviceBatch:
        start = g.columns["__wid"].data * self.hop
        g = g.with_column("window_start", self._restore_time(start))
        g = g.with_column("window_end", self._restore_time(start + self.size))
        out = self.helper.finalize(g, extra=["window_start", "window_end"])
        return out

    def done(self, channel):
        if self.state is None:
            return None
        out, self.state = self._emit(self.state), None
        return out


TumblingWindowExecutor = HoppingWindowExecutor


class SessionWindowExecutor(_TimeRebase, Executor):
    """Gap-based session windows: sessions close when the per-key gap exceeds
    the timeout; open sessions are carried as partial rows across batches
    (ts_executors.py:197 semantics, batched)."""

    def __init__(self, time_col: str, keys: Sequence[str], window: SessionWindow,
                 plan: AggPlan):
        self.time_col = time_col
        self.keys = list(keys)
        self.timeout = window.timeout
        self.plan = plan
        self.open: Optional[DeviceBatch] = None  # partial rows of open sessions
        self.watermark: Optional[float] = None

    def _to_partial_rows(self, batch: DeviceBatch) -> DeviceBatch:
        """Raw rows -> partial-agg rows (count=1 etc.) + first/last time."""
        b = batch
        for name, e in self.plan.pre:
            b = b.with_column(name, evaluate_to_column(e, b))
        t = b.columns[self.time_col].data
        cols = {k: b.columns[k] for k in self.keys}
        for pname, op, tmp in self.plan.partials:
            if op == "count":
                cols[pname] = NumCol(
                    b.valid.astype(jnp.int32), "i"
                )
            else:
                cols[pname] = b.columns[tmp]
        cols["__first_t"] = NumCol(t, "i")
        cols["__last_t"] = NumCol(t, "i")
        return DeviceBatch(cols, b.valid, b.nrows, None)

    def _sessionize(self, rows: DeviceBatch) -> DeviceBatch:
        """Assign session ids over key+time-sorted partial rows and combine."""
        s = kernels.sort_batch(rows, self.keys + ["__last_t"])
        from quokka_tpu.ops.batch import key_limbs

        limbs = key_limbs(s, self.keys) if self.keys else []
        n = s.padded_len
        iota = jnp.arange(n, dtype=jnp.int32)
        key_changed = jnp.zeros(n, dtype=bool)
        for l in limbs:
            key_changed = key_changed | (l != jnp.roll(l, 1))
        first_t = s.columns["__first_t"].data
        last_t = s.columns["__last_t"].data
        prev_last = jnp.roll(last_t, 1)
        gap = first_t - prev_last
        new_sess = (iota == 0) | key_changed | (gap > self.timeout)
        sess_id = jnp.cumsum(new_sess.astype(jnp.int32)) - 1
        s = s.with_column("__sess", NumCol(sess_id, "i"))
        aggs = [(p, op, s.columns[p].data) for (p, op) in self.plan.recombine]
        aggs += [("__first_t", "min", first_t), ("__last_t", "max", last_t)]
        g = kernels.groupby_aggregate(s, self.keys + ["__sess"], aggs)
        return kernels.compact(
            g.select(self.keys + [p for p, _ in self.plan.recombine]
                     + ["__first_t", "__last_t"])
        )

    def execute(self, batches, stream_id, channel):
        parts = []
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            b = self._rebase_batch(b, self.time_col, headroom=self.timeout + 1)
            self.watermark = _time_max(b, self.time_col)
            parts.append(self._to_partial_rows(b))
        if self.open is not None:
            parts.append(self.open)
        if not parts:
            return None
        merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
        sessions = self._sessionize(merged)
        if self.watermark is None:
            self.open = sessions
            return None
        last = sessions.columns["__last_t"].data
        closed = sessions.valid & (last < self.watermark - self.timeout)
        ready = kernels.compact(kernels.apply_mask(sessions, closed))
        rest = kernels.compact(kernels.apply_mask(sessions, sessions.valid & ~closed))
        self.open = rest if rest.count_valid() > 0 else None
        if ready.count_valid() == 0:
            return None
        return self._emit(ready)

    def _emit(self, g: DeviceBatch) -> DeviceBatch:
        g = g.rename({"__first_t": "session_start", "__last_t": "session_end"})
        if self._tbase is not None:
            for c in ("session_start", "session_end"):
                g = g.with_column(c, self._restore_time(g.columns[c].data))
        helper = _PartialWindowAgg(self.keys, self.plan, wid_col="session_start")
        return helper.finalize(g, extra=["session_start", "session_end"])

    def done(self, channel):
        if self.open is None:
            return None
        out, self.open = self._emit(self.open), None
        return out


class SlidingWindowExecutor(_TimeRebase, Executor):
    """Per-event trailing window [t - size, t] aggregates (groupby_rolling,
    ts_executors.py:147).  Sum/count/avg via segmented prefix sums + a
    vectorized lower-bound search; each batch needs the previous tail rows,
    kept in state."""

    def __init__(self, time_col: str, keys: Sequence[str], window: SlidingWindow,
                 plan: AggPlan):
        self.time_col = time_col
        self.keys = list(keys)
        self.size = window.size_before
        self.plan = plan
        for _, op, _ in plan.partials:
            if op not in ("sum", "count", "min", "max"):
                raise NotImplementedError(
                    f"sliding windows support sum/count/avg/min/max (got {op})"
                )
        self.tail: Optional[DeviceBatch] = None

    def execute(self, batches, stream_id, channel):
        outs = []
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            out = self._process(b)
            if out is not None:
                outs.append(out)
        if not outs:
            return None
        return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]

    def _process(self, batch: DeviceBatch) -> Optional[DeviceBatch]:
        b = self._rebase_batch(batch, self.time_col, headroom=int(self.size) + 1)
        for name, e in self.plan.pre:
            b = b.with_column(name, evaluate_to_column(e, b))
        b = b.with_column("__new", NumCol(jnp.ones(b.padded_len, dtype=jnp.bool_), "b"))
        if self.tail is not None:
            t0 = self.tail
            t0 = t0.with_column(
                "__new", NumCol(jnp.zeros(t0.padded_len, dtype=jnp.bool_), "b")
            )
            missing = [c for c in b.names if c not in t0.columns]
            for c in missing:
                col = b.columns[c]
                if isinstance(col, NumCol):
                    t0 = t0.with_column(
                        c, NumCol(jnp.zeros(t0.padded_len, col.data.dtype), col.kind)
                    )
            merged = bridge.concat_batches([t0.select(b.names), b])
        else:
            merged = b
        out = self._rolling(merged)
        # new tail: rows within `size` of the max time
        wm = _time_max(b, self.time_col)
        t = merged.columns[self.time_col].data
        tail_mask = merged.valid & (t >= wm - self.size)
        tail = kernels.compact(kernels.apply_mask(merged, tail_mask))
        self.tail = tail.drop(["__new"]) if tail.count_valid() > 0 else None
        if out is not None and self._tbase is not None and self.time_col in out.columns:
            out = out.with_column(
                self.time_col, self._restore_time(out.columns[self.time_col].data)
            )
        return out

    def _rolling(self, merged: DeviceBatch) -> Optional[DeviceBatch]:
        s = kernels.sort_batch(merged, self.keys + [self.time_col])
        from quokka_tpu.ops.batch import key_limbs

        n = s.padded_len
        iota = jnp.arange(n, dtype=jnp.int32)
        limbs = key_limbs(s, self.keys) if self.keys else []
        key_changed = jnp.zeros(n, dtype=bool)
        for l in limbs:
            key_changed = key_changed | (l != jnp.roll(l, 1))
        seg_start_flag = key_changed | (iota == 0)
        seg_start = asof_ops._seg_fill_forward(
            jnp.where(seg_start_flag, iota, -1), seg_start_flag
        )
        t = s.columns[self.time_col].data
        lo_t = t - self.size
        # window rows within the key segment: [first time >= t-size, last time == t]
        left = _bisect_left_segmented(t, lo_t, seg_start, iota)
        n_total = s.padded_len
        seg_end = iota + _rows_from_segment_end(iota, seg_start_flag, n_total)
        right = _bisect_right_segmented(t, t, iota, seg_end)
        outs = {}
        for pname, op, tmp in self.plan.partials:
            if op in ("min", "max"):
                # arbitrary [left, right] range min/max via a sparse table:
                # log2(n) doubling levels, query = two overlapping power-of-2
                # blocks (prefix sums can't invert min/max)
                x = s.columns[tmp].data
                fill = _max_fill(x.dtype) if op == "min" else _min_fill(x.dtype)
                x = jnp.where(s.valid, x, fill)
                outs[pname] = _range_minmax(x, left, right, op)
                continue
            if op == "count":
                x = s.valid.astype(jnp.float32 if not kernels.config.x64_enabled() else jnp.float64)
            else:
                x = jnp.where(s.valid, s.columns[tmp].data, 0)
            cs = jnp.cumsum(x)
            before = jnp.where(left > 0, cs[jnp.maximum(left - 1, 0)], 0)
            outs[pname] = cs[right] - before
        g = s
        for pname in outs:
            g = g.with_column(pname, NumCol(outs[pname], "f"))
        for name, e in self.plan.finals:
            g = g.with_column(name, evaluate_to_column(e, g))
        only_new = kernels.apply_mask(g, g.valid & g.columns["__new"].data)
        keep = [c for c in merged.names if c != "__new" and not c.startswith("__pre")]
        keep += [nm for nm, _ in self.plan.finals if nm not in keep]
        keep = [c for c in keep if c in g.columns and not c.startswith("__agg")]
        return kernels.compact(only_new.select(keep))

    def done(self, channel):
        self.tail = None
        return None


class ShiftExecutor(Executor):
    """Per-key lag: value of `columns` n rows earlier within the key partition
    (orderedstream.py:13 shift).  Keeps the last n rows per key as carry."""

    def __init__(self, time_col: str, keys: Sequence[str], columns: Sequence[str], n: int):
        self.time_col = time_col
        self.keys = list(keys)
        self.columns = list(columns)
        self.n = n
        self.tail: Optional[DeviceBatch] = None

    def execute(self, batches, stream_id, channel):
        outs = []
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            out = self._process(b)
            if out is not None:
                outs.append(out)
        if not outs:
            return None
        return bridge.concat_batches(outs) if len(outs) > 1 else outs[0]

    def _process(self, batch: DeviceBatch) -> Optional[DeviceBatch]:
        b = batch.with_column(
            "__new", NumCol(jnp.ones(batch.padded_len, dtype=jnp.bool_), "b")
        )
        if self.tail is not None:
            t0 = self.tail.with_column(
                "__new", NumCol(jnp.zeros(self.tail.padded_len, dtype=jnp.bool_), "b")
            )
            merged = bridge.concat_batches([t0.select(b.names), b])
        else:
            merged = b
        s = kernels.sort_batch(merged, self.keys + [self.time_col])
        from quokka_tpu.ops.batch import key_limbs

        n = s.padded_len
        iota = jnp.arange(n, dtype=jnp.int32)
        limbs = key_limbs(s, self.keys) if self.keys else []
        key_changed = jnp.zeros(n, dtype=bool)
        for l in limbs:
            key_changed = key_changed | (l != jnp.roll(l, 1))
        seg_start_flag = key_changed | (iota == 0)
        seg_start = asof_ops._seg_fill_forward(
            jnp.where(seg_start_flag, iota, -1), seg_start_flag
        )
        src = iota - self.n
        ok = src >= seg_start
        src = jnp.clip(src, 0, n - 1)
        from quokka_tpu.ops.batch import with_nulls

        out = s
        for c in self.columns:
            col = s.columns[c]
            taken = col.take(src)
            # rows with no history (under n predecessors in their key
            # segment) get NULL, not a clipped gather's garbage — polars
            # shift semantics for every column kind, not just floats
            taken = with_nulls(taken, ~ok)
            out = out.with_column(f"{c}_shifted_{self.n}", taken)
        # keep last n rows per key as the next batch's carry
        rank_from_end = _rows_from_segment_end(iota, seg_start_flag, n)
        tail_mask = s.valid & (rank_from_end < self.n)
        tail = kernels.compact(kernels.apply_mask(s, tail_mask))
        self.tail = tail.select(batch.names) if tail.count_valid() > 0 else None
        only_new = kernels.apply_mask(out, out.valid & out.columns["__new"].data)
        keep = [c for c in out.names if not c.startswith("__")]
        return kernels.compact(only_new.select(keep))


def _max_fill(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)


def _min_fill(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype)


def _range_minmax(x, left, right, op: str):
    """Per-row min/max over x[left[i] .. right[i]] (inclusive), arbitrary
    ranges: O(n log n) sparse table + two-block queries, all vectorized."""
    import math

    combine = jnp.minimum if op == "min" else jnp.maximum
    n = x.shape[0]
    levels = [x]
    span = 1
    while span < n:
        prev = levels[-1]
        shifted = jnp.concatenate([prev[span:], prev[-1:].repeat(span)])
        levels.append(combine(prev, shifted))
        span *= 2
    length = jnp.maximum(right - left + 1, 1)
    k = jnp.clip(
        jnp.floor(jnp.log2(length.astype(jnp.float32))).astype(jnp.int32),
        0, len(levels) - 1,
    )
    table = jnp.stack(levels)  # [L, n]
    a = table[k, left]
    b_start = jnp.clip(right - (1 << k) + 1, 0, n - 1)
    b = table[k, b_start]
    return combine(a, b)


def _rows_from_segment_end(iota, seg_start_flag, n):
    """Distance from each row to its segment's last row (0 = last).  The
    segment end is (next start strictly after i) - 1, found with a suffix-min
    scan over start indices."""
    import jax

    starts_idx = jnp.where(seg_start_flag, iota, n)
    suffix_min = jnp.flip(jax.lax.associative_scan(jnp.minimum, jnp.flip(starts_idx)))
    after = jnp.concatenate([suffix_min[1:], jnp.array([n], dtype=suffix_min.dtype)])
    seg_end = after - 1
    return seg_end - iota


def _bisect_left_segmented(times, targets, seg_start, iota):
    """For each i: smallest j in [seg_start[i], i] with times[j] >= targets[i]
    (times sorted within segments)."""
    import jax

    lo = seg_start
    hi = iota

    def body(_, carry):
        lo, hi = carry
        mid = (lo + hi) // 2
        go_right = times[mid] < targets[iota]
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    return lo


def _bisect_right_segmented(times, targets, iota, seg_end):
    """For each i: largest j in [i, seg_end[i]] with times[j] <= targets[i]."""
    import jax

    lo = iota
    hi = seg_end

    def body(_, carry):
        lo, hi = carry
        # find first j with times[j] > target, then step back
        mid = (lo + hi + 1) // 2
        le = times[jnp.clip(mid, 0, times.shape[0] - 1)] <= targets[iota]
        lo = jnp.where(le, mid, lo)
        hi = jnp.where(le, hi, mid - 1)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, 32, body, (lo, hi))
    return lo
