from quokka_tpu.executors.base import Executor
from quokka_tpu.executors.sql_execs import (
    BroadcastJoinExecutor,
    BuildProbeJoinExecutor,
    CountExecutor,
    DistinctExecutor,
    FinalAggExecutor,
    PartialAggExecutor,
    SortExecutor,
    StorageExecutor,
    TopKExecutor,
    UDFExecutor,
)
