"""Output writers: stream batches to Parquet / CSV files.

Reference parity: OutputExecutor (pyquokka/executors/sql_executors.py:189-273)
— accumulate rows until a target row-group size, write numbered files per
channel, emit the written filenames downstream."""

from __future__ import annotations

import os
from typing import List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from quokka_tpu.executors.base import Executor
from quokka_tpu.ops import bridge
from quokka_tpu.ops.batch import DeviceBatch


class OutputExecutor(Executor):
    def __init__(self, path: str, fmt: str = "parquet", rows_per_file: int = 1 << 20,
                 prefix: str = "part"):
        assert fmt in ("parquet", "csv")
        self.path = path
        self.fmt = fmt
        self.rows_per_file = rows_per_file
        self.prefix = prefix
        self.pending: List[pa.Table] = []
        self.pending_rows = 0
        self.file_no = 0
        self.written: List[str] = []
        os.makedirs(path, exist_ok=True)

    def execute(self, batches, stream_id, channel):
        for b in batches:
            if b is None:
                continue
            t = bridge.device_to_arrow(b)
            if t.num_rows == 0:
                continue
            self.pending.append(t)
            self.pending_rows += t.num_rows
        out = []
        while self.pending_rows >= self.rows_per_file:
            out.append(self._flush(channel, self.rows_per_file))
        return self._names_batch(out) if out else None

    def done(self, channel):
        out = []
        while self.pending_rows > 0:
            out.append(self._flush(channel, self.rows_per_file))
        return self._names_batch(out) if out else None

    def _flush(self, channel: int, rows: int) -> str:
        take, taken = [], 0
        while self.pending and taken < rows:
            t = self.pending[0]
            need = rows - taken
            if t.num_rows <= need:
                take.append(self.pending.pop(0))
                taken += t.num_rows
            else:
                take.append(t.slice(0, need))
                self.pending[0] = t.slice(need)
                taken += need
        self.pending_rows -= taken
        table = pa.concat_tables(take, promote_options="permissive")
        name = os.path.join(
            self.path, f"{self.prefix}-{channel}-{self.file_no}.{self.fmt}"
        )
        self.file_no += 1
        if self.fmt == "parquet":
            pq.write_table(table, name)
        else:
            pacsv.write_csv(table, name)
        self.written.append(name)
        return name

    def _names_batch(self, names: List[str]) -> DeviceBatch:
        return bridge.arrow_to_device(pa.table({"filename": names}))
