"""Numeric/linear-algebra executors: gramian, covariance, approximate
quantiles.

Reference parity: DataStream.gramian/covariance/approximate_quantile
(pyquokka/datastream.py:1033/1100/921).  Gramian partials are X^T X matmuls —
pure MXU work — summed across batches and channels; approximate quantiles use
per-channel uniform reservoir sampling (the reference's t-digest dependency is
optional there too)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from quokka_tpu.executors.base import Executor
from quokka_tpu.ops import bridge
from quokka_tpu.ops.batch import DeviceBatch


class GramianExecutor(Executor):
    """Running X^T X (and column sums + count for covariance) over the given
    float columns."""

    def __init__(self, columns: Sequence[str], covariance: bool = False):
        self.columns = list(columns)
        self.covariance = covariance
        self.gram: Optional[jnp.ndarray] = None
        self.sums: Optional[jnp.ndarray] = None
        self.count = 0

    @staticmethod
    @jax.jit
    def _accumulate(mat, valid):
        m = jnp.where(valid[:, None], mat, 0.0)
        return m.T @ m, jnp.sum(m, axis=0)

    def execute(self, batches, stream_id, channel):
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            mat = jnp.stack([b.columns[c].data for c in self.columns], axis=1)
            g, s = self._accumulate(mat.astype(jnp.float32), b.valid)
            self.gram = g if self.gram is None else self.gram + g
            self.sums = s if self.sums is None else self.sums + s
            self.count += b.count_valid()

    def done(self, channel):
        if self.gram is None:
            return None
        # emit RAW partials (gram rows + a sums row + a count row): channels
        # must combine raw moments before any normalization, otherwise
        # per-channel covariances sum to N-channels times the true value
        g = np.asarray(self.gram, dtype=np.float64)
        sums = np.asarray(self.sums, dtype=np.float64)
        labels = list(self.columns) + ["__sums__", "__count__"]
        count_row = np.zeros(len(self.columns))
        count_row[0] = self.count
        mat = np.vstack([g, sums[None, :], count_row[None, :]])
        cols = {"__row": np.array(labels, dtype=object)}
        for j, c in enumerate(self.columns):
            cols[c] = mat[:, j]
        self.gram = None
        self.sums = None
        return bridge.arrow_to_device(pa.table(cols))


class CombineGramianExecutor(Executor):
    """Sum per-channel RAW gramian partials, then normalize once."""

    def __init__(self, columns: Sequence[str], covariance: bool = False):
        self.columns = list(columns)
        self.covariance = covariance
        self.parts: List[DeviceBatch] = []

    def execute(self, batches, stream_id, channel):
        self.parts.extend(b for b in batches if b is not None)

    def done(self, channel):
        if not self.parts:
            return None
        import pandas as pd

        dfs = [bridge.to_pandas(b) for b in self.parts]
        self.parts = []
        acc = dfs[0].set_index("__row")[self.columns]
        for d in dfs[1:]:
            acc = acc + d.set_index("__row")[self.columns]
        g = acc.loc[self.columns].to_numpy()
        if self.covariance:
            count = float(acc.loc["__count__"].to_numpy()[0])
            sums = acc.loc["__sums__"].to_numpy()
            if count > 1:
                mu = sums / count
                g = g / count - np.outer(mu, mu)
        out = pd.DataFrame({"column": self.columns})
        for j, c in enumerate(self.columns):
            out[c] = g[:, j]
        return bridge.arrow_to_device(pa.Table.from_pandas(out, preserve_index=False))


class ReservoirQuantileExecutor(Executor):
    """Per-channel MERGEABLE quantile sketch (merging t-digest,
    ops/tdigest.py — the ldbpy t-digest role in the reference).  Emits the
    serialized digest; the combine stage merges digests exactly, so results
    are partitioning-independent (the round-1 reservoir version averaged
    per-channel quantiles).  Name kept for API stability."""

    def __init__(self, column: str, quantiles: Sequence[float],
                 compression: float = 200.0, **_legacy):
        from quokka_tpu.ops.tdigest import TDigest

        self.column = column
        self.quantiles = list(quantiles)
        self.digest = TDigest(compression)

    def execute(self, batches, stream_id, channel):
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            x = np.asarray(b.columns[self.column].data)[np.asarray(b.valid)]
            self.digest.add(x.astype(np.float64))

    def done(self, channel):
        means, weights = self.digest.to_arrays()
        if len(means) == 0:
            return None
        return bridge.arrow_to_device(
            pa.table({"__td_mean": means, "__td_weight": weights})
        )


class CombineQuantileExecutor(Executor):
    """Merge the per-channel t-digests EXACTLY, then evaluate the quantiles
    on the combined sketch — no partitioning dependence."""

    def __init__(self, column: str, quantiles: Sequence[float],
                 compression: float = 200.0):
        from quokka_tpu.ops.tdigest import TDigest

        self.column = column
        self.quantiles = list(quantiles)
        self.digest = TDigest(compression)
        self.any = False

    def execute(self, batches, stream_id, channel):
        from quokka_tpu.ops.tdigest import TDigest

        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            t = bridge.device_to_arrow(b)
            self.digest.merge(TDigest.from_arrays(
                t.column("__td_mean").to_numpy(zero_copy_only=False),
                t.column("__td_weight").to_numpy(zero_copy_only=False),
            ))
            self.any = True

    def done(self, channel):
        if not self.any:
            return None
        qs = [self.digest.quantile(q) for q in self.quantiles]
        return bridge.arrow_to_device(
            pa.table({"quantile": np.array(self.quantiles), self.column: np.array(qs)})
        )
