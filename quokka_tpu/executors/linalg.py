"""Numeric/linear-algebra executors: gramian, covariance, approximate
quantiles.

Reference parity: DataStream.gramian/covariance/approximate_quantile
(pyquokka/datastream.py:1033/1100/921).  Gramian partials are X^T X matmuls —
pure MXU work — summed across batches and channels; approximate quantiles use
per-channel uniform reservoir sampling (the reference's t-digest dependency is
optional there too)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from quokka_tpu.executors.base import Executor
from quokka_tpu.ops import bridge
from quokka_tpu.ops.batch import DeviceBatch


class GramianExecutor(Executor):
    """Running X^T X (and column sums + count for covariance) over the given
    float columns."""

    def __init__(self, columns: Sequence[str], covariance: bool = False):
        self.columns = list(columns)
        self.covariance = covariance
        self.gram: Optional[jnp.ndarray] = None
        self.sums: Optional[jnp.ndarray] = None
        self.count = 0

    @staticmethod
    @jax.jit
    def _accumulate(mat, valid):
        m = jnp.where(valid[:, None], mat, 0.0)
        return m.T @ m, jnp.sum(m, axis=0)

    def execute(self, batches, stream_id, channel):
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            mat = jnp.stack([b.columns[c].data for c in self.columns], axis=1)
            g, s = self._accumulate(mat.astype(jnp.float32), b.valid)
            self.gram = g if self.gram is None else self.gram + g
            self.sums = s if self.sums is None else self.sums + s
            self.count += b.count_valid()

    def done(self, channel):
        if self.gram is None:
            return None
        # emit RAW partials (gram rows + a sums row + a count row): channels
        # must combine raw moments before any normalization, otherwise
        # per-channel covariances sum to N-channels times the true value
        g = np.asarray(self.gram, dtype=np.float64)
        sums = np.asarray(self.sums, dtype=np.float64)
        labels = list(self.columns) + ["__sums__", "__count__"]
        count_row = np.zeros(len(self.columns))
        count_row[0] = self.count
        mat = np.vstack([g, sums[None, :], count_row[None, :]])
        cols = {"__row": np.array(labels, dtype=object)}
        for j, c in enumerate(self.columns):
            cols[c] = mat[:, j]
        self.gram = None
        self.sums = None
        return bridge.arrow_to_device(pa.table(cols))


class CombineGramianExecutor(Executor):
    """Sum per-channel RAW gramian partials, then normalize once."""

    def __init__(self, columns: Sequence[str], covariance: bool = False):
        self.columns = list(columns)
        self.covariance = covariance
        self.parts: List[DeviceBatch] = []

    def execute(self, batches, stream_id, channel):
        self.parts.extend(b for b in batches if b is not None)

    def done(self, channel):
        if not self.parts:
            return None
        import pandas as pd

        dfs = [bridge.to_pandas(b) for b in self.parts]
        self.parts = []
        acc = dfs[0].set_index("__row")[self.columns]
        for d in dfs[1:]:
            acc = acc + d.set_index("__row")[self.columns]
        g = acc.loc[self.columns].to_numpy()
        if self.covariance:
            count = float(acc.loc["__count__"].to_numpy()[0])
            sums = acc.loc["__sums__"].to_numpy()
            if count > 1:
                mu = sums / count
                g = g / count - np.outer(mu, mu)
        out = pd.DataFrame({"column": self.columns})
        for j, c in enumerate(self.columns):
            out[c] = g[:, j]
        return bridge.arrow_to_device(pa.Table.from_pandas(out, preserve_index=False))


class ReservoirQuantileExecutor(Executor):
    """Approximate quantiles by uniform reservoir sampling per channel; the
    final quantile is computed on the merged reservoir."""

    def __init__(self, column: str, quantiles: Sequence[float], reservoir: int = 65_536,
                 seed: int = 0):
        self.column = column
        self.quantiles = list(quantiles)
        self.cap = reservoir
        self.rng = np.random.default_rng(seed)
        self.sample = np.zeros(0, dtype=np.float64)
        self.seen = 0

    def execute(self, batches, stream_id, channel):
        for b in batches:
            if b is None or b.count_valid() == 0:
                continue
            x = np.asarray(b.columns[self.column].data)[np.asarray(b.valid)]
            x = x.astype(np.float64)
            if len(self.sample) < self.cap:
                take = min(self.cap - len(self.sample), len(x))
                self.sample = np.concatenate([self.sample, x[:take]])
                x = x[take:]
                self.seen += take
            for v in x:  # classic reservoir replacement
                self.seen += 1
                j = self.rng.integers(0, self.seen)
                if j < self.cap:
                    self.sample[j] = v

    def done(self, channel):
        if self.seen == 0:
            return None
        qs = np.quantile(self.sample, self.quantiles)
        return bridge.arrow_to_device(
            pa.table({"quantile": np.array(self.quantiles), self.column: qs})
        )


class CombineQuantileExecutor(Executor):
    """Merge per-channel reservoirs is approximated by re-sampling the emitted
    per-channel quantiles weighted equally (adequate for the advertised
    approximate semantics); single-channel plans skip this."""

    def __init__(self, column: str, quantiles: Sequence[float]):
        self.column = column
        self.quantiles = list(quantiles)
        self.parts: List[DeviceBatch] = []

    def execute(self, batches, stream_id, channel):
        self.parts.extend(b for b in batches if b is not None)

    def done(self, channel):
        if not self.parts:
            return None
        import pandas as pd

        df = pd.concat([bridge.to_pandas(b) for b in self.parts], ignore_index=True)
        self.parts = []
        out = df.groupby("quantile")[self.column].mean().reset_index()
        return bridge.arrow_to_device(pa.Table.from_pandas(out, preserve_index=False))
