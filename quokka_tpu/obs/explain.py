"""EXPLAIN ANALYZE rendering: the plan DAG annotated with measured actuals.

``opstats.py`` owns the ledger; this module turns one query's snapshot into
the three artifacts the doctor workflow reads:

- ``render(snap)``: the annotated DAG — one line per operator (rows in/out,
  selectivity, padded-waste, time share, executor-noted figures like join
  build/probe rows), a skew report per exchange edge (max/mean channel
  rows, flagged above ``QK_SKEW_RATIO``), and the top-N hot operators;
- ``operators_detail(snap)``: the compact per-operator dict list bench.py
  embeds as ``detail.operators`` in every bench line;
- ``QueryHandle.explain()`` (service/session.py) serves ``render`` over the
  live ledger while the query runs and over the finish-time snapshot after.

Pure host-side formatting over an already-resolved snapshot: no device
work, no registry mutation.
"""

from __future__ import annotations

from typing import List, Optional


def _fmt_rows(n: int) -> str:
    if n >= 10_000_000:
        return f"{n / 1e6:.1f}M"
    if n >= 100_000:
        return f"{n / 1e3:.0f}k"
    return str(n)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


_NOTE_FIELDS = ("join_build_rows", "join_probe_rows")


def _op_line(o: dict) -> str:
    bits = [f"a{o['actor']} {o['op']}",
            f"[{o['kind']} x{o['channels']}]"]
    if o["targets"]:
        bits.append("-> " + ",".join(f"a{t}" for t in o["targets"]))
    if o["kind"] != "input":
        bits.append(f"rows_in={_fmt_rows(o['rows_in'])}")
    bits.append(f"rows_out={_fmt_rows(o['rows_out'])}")
    if o.get("selectivity") is not None:
        bits.append(f"sel={o['selectivity']:.3f}")
    if o.get("pad_waste"):
        bits.append(f"pad_waste={o['pad_waste']:.0%}")
    if o["bytes_in"]:
        bits.append(f"bytes={_fmt_bytes(o['bytes_in'])}")
    bits.append(f"time={o['time_s']:.3f}s({o['time_share']:.0%})")
    bits.append(f"dispatches={o['dispatches']}")
    for f in _NOTE_FIELDS:
        if o.get(f):
            bits.append(f"{f.replace('join_', '')}={_fmt_rows(o[f])}")
    if o["rows_unknown"]:
        bits.append(f"rows_unknown={o['rows_unknown']}")
    return "  ".join(bits)


def _decision_line(d: dict) -> str:
    """One planner decision (planner/decide.py record shapes + the
    engine's runtime adapt_runtime records) as a terminal line."""
    kind = d.get("kind", "?")
    if kind == "broadcast":
        bits = [f"broadcast? {d.get('node')}: {d.get('choice')}",
                f"basis={d.get('basis')}"]
        if d.get("build_rows") is not None:
            bits.append(f"build_rows={_fmt_rows(d['build_rows'])}")
        if d.get("build_bytes") is not None:
            bits.append(f"build_bytes={_fmt_bytes(d['build_bytes'])}")
        if d.get("threshold_bytes") is not None:
            bits.append(
                f"QK_BROADCAST_BYTES={_fmt_bytes(d['threshold_bytes'])}")
        elif d.get("threshold_rows") is not None:
            bits.append(f"threshold_rows={_fmt_rows(d['threshold_rows'])}")
        if d.get("est_s_basis"):
            bits.append(
                f"broadcast_s={d.get('broadcast_s')}"
                f" partition_s={d.get('partition_s')}"
                f" [{d['est_s_basis']}"
                + (f", probe {d['probe_s_basis']}]"
                   if d.get("probe_s_basis") else "]"))
        return "  ".join(bits)
    if kind == "join_order":
        line = (f"join_order [{d.get('basis')}]: "
                + " | ".join(d.get("after") or []))
        if d.get("est_s_basis"):
            line += f"  est_s_basis={d['est_s_basis']}"
        return line
    if kind == "channels":
        return (f"channels {d.get('node')}: {d.get('default')}"
                f"->{d.get('channels')}  basis={d.get('basis')}"
                f" rows={_fmt_rows(d.get('rows', 0))}")
    if kind == "adapt_mark":
        joins = ", ".join(d.get("joins") or [])
        return (f"adaptive exchanges armed (QK_SKEW_RATIO="
                f"{d.get('skew_ratio')}): {joins}")
    if kind == "adapt_runtime":
        return (f"RUNTIME adapt {d.get('edge')}: channel "
                f"{d.get('fat_channel')} had "
                f"{_fmt_rows(d.get('fat_rows', 0))} of "
                f"{_fmt_rows(d.get('total_rows', 0))} rows "
                f"(ratio={d.get('ratio')}) -> {d.get('action')}")
    return " ".join(f"{k}={v}" for k, v in d.items())


def render(snap: Optional[dict], top_n: int = 5) -> str:
    """The human EXPLAIN ANALYZE report for one query's snapshot (what
    ``QueryHandle.explain()`` and ``bench.py --measure`` print)."""
    if not snap:
        return "explain: no operator statistics recorded"
    lines = [
        f"EXPLAIN ANALYZE {snap['query_id']}"
        f"  wall={snap['wall_s']:.3f}s dispatch_time={snap['time_s']:.3f}s"
        f"  operators={len(snap['operators'])}"
        f" exchange_edges={len(snap['edges'])}"
    ]
    # operators in stage-then-id order: sources first, sink last — the
    # closest linearization of the DAG a terminal can carry
    for o in sorted(snap["operators"],
                    key=lambda o: (o.get("stage", 0), o["actor"])):
        lines.append("  " + _op_line(o))
    if snap["edges"]:
        lines.append(f"skew report (QK_SKEW_RATIO={snap['skew_threshold']}):")
        for e in snap["edges"]:
            flag = "  ** SKEWED **" if e["skewed"] else ""
            lines.append(
                f"  {e['edge']}: channels={e['channels']} "
                f"rows={_fmt_rows(e['rows_total'])} "
                f"max={_fmt_rows(e['rows_max'])} mean={e['rows_mean']:.0f} "
                f"ratio={e['skew_ratio']:.2f}{flag}")
    eff = snap.get("efficiency")
    if eff and eff.get("operators"):
        peaks = eff.get("peaks")
        head = "device efficiency"
        if peaks:
            head += (f" (peaks: {peaks['peak_flops_s']:.3g} FLOP/s, "
                     f"{peaks['peak_bw_bytes_s']:.3g} B/s)")
        else:
            head += " (uncalibrated: run devprof.calibrate())"
        lines.append(head + ":")
        for r in eff["operators"]:
            bits = [f"a{r['actor']} {r['op']}"]
            if r.get("achieved_flops_s") is not None:
                bits.append(f"flops/s={r['achieved_flops_s']:.3g}")
            if r.get("achieved_bw_s") is not None:
                bits.append(f"bw={r['achieved_bw_s']:.3g}B/s")
            if r.get("intensity") is not None:
                bits.append(f"intensity={r['intensity']:.2f}")
            if r.get("efficiency") is not None:
                bits.append(f"roofline={r['efficiency']:.1%}")
            bits.append(f"programs={r['program_dispatches']}")
            flag = "  ** BELOW QK_EFF_FLOOR **" if r.get("flagged") else ""
            lines.append("  " + "  ".join(bits) + flag)
    planner = snap.get("planner") or []
    if planner:
        lines.append("planner decisions:")
        for d in planner:
            lines.append("  " + _decision_line(d))
    hot = (snap.get("top_operators") or [])[:top_n]
    if hot:
        lines.append("top operators by dispatch time:")
        for i, o in enumerate(hot, 1):
            lines.append(
                f"  {i}. a{o['actor']} {o['op']}  {o['time_s']:.3f}s "
                f"({o['time_share']:.0%})  rows_out={_fmt_rows(o['rows_out'])}")
    if snap.get("rows_unknown"):
        lines.append(f"note: {snap['rows_unknown']} batch(es) carried no "
                     "host-resolvable row count (never synced for a stat)")
    return "\n".join(lines)


def operators_detail(snap: Optional[dict]) -> Optional[dict]:
    """The compact machine-readable digest bench.py embeds as
    ``detail.operators``: per-operator actuals + the per-edge skew report."""
    if not snap or not snap.get("operators"):
        return None
    ops: List[dict] = []
    for o in snap["operators"]:
        ent = {
            "actor": o["actor"],
            "op": o["op"],
            "kind": o["kind"],
            "rows_in": o["rows_in"],
            "rows_out": o["rows_out"],
            "bytes_in": o["bytes_in"],
            "dispatches": o["dispatches"],
            "time_s": o["time_s"],
            "time_share": o["time_share"],
        }
        for k in ("selectivity", "pad_waste", *_NOTE_FIELDS):
            if o.get(k) is not None:
                ent[k] = o[k]
        ops.append(ent)
    return {
        "operators": ops,
        "skew": [
            {"edge": e["edge"], "channels": e["channels"],
             "rows_max": e["rows_max"], "rows_mean": e["rows_mean"],
             "ratio": e["skew_ratio"], "skewed": e["skewed"]}
            for e in snap["edges"]],
        "rows_unknown": snap.get("rows_unknown", 0),
        # plan-time choices + runtime adaptations (bench detail.plan's
        # "planner" section; same records explain() renders)
        "planner": [dict(d) for d in snap.get("planner") or []],
    }


def efficiency_detail(snap: Optional[dict]) -> Optional[dict]:
    """The compact device-efficiency digest bench.py embeds as
    ``detail.efficiency``: calibrated peaks + per-operator achieved rates
    and roofline percentages (obs/devprof.py attach)."""
    if not snap:
        return None
    eff = snap.get("efficiency")
    if not eff or not eff.get("operators"):
        return None
    return {
        "peaks": eff.get("peaks"),
        "operators": [
            {k: r.get(k)
             for k in ("actor", "op", "time_s", "flops", "bytes",
                       "intensity", "achieved_flops_s", "achieved_bw_s",
                       "efficiency", "program_dispatches", "flagged")}
            for r in eff["operators"]],
    }


def skew_flags(snap: Optional[dict]) -> List[str]:
    """The flagged edges only (what a stall dump headline cites)."""
    if not snap:
        return []
    return [e["edge"] for e in snap.get("edges", ()) if e["skewed"]]
