"""Per-process flight recorder: a lock-light ring buffer of timestamped
events.

Every runtime component records what it just did — task begin/end, batch
push/pull, compiles, cache hits/misses, lock waits, heartbeats, state
transitions — into a bounded ring.  Workers ship incremental snapshots to
the coordinator through the control store; the coordinator's merger
(obs/merge.py) assembles the per-worker streams into one timeline.  When a
run wedges, the last-N events per process ARE the diagnosis: the ring is
what the stall detector and the QK_SANITIZE watchdog dump.

Lock-light by construction: a slot index comes from ``itertools.count``
(atomic under CPython — implemented in C, no bytecode boundary inside
``next``) and the event lands with a single list-item store.  No lock is
taken on the record path; snapshots tolerate a torn read by sorting on the
embedded sequence number and dropping slots mid-overwrite.

Event wire format (what ships to the coordinator): a plain tuple

    (seq, ts, kind, name, dur_s, thread, args_or_None)

with ``ts = time.time()`` at event END (wall clock, so streams from
different processes merge on one axis) and ``dur_s`` the event's duration
(0.0 for instants).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

Event = Tuple[int, float, str, str, float, str, Optional[dict]]

_DEFAULT_CAPACITY = 4096
_OFF_VALUES = ("0", "false", "no", "off")


def recorder_enabled() -> bool:
    """The recorder is ON unless QK_TRACE_EVENTS explicitly disables it —
    it must be live BEFORE anyone knows the run is going to wedge."""
    return os.environ.get(
        "QK_TRACE_EVENTS", "").strip().lower() not in _OFF_VALUES


def trace_export_path() -> Optional[str]:
    """Chrome-trace export destination, or None when only the in-memory
    ring is wanted.  ``QK_TRACE_EVENTS=1`` -> ``quokka_trace.json`` in the
    cwd; any other non-off value is taken as the path itself."""
    v = os.environ.get("QK_TRACE_EVENTS", "").strip()
    if not v or v.lower() in _OFF_VALUES:
        return None
    if v.lower() in ("1", "true", "yes", "on"):
        return "quokka_trace.json"
    return v


class FlightRecorder:
    """Bounded event ring + a per-thread "current activity" marker.

    The activity marker exists for the in-process dump path (watchdog,
    faulthandler): a blocked call never produces its completion event, so
    the marker is the only record of WHAT is blocked."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 enabled: Optional[bool] = None):
        self.capacity = max(16, int(capacity))
        self.enabled = recorder_enabled() if enabled is None else enabled
        self._buf: List[Optional[Event]] = [None] * self.capacity
        self._seq = itertools.count()
        # highest sequence number issued so far; a plain store racing other
        # recorders only ever reads slightly stale, which a drop COUNTER
        # tolerates (it exists to say "the ring wrapped, the tail is gone",
        # not to account bytes)
        self._last = -1
        # thread name -> (activity, since_ts); plain dict stores are atomic
        # under the GIL and each thread only writes its own key
        self._current: Dict[str, Tuple[str, float]] = {}

    # -- hot path -----------------------------------------------------------
    def record(self, kind: str, name: str = "", dur: float = 0.0,
               **args) -> int:
        if not self.enabled:
            return -1
        i = next(self._seq)
        self._buf[i % self.capacity] = (
            i, time.time(), kind, name, float(dur),
            threading.current_thread().name, args or None,
        )
        if i > self._last:
            self._last = i
        return i

    @property
    def dropped(self) -> int:
        """Events silently overwritten since the last reset: once the ring
        wraps, every record evicts the oldest event.  Nonzero means a
        merged timeline / critical-path profile is missing its earliest
        tail — raise QK_TRACE_BUFFER when it matters."""
        return max(0, self._last + 1 - self.capacity)

    def set_current(self, activity: str) -> None:
        if self.enabled:
            self._current[threading.current_thread().name] = (
                activity, time.time())

    def clear_current(self) -> None:
        if self.enabled:
            self._current.pop(threading.current_thread().name, None)

    class _Activity:
        __slots__ = ("rec", "name", "prev")

        def __init__(self, rec: "FlightRecorder", name: str):
            self.rec = rec
            self.name = name
            self.prev = None

        def __enter__(self):
            if self.rec.enabled:
                # markers nest (a task dispatch performs many RPCs): save
                # the outer marker so an inner completion restores it —
                # clearing instead would blind the watchdog to the task a
                # thread wedges in AFTER its last completed RPC
                key = threading.current_thread().name
                self.prev = self.rec._current.get(key)
                self.rec._current[key] = (self.name, time.time())
            return self

        def __exit__(self, *exc):
            if self.rec.enabled:
                key = threading.current_thread().name
                if self.prev is not None:
                    self.rec._current[key] = self.prev
                else:
                    self.rec._current.pop(key, None)
            return False

    def activity(self, name: str) -> "_Activity":
        """``with RECORDER.activity("rpc:get"):`` — marks the thread's
        current (possibly about-to-block) operation for stall dumps;
        nested markers restore the enclosing one on exit."""
        return FlightRecorder._Activity(self, name)

    # -- snapshots ----------------------------------------------------------
    def snapshot(self, since: int = -1,
                 last_n: Optional[int] = None) -> List[Event]:
        """Events with seq > ``since`` in sequence order.  Tolerates
        concurrent writers: a slot overwritten mid-scan just yields its
        newer event (or is dropped if it moved below ``since``)."""
        evs = [e for e in list(self._buf) if e is not None and e[0] > since]
        evs.sort(key=lambda e: e[0])
        if last_n is not None and len(evs) > last_n:
            evs = evs[-last_n:]
        return evs

    def current(self) -> Dict[str, Tuple[str, float]]:
        """thread name -> (activity, seconds_in_it)."""
        now = time.time()
        return {t: (name, now - t0)
                for t, (name, t0) in list(self._current.items())}

    def dump_text(self, stream, last_n: int = 40) -> None:
        """Human-readable tail + per-thread current activity (what the
        QK_SANITIZE watchdog appends under its stack dump)."""
        cur = self.current()
        if cur:
            stream.write("[flight-recorder] current activity per thread:\n")
            for t, (name, age) in sorted(cur.items()):
                stream.write(f"  {t}: {name} (for {age:.2f}s)\n")
        if self.dropped:
            stream.write(f"[flight-recorder] WARNING: ring dropped "
                         f"{self.dropped} event(s) (capacity "
                         f"{self.capacity}; raise QK_TRACE_BUFFER)\n")
        evs = self.snapshot(last_n=last_n)
        stream.write(f"[flight-recorder] last {len(evs)} event(s):\n")
        for (_seq, ts, kind, name, dur, thread, args) in evs:
            extra = f" {args}" if args else ""
            stream.write(
                f"  {ts:.6f} [{thread}] {kind}:{name}"
                + (f" dur={dur * 1e3:.2f}ms" if dur else "") + extra + "\n")

    def reset(self) -> None:
        self._buf = [None] * self.capacity
        self._seq = itertools.count()
        self._last = -1
        self._current.clear()


def _capacity_from_env() -> int:
    try:
        return int(os.environ.get("QK_TRACE_BUFFER", _DEFAULT_CAPACITY))
    except ValueError:
        return _DEFAULT_CAPACITY


RECORDER = FlightRecorder(capacity=_capacity_from_env())
