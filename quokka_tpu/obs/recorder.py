"""Per-process flight recorder: a lock-light ring buffer of timestamped
events.

Every runtime component records what it just did — task begin/end, batch
push/pull, compiles, cache hits/misses, lock waits, heartbeats, state
transitions — into a bounded ring.  Workers ship incremental snapshots to
the coordinator through the control store; the coordinator's merger
(obs/merge.py) assembles the per-worker streams into one timeline.  When a
run wedges, the last-N events per process ARE the diagnosis: the ring is
what the stall detector and the QK_SANITIZE watchdog dump.

Lock-light by construction: a slot index comes from ``itertools.count``
(atomic under CPython — implemented in C, no bytecode boundary inside
``next``) and the event lands with a single list-item store.  No lock is
taken on the record path; snapshots tolerate a torn read by sorting on the
embedded sequence number and dropping slots mid-overwrite.

Event wire format (what ships to the coordinator): a plain tuple

    (seq, ts, kind, name, dur_s, thread, args_or_None)

with ``ts = time.time()`` at event END (wall clock, so streams from
different processes merge on one axis) and ``dur_s`` the event's duration
(0.0 for instants).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

Event = Tuple[int, float, str, str, float, str, Optional[dict]]

_DEFAULT_CAPACITY = 4096
_OFF_VALUES = ("0", "false", "no", "off")

# the high-rate event kinds a bare QK_TRACE_SAMPLE=N applies to: these are
# per-task / per-batch / per-store-op and can evict the rare stall/chaos/
# strategy events a post-mortem actually needs from the ring
_DEFAULT_SAMPLED_KINDS = ("task", "task.wait", "cache.hit", "mem.track",
                          "rpc", "push.batch", "pull.batch")


def _sample_from_env() -> Dict[str, int]:
    """``QK_TRACE_SAMPLE``: per-event-type sampling — keep 1 in N of each
    listed kind.  ``QK_TRACE_SAMPLE=8`` samples the default high-rate set
    at 1/8; ``QK_TRACE_SAMPLE=task=8,rpc=4`` names kinds explicitly.
    Unlisted kinds always record (rare events must never be sampled)."""
    spec = os.environ.get("QK_TRACE_SAMPLE", "").strip()
    if not spec or spec in ("0", "1"):
        return {}
    rates: Dict[str, int] = {}
    if spec.isdigit():
        n = int(spec)
        return {k: n for k in _DEFAULT_SAMPLED_KINDS} if n > 1 else {}
    for part in spec.split(","):
        kind, _, n = part.strip().partition("=")
        try:
            rate = int(n)
        except ValueError:
            continue
        if kind and rate > 1:
            rates[kind] = rate
    return rates


def recorder_enabled() -> bool:
    """The recorder is ON unless QK_TRACE_EVENTS explicitly disables it —
    it must be live BEFORE anyone knows the run is going to wedge."""
    return os.environ.get(
        "QK_TRACE_EVENTS", "").strip().lower() not in _OFF_VALUES


def trace_export_path() -> Optional[str]:
    """Chrome-trace export destination, or None when only the in-memory
    ring is wanted.  ``QK_TRACE_EVENTS=1`` -> ``quokka_trace.json`` in the
    cwd; any other non-off value is taken as the path itself."""
    v = os.environ.get("QK_TRACE_EVENTS", "").strip()
    if not v or v.lower() in _OFF_VALUES:
        return None
    if v.lower() in ("1", "true", "yes", "on"):
        return "quokka_trace.json"
    return v


class FlightRecorder:
    """Bounded event ring + a per-thread "current activity" marker.

    The activity marker exists for the in-process dump path (watchdog,
    faulthandler): a blocked call never produces its completion event, so
    the marker is the only record of WHAT is blocked."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 enabled: Optional[bool] = None,
                 sample: Optional[Dict[str, int]] = None):
        self.capacity = max(16, int(capacity))
        self.enabled = recorder_enabled() if enabled is None else enabled
        self._buf: List[Optional[Event]] = [None] * self.capacity
        self._seq = itertools.count()
        # highest sequence number issued so far; a plain store racing other
        # recorders only ever reads slightly stale, which a drop COUNTER
        # tolerates (it exists to say "the ring wrapped, the tail is gone",
        # not to account bytes)
        self._last = -1
        # per-kind eviction accounting: lock-light dict increments (a rare
        # racing undercount is within the drop counter's stated tolerance)
        self._dropped_by: Dict[str, int] = {}
        # per-kind sampling: kind -> keep 1 in N; per-kind admission
        # counters via itertools.count (atomic under CPython) so the
        # decision is deterministic, not random
        self._sample = dict(sample if sample is not None
                            else _sample_from_env())
        self._sample_seq: Dict[str, itertools.count] = {
            k: itertools.count() for k in self._sample}
        self._sampled_by: Dict[str, int] = {}
        # thread name -> (activity, start_ts): the per-thread marker the
        # stall dumps read when a blocked call never completes
        self._current: Dict[str, Tuple[str, float]] = {}

    # -- hot path -----------------------------------------------------------
    def record(self, kind: str, name: str = "", dur: float = 0.0,
               **args) -> int:
        if not self.enabled:
            return -1
        rate = self._sample.get(kind)
        if rate is not None and next(self._sample_seq[kind]) % rate:
            # sampled down, not dropped: the rare kinds this protects from
            # ring eviction are never listed in the sample map
            self._sampled_by[kind] = self._sampled_by.get(kind, 0) + 1
            return -1
        i = next(self._seq)
        slot = i % self.capacity
        old = self._buf[slot]
        if old is not None:
            # the ring wrapped: the evicted event's KIND is what a
            # post-mortem lost — account per type, not just a total
            k = old[2]
            self._dropped_by[k] = self._dropped_by.get(k, 0) + 1
        self._buf[slot] = (
            i, time.time(), kind, name, float(dur),
            threading.current_thread().name, args or None,
        )
        if i > self._last:
            self._last = i
        return i

    @property
    def dropped(self) -> Dict[str, int]:
        """Per-event-type counts of events silently overwritten since the
        last reset: once the ring wraps, every record evicts the oldest
        event.  A nonzero type means merged timelines / critical-path
        profiles are missing that kind's earliest tail — sample the
        high-rate kinds down (QK_TRACE_SAMPLE) or raise QK_TRACE_BUFFER."""
        return dict(self._dropped_by)

    @property
    def dropped_total(self) -> int:
        """Total evicted events (the scalar the drop gauges export)."""
        return sum(self._dropped_by.values())

    @property
    def sampled(self) -> Dict[str, int]:
        """Per-kind counts of events QK_TRACE_SAMPLE elided (never entered
        the ring; distinct from ``dropped``, which is ring eviction)."""
        return dict(self._sampled_by)

    def set_current(self, activity: str) -> None:
        if self.enabled:
            self._current[threading.current_thread().name] = (
                activity, time.time())

    def clear_current(self) -> None:
        if self.enabled:
            self._current.pop(threading.current_thread().name, None)

    class _Activity:
        __slots__ = ("rec", "name", "prev")

        def __init__(self, rec: "FlightRecorder", name: str):
            self.rec = rec
            self.name = name
            self.prev = None

        def __enter__(self):
            if self.rec.enabled:
                # markers nest (a task dispatch performs many RPCs): save
                # the outer marker so an inner completion restores it —
                # clearing instead would blind the watchdog to the task a
                # thread wedges in AFTER its last completed RPC
                key = threading.current_thread().name
                self.prev = self.rec._current.get(key)
                self.rec._current[key] = (self.name, time.time())
            return self

        def __exit__(self, *exc):
            if self.rec.enabled:
                key = threading.current_thread().name
                if self.prev is not None:
                    self.rec._current[key] = self.prev
                else:
                    self.rec._current.pop(key, None)
            return False

    def activity(self, name: str) -> "_Activity":
        """``with RECORDER.activity("rpc:get"):`` — marks the thread's
        current (possibly about-to-block) operation for stall dumps;
        nested markers restore the enclosing one on exit."""
        return FlightRecorder._Activity(self, name)

    # -- snapshots ----------------------------------------------------------
    def snapshot(self, since: int = -1,
                 last_n: Optional[int] = None) -> List[Event]:
        """Events with seq > ``since`` in sequence order.  Tolerates
        concurrent writers: a slot overwritten mid-scan just yields its
        newer event (or is dropped if it moved below ``since``)."""
        evs = [e for e in list(self._buf) if e is not None and e[0] > since]
        evs.sort(key=lambda e: e[0])
        if last_n is not None and len(evs) > last_n:
            evs = evs[-last_n:]
        return evs

    def current(self) -> Dict[str, Tuple[str, float]]:
        """thread name -> (activity, seconds_in_it)."""
        now = time.time()
        return {t: (name, now - t0)
                for t, (name, t0) in list(self._current.items())}

    def dump_text(self, stream, last_n: int = 40) -> None:
        """Human-readable tail + per-thread current activity (what the
        QK_SANITIZE watchdog appends under its stack dump)."""
        cur = self.current()
        if cur:
            stream.write("[flight-recorder] current activity per thread:\n")
            for t, (name, age) in sorted(cur.items()):
                stream.write(f"  {t}: {name} (for {age:.2f}s)\n")
        if self.dropped_total:
            by_kind = ", ".join(f"{k}={n}" for k, n in
                                sorted(self.dropped.items()) if n)
            stream.write(f"[flight-recorder] WARNING: ring dropped "
                         f"{self.dropped_total} event(s) ({by_kind}; "
                         f"capacity {self.capacity}; raise QK_TRACE_BUFFER "
                         f"or sample with QK_TRACE_SAMPLE)\n")
        evs = self.snapshot(last_n=last_n)
        stream.write(f"[flight-recorder] last {len(evs)} event(s):\n")
        for (_seq, ts, kind, name, dur, thread, args) in evs:
            extra = f" {args}" if args else ""
            stream.write(
                f"  {ts:.6f} [{thread}] {kind}:{name}"
                + (f" dur={dur * 1e3:.2f}ms" if dur else "") + extra + "\n")

    def reset(self) -> None:
        self._buf = [None] * self.capacity
        self._seq = itertools.count()
        self._last = -1
        self._current.clear()
        self._dropped_by.clear()
        self._sampled_by.clear()
        self._sample_seq = {k: itertools.count() for k in self._sample}


def _capacity_from_env() -> int:
    try:
        return int(os.environ.get("QK_TRACE_BUFFER", _DEFAULT_CAPACITY))
    except ValueError:
        return _DEFAULT_CAPACITY


RECORDER = FlightRecorder(capacity=_capacity_from_env())
