"""EXPLAIN ANALYZE smoke: the operator-statistics ledger reconciles,
detects skew, adds no host syncs, and feeds admission.

    python -m quokka_tpu.obs.explain_smoke      (or: make explain-smoke)

One process, four proofs over a seeded Q3-shaped join+aggregate submitted
through the QueryService with 2 io + 2 exec channels (so every exchange
edge has real per-channel histograms):

1. **row reconciliation** — each scan operator's ``rows_in`` equals its
   parquet table's row count exactly, and every downstream operator's
   ``rows_in`` equals the summed delivered totals of its in-edges (the
   push-side edge histograms and the exec-side intake are two independent
   tallies of the same rows — broadcast fan-out included);
2. **skew report** — the snapshot carries a per-exchange-edge report
   (channel rows, max/mean ratio) for every edge of the plan, and the
   rendered EXPLAIN ANALYZE shows it;
3. **zero added syncs** — the whole run, stats collection included, adds
   ZERO ``shuffle.host_syncs`` (the ledger rides the existing async-count
   discipline; blocking readbacks on the hot path would show here);
4. **measured admission** — with the memory profile disabled, a second
   submission of the SAME plan must be admitted on the measured source
   bytes persisted in the cardinality profile
   (``max(src_bytes * PIPELINE_OVERHEAD, 1 MiB)``), beating the first
   run's size_hint-derived estimate.

Exit nonzero on any violation, with the observed figures printed.
"""

from __future__ import annotations

import os
import sys
import tempfile
from typing import Optional


def _make_tables(tmp: str, seed: int = 20260805):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(seed)
    n_fact, n_dim = 200_000, 20_000
    fact = pa.table({
        "fk": r.integers(0, n_dim, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
        "flag": r.integers(0, 4, n_fact).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(n_dim, dtype=np.int64),
        "grp": r.integers(0, 64, n_dim).astype(np.int64),
    })
    fp = os.path.join(tmp, "fact.parquet")
    dp = os.path.join(tmp, "dim.parquet")
    pq.write_table(fact, fp, row_group_size=1 << 16)
    pq.write_table(dim, dp)
    return (fp, n_fact), (dp, n_dim)


def _query(ctx, fp, dp):
    from quokka_tpu.expression import col

    fact = ctx.read_parquet(fp)
    dim = ctx.read_parquet(dp)
    return (
        fact.filter(col("flag") < 3)
        .join(dim, left_on="fk", right_on="pk")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
    )


def _reconcile(snap, n_fact: int, n_dim: int) -> Optional[str]:
    """Proof 1: scans read exactly the parquet rows; every exec's intake
    equals its in-edges' delivered totals.  Returns the violation, or None."""
    ops = snap.get("operators") or []
    edges = snap.get("edges") or []
    if snap.get("rows_unknown", 0):
        return (f"{snap['rows_unknown']} batch(es) ended with unresolved "
                "row counts — the pending-resolution sweep missed them")
    scans = [o for o in ops if o.get("kind") == "input"]
    scan_rows = sorted(o["rows_in"] for o in scans)
    if scan_rows != sorted((n_fact, n_dim)):
        return (f"scan rows_in {scan_rows} != parquet row counts "
                f"{sorted((n_fact, n_dim))}")
    delivered = {}  # tgt actor -> summed in-edge delivered rows
    for e in edges:
        delivered[e["tgt"]] = delivered.get(e["tgt"], 0) + e["rows_total"]
    for o in ops:
        if o.get("kind") == "input":
            continue
        want = delivered.get(o["actor"], 0)
        if o["rows_in"] != want:
            return (f"operator a{o['actor']} ({o['op']}) consumed "
                    f"{o['rows_in']} row(s) but its in-edges delivered "
                    f"{want} — the push-side and exec-side tallies disagree")
    return None


def _skew_violation(snap, rendered: str) -> Optional[str]:
    """Proof 2: every exchange edge reports a channel histogram and a
    max/mean ratio; the rendering surfaces the report."""
    edges = snap.get("edges") or []
    if not edges:
        return ("no exchange edges in the snapshot — the push path "
                "recorded nothing")
    for e in edges:
        if not e.get("channel_rows"):
            return f"edge {e['edge']} has no channel histogram"
        if e.get("skew_ratio", 0) < 1.0 and e.get("rows_total", 0) > 0:
            return (f"edge {e['edge']} reports impossible skew ratio "
                    f"{e.get('skew_ratio')}")
    if "skew report" not in rendered:
        return "rendered EXPLAIN ANALYZE carries no skew report section"
    return None


def main() -> int:  # noqa: C901 — linear proof script, mem_smoke idiom
    # the memory profile would win admission for the second submission;
    # disable it so this smoke proves the CARDINALITY feedback path, and
    # isolate the cardinality profile itself in a temp dir
    env_overrides = {
        "QK_MEMPROFILE_DIR": "",
        "QK_CARDPROFILE_DIR": tempfile.mkdtemp(prefix="qk-cardprofile-"),
    }
    saved = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)
    profile_dir = env_overrides["QK_CARDPROFILE_DIR"]

    def fail(msg: str) -> int:
        sys.stderr.write(f"explain-smoke: FAIL — {msg}\n")
        return 1

    try:
        from quokka_tpu import QuokkaContext, obs
        from quokka_tpu.obs import opstats
        from quokka_tpu.service import QueryService
        from quokka_tpu.service import admission

        with tempfile.TemporaryDirectory(prefix="qk-explain-smoke-") as tmp:
            (fp, n_fact), (dp, n_dim) = _make_tables(tmp)
            syncs0 = obs.REGISTRY.snapshot().get("shuffle.host_syncs", 0)
            with QueryService(pool_size=2) as svc:
                ctx = QuokkaContext(io_channels=2, exec_channels=2)
                h1 = svc.submit(_query(ctx, fp, dp))
                rows = h1.to_arrow(timeout=600)
                if rows.num_rows <= 0:
                    return fail("smoke query returned no rows")
                est1 = h1._s.est_bytes
                plan_fp = h1._s.graph.plan_fp
                snap = h1.explain(as_dict=True)
                if not snap:
                    return fail("no opstats snapshot survived the query GC")
                rendered = h1.explain()
                print(rendered)

                # -- proof 1: row reconciliation --------------------------
                err = _reconcile(snap, n_fact, n_dim)
                if err:
                    return fail(err)
                n_scans = sum(1 for o in snap["operators"]
                              if o["kind"] == "input")
                print(f"explain-smoke: reconciled {n_scans} scan(s) and "
                      f"{len(snap['operators']) - n_scans} exec operator(s) "
                      f"against {len(snap['edges'])} exchange edge(s)")

                # -- proof 2: skew report ---------------------------------
                err = _skew_violation(snap, rendered)
                if err:
                    return fail(err)
                worst = max(e["skew_ratio"] for e in snap["edges"])
                print(f"explain-smoke: skew report over "
                      f"{len(snap['edges'])} edge(s), worst ratio "
                      f"{worst:.3f} (threshold {snap.get('skew_threshold')})")

                # -- proof 3: zero added host syncs -----------------------
                syncs = obs.REGISTRY.snapshot().get("shuffle.host_syncs",
                                                    0) - syncs0
                print(f"explain-smoke: host_syncs delta {syncs}")
                if syncs:
                    return fail(f"collecting operator stats cost {syncs} "
                                "host sync(s) — the ledger must ride the "
                                "async-count discipline")

                # -- proof 4: measured-cardinality admission --------------
                src_bytes = opstats.measured_source_bytes(plan_fp)
                if not src_bytes:
                    return fail(f"no measured cardinalities persisted for "
                                f"plan {plan_fp!r} under {profile_dir}")
                h2 = svc.submit(_query(QuokkaContext(io_channels=2,
                                                     exec_channels=2),
                                       fp, dp))
                est2 = h2._s.est_bytes
                h2.result(timeout=600)
                want = max(int(src_bytes * admission.PIPELINE_OVERHEAD),
                           1 << 20)
                print(f"explain-smoke: admission est first={est1} "
                      f"second={est2} measured_src_bytes={src_bytes}")
                if est2 != want:
                    return fail(f"second admission charged {est2}, expected "
                                f"the measured-cardinality estimate {want}")
                if est2 >= est1:
                    return fail(f"measured admission ({est2}) did not beat "
                                f"the size_hint estimate ({est1}) on this "
                                "deliberately tiny plan")
        print("explain-smoke: OK — rows reconcile scan->exec->edges, skew "
              "report present, zero added host syncs, second admission "
              "used measured cardinalities")
        return 0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    sys.exit(main())
