"""Observability layer: flight recorder, spans, metrics, timeline merger.

One import surface for every runtime component:

- ``recorder`` / ``RECORDER``: per-process lock-light ring buffer of
  timestamped events (task begin/end, batch push/pull, compile, cache
  hit/miss, heartbeats, state transitions).  Always on by default — it is
  the forensic record the stall detector dumps when a run wedges — and
  cheap enough to leave on (a tuple store per event, no locks on the hot
  path).  ``QK_TRACE_EVENTS=0`` disables it outright.
- ``spans``: the span API (``QUOKKA_TRACE=1`` aggregate summary, the role
  utils/tracing.py used to play) — spans additionally land in the flight
  recorder as duration events.
- ``metrics``: typed counters/gauges plus the engine's per-channel task
  accounting (folded out of runtime/engine.py).
- ``merge``: coordinator-side merger — assembles per-worker event streams
  into one ordered timeline, exports Chrome trace-event JSON (loadable in
  Perfetto: ui.perfetto.dev -> Open trace file) and renders human-readable
  stall reports naming the stuck worker and its in-flight task.

Env vars (the full table is in README "Observability"):

- ``QK_TRACE_EVENTS``: unset/1 -> recorder on; ``0`` -> recorder off; a
  path (or ``1`` for ``quokka_trace.json``) -> ALSO export the merged
  Chrome trace at run end.
- ``QK_DUMP_DIR``: where stall dumps land (default
  ``<tmp>/quokka_tpu_dumps``).
- ``QUOKKA_TRACE=1``: print the span summary at bench end (unchanged).
- ``QK_COORD_TIMEOUT``: coordinator run timeout seconds (default 600).
- ``QK_CHAOS``: seeded fault-injection spec (quokka_tpu/chaos).  Every
  injected fault lands here as a ``chaos.*`` event, every checksum
  rejection as ``integrity.corrupt``, and every recovery escalation as a
  ``recover.*`` event — a chaos soak is triaged from the same merged
  timeline as a production stall.
"""

from __future__ import annotations

import contextlib
import sys

from quokka_tpu.obs import (
    alerts,
    critpath,
    devprof,
    explain,
    export,
    history,
    memplane,
    merge,
    metrics,
    opstats,
    progress,
    recorder,
    spans,
)
from quokka_tpu.obs.opstats import OPSTATS
from quokka_tpu.obs.merge import (
    dump_flight,
    merge_streams,
    stall_report,
    to_chrome_trace,
    write_chrome_trace,
)
from quokka_tpu.obs.metrics import (
    REGISTRY,
    Counter,
    EngineMetrics,
    Gauge,
    Histogram,
)
from quokka_tpu.obs.recorder import (
    RECORDER,
    FlightRecorder,
    recorder_enabled,
    trace_export_path,
)
from quokka_tpu.obs.spans import add, span, summary

_RPC_SLOW_S = 0.005


def diag(msg: str) -> None:
    """The sanctioned diagnostic logger for library code (lint rule QK007
    bans bare ``print`` outside CLI entry points): one line to stderr,
    flushed, plus a ``diag`` event in the flight recorder so the message
    shows up in merged timelines next to what the process was doing."""
    line = msg.rstrip("\n")
    RECORDER.record("diag", line[:200])
    # a closed stderr (daemonized worker) must not kill the caller
    with contextlib.suppress(OSError, ValueError):
        sys.stderr.write(line + "\n")
        sys.stderr.flush()


def rpc_event(method: str, dur: float) -> None:
    """Account one client-side RPC: always a counter + latency-histogram
    observation, an event only when it was slow (every store op would
    otherwise flood the ring and evict the task-level events a stall dump
    needs)."""
    REGISTRY.counter(f"rpc.{method}").inc()
    REGISTRY.histogram("rpc.latency_s").observe(dur)
    if dur > _RPC_SLOW_S:
        RECORDER.record("rpc", method, dur=dur)
