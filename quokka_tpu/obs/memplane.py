"""Memory observability plane: the device/host-buffer ledger.

The obs plane could attribute every second of a query's wall time (critpath)
but not a single byte of its memory.  This module closes that gap with a
process-wide ledger: every tracked allocation — reader batches in the device
scan cache, shuffle partitions in the BatchCache, a join's finalized build
side, HBQ spill residency, checkpoint snapshots, persisted AOT executables —
registers a ``(query_id, site, nbytes, device)`` entry on create and retires
it on free/spill/GC.  From the ledger the plane serves:

- **gauges**: ``mem.live_bytes`` / ``mem.peak_bytes`` /
  ``mem.spill_resident_bytes`` aggregates, per-query twins (GC'd in
  ``TaskGraph.cleanup`` like every per-query family) and a per-site-class
  residency family ``mem.site_bytes.<site>``;
- **reconciliation**: the device-class ledger total checked against
  ``jax.live_arrays()`` within a tolerance (``QK_MEM_RECONCILE``), so drift
  between what we think is resident and what the runtime actually holds is
  measurable, not folklore;
- **leak flagging**: any entry still live after its query's namespace drop
  becomes a named ``MemLeakError`` report with the allocation-site flight
  events attached (strict mode ``QK_MEM_STRICT=1`` raises it);
- **OOM forensics**: on an allocation failure (``alloc_guard``) or a
  ``QK_MEM_BUDGET`` breach, a forensics bundle lands in ``QK_DUMP_DIR`` —
  top-K holders by site, per-query footprints, the recent ledger tail and
  the merged flight timeline — the memory analogue of the stall dump;
- **measured admission**: each finished query persists its measured
  ``peak_bytes`` keyed by plan fingerprint (the strategy-profile atomic
  pattern, one file per backend fingerprint under
  ``<cache>/memprofile/``), and ``service/admission.py`` prefers that
  figure over reader ``size_hint()`` guesses on the next submit of the
  same plan shape.

Tracking happens at the choke points the runtime already owns (cache put/gc,
HBQ put/gc/wipe, scan-cache put/evict, checkpoint save/wipe, AOT persist) —
not by wrapping every ``jnp`` call; lint rule QK018 keeps new device
allocations from growing outside those ledgered paths.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# site classes: where in the runtime a tracked allocation lives
SITE_READER = "reader"          # device scan cache (post-bridge batches)
SITE_SHUFFLE = "shuffle"        # BatchCache partitions awaiting consumers
SITE_BUILD = "build"            # a join's finalized build side
SITE_SPILL = "spill"            # HBQ spill files (host disk)
SITE_CKPT = "checkpoint"        # executor-state snapshots
SITE_EXEC = "executable"        # persisted AOT executables

DEVICE = "device"
HOST = "host"

_PROFILE_VERSION = 1
_TAIL_LEN = 256
_TOP_K = 20


def budget_bytes() -> int:
    """``QK_MEM_BUDGET``: soft byte budget for tracked live memory; 0/unset
    disables the breach check (the bundle, not an allocator limit)."""
    try:
        return int(os.environ.get("QK_MEM_BUDGET", 0))
    except ValueError:
        return 0


def reconcile_tolerance() -> float:
    """``QK_MEM_RECONCILE``: allowed relative drift between the ledger's
    device-class total and what jax reports live (default 10%)."""
    try:
        return float(os.environ.get("QK_MEM_RECONCILE", 0.10))
    except ValueError:
        return 0.10


def strict_mode() -> bool:
    """``QK_MEM_STRICT=1``: a leak report raises instead of diagnosing."""
    return os.environ.get("QK_MEM_STRICT", "").strip().lower() in (
        "1", "true", "yes", "on")


class MemLeakError(RuntimeError):
    """Ledger entries survived their query's namespace drop.  ``leaks`` is
    a list of {token, site, nbytes, device, events} dicts — ``events`` are
    the allocation-site flight-recorder events, so the report names WHERE
    each leaked buffer came from, not just that one exists."""

    def __init__(self, query_id: str, leaks: List[dict]):
        self.query_id = query_id
        self.leaks = list(leaks)
        total = sum(leak["nbytes"] for leak in self.leaks)
        sites = sorted({leak["site"] for leak in self.leaks})
        super().__init__(
            f"query {query_id}: {len(self.leaks)} ledger entr"
            f"{'y' if len(self.leaks) == 1 else 'ies'} still live after "
            f"namespace GC ({total} bytes; sites: {', '.join(sites)})")


def _tok_id(token) -> str:
    """Compact per-process id stamped into flight events so a leak report
    can find the exact allocation event for each surviving entry."""
    return format(hash(token) & 0xFFFFFFFF, "08x")


class MemLedger:
    """Thread-safe allocation ledger.  Entries are keyed by an arbitrary
    hashable token (the tracking site picks one that identifies the buffer:
    a cache name 6-tuple, a spill filename, a checkpoint path).  ``track``
    of an existing token replaces it (BatchCache dedup semantics)."""

    def __init__(self, tail: int = _TAIL_LEN):
        self._lock = threading.Lock()
        # token -> (query_id, site, nbytes, device)
        self._entries: Dict[object, Tuple[Optional[str], str, int, str]] = {}
        self._live = 0
        self._peak = 0
        self._device_live = 0
        self._spill = 0
        self._site: Dict[str, int] = {}
        self._live_q: Dict[str, int] = {}
        self._peak_q: Dict[str, int] = {}
        self._spill_q: Dict[str, int] = {}
        self._spill_peak_q: Dict[str, int] = {}
        self._tail: deque = deque(maxlen=tail)
        self._breached = False
        # reconciliation baselines: jax holds buffers the ledger never
        # claims to track (jit constants, RNG state), so both sides compare
        # as DELTAS from the moment set_baseline() was called
        self._jax_baseline = 0
        self._ledger_baseline = 0

    # -- accounting core (callers hold self._lock) ---------------------------
    def _apply(self, ent, sign: int) -> None:
        query, site, nbytes, device = ent
        delta = sign * nbytes
        self._live += delta
        if device == DEVICE:
            self._device_live += delta
        if site == SITE_SPILL:
            self._spill += delta
        self._site[site] = self._site.get(site, 0) + delta
        if query is not None and query in self._live_q:
            self._live_q[query] += delta
            if site == SITE_SPILL:
                self._spill_q[query] = self._spill_q.get(query, 0) + delta
        if sign > 0:
            if self._live > self._peak:
                self._peak = self._live
            if query is not None:
                q_live = self._live_q.get(query, 0)
                if q_live > self._peak_q.get(query, 0):
                    self._peak_q[query] = q_live
                q_spill = self._spill_q.get(query, 0)
                if q_spill > self._spill_peak_q.get(query, 0):
                    self._spill_peak_q[query] = q_spill

    def _gauge_pairs(self, query: Optional[str],
                     site: Optional[str]) -> List[Tuple[str, float]]:
        pairs = [("mem.live_bytes", self._live),
                 ("mem.peak_bytes", self._peak),
                 ("mem.spill_resident_bytes", self._spill)]
        if site is not None:
            pairs.append((f"mem.site_bytes.{site}", self._site.get(site, 0)))
        # per-query twins only while the query's accounting is live:
        # a straggler retire after drop_query must never resurrect a GC'd
        # instrument as a permanent /metrics family
        if query is not None and query in self._live_q:
            pairs += [
                (f"mem.live_bytes.{query}", self._live_q[query]),
                (f"mem.peak_bytes.{query}", self._peak_q.get(query, 0)),
                (f"mem.spill_resident_bytes.{query}",
                 self._spill_q.get(query, 0)),
            ]
        return pairs

    @staticmethod
    def _set_gauges(pairs: List[Tuple[str, float]]) -> None:
        from quokka_tpu import obs

        for name, value in pairs:
            obs.REGISTRY.gauge(name).set(value)

    # -- track / retire ------------------------------------------------------
    def track(self, token, site: str, nbytes, *,
              query: Optional[str] = None, device: str = DEVICE) -> None:
        nbytes = max(0, int(nbytes))
        breach = False
        with self._lock:
            old = self._entries.pop(token, None)
            if old is not None:
                self._apply(old, -1)
            if query is not None and query not in self._live_q:
                self._live_q[query] = 0
            ent = (query, site, nbytes, device)
            self._entries[token] = ent
            self._apply(ent, +1)
            self._tail.append((time.time(), "track", site, query, nbytes))
            budget = budget_bytes()
            if budget > 0:
                if self._live > budget and not self._breached:
                    self._breached = True  # latch: one bundle per episode
                    breach = True
                elif self._live <= budget:
                    self._breached = False
            pairs = self._gauge_pairs(query, site)
        self._set_gauges(pairs)
        from quokka_tpu import obs

        obs.RECORDER.record("mem.track", site, nbytes=nbytes,
                            tok=_tok_id(token),
                            **({"q": query} if query else {}))
        if breach:
            obs.REGISTRY.counter("mem.budget_breach").inc()
            obs.diag(f"[memplane] live tracked memory {self._live} exceeds "
                     f"QK_MEM_BUDGET={budget_bytes()} (site {site!r}"
                     + (f", query {query}" if query else "") + ")")
            oom_bundle(f"QK_MEM_BUDGET breach at site {site!r}", ledger=self)

    def retire(self, token) -> None:
        with self._lock:
            ent = self._entries.pop(token, None)
            if ent is None:
                return
            self._apply(ent, -1)
            query, site, nbytes, _device = ent
            self._tail.append((time.time(), "retire", site, query, nbytes))
            pairs = self._gauge_pairs(query, site)
        self._set_gauges(pairs)

    def retire_prefix(self, prefix: Tuple) -> None:
        """Retire every tuple-keyed entry whose token starts with ``prefix``
        (bulk GC: an HBQ wipe, a checkpoint namespace drop)."""
        plen = len(prefix)
        pairs: List[Tuple[str, float]] = []
        with self._lock:
            toks = [t for t in self._entries
                    if isinstance(t, tuple) and t[:plen] == prefix]
            queries, sites = set(), set()
            for tok in toks:
                ent = self._entries.pop(tok)
                self._apply(ent, -1)
                query, site, nbytes, _device = ent
                queries.add(query)
                sites.add(site)
                self._tail.append(
                    (time.time(), "retire", site, query, nbytes))
            if toks:
                pairs = self._gauge_pairs(None, None)
                for site in sites:
                    pairs.append((f"mem.site_bytes.{site}",
                                  self._site.get(site, 0)))
                for query in queries:
                    if query is not None and query in self._live_q:
                        pairs += self._gauge_pairs(query, None)[3:]
        if pairs:
            self._set_gauges(pairs)

    # -- readers -------------------------------------------------------------
    def live_bytes(self, query: Optional[str] = None) -> int:
        with self._lock:
            return self._live if query is None \
                else self._live_q.get(query, 0)

    def peak_bytes(self, query: Optional[str] = None) -> int:
        with self._lock:
            return self._peak if query is None \
                else self._peak_q.get(query, 0)

    def spill_bytes(self, query: Optional[str] = None) -> int:
        with self._lock:
            return self._spill if query is None \
                else self._spill_q.get(query, 0)

    def device_live_bytes(self) -> int:
        with self._lock:
            return self._device_live

    def site_totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._site)

    def entry_count(self, query: Optional[str] = None) -> int:
        with self._lock:
            if query is None:
                return len(self._entries)
            return sum(1 for ent in self._entries.values()
                       if ent[0] == query)

    def query_footprint(self, query: str) -> Dict[str, int]:
        """{live_bytes, peak_bytes, spill_resident_bytes} for one query —
        what the session snapshots at finish (the per-query gauges GC with
        the namespace; the handle keeps answering)."""
        with self._lock:
            return {
                "live_bytes": self._live_q.get(query, 0),
                "peak_bytes": self._peak_q.get(query, 0),
                "spill_resident_bytes": self._spill_q.get(query, 0),
            }

    def reset_peak(self) -> None:
        """Re-arm the aggregate high-water mark at the current live total
        (bench.py brackets each measured query with this)."""
        with self._lock:
            self._peak = self._live
            pairs = self._gauge_pairs(None, None)
        self._set_gauges(pairs)

    def snapshot(self, top_k: int = _TOP_K) -> Dict:
        """Everything the OOM bundle wants, in one locked read."""
        with self._lock:
            holders = sorted(self._entries.items(),
                             key=lambda kv: -kv[1][2])[:top_k]
            queries = set(self._live_q) | set(self._peak_q)
            return {
                "live_bytes": self._live,
                "peak_bytes": self._peak,
                "device_live_bytes": self._device_live,
                "spill_resident_bytes": self._spill,
                "entries": len(self._entries),
                "site_bytes": dict(self._site),
                "query_footprints": {
                    q: {"live_bytes": self._live_q.get(q, 0),
                        "peak_bytes": self._peak_q.get(q, 0),
                        "spill_resident_bytes": self._spill_q.get(q, 0)}
                    for q in sorted(queries)},
                "top_holders": [
                    {"token": repr(tok), "query": ent[0], "site": ent[1],
                     "nbytes": ent[2], "device": ent[3]}
                    for tok, ent in holders],
                "ledger_tail": [
                    {"ts": ts, "op": op, "site": site, "query": q,
                     "nbytes": nb}
                    for ts, op, site, q, nb in self._tail],
            }

    # -- reconciliation ------------------------------------------------------
    def set_baseline(self) -> None:
        """Mark the current moment as reconciliation zero: jax buffers that
        predate it (jit constants, caches, RNG state) are outside the
        ledger's claim and must not count as drift."""
        with self._lock:
            self._jax_baseline = _jax_live_bytes()
            self._ledger_baseline = self._device_live

    def reconcile(self, tolerance: Optional[float] = None) -> Dict:
        """Compare the ledger's device-class growth since ``set_baseline``
        against what ``jax.live_arrays()`` actually reports.  Returns
        {available, ledger_bytes, jax_bytes, drift_frac, within,
        tolerance}."""
        tol = reconcile_tolerance() if tolerance is None else float(tolerance)
        jax_now = _jax_live_bytes()
        if jax_now < 0:
            return {"available": False, "within": True, "tolerance": tol,
                    "ledger_bytes": 0, "jax_bytes": 0, "drift_frac": 0.0}
        with self._lock:
            ledger_delta = self._device_live - self._ledger_baseline
            jax_delta = jax_now - self._jax_baseline
        denom = max(ledger_delta, jax_delta, 1)
        drift = abs(jax_delta - ledger_delta) / denom
        return {
            "available": True,
            "ledger_bytes": ledger_delta,
            "jax_bytes": jax_delta,
            "drift_frac": round(drift, 6),
            "tolerance": tol,
            "within": drift <= tol,
        }

    # -- leak detection + query GC -------------------------------------------
    def check_leaks(self, query_id: str, *,
                    strict: Optional[bool] = None) -> Optional[MemLeakError]:
        """Collect (and retire) every entry still charged to ``query_id``.
        Returns the MemLeakError report (None when clean); raises it when
        strict (param, else ``QK_MEM_STRICT``)."""
        if query_id is None:
            return None
        with self._lock:
            leaked = [(tok, ent) for tok, ent in self._entries.items()
                      if ent[0] == query_id]
            sites = set()
            for tok, ent in leaked:
                del self._entries[tok]
                self._apply(ent, -1)
                sites.add(ent[1])
                self._tail.append(
                    (time.time(), "leak", ent[1], query_id, ent[2]))
            pairs = self._gauge_pairs(query_id, None) if leaked else []
            for site in sites:
                pairs.append((f"mem.site_bytes.{site}",
                              self._site.get(site, 0)))
        if not leaked:
            return None
        self._set_gauges(pairs)
        from quokka_tpu import obs

        # attach each leaked entry's allocation-site flight events: the
        # report should say where the buffer CAME from, not just its size
        by_tok: Dict[str, List] = {}
        for ev in obs.RECORDER.snapshot():
            if ev[2] == "mem.track" and ev[6]:
                by_tok.setdefault(ev[6].get("tok", ""), []).append(
                    {"ts": ev[1], "site": ev[3], "thread": ev[5],
                     "args": ev[6]})
        leaks = [{"token": repr(tok), "site": ent[1], "nbytes": ent[2],
                  "device": ent[3], "events": by_tok.get(_tok_id(tok), [])}
                 for tok, ent in leaked]
        err = MemLeakError(query_id, leaks)
        obs.REGISTRY.counter("mem.leaked").inc(len(leaked))
        obs.RECORDER.record("mem.leak", query_id, n=len(leaked),
                            nbytes=sum(leak["nbytes"] for leak in leaks))
        obs.diag(f"[memplane] {err}")
        if strict if strict is not None else strict_mode():
            raise err
        return err

    def drop_query(self, query_id: str) -> None:
        """Forget a finished query's per-query accounting (the engine
        removes the per-query gauge instruments right after)."""
        with self._lock:
            self._live_q.pop(query_id, None)
            self._peak_q.pop(query_id, None)
            self._spill_q.pop(query_id, None)
            self._spill_peak_q.pop(query_id, None)

    def on_query_gc(self, query_id: str,
                    plan_fp: Optional[str] = None) -> Optional[MemLeakError]:
        """The ``TaskGraph.cleanup`` hook: persist the measured footprint
        under the plan fingerprint, flag leaks, drop per-query state."""
        if query_id is None:
            return None
        with self._lock:
            peak = self._peak_q.get(query_id, 0)
            spill_peak = self._spill_peak_q.get(query_id, 0)
        if plan_fp and peak > 0:
            record_footprint(plan_fp, peak, spill_peak)
        try:
            return self.check_leaks(query_id)
        finally:
            self.drop_query(query_id)

    def reset(self) -> None:
        """Tests only: forget everything and zero the aggregate gauges."""
        with self._lock:
            self._entries.clear()
            self._live = self._peak = self._device_live = self._spill = 0
            self._site.clear()
            self._live_q.clear()
            self._peak_q.clear()
            self._spill_q.clear()
            self._spill_peak_q.clear()
            self._tail.clear()
            self._breached = False
            self._jax_baseline = self._ledger_baseline = 0
            pairs = self._gauge_pairs(None, None)
        self._set_gauges(pairs)


def _jax_live_bytes() -> int:
    """Total bytes of live jax arrays, or -1 when jax is unavailable."""
    try:
        import jax

        return sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays())
    except Exception:  # noqa: BLE001 — reconciliation is diagnostics
        return -1


LEDGER = MemLedger()


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

_BUNDLE_SEQ = itertools.count()


def oom_bundle(reason: str, directory: Optional[str] = None,
               ledger: Optional[MemLedger] = None,
               top_k: int = _TOP_K) -> str:
    """Write the memory forensics bundle into ``QK_DUMP_DIR``: top-K holders
    by site, per-query footprints, the recent ledger tail and the merged
    flight timeline (+ a Chrome trace beside it).  Returns the bundle path;
    never raises — a failed dump must not mask the OOM it describes."""
    try:
        from quokka_tpu import obs
        from quokka_tpu.obs import merge

        ledger = LEDGER if ledger is None else ledger
        d = directory or merge.dump_dir()
        os.makedirs(d, exist_ok=True)
        # per-process sequence: two bundles in the same second (breach
        # followed immediately by the allocator error) must not collide
        stamp = f"{os.getpid()}-{int(time.time())}-{next(_BUNDLE_SEQ)}"
        path = os.path.join(d, f"mem-{stamp}.oom.json")
        trace_path = os.path.join(d, f"mem-{stamp}.trace.json")
        events = obs.RECORDER.snapshot()
        with contextlib.suppress(Exception):
            merge.write_chrome_trace(
                trace_path, merge.merge_streams({"local": events}))
        bundle = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "budget_bytes": budget_bytes(),
            **ledger.snapshot(top_k=top_k),
            "flight_timeline": [
                {"ts": ev[1], "kind": ev[2], "name": ev[3],
                 "dur_s": ev[4], "thread": ev[5], "args": ev[6]}
                for ev in events[-200:]],
            "chrome_trace": trace_path,
        }
        # operator-statistics snapshots: which operator's rows/bytes were
        # in flight when memory ran out (a blown join build reads straight
        # off its rows_in here)
        with contextlib.suppress(Exception):
            from quokka_tpu.obs import opstats as _opstats

            snaps = [s for s in (_opstats.OPSTATS.snapshot(q)
                                 for q in _opstats.OPSTATS.live_queries())
                     if s]
            if not snaps:
                last = _opstats.OPSTATS.last_finished()
                snaps = [last] if last else []
            bundle["opstats"] = snaps
        with open(path, "w", encoding="utf-8") as f:
            json.dump(bundle, f, indent=2, default=repr)
        obs.REGISTRY.counter("mem.oom_bundles").inc()
        obs.diag(f"[memplane] OOM forensics bundle: {path} ({reason})")
        return path
    except Exception as e:  # noqa: BLE001 — diagnostics must not mask OOM
        with contextlib.suppress(OSError, ValueError):
            sys.stderr.write(f"[memplane] oom bundle failed: {e!r}\n")
        return ""


@contextlib.contextmanager
def alloc_guard(site: str):
    """Wrap a device-allocating region: an allocator out-of-memory error
    writes the forensics bundle before re-raising, so the post-mortem has
    the ledger state from the exact failing moment."""
    try:
        yield
    except Exception as e:
        msg = str(e)
        if ("RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()
                or isinstance(e, MemoryError)):
            oom_bundle(f"allocation failure at site {site!r}: {msg[:200]}")
        raise


# ---------------------------------------------------------------------------
# Measured footprints (admission's input): strategy-profile persistence
# ---------------------------------------------------------------------------


def _profile_dir() -> Optional[str]:
    """``QK_MEMPROFILE_DIR`` overrides (empty disables, the QK_STRATEGY_DIR
    idiom); default lives beside the strategy profiles under the cache
    root."""
    env = os.environ.get("QK_MEMPROFILE_DIR")
    if env is not None:
        return env or None
    from quokka_tpu import config

    if not config.CACHE_ROOT:
        return None
    return os.path.join(config.CACHE_ROOT, "memprofile")


def _profile_path() -> Optional[str]:
    d = _profile_dir()
    if d is None:
        return None
    from quokka_tpu.runtime import compileplane

    return os.path.join(d, compileplane.backend_fingerprint() + ".json")


def _load_profile(path: str) -> Optional[dict]:
    """The profile dict, or None when absent/corrupt/foreign.  A profile
    measured on a different backend topology is rejected wholesale — its
    footprints describe different device placement."""
    try:
        with open(path, encoding="utf-8") as f:
            prof = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(prof, dict) or prof.get("version") != _PROFILE_VERSION:
        return None
    from quokka_tpu.runtime import compileplane

    if prof.get("fingerprint") != compileplane.backend_fingerprint():
        return None
    return prof if isinstance(prof.get("plans"), dict) else None


def record_footprint(plan_fp: str, peak_bytes: int,
                     spill_bytes: int = 0) -> None:
    """Persist a finished query's measured peak under its plan fingerprint
    (atomic tmp + replace, max-merged across runs so a lightly-loaded run
    never shrinks the admission charge below an observed peak).  Best
    effort: never raises."""
    if not plan_fp or peak_bytes <= 0:
        return
    path = _profile_path()
    if path is None:
        return
    try:
        from quokka_tpu.runtime import compileplane

        prof = _load_profile(path) or {
            "version": _PROFILE_VERSION,
            "fingerprint": compileplane.backend_fingerprint(),
            "plans": {},
        }
        ent = prof["plans"].get(plan_fp)
        ent = ent if isinstance(ent, dict) else {}
        prof["plans"][plan_fp] = {
            "peak_bytes": max(int(peak_bytes),
                              int(ent.get("peak_bytes", 0) or 0)),
            "spill_bytes": max(int(spill_bytes),
                               int(ent.get("spill_bytes", 0) or 0)),
            "runs": int(ent.get("runs", 0) or 0) + 1,
        }
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(prof, f)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError) as e:
        from quokka_tpu import obs

        obs.diag(f"[memplane] footprint persist for {plan_fp} failed: {e!r}")


def measured_footprint(plan_fp: Optional[str]) -> Optional[int]:
    """The measured peak bytes for a plan fingerprint, or None (no profile,
    foreign backend fingerprint, unknown plan) — admission falls back to
    ``size_hint()`` estimation then."""
    if not plan_fp:
        return None
    path = _profile_path()
    if path is None:
        return None
    prof = _load_profile(path)
    if prof is None:
        return None
    ent = prof["plans"].get(plan_fp)
    if not isinstance(ent, dict):
        return None
    try:
        peak = int(ent.get("peak_bytes", 0))
    except (TypeError, ValueError):
        return None
    return peak if peak > 0 else None
